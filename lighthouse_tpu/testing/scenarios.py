"""Adversarial mainnet scenarios over the fault-injecting LocalNetwork.

ISSUE 7 tentpole, ROADMAP item 4: each scenario drives production nodes
through a mainnet incident shape — long non-finality, partition + heal,
slashable equivocation, checkpoint sync into a partitioned network, an
invalid-signature gossip flood — and asserts a DEGRADATION ENVELOPE
evaluated by the graftwatch SLO engine (pipeline-p95 and head-lag
objectives over the slot-sampled rings, plus the scoped graftscope
capture) alongside the correctness outcome.
"Didn't crash and eventually agreed" is not a pass; "stayed inside the
envelope while degraded and recovered the invariants afterwards" is.

Every scenario is a pure function of its seed: the fault schedule comes
from ``FaultInjector(seed)``'s RNG on a logical tick clock, and the spam
in the flood scenario is generated from the same seed.

Run one:    python -m lighthouse_tpu.testing.simulator \
                --scenario partition_heal --seed 7
List:       python -m lighthouse_tpu.testing.simulator --scenario list
"""
from __future__ import annotations

import random
import shutil
import tempfile
import time
from dataclasses import dataclass, field

from ..api.metrics import counter_value
from ..network.faults import FaultInjector, PeerBehavior
from ..network.sync import range_sync as range_sync_mod
from ..obs import doctor as flight_doctor
from ..obs import graftwatch, timeseries
from ..obs.capture import ScenarioTrace, scenario_capture
from ..specs import minimal_spec
from ..ssz import htr
from ..validator_client.byzantine import ByzantineValidatorClient
from .simulator import CheckResult, LocalNetwork

#: wall-clock p95 envelope for one gossip block through the full
#: verify->import pipeline under fault load (generous: CI boxes are slow,
#: and the assertion exists to catch order-of-magnitude regressions like
#: a lock convoy or a state-replay storm, not 10% noise)
PIPELINE_P95_MS = 5000.0


@dataclass
class ScenarioResult:
    name: str
    seed: int
    checks: list[CheckResult] = field(default_factory=list)
    trace: ScenarioTrace | None = None
    dump_path: str | None = None        # flight dump, if one was written
    diagnosis: str | None = None        # rendered doctor report over it

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def render(self) -> str:
        lines = [f"scenario {self.name} (seed {self.seed}): "
                 f"{'PASS' if self.ok else 'FAIL'}"]
        for c in self.checks:
            lines.append(f"  [{'PASS' if c.ok else 'FAIL'}] "
                         f"{c.name}: {c.detail}")
        if self.trace is not None and self.trace.spans:
            lines.append(self.trace.table())
        return "\n".join(lines)


_REGISTRY: dict[str, object] = {}
#: scenarios too long for tier-1; tests put these behind the slow marker
SLOW_SCENARIOS = frozenset({"long_nonfinality",
                            "checkpoint_sync_partition",
                            "sync_byzantine_pool",
                            "backfill_under_stall",
                            "checkpoint_backfill_replay"})


def scenario(name: str):
    def wrap(fn):
        _REGISTRY[name] = fn
        return fn
    return wrap


def scenario_names() -> list[str]:
    return sorted(_REGISTRY)


def run_scenario(name: str, seed: int = 0) -> ScenarioResult:
    if name not in _REGISTRY:
        raise KeyError(f"unknown scenario {name!r}; have "
                       f"{scenario_names()}")
    return _REGISTRY[name](seed)


# -- shared assertion helpers -------------------------------------------------

def _chk(result: ScenarioResult, name: str, ok: bool, detail: str) -> bool:
    result.checks.append(CheckResult(name, bool(ok), detail))
    return bool(ok)


def _envelope_checks(result: ScenarioResult, net: LocalNetwork,
                     trace: ScenarioTrace, max_head_lag: int = 1,
                     require_propagation: bool = False) -> None:
    """The degradation envelope every scenario ends on, evaluated by the
    graftwatch SLO engine — the same objectives a live node watches each
    slot: blocks kept flowing through the pipeline, the pipeline-p95
    objective never breached, and the head-lag objective is clean (any
    mid-scenario incident resolved) by scenario end.  With
    ``require_propagation`` the graftpath publish->import propagation
    histogram must have seen traffic and the propagation_p95 SLO must be
    clean (ISSUE 13)."""
    _chk(result, "pipeline_active", trace.count("block_pipeline") > 0,
         f"{trace.count('block_pipeline')} gossip block pipelines traced")
    status = graftwatch.get().engine.status()
    p95 = trace.p95_ms("block_pipeline")
    pipe = status["block_pipeline_p95"]
    _chk(result, "pipeline_p95",
         pipe["open_incident"] is None and p95 < PIPELINE_P95_MS,
         f"SLO clean ({pipe['last_detail']}); capture p95 {p95:.1f}ms "
         f"< {PIPELINE_P95_MS:.0f}ms")
    chain = net.live_nodes[0].harness.chain
    lag = chain.slot() - chain.head().head_state.slot
    head = status["head_lag"]
    _chk(result, "head_lag",
         head["open_incident"] is None and lag <= max_head_lag,
         f"SLO clean ({head['last_detail']}); live lag {lag} slots "
         f"(max {max_head_lag})")
    if require_propagation:
        _propagation_check(result, status)


def _propagation_check(result: ScenarioResult, status: dict) -> None:
    """Assert the graftpath publish->import propagation histogram saw
    traffic over the scenario and the propagation_p95 SLO ended clean."""
    import numpy as np
    sampler = timeseries.get_sampler()
    _slots, counts = sampler.series("block_propagation_seconds.count")
    total = float(np.nansum(counts)) if counts.size else 0.0
    p95_s = sampler.latest("block_propagation_seconds.p95")
    prop = status["propagation_p95"]
    _chk(result, "propagation_p95",
         prop["open_incident"] is None and total > 0,
         f"SLO clean ({prop['last_detail']}); {total:.0f} stitched "
         f"publish->import propagations sampled, last-slot p95 "
         f"{(p95_s or 0.0) * 1000.0:.1f}ms")


def _chain_blocks(chain, max_back: int = 128):
    """Head-chain blocks, newest first."""
    root = chain.head().head_block_root
    for _ in range(max_back):
        blk = chain.store.get_block(root)
        if blk is None:
            return
        yield blk
        if blk.message.slot == 0:
            return
        root = bytes(blk.message.parent_root)


def _head_ancestors(chain, max_back: int = 256) -> set[bytes]:
    out = set()
    root = chain.head().head_block_root
    for _ in range(max_back):
        out.add(root)
        blk = chain.store.get_block(root)
        if blk is None or blk.message.slot == 0:
            return out
        root = bytes(blk.message.parent_root)
    return out


def _fork_slot(chain_a, chain_b) -> int:
    """Slot of the last block both heads descend from."""
    seen = _head_ancestors(chain_a)
    root = chain_b.head().head_block_root
    for _ in range(256):
        if root in seen:
            blk = chain_b.store.get_block(root)
            return int(blk.message.slot) if blk is not None else 0
        blk = chain_b.store.get_block(root)
        if blk is None:
            return 0
        root = bytes(blk.message.parent_root)
    return 0


def _wait_statuses(node, node_ids, timeout: float = 8.0) -> bool:
    """Block until `node` holds a STATUS for every peer in node_ids —
    the connect-time exchange runs on background threads."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        infos = node.network.peers.peers
        if all(infos.get(n) is not None and infos[n].status is not None
               for n in node_ids):
            return True
        time.sleep(0.02)
    return False


# -- 1. slashable equivocation ------------------------------------------------

@scenario("equivocation")
def scenario_equivocation(seed: int = 0) -> ScenarioResult:
    """A byzantine VC double-proposes for one epoch, then double-votes
    for two slots.  The honest pipeline must reject the equivocations
    from gossip, the slasher must mint records carrying BOTH signed
    messages, and the resulting slashing operations must reach a block
    and flip validators.slashed."""
    result = ScenarioResult("equivocation", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    net = LocalNetwork(spec, 2, 32, with_slasher=True)
    try:
        byz = ByzantineValidatorClient(net.nodes[1].vc,
                                       mode="double_propose")
        net.nodes[1].vc = byz
        with scenario_capture() as trace:
            net.run_slots(spe)               # double proposals
            byz.mode = "double_vote"
            net.run_slots(2)                 # a couple of double votes
            byz.mode = "honest"
            net.run_slots(2 * spe)           # recovery: slashings land
        result.trace = trace
        _chk(result, "equivocations_published", byz.equivocations > 0,
             f"{byz.equivocations} second messages published")
        records = net.nodes[1].slasher.slashings
        prop = [r for r in records
                if r.kind == "double" and hasattr(r.attestation_1,
                                                  "message")]
        att = [r for r in records
               if r.kind == "double" and hasattr(r.attestation_1,
                                                 "attesting_indices")]
        _chk(result, "proposer_records", len(prop) > 0,
             f"{len(prop)} double-proposal records (both headers "
             "attached)")
        _chk(result, "attester_records", len(att) > 0,
             f"{len(att)} double-vote records (both attestations "
             "attached)")
        # the slashings must have been packed into canonical blocks
        chain = net.nodes[0].harness.chain
        packed_prop = packed_att = 0
        for blk in _chain_blocks(chain):
            packed_prop += len(blk.message.body.proposer_slashings)
            packed_att += len(blk.message.body.attester_slashings)
        _chk(result, "slashings_in_blocks",
             packed_prop > 0 and packed_att > 0,
             f"{packed_prop} proposer + {packed_att} attester slashings "
             "on the canonical chain")
        slashed = int(chain.head().head_state.validators.slashed.sum())
        _chk(result, "validators_slashed", slashed > 0,
             f"{slashed} validators slashed in the head state")
        heads = {n.harness.chain.head().head_block_root
                 for n in net.live_nodes}
        _chk(result, "converged", len(heads) == 1,
             f"{len(heads)} distinct heads after recovery")
        _envelope_checks(result, net, trace)
    finally:
        net.stop()
    return result


# -- 2. invalid-signature gossip flood ----------------------------------------

@scenario("signature_flood")
def scenario_signature_flood(seed: int = 0) -> ScenarioResult:
    """One node floods the attestation subnets with structurally valid,
    wrongly-signed attestations.  The victim runs batched gossip
    verification behind the priority processor with a lowered
    attestation queue cap: the batch verifier must take the per-item
    fallback split, the queue must shed load (counter + high-water), and
    honest block flow must stay inside the envelope.

    Doubles as the serving-tier load test (ISSUE 12): while the flood
    runs, a VC-fleet-shaped read load (duties + attestation_data every
    slot) hammers the victim's API through the serving tier — the tier
    must coalesce/cache the reads and the ``serving_p95`` SLO must be
    clean at scenario end."""
    result = ScenarioResult("signature_flood", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    rng = random.Random(seed)
    net = LocalNetwork(spec, 2, 32, use_processor=True,
                       batch_gossip_verification=True)
    try:
        from ..beacon_processor import WorkType
        from ..containers import get_types
        from ..state_transition.helpers import get_beacon_committee
        victim = net.nodes[0]
        proc = victim.network.processor
        CAP = 32
        proc.caps[WorkType.GOSSIP_ATTESTATION] = CAP
        # peer scoring would (correctly) ban the flooding peer after its
        # first garbage batch — on mainnet the attacker just reconnects
        # from the next Sybil identity, so model that by disabling the
        # cut-off and asserting the ban-worthy downscore happened instead
        victim.network.peers.BAN_THRESHOLD = float("-inf")
        # fake-BLS verification is free; restore a mainnet-shaped cost
        # (~1ms/signature) on the victim so the flood actually pressures
        # the queue the way real BLS would
        chain0 = victim.harness.chain
        real_batch = chain0.batch_verify_unaggregated_attestations_for_gossip

        def costed_batch(pairs):
            time.sleep(0.001 * len(pairs))
            return real_batch(pairs)

        chain0.batch_verify_unaggregated_attestations_for_gossip = \
            costed_batch
        T = get_types(spec.preset)
        net.run_slots(spe)                   # honest warm-up
        drop0 = counter_value("beacon_processor_work_dropped_total")
        fb0 = counter_value("beacon_batch_verify_fallback_total")
        flooded = 0
        # the victim also serves a VC fleet while under flood: route the
        # per-slot hot-path reads through the serving tier (keep a strong
        # ref — the graftwatch registry is weak)
        from ..api.serving import ServingTier
        serving = ServingTier(victim.backend)

        def flood(slot: int) -> None:
            # structurally valid for the victim's inline checks; only
            # the (deferred) signature is garbage — so every one of
            # these rides the batch queue to the verifier
            nonlocal flooded
            src = net.nodes[1]
            state = src.harness.chain.head().head_state
            data = src.backend.attestation_data(slot, 0)
            committee = get_beacon_committee(state, slot, 0)
            for _ in range(300):
                pos = rng.randrange(len(committee))
                bits = [i == pos for i in range(len(committee))]
                # leading 0xff = invalid under every backend (poison
                # byte on fake, non-canonical G2 on real); random tail
                # keeps every message id distinct so gossip dedup
                # doesn't thin the flood
                att = T.Attestation(
                    aggregation_bits=bits, data=data,
                    signature=b"\xff" + bytes(rng.getrandbits(8)
                                              for _ in range(95)))
                src.network.publish_attestation(att, subnet=0)
                flooded += 1
            # the VC fleet's reads for this slot: identical per-slot
            # requests the tier should collapse to one computation each
            for _ in range(40):
                serving.proposer_duties(slot // spe)
                serving.attestation_data(slot, 0)

        with scenario_capture() as trace:
            net.run_slots(3, mid_slot=flood)
            proc.wait_idle()
            net.run_slots(spe - 3)           # drain + recover
        result.trace = trace
        fallback = counter_value("beacon_batch_verify_fallback_total") - fb0
        dropped = counter_value("beacon_processor_work_dropped_total") \
            - drop0
        _chk(result, "flood_sent", flooded >= 900,
             f"{flooded} invalid attestations flooded")
        _chk(result, "batch_fallback_split", fallback > 0,
             f"batch verifier split into per-item retries {fallback:.0f} "
             "times")
        _chk(result, "load_shed", dropped > 0 and proc.dropped > 0,
             f"{dropped:.0f} work items shed at the cap "
             f"(processor.dropped={proc.dropped})")
        shed_incs = graftwatch.get().engine.incidents_for(
            "processor_shedding")
        _chk(result, "slo_shedding_incident", len(shed_incs) > 0,
             f"flood tripped the processor_shedding SLO "
             f"{len(shed_incs)} time(s), first at slot "
             f"{shed_incs[0].opened_slot if shed_incs else '-'}")
        _chk(result, "queue_high_water", proc.high_water >= CAP,
             f"queue high-water {proc.high_water} >= cap {CAP}")
        ssnap = serving.snapshot()
        _chk(result, "serving_coalesced",
             ssnap["requests"] >= 200
             and (ssnap["cache_hits"] + ssnap["coalesced"]) > 0,
             f"{ssnap['requests']} VC reads served, "
             f"{ssnap['cache_hits']} cache hits + "
             f"{ssnap['coalesced']} coalesced (hit ratio "
             f"{(ssnap['cache_hit_ratio'] or 0.0):.2f})")
        sp = graftwatch.get().engine.status()["serving_p95"]
        _chk(result, "serving_p95", sp["open_incident"] is None,
             f"serving-tier p95 SLO clean at scenario end "
             f"({sp['last_detail']})")
        flooder_score = victim.network.peers.score(
            net.nodes[1].network.transport.node_id)
        _chk(result, "flooder_downscored", flooder_score < -20.0,
             f"flooding peer's score {flooder_score:.1f} crossed the "
             "default ban threshold (-20)")
        heads = {n.harness.chain.head().head_block_root
                 for n in net.live_nodes}
        _chk(result, "converged", len(heads) == 1,
             f"{len(heads)} distinct heads after the flood")
        _envelope_checks(result, net, trace, require_propagation=True)
    finally:
        net.stop()
    return result


# -- 3. partition and heal ----------------------------------------------------

@scenario("partition_heal")
def scenario_partition_heal(seed: int = 0) -> ScenarioResult:
    """Split a 4-node mesh 2|2 for two epochs, then heal.  Both sides
    must keep producing on their fork; after healing every node must
    re-org onto one winner, with the measured re-org depth bounded by
    the partition length and convergence inside a wall-clock budget."""
    result = ScenarioResult("partition_heal", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    net = LocalNetwork(spec, 4, 32, topology="mesh", injector=injector)
    # the partition must surface through graftwatch, not just the
    # hand-rolled fork checks: auto-dump a flight recording the moment
    # an incident opens, assert the head-lag incident lifecycle, and
    # round-trip the dump through the offline doctor
    watch = graftwatch.get()
    dump_dir = tempfile.mkdtemp(prefix="graftwatch_scn_")
    watch.configure(auto_dump=True, dump_dir=dump_dir)
    try:
        net.run_slots(spe)                   # healthy baseline
        part_start = int(net.nodes[0].harness.chain.slot())
        net.partition([0, 1], [2, 3])
        partition_slots = 2 * spe
        with scenario_capture() as trace:
            net.run_slots(partition_slots)
            chain_a = net.nodes[0].harness.chain
            chain_b = net.nodes[2].harness.chain
            head_a = chain_a.head()
            head_b = chain_b.head()
            _chk(result, "links_severed", injector.links_severed > 0,
                 f"{injector.links_severed} cross-partition connections "
                 "closed")
            _chk(result, "sides_diverged",
                 head_a.head_block_root != head_b.head_block_root,
                 "partition sides built distinct forks")
            _chk(result, "both_sides_advanced",
                 head_a.head_state.slot > spe
                 and head_b.head_state.slot > spe,
                 f"side heads at slots {head_a.head_state.slot} / "
                 f"{head_b.head_state.slot}")
            fork_slot = _fork_slot(chain_a, chain_b)
            t0 = time.monotonic()
            net.heal()
            net.run_slots(spe)
            converged = net._wait_convergence(timeout=20.0)
            t_heal = time.monotonic() - t0
        result.trace = trace
        _chk(result, "reconverged", converged,
             f"all nodes on one head {t_heal:.1f}s after heal")
        _chk(result, "convergence_time", t_heal < 60.0,
             f"{t_heal:.1f}s < 60s")
        # the losing side's fork is fully re-orged out; its depth is
        # bounded by what the partition could have built
        final = net.nodes[0].harness.chain.head().head_block_root
        loser = (head_b if final in _head_ancestors(chain_a)
                 or head_a.head_block_root == final else head_a)
        depth = int(loser.head_state.slot) - fork_slot
        _chk(result, "reorg_depth_bounded",
             0 < depth <= partition_slots,
             f"re-org depth {depth} slots (fork at {fork_slot}, "
             f"partition lasted {partition_slots})")
        # SLO-engine view of the same event: the partition opened a
        # head-lag incident, and the heal let every incident resolve
        incs = [i for i in watch.engine.incidents_for("head_lag")
                if i.opened_slot > part_start]
        _chk(result, "slo_incident_opened", len(incs) > 0,
             f"head-lag incidents opened at slots "
             f"{[i.opened_slot for i in incs]} "
             f"(partition began after slot {part_start})")
        _chk(result, "slo_incident_resolved",
             bool(incs) and all(not i.open for i in incs)
             and not watch.engine.open_incidents(),
             f"resolved at slots {[i.resolved_slot for i in incs]}; "
             "no incident still open after heal")
        # incident-open wrote a flight dump; the offline doctor must
        # turn it into a non-empty correlated diagnosis
        result.dump_path = watch.recorder.last_path
        dumped = result.dump_path is not None
        _chk(result, "flight_dump_written", dumped,
             f"auto-dump wrote {result.dump_path}")
        if dumped:
            diag = flight_doctor.diagnose(
                flight_doctor.load(result.dump_path))
            result.diagnosis = flight_doctor.render(diag)
            lag_diags = [d for d in diag["incidents"]
                         if d["slo"] == "head_lag" and d["correlations"]]
            _chk(result, "doctor_diagnosis", len(lag_diags) > 0,
                 f"doctor correlated {len(lag_diags)} head-lag "
                 f"incident(s) with "
                 f"{sum(len(d['correlations']) for d in lag_diags)} "
                 "co-occurring signals")
        # graftpath: blocks still crossed the (healed) mesh under
        # observation, and the propagation objective ended clean — the
        # second scenario envelope asserting through propagation_p95
        _propagation_check(result, watch.engine.status())
    finally:
        watch.configure(auto_dump=False)
        watch.recorder.dump_dir = None
        shutil.rmtree(dump_dir, ignore_errors=True)
        net.stop()
    return result


# -- 4. long non-finality -----------------------------------------------------

@scenario("long_nonfinality")
def scenario_long_nonfinality(seed: int = 0) -> ScenarioResult:
    """Half the stake goes vote-silent (still proposing) for six epochs:
    finality must stall, the head must keep tracking the slot clock, and
    proto-array growth must stay bounded.  When the silent stake returns,
    finality must resume and maybe_prune must reclaim the fork-choice
    array."""
    result = ScenarioResult("long_nonfinality", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    net = LocalNetwork(spec, 2, 32)
    try:
        net.run_slots(4 * spe)               # establish finality
        chain = net.nodes[0].harness.chain
        fin0 = chain.finalized_checkpoint()[0]
        _chk(result, "finality_established", fin0 >= 1,
             f"finalized epoch {fin0} before the outage")
        byz = ByzantineValidatorClient(net.nodes[1].vc, mode="silent")
        net.nodes[1].vc = byz
        stall_epochs = 6
        with scenario_capture() as trace:
            net.run_slots(stall_epochs * spe)
        result.trace = trace
        fin_stalled = chain.finalized_checkpoint()[0]
        _chk(result, "finality_stalled", fin_stalled <= fin0 + 1,
             f"finalized epoch {fin_stalled} after {stall_epochs} silent "
             f"epochs (was {fin0})")
        nodes_peak = len(chain.fork_choice.proto_array.nodes)
        slots_elapsed = (4 + stall_epochs) * spe
        _chk(result, "proto_array_bounded",
             nodes_peak <= slots_elapsed + 16,
             f"proto-array holds {nodes_peak} nodes <= "
             f"{slots_elapsed + 16}")
        _envelope_checks(result, net, trace)
        # recovery: votes return, finality advances, prune reclaims.
        # The production prune_threshold (256) exists to amortize index
        # rewrites on mainnet-sized arrays; drop it so this ~100-node run
        # exercises the reclaim path itself.
        for n in net.nodes:
            n.harness.chain.fork_choice.proto_array.prune_threshold = 0
        byz.mode = "honest"
        net.run_slots(4 * spe)
        fin_rec = chain.finalized_checkpoint()[0]
        _chk(result, "finality_recovered", fin_rec > fin_stalled,
             f"finalized epoch {fin_rec} > {fin_stalled}")
        nodes_after = len(chain.fork_choice.proto_array.nodes)
        _chk(result, "proto_array_pruned", nodes_after < nodes_peak,
             f"maybe_prune reclaimed {nodes_peak - nodes_after} "
             f"proto-array nodes ({nodes_peak} -> {nodes_after})")
    finally:
        net.stop()
    return result


# -- 5. checkpoint sync into a partition --------------------------------------

@scenario("checkpoint_sync_partition")
def scenario_checkpoint_sync_partition(seed: int = 0) -> ScenarioResult:
    """A fresh node weak-subjectivity-syncs against a node that, unknown
    to it, sits on the minority side of a partition.  It must follow the
    minority fork (that is all it can see), then re-org onto the
    majority chain once the partition heals — checkpoint sync must not
    pin it to the minority."""
    result = ScenarioResult("checkpoint_sync_partition", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    net = LocalNetwork(spec, 3, 48, topology="mesh", injector=injector)
    try:
        net.run_slots(4 * spe)               # finality for the anchor
        fin0 = net.nodes[2].harness.chain.finalized_checkpoint()[0]
        _chk(result, "anchor_finalized", fin0 >= 2,
             f"anchor node finalized epoch {fin0}")
        net.partition([0, 1], [2])
        net.run_slots(spe)                   # sides diverge
        with scenario_capture() as trace:
            i3 = net.add_node(anchor_from=2, dial=[2], group=1)
            net.run_slots(spe)
            chain3 = net.nodes[i3].harness.chain
            chain_minor = net.nodes[2].harness.chain
            chain_major = net.nodes[0].harness.chain
            _chk(result, "synced_past_anchor",
                 chain3.head().head_state.slot >
                 fin0 * spe,
                 f"synced node head at slot "
                 f"{chain3.head().head_state.slot}")
            _chk(result, "follows_minority",
                 chain3.head().head_block_root ==
                 chain_minor.head().head_block_root,
                 "synced node sits on the minority head")
            _chk(result, "minority_is_fork",
                 chain3.head().head_block_root !=
                 chain_major.head().head_block_root,
                 "minority head differs from the majority head")
            net.heal()
            net.run_slots(2 * spe)
            converged = net._wait_convergence(timeout=20.0)
        result.trace = trace
        _chk(result, "healed_converged", converged,
             "all four nodes agree after heal")
        _chk(result, "reorged_to_majority",
             chain3.head().head_block_root ==
             chain_major.head().head_block_root,
             "synced node re-orged onto the majority chain")
        _envelope_checks(result, net, trace, max_head_lag=2)
    finally:
        net.stop()
    return result


# -- 6. byzantine range-sync pool ---------------------------------------------

@scenario("sync_byzantine_pool")
def scenario_sync_byzantine_pool(seed: int = 0) -> ScenarioResult:
    """A fresh node range-syncs with 3 of its 5 serving peers byzantine
    (one each stall / junk / truncate).  Per-request deadlines, the
    download-time batch validator and precise truncation blame must
    penalize each adversary below the ban threshold WITHOUT a single
    rejected batch reaching process_chain_segment and without any
    global pump stall, and the sync must then complete from the honest
    peers that the failed byzantine pool must not be able to poison."""
    result = ScenarioResult("sync_byzantine_pool", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    # behaviors are label-keyed, so the victim n5's links can be rigged
    # before it exists
    injector.set_behavior("n0", "n5",
                          PeerBehavior("stall", stall_secs=6.0))
    injector.set_behavior("n1", "n5", PeerBehavior("junk"))
    injector.set_behavior("n2", "n5",
                          PeerBehavior("truncate", keep_fraction=0.5))
    net = LocalNetwork(spec, 5, 40, topology="mesh", injector=injector)
    watch = graftwatch.get()
    rejects0 = counter_value("sync_batch_validation_rejects_total")
    expired0 = counter_value("sync_request_deadline_expired_total")
    gstall0 = counter_value("sync_pump_global_stall_total")
    restore = []
    try:
        net.run_slots(3 * spe)               # history worth syncing
        vi = net.add_fresh_node(dial=[])     # knobs first, dial after
        victim = net.nodes[vi]
        sync = victim.network.sync
        peers = victim.network.peers
        chain5 = victim.harness.chain
        # scenario-speed knobs: tight deadlines and near-zero backoff so
        # the stall adversary burns 0.75s per hit instead of 20s, small
        # batches so every adversary serves several times.  Quarantine is
        # disabled (scenario 7 exercises it): here the SCORE ledger alone
        # must cross the ban line, which is lowered to what a pool-scoped
        # penalty run can reach before the pool excludes negative peers.
        sync.ctx.request_timeout = 0.75
        bo = sync.ctx.backoff
        bo.BASE_DELAY = 0.05
        bo.MAX_DELAY = 0.2
        bo.QUARANTINE_AFTER = 10 ** 6
        sync.range.batch_slots = 2
        peers.BAN_THRESHOLD = -8.0
        # a banned peer disconnects and its PeerInfo is dropped, after
        # which score() reads 0.0 — mirror the ledger here
        tally: dict[str, float] = {}
        real_report = peers.report

        def tallied_report(node_id, event):
            tally[node_id] = (tally.get(node_id, 0.0)
                              + peers.SCORES.get(event, 0.0))
            real_report(node_id, event)

        peers.report = tallied_report
        restore.append(lambda: setattr(peers, "report", real_report))
        # reject spy: the exact list object a validation reject discarded
        # must never reach process_chain_segment (the junk adversary
        # serves REAL blocks from the wrong range, so root-based matching
        # would false-positive on their later honest arrival)
        real_validate = range_sync_mod.validate_range_batch
        rejected_lists: list = []

        def spying_validate(blocks, start, count, **kw):
            res = real_validate(blocks, start, count, **kw)
            if not res.ok and res.reason != "continuity" and blocks:
                rejected_lists.append(blocks)
            return res

        range_sync_mod.validate_range_batch = spying_validate
        restore.append(lambda: setattr(
            range_sync_mod, "validate_range_batch", real_validate))
        real_process = chain5.process_chain_segment
        leaked: list = []

        def guarded_process(blocks):
            if any(blocks is r for r in rejected_lists):
                leaked.append(len(blocks))
            return real_process(blocks)

        chain5.process_chain_segment = guarded_process
        nid = [net.nodes[j].network.transport.node_id for j in range(5)]
        with scenario_capture() as trace:
            # phase A: only the three byzantine peers serve
            for j in (0, 1, 2):
                victim.network.dial("127.0.0.1",
                                    net.nodes[j].network.port)
            _wait_statuses(victim, nid[:3])
            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline:
                sync.maybe_sync()
                if all(tally.get(n, 0.0) < peers.BAN_THRESHOLD
                       for n in nid[:3]):
                    break
                time.sleep(0.05)
            # phase B: honest peers arrive; the targets the byzantine
            # pool failed must still be syncable from them
            for j in (3, 4):
                victim.network.dial("127.0.0.1",
                                    net.nodes[j].network.port)
            _wait_statuses(victim, nid[3:5])
            target = net.nodes[3].harness.chain.head().head_block_root
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                sync.maybe_sync()
                if chain5.head().head_block_root == target:
                    break
                time.sleep(0.05)
            net.run_slots(spe)               # envelope traffic
        result.trace = trace
        _chk(result, "synced_from_honest",
             chain5.head().head_block_root ==
             net.nodes[3].harness.chain.head().head_block_root,
             f"victim head at slot {chain5.head().head_state.slot} "
             "matches the honest peers'")
        for j, kind in ((0, "stall"), (1, "junk"), (2, "truncate")):
            _chk(result, f"{kind}_peer_banned",
                 tally.get(nid[j], 0.0) < peers.BAN_THRESHOLD,
                 f"n{j} ({kind}) penalty ledger "
                 f"{tally.get(nid[j], 0.0):.1f} < ban threshold "
                 f"{peers.BAN_THRESHOLD}")
        rejects = counter_value("sync_batch_validation_rejects_total") \
            - rejects0
        _chk(result, "batches_rejected_at_download", rejects > 0,
             f"{rejects:.0f} batches rejected by download-time "
             "validation")
        _chk(result, "rejects_never_processed",
             len(rejected_lists) > 0 and not leaked,
             f"{len(rejected_lists)} rejected batches, "
             f"{len(leaked)} reached process_chain_segment")
        expired = counter_value("sync_request_deadline_expired_total") \
            - expired0
        _chk(result, "per_request_deadlines_fired", expired > 0,
             f"{expired:.0f} per-request deadline expiries (stall peer)")
        gstall = counter_value("sync_pump_global_stall_total") - gstall0
        _chk(result, "zero_global_stalls", gstall == 0,
             f"{gstall:.0f} global pump stalls (per-request deadlines "
             "replace them)")
        sp = watch.engine.status()["sync_progress"]
        sp_incs = watch.engine.incidents_for("sync_progress")
        _chk(result, "slo_sync_progress_clean",
             sp["open_incident"] is None
             and all(not i.open for i in sp_incs),
             f"sync_progress SLO open_incident={sp['open_incident']}, "
             f"{len(sp_incs)} incident(s) all resolved")
        _envelope_checks(result, net, trace, max_head_lag=2)
    finally:
        for undo in restore:
            undo()
        net.stop()
    return result


# -- 7. backfill under stall --------------------------------------------------

@scenario("backfill_under_stall")
def scenario_backfill_under_stall(seed: int = 0) -> ScenarioResult:
    """A checkpoint-synced node backfills its pre-anchor history while
    one serving peer stalls every by-range request and another truncates
    its responses.  The per-request deadline must fail the stalled
    requests individually, consecutive failures must QUARANTINE the
    stall peer, and backfill must still walk the anchor to genesis with
    a complete block history."""
    result = ScenarioResult("backfill_under_stall", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    injector.set_behavior("n1", "n3",
                          PeerBehavior("stall", stall_secs=5.0))
    injector.set_behavior("n2", "n3",
                          PeerBehavior("truncate", keep_fraction=0.5))
    net = LocalNetwork(spec, 3, 48, topology="mesh", injector=injector)
    quar0 = counter_value("sync_peer_quarantined_total")
    expired0 = counter_value("sync_request_deadline_expired_total")
    gstall0 = counter_value("sync_pump_global_stall_total")
    try:
        net.run_slots(4 * spe)               # finality for the anchor
        fin0 = net.nodes[0].harness.chain.finalized_checkpoint()[0]
        _chk(result, "anchor_finalized", fin0 >= 2,
             f"anchor node finalized epoch {fin0}")
        i3 = net.add_node(anchor_from=0, dial=[])
        node3 = net.nodes[i3]
        sync3 = node3.network.sync
        chain3 = node3.harness.chain
        # peer-table entries can be popped by benign duplicate-dial
        # teardowns, so "never banned" is asserted on the on_ban
        # callback, not on the entry's survival
        bans: list[str] = []
        real_on_ban = node3.network.peers.on_ban

        def recording_on_ban(node_id):
            bans.append(node_id)
            real_on_ban(node_id)

        node3.network.peers.on_ban = recording_on_ban
        sync3.ctx.request_timeout = 0.75
        bo = sync3.ctx.backoff
        bo.BASE_DELAY = 0.05
        bo.MAX_DELAY = 0.2
        bo.QUARANTINE_AFTER = 2              # quarantine ON and quick
        nid = [net.nodes[j].network.transport.node_id for j in range(3)]
        for j in range(3):
            node3.network.dial("127.0.0.1", net.nodes[j].network.port)
        _wait_statuses(node3, nid)
        anchor_start = chain3.store.backfill_anchor()
        with scenario_capture() as trace:
            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline:
                sync3.backfill(batch_slots=4)
                anchor = chain3.store.backfill_anchor()
                if anchor is None or anchor[0] == 0:
                    break
                time.sleep(0.05)
            net.run_slots(spe)               # envelope traffic
        result.trace = trace
        anchor = chain3.store.backfill_anchor()
        _chk(result, "backfill_complete",
             anchor is None or anchor[0] == 0,
             f"backfill anchor {anchor} (started at {anchor_start})")
        # every canonical block below the original anchor must now be in
        # the synced node's store
        checked = missing = 0
        for blk in _chain_blocks(net.nodes[0].harness.chain):
            if (anchor_start is not None
                    and blk.message.slot < anchor_start[0]):
                checked += 1
                if chain3.store.get_block(htr(blk.message)) is None:
                    missing += 1
        _chk(result, "history_complete", checked > 0 and missing == 0,
             f"{checked} pre-anchor canonical blocks checked, "
             f"{missing} missing")
        quarantined = counter_value("sync_peer_quarantined_total") - quar0
        _chk(result, "stall_peer_quarantined", quarantined >= 1,
             f"{quarantined:.0f} peer quarantines (stall peer cut off "
             "after consecutive deadline failures)")
        expired = counter_value("sync_request_deadline_expired_total") \
            - expired0
        _chk(result, "per_request_deadlines_fired", expired > 0,
             f"{expired:.0f} per-request deadline expiries")
        gstall = counter_value("sync_pump_global_stall_total") - gstall0
        _chk(result, "zero_global_stalls", gstall == 0,
             f"{gstall:.0f} global pump stalls")
        served = injector.behaviors_served
        _chk(result, "adversaries_served",
             served.get("stall", 0) > 0 and served.get("truncate", 0) > 0,
             f"byzantine serves: {dict(served)}")
        info0 = node3.network.peers.peers.get(nid[0])
        _chk(result, "honest_peer_retained",
             nid[0] not in bans
             and (info0 is None or not info0.banned),
             "the honest serving peer was never banned")
        _envelope_checks(result, net, trace, max_head_lag=2)
    finally:
        net.stop()
    return result


# -- 8. lying STATUS chain ----------------------------------------------------

@scenario("lying_status_chain")
def scenario_lying_status_chain(seed: int = 0) -> ScenarioResult:
    """One peer answers STATUS with a fabricated far-ahead head and
    finalized checkpoint.  Range sync forms a chain toward the fake
    target, but every batch comes back empty: the consecutive-empty
    fail-fast must abandon the chain after a bounded number of batches
    (not walk 2000 fake slots), charge the liar `empty_batch`, and the
    per-peer failed-target memory must keep the same lie from re-forming
    the chain — all without disturbing the honest network."""
    result = ScenarioResult("lying_status_chain", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    lie = {"head_slot": 256 * spe, "head_root": "ab" * 32,
           "finalized_epoch": 254, "finalized_root": "cd" * 32}
    injector.set_behavior("n2", "n0",
                          PeerBehavior("lying_status", status_lie=lie))
    net = LocalNetwork(spec, 3, 32, topology="mesh", injector=injector)
    watch = graftwatch.get()
    dl0 = counter_value("sync_range_batches_downloaded_total")
    eb0 = counter_value("sync_penalties_total_empty_batch")
    try:
        victim = net.nodes[0]
        nid2 = net.nodes[2].network.transport.node_id
        # the mesh dials both directions at once, so the victim can hold
        # two connections to the liar; when the duplicate is torn down
        # the PeerInfo entry goes with it.  That is a benign disconnect,
        # not a ban — so the ban oracle is the on_ban callback itself,
        # not the survival of the peer-table entry.
        bans: list[str] = []
        real_on_ban = victim.network.peers.on_ban

        def recording_on_ban(node_id):
            bans.append(node_id)
            real_on_ban(node_id)

        victim.network.peers.on_ban = recording_on_ban
        with scenario_capture() as trace:
            net.run_slots(spe)
            # the liar's own outbound STATUS (served honestly BY the
            # victim's transport) races the lie on the victim's peer
            # table; force one synchronous exchange so the fake-ahead
            # STATUS deterministically had the last word at least once.
            # The mesh dial itself can still be mid-handshake right
            # after warmup, so wait for the connection (re-dialing if
            # it never lands) before forcing the exchange.
            def _liar_conn():
                return next((p for p in
                             victim.network.transport.peers.values()
                             if p.node_id == nid2), None)
            deadline = time.monotonic() + 10.0
            peer2 = _liar_conn()
            while peer2 is None and time.monotonic() < deadline:
                time.sleep(0.05)
                peer2 = _liar_conn()
            if peer2 is None:
                victim.network.dial("127.0.0.1",
                                    net.nodes[2].network.port)
                while peer2 is None and time.monotonic() < deadline + 5.0:
                    time.sleep(0.05)
                    peer2 = _liar_conn()
            victim.network._status_exchange(peer2)
            net.run_slots(2 * spe)
        result.trace = trace
        served = injector.behaviors_served.get("lying_status", 0)
        _chk(result, "lie_served", served > 0,
             f"{served} fabricated STATUS responses served")
        empty_pen = counter_value("sync_penalties_total_empty_batch") \
            - eb0
        _chk(result, "liar_charged_empty_batch", empty_pen > 0,
             f"{empty_pen:.0f} empty_batch penalties for the fake "
             "target's pool")
        downloaded = counter_value("sync_range_batches_downloaded_total") \
            - dl0
        _chk(result, "fail_fast_bounded", 0 < downloaded < 40,
             f"{downloaded:.0f} batches downloaded before the "
             "consecutive-empty fail-fast (naive walk to the fake head "
             f"would be ~{(256 * spe) // (2 * spe)})")
        # the liar keeps gossiping honestly (it is a real validator
        # node), so its NET score stays positive — the precise outcome
        # is attribution: both fabricated targets are remembered as
        # failed *from this peer* and cannot re-form a chain
        fake_roots = {bytes.fromhex("ab" * 32), bytes.fromhex("cd" * 32)}
        blocked = {k for k, pool in
                   victim.network.sync.range.failed_from.items()
                   if k[1] in fake_roots and nid2 in pool}
        _chk(result, "fake_targets_blocked_for_liar", len(blocked) > 0,
             f"{len(blocked)} fabricated target(s) in the per-peer "
             "failed-target memory, pinned on the liar")
        info2 = victim.network.peers.peers.get(nid2)
        state = ("still connected (score "
                 f"{victim.network.peers.score(nid2):.1f})"
                 if info2 is not None else
                 "duplicate connection torn down, never banned")
        _chk(result, "liar_not_banned",
             nid2 not in bans and (info2 is None or not info2.banned),
             f"liar {state}: a STATUS lie alone is penalized, "
             "not ban-worthy")
        heads = {n.harness.chain.head().head_block_root
                 for n in net.live_nodes}
        _chk(result, "converged", len(heads) == 1,
             f"{len(heads)} distinct heads — honest traffic undisturbed")
        sp = watch.engine.status()["sync_progress"]
        _chk(result, "slo_sync_progress_clean",
             sp["open_incident"] is None,
             f"sync_progress SLO clean ({sp['last_detail']})")
        _envelope_checks(result, net, trace)
    finally:
        net.stop()
    return result


# -- 9. checkpoint sync + graftflow replay catch-up ---------------------------

@scenario("checkpoint_backfill_replay")
def scenario_checkpoint_backfill_replay(seed: int = 0) -> ScenarioResult:
    """A checkpoint-synced node catches up to the live head through
    range sync — which now routes every segment through graftflow's
    epoch-pipelined replay engine — and then backfills its pre-anchor
    history through the engine's atomic batch path (ISSUE 14).  The
    pipelined path must actually run (epoch commits observable on the
    replay counters and the engine snapshot), converge bit-exactly on
    the network head, complete the pre-anchor history, and end with the
    ``replay_throughput`` SLO clean — a wedged pipeline stage must
    surface as an incident, not as silent non-progress."""
    result = ScenarioResult("checkpoint_backfill_replay", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    net = LocalNetwork(spec, 3, 48, topology="mesh", injector=injector)
    watch = graftwatch.get()
    blocks0 = counter_value("replay_blocks_committed_total")
    epochs0 = counter_value("replay_epochs_committed_total")
    try:
        net.run_slots(4 * spe)               # finality for the anchor
        fin0 = net.nodes[0].harness.chain.finalized_checkpoint()[0]
        _chk(result, "anchor_finalized", fin0 >= 2,
             f"anchor node finalized epoch {fin0}")
        i3 = net.add_node(anchor_from=0, dial=[])
        node3 = net.nodes[i3]
        sync3 = node3.network.sync
        chain3 = node3.harness.chain
        nid = [net.nodes[j].network.transport.node_id for j in range(3)]
        for j in range(3):
            node3.network.dial("127.0.0.1", net.nodes[j].network.port)
        _wait_statuses(node3, nid)
        anchor_start = chain3.store.backfill_anchor()
        with scenario_capture() as trace:
            # phase A: range-sync forward from the anchor to the head —
            # every accepted segment replays through the graftflow
            # pipeline behind process_segment
            target = net.nodes[0].harness.chain.head().head_block_root
            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline:
                sync3.maybe_sync()
                if chain3.head().head_block_root == target:
                    break
                time.sleep(0.05)
            # phase B: walk the pre-anchor history to genesis through
            # the engine's one-atomic-batch-per-response backfill path
            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline:
                sync3.backfill(batch_slots=spe)
                anchor = chain3.store.backfill_anchor()
                if anchor is None or anchor[0] == 0:
                    break
                time.sleep(0.05)
            net.run_slots(spe)               # envelope traffic
        result.trace = trace
        _chk(result, "caught_up_to_live_head",
             chain3.head().head_block_root ==
             net.nodes[0].harness.chain.head().head_block_root,
             f"synced node head at slot {chain3.head().head_state.slot} "
             "matches the network's")
        engine = chain3.replay_engine()
        snap = engine.snapshot()
        replayed = counter_value("replay_blocks_committed_total") - blocks0
        epochs = counter_value("replay_epochs_committed_total") - epochs0
        _chk(result, "segments_replayed_through_graftflow",
             snap["commit_seq"] >= 1 and replayed > 0,
             f"{replayed:.0f} blocks in {epochs:.0f} epoch commits "
             f"through the pipeline (engine commit_seq "
             f"{snap['commit_seq']})")
        last = snap["last_segment"]
        _chk(result, "stage_occupancy_observed",
             last is not None and set(last["occupancy"]) ==
             {"admission", "signature", "stf", "merkle", "commit"},
             "engine snapshot carries per-stage occupancy for the "
             "flight recorder")
        anchor = chain3.store.backfill_anchor()
        _chk(result, "backfill_complete",
             anchor is None or anchor[0] == 0,
             f"backfill anchor {anchor} (started at {anchor_start})")
        _chk(result, "backfill_batches_atomic",
             snap["backfill_batches"] >= 1,
             f"{snap['backfill_batches']} atomic backfill batches")
        checked = missing = 0
        for blk in _chain_blocks(net.nodes[0].harness.chain):
            if (anchor_start is not None
                    and blk.message.slot < anchor_start[0]):
                checked += 1
                if chain3.store.get_block(htr(blk.message)) is None:
                    missing += 1
        _chk(result, "history_complete", checked > 0 and missing == 0,
             f"{checked} pre-anchor canonical blocks checked, "
             f"{missing} missing")
        rt = watch.engine.status()["replay_throughput"]
        rt_incs = watch.engine.incidents_for("replay_throughput")
        _chk(result, "slo_replay_throughput_clean",
             rt["open_incident"] is None
             and all(not i.open for i in rt_incs),
             f"replay_throughput SLO open_incident="
             f"{rt['open_incident']}, {len(rt_incs)} incident(s) all "
             "resolved")
        _envelope_checks(result, net, trace, max_head_lag=2)
    finally:
        net.stop()
    return result
