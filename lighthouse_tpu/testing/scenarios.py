"""Adversarial mainnet scenarios over the fault-injecting LocalNetwork.

ISSUE 7 tentpole, ROADMAP item 4: each scenario drives production nodes
through a mainnet incident shape — long non-finality, partition + heal,
slashable equivocation, checkpoint sync into a partitioned network, an
invalid-signature gossip flood — and asserts a DEGRADATION ENVELOPE
evaluated by the graftwatch SLO engine (pipeline-p95 and head-lag
objectives over the slot-sampled rings, plus the scoped graftscope
capture) alongside the correctness outcome.
"Didn't crash and eventually agreed" is not a pass; "stayed inside the
envelope while degraded and recovered the invariants afterwards" is.

Every scenario is a pure function of its seed: the fault schedule comes
from ``FaultInjector(seed)``'s RNG on a logical tick clock, and the spam
in the flood scenario is generated from the same seed.

Run one:    python -m lighthouse_tpu.testing.simulator \
                --scenario partition_heal --seed 7
List:       python -m lighthouse_tpu.testing.simulator --scenario list
"""
from __future__ import annotations

import random
import shutil
import tempfile
import time
from dataclasses import dataclass, field

from ..api.metrics import counter_value
from ..network.faults import FaultInjector
from ..obs import doctor as flight_doctor
from ..obs import graftwatch
from ..obs.capture import ScenarioTrace, scenario_capture
from ..specs import minimal_spec
from ..validator_client.byzantine import ByzantineValidatorClient
from .simulator import CheckResult, LocalNetwork

#: wall-clock p95 envelope for one gossip block through the full
#: verify->import pipeline under fault load (generous: CI boxes are slow,
#: and the assertion exists to catch order-of-magnitude regressions like
#: a lock convoy or a state-replay storm, not 10% noise)
PIPELINE_P95_MS = 5000.0


@dataclass
class ScenarioResult:
    name: str
    seed: int
    checks: list[CheckResult] = field(default_factory=list)
    trace: ScenarioTrace | None = None
    dump_path: str | None = None        # flight dump, if one was written
    diagnosis: str | None = None        # rendered doctor report over it

    @property
    def ok(self) -> bool:
        return all(c.ok for c in self.checks)

    def render(self) -> str:
        lines = [f"scenario {self.name} (seed {self.seed}): "
                 f"{'PASS' if self.ok else 'FAIL'}"]
        for c in self.checks:
            lines.append(f"  [{'PASS' if c.ok else 'FAIL'}] "
                         f"{c.name}: {c.detail}")
        if self.trace is not None and self.trace.spans:
            lines.append(self.trace.table())
        return "\n".join(lines)


_REGISTRY: dict[str, object] = {}
#: scenarios too long for tier-1; tests put these behind the slow marker
SLOW_SCENARIOS = frozenset({"long_nonfinality",
                            "checkpoint_sync_partition"})


def scenario(name: str):
    def wrap(fn):
        _REGISTRY[name] = fn
        return fn
    return wrap


def scenario_names() -> list[str]:
    return sorted(_REGISTRY)


def run_scenario(name: str, seed: int = 0) -> ScenarioResult:
    if name not in _REGISTRY:
        raise KeyError(f"unknown scenario {name!r}; have "
                       f"{scenario_names()}")
    return _REGISTRY[name](seed)


# -- shared assertion helpers -------------------------------------------------

def _chk(result: ScenarioResult, name: str, ok: bool, detail: str) -> bool:
    result.checks.append(CheckResult(name, bool(ok), detail))
    return bool(ok)


def _envelope_checks(result: ScenarioResult, net: LocalNetwork,
                     trace: ScenarioTrace, max_head_lag: int = 1) -> None:
    """The degradation envelope every scenario ends on, evaluated by the
    graftwatch SLO engine — the same objectives a live node watches each
    slot: blocks kept flowing through the pipeline, the pipeline-p95
    objective never breached, and the head-lag objective is clean (any
    mid-scenario incident resolved) by scenario end."""
    _chk(result, "pipeline_active", trace.count("block_pipeline") > 0,
         f"{trace.count('block_pipeline')} gossip block pipelines traced")
    status = graftwatch.get().engine.status()
    p95 = trace.p95_ms("block_pipeline")
    pipe = status["block_pipeline_p95"]
    _chk(result, "pipeline_p95",
         pipe["open_incident"] is None and p95 < PIPELINE_P95_MS,
         f"SLO clean ({pipe['last_detail']}); capture p95 {p95:.1f}ms "
         f"< {PIPELINE_P95_MS:.0f}ms")
    chain = net.live_nodes[0].harness.chain
    lag = chain.slot() - chain.head().head_state.slot
    head = status["head_lag"]
    _chk(result, "head_lag",
         head["open_incident"] is None and lag <= max_head_lag,
         f"SLO clean ({head['last_detail']}); live lag {lag} slots "
         f"(max {max_head_lag})")


def _chain_blocks(chain, max_back: int = 128):
    """Head-chain blocks, newest first."""
    root = chain.head().head_block_root
    for _ in range(max_back):
        blk = chain.store.get_block(root)
        if blk is None:
            return
        yield blk
        if blk.message.slot == 0:
            return
        root = bytes(blk.message.parent_root)


def _head_ancestors(chain, max_back: int = 256) -> set[bytes]:
    out = set()
    root = chain.head().head_block_root
    for _ in range(max_back):
        out.add(root)
        blk = chain.store.get_block(root)
        if blk is None or blk.message.slot == 0:
            return out
        root = bytes(blk.message.parent_root)
    return out


def _fork_slot(chain_a, chain_b) -> int:
    """Slot of the last block both heads descend from."""
    seen = _head_ancestors(chain_a)
    root = chain_b.head().head_block_root
    for _ in range(256):
        if root in seen:
            blk = chain_b.store.get_block(root)
            return int(blk.message.slot) if blk is not None else 0
        blk = chain_b.store.get_block(root)
        if blk is None:
            return 0
        root = bytes(blk.message.parent_root)
    return 0


# -- 1. slashable equivocation ------------------------------------------------

@scenario("equivocation")
def scenario_equivocation(seed: int = 0) -> ScenarioResult:
    """A byzantine VC double-proposes for one epoch, then double-votes
    for two slots.  The honest pipeline must reject the equivocations
    from gossip, the slasher must mint records carrying BOTH signed
    messages, and the resulting slashing operations must reach a block
    and flip validators.slashed."""
    result = ScenarioResult("equivocation", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    net = LocalNetwork(spec, 2, 32, with_slasher=True)
    try:
        byz = ByzantineValidatorClient(net.nodes[1].vc,
                                       mode="double_propose")
        net.nodes[1].vc = byz
        with scenario_capture() as trace:
            net.run_slots(spe)               # double proposals
            byz.mode = "double_vote"
            net.run_slots(2)                 # a couple of double votes
            byz.mode = "honest"
            net.run_slots(2 * spe)           # recovery: slashings land
        result.trace = trace
        _chk(result, "equivocations_published", byz.equivocations > 0,
             f"{byz.equivocations} second messages published")
        records = net.nodes[1].slasher.slashings
        prop = [r for r in records
                if r.kind == "double" and hasattr(r.attestation_1,
                                                  "message")]
        att = [r for r in records
               if r.kind == "double" and hasattr(r.attestation_1,
                                                 "attesting_indices")]
        _chk(result, "proposer_records", len(prop) > 0,
             f"{len(prop)} double-proposal records (both headers "
             "attached)")
        _chk(result, "attester_records", len(att) > 0,
             f"{len(att)} double-vote records (both attestations "
             "attached)")
        # the slashings must have been packed into canonical blocks
        chain = net.nodes[0].harness.chain
        packed_prop = packed_att = 0
        for blk in _chain_blocks(chain):
            packed_prop += len(blk.message.body.proposer_slashings)
            packed_att += len(blk.message.body.attester_slashings)
        _chk(result, "slashings_in_blocks",
             packed_prop > 0 and packed_att > 0,
             f"{packed_prop} proposer + {packed_att} attester slashings "
             "on the canonical chain")
        slashed = int(chain.head().head_state.validators.slashed.sum())
        _chk(result, "validators_slashed", slashed > 0,
             f"{slashed} validators slashed in the head state")
        heads = {n.harness.chain.head().head_block_root
                 for n in net.live_nodes}
        _chk(result, "converged", len(heads) == 1,
             f"{len(heads)} distinct heads after recovery")
        _envelope_checks(result, net, trace)
    finally:
        net.stop()
    return result


# -- 2. invalid-signature gossip flood ----------------------------------------

@scenario("signature_flood")
def scenario_signature_flood(seed: int = 0) -> ScenarioResult:
    """One node floods the attestation subnets with structurally valid,
    wrongly-signed attestations.  The victim runs batched gossip
    verification behind the priority processor with a lowered
    attestation queue cap: the batch verifier must take the per-item
    fallback split, the queue must shed load (counter + high-water), and
    honest block flow must stay inside the envelope."""
    result = ScenarioResult("signature_flood", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    rng = random.Random(seed)
    net = LocalNetwork(spec, 2, 32, use_processor=True,
                       batch_gossip_verification=True)
    try:
        from ..beacon_processor import WorkType
        from ..containers import get_types
        from ..state_transition.helpers import get_beacon_committee
        victim = net.nodes[0]
        proc = victim.network.processor
        CAP = 32
        proc.caps[WorkType.GOSSIP_ATTESTATION] = CAP
        # peer scoring would (correctly) ban the flooding peer after its
        # first garbage batch — on mainnet the attacker just reconnects
        # from the next Sybil identity, so model that by disabling the
        # cut-off and asserting the ban-worthy downscore happened instead
        victim.network.peers.BAN_THRESHOLD = float("-inf")
        # fake-BLS verification is free; restore a mainnet-shaped cost
        # (~1ms/signature) on the victim so the flood actually pressures
        # the queue the way real BLS would
        chain0 = victim.harness.chain
        real_batch = chain0.batch_verify_unaggregated_attestations_for_gossip

        def costed_batch(pairs):
            time.sleep(0.001 * len(pairs))
            return real_batch(pairs)

        chain0.batch_verify_unaggregated_attestations_for_gossip = \
            costed_batch
        T = get_types(spec.preset)
        net.run_slots(spe)                   # honest warm-up
        drop0 = counter_value("beacon_processor_work_dropped_total")
        fb0 = counter_value("beacon_batch_verify_fallback_total")
        flooded = 0

        def flood(slot: int) -> None:
            # structurally valid for the victim's inline checks; only
            # the (deferred) signature is garbage — so every one of
            # these rides the batch queue to the verifier
            nonlocal flooded
            src = net.nodes[1]
            state = src.harness.chain.head().head_state
            data = src.backend.attestation_data(slot, 0)
            committee = get_beacon_committee(state, slot, 0)
            for _ in range(300):
                pos = rng.randrange(len(committee))
                bits = [i == pos for i in range(len(committee))]
                # leading 0xff = invalid under every backend (poison
                # byte on fake, non-canonical G2 on real); random tail
                # keeps every message id distinct so gossip dedup
                # doesn't thin the flood
                att = T.Attestation(
                    aggregation_bits=bits, data=data,
                    signature=b"\xff" + bytes(rng.getrandbits(8)
                                              for _ in range(95)))
                src.network.publish_attestation(att, subnet=0)
                flooded += 1

        with scenario_capture() as trace:
            net.run_slots(3, mid_slot=flood)
            proc.wait_idle()
            net.run_slots(spe - 3)           # drain + recover
        result.trace = trace
        fallback = counter_value("beacon_batch_verify_fallback_total") - fb0
        dropped = counter_value("beacon_processor_work_dropped_total") \
            - drop0
        _chk(result, "flood_sent", flooded >= 900,
             f"{flooded} invalid attestations flooded")
        _chk(result, "batch_fallback_split", fallback > 0,
             f"batch verifier split into per-item retries {fallback:.0f} "
             "times")
        _chk(result, "load_shed", dropped > 0 and proc.dropped > 0,
             f"{dropped:.0f} work items shed at the cap "
             f"(processor.dropped={proc.dropped})")
        shed_incs = graftwatch.get().engine.incidents_for(
            "processor_shedding")
        _chk(result, "slo_shedding_incident", len(shed_incs) > 0,
             f"flood tripped the processor_shedding SLO "
             f"{len(shed_incs)} time(s), first at slot "
             f"{shed_incs[0].opened_slot if shed_incs else '-'}")
        _chk(result, "queue_high_water", proc.high_water >= CAP,
             f"queue high-water {proc.high_water} >= cap {CAP}")
        flooder_score = victim.network.peers.score(
            net.nodes[1].network.transport.node_id)
        _chk(result, "flooder_downscored", flooder_score < -20.0,
             f"flooding peer's score {flooder_score:.1f} crossed the "
             "default ban threshold (-20)")
        heads = {n.harness.chain.head().head_block_root
                 for n in net.live_nodes}
        _chk(result, "converged", len(heads) == 1,
             f"{len(heads)} distinct heads after the flood")
        _envelope_checks(result, net, trace)
    finally:
        net.stop()
    return result


# -- 3. partition and heal ----------------------------------------------------

@scenario("partition_heal")
def scenario_partition_heal(seed: int = 0) -> ScenarioResult:
    """Split a 4-node mesh 2|2 for two epochs, then heal.  Both sides
    must keep producing on their fork; after healing every node must
    re-org onto one winner, with the measured re-org depth bounded by
    the partition length and convergence inside a wall-clock budget."""
    result = ScenarioResult("partition_heal", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    net = LocalNetwork(spec, 4, 32, topology="mesh", injector=injector)
    # the partition must surface through graftwatch, not just the
    # hand-rolled fork checks: auto-dump a flight recording the moment
    # an incident opens, assert the head-lag incident lifecycle, and
    # round-trip the dump through the offline doctor
    watch = graftwatch.get()
    dump_dir = tempfile.mkdtemp(prefix="graftwatch_scn_")
    watch.configure(auto_dump=True, dump_dir=dump_dir)
    try:
        net.run_slots(spe)                   # healthy baseline
        part_start = int(net.nodes[0].harness.chain.slot())
        net.partition([0, 1], [2, 3])
        partition_slots = 2 * spe
        with scenario_capture() as trace:
            net.run_slots(partition_slots)
            chain_a = net.nodes[0].harness.chain
            chain_b = net.nodes[2].harness.chain
            head_a = chain_a.head()
            head_b = chain_b.head()
            _chk(result, "links_severed", injector.links_severed > 0,
                 f"{injector.links_severed} cross-partition connections "
                 "closed")
            _chk(result, "sides_diverged",
                 head_a.head_block_root != head_b.head_block_root,
                 "partition sides built distinct forks")
            _chk(result, "both_sides_advanced",
                 head_a.head_state.slot > spe
                 and head_b.head_state.slot > spe,
                 f"side heads at slots {head_a.head_state.slot} / "
                 f"{head_b.head_state.slot}")
            fork_slot = _fork_slot(chain_a, chain_b)
            t0 = time.monotonic()
            net.heal()
            net.run_slots(spe)
            converged = net._wait_convergence(timeout=20.0)
            t_heal = time.monotonic() - t0
        result.trace = trace
        _chk(result, "reconverged", converged,
             f"all nodes on one head {t_heal:.1f}s after heal")
        _chk(result, "convergence_time", t_heal < 60.0,
             f"{t_heal:.1f}s < 60s")
        # the losing side's fork is fully re-orged out; its depth is
        # bounded by what the partition could have built
        final = net.nodes[0].harness.chain.head().head_block_root
        loser = (head_b if final in _head_ancestors(chain_a)
                 or head_a.head_block_root == final else head_a)
        depth = int(loser.head_state.slot) - fork_slot
        _chk(result, "reorg_depth_bounded",
             0 < depth <= partition_slots,
             f"re-org depth {depth} slots (fork at {fork_slot}, "
             f"partition lasted {partition_slots})")
        # SLO-engine view of the same event: the partition opened a
        # head-lag incident, and the heal let every incident resolve
        incs = [i for i in watch.engine.incidents_for("head_lag")
                if i.opened_slot > part_start]
        _chk(result, "slo_incident_opened", len(incs) > 0,
             f"head-lag incidents opened at slots "
             f"{[i.opened_slot for i in incs]} "
             f"(partition began after slot {part_start})")
        _chk(result, "slo_incident_resolved",
             bool(incs) and all(not i.open for i in incs)
             and not watch.engine.open_incidents(),
             f"resolved at slots {[i.resolved_slot for i in incs]}; "
             "no incident still open after heal")
        # incident-open wrote a flight dump; the offline doctor must
        # turn it into a non-empty correlated diagnosis
        result.dump_path = watch.recorder.last_path
        dumped = result.dump_path is not None
        _chk(result, "flight_dump_written", dumped,
             f"auto-dump wrote {result.dump_path}")
        if dumped:
            diag = flight_doctor.diagnose(
                flight_doctor.load(result.dump_path))
            result.diagnosis = flight_doctor.render(diag)
            lag_diags = [d for d in diag["incidents"]
                         if d["slo"] == "head_lag" and d["correlations"]]
            _chk(result, "doctor_diagnosis", len(lag_diags) > 0,
                 f"doctor correlated {len(lag_diags)} head-lag "
                 f"incident(s) with "
                 f"{sum(len(d['correlations']) for d in lag_diags)} "
                 "co-occurring signals")
    finally:
        watch.configure(auto_dump=False)
        watch.recorder.dump_dir = None
        shutil.rmtree(dump_dir, ignore_errors=True)
        net.stop()
    return result


# -- 4. long non-finality -----------------------------------------------------

@scenario("long_nonfinality")
def scenario_long_nonfinality(seed: int = 0) -> ScenarioResult:
    """Half the stake goes vote-silent (still proposing) for six epochs:
    finality must stall, the head must keep tracking the slot clock, and
    proto-array growth must stay bounded.  When the silent stake returns,
    finality must resume and maybe_prune must reclaim the fork-choice
    array."""
    result = ScenarioResult("long_nonfinality", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    net = LocalNetwork(spec, 2, 32)
    try:
        net.run_slots(4 * spe)               # establish finality
        chain = net.nodes[0].harness.chain
        fin0 = chain.finalized_checkpoint()[0]
        _chk(result, "finality_established", fin0 >= 1,
             f"finalized epoch {fin0} before the outage")
        byz = ByzantineValidatorClient(net.nodes[1].vc, mode="silent")
        net.nodes[1].vc = byz
        stall_epochs = 6
        with scenario_capture() as trace:
            net.run_slots(stall_epochs * spe)
        result.trace = trace
        fin_stalled = chain.finalized_checkpoint()[0]
        _chk(result, "finality_stalled", fin_stalled <= fin0 + 1,
             f"finalized epoch {fin_stalled} after {stall_epochs} silent "
             f"epochs (was {fin0})")
        nodes_peak = len(chain.fork_choice.proto_array.nodes)
        slots_elapsed = (4 + stall_epochs) * spe
        _chk(result, "proto_array_bounded",
             nodes_peak <= slots_elapsed + 16,
             f"proto-array holds {nodes_peak} nodes <= "
             f"{slots_elapsed + 16}")
        _envelope_checks(result, net, trace)
        # recovery: votes return, finality advances, prune reclaims.
        # The production prune_threshold (256) exists to amortize index
        # rewrites on mainnet-sized arrays; drop it so this ~100-node run
        # exercises the reclaim path itself.
        for n in net.nodes:
            n.harness.chain.fork_choice.proto_array.prune_threshold = 0
        byz.mode = "honest"
        net.run_slots(4 * spe)
        fin_rec = chain.finalized_checkpoint()[0]
        _chk(result, "finality_recovered", fin_rec > fin_stalled,
             f"finalized epoch {fin_rec} > {fin_stalled}")
        nodes_after = len(chain.fork_choice.proto_array.nodes)
        _chk(result, "proto_array_pruned", nodes_after < nodes_peak,
             f"maybe_prune reclaimed {nodes_peak - nodes_after} "
             f"proto-array nodes ({nodes_peak} -> {nodes_after})")
    finally:
        net.stop()
    return result


# -- 5. checkpoint sync into a partition --------------------------------------

@scenario("checkpoint_sync_partition")
def scenario_checkpoint_sync_partition(seed: int = 0) -> ScenarioResult:
    """A fresh node weak-subjectivity-syncs against a node that, unknown
    to it, sits on the minority side of a partition.  It must follow the
    minority fork (that is all it can see), then re-org onto the
    majority chain once the partition heals — checkpoint sync must not
    pin it to the minority."""
    result = ScenarioResult("checkpoint_sync_partition", seed)
    spec = minimal_spec(altair_fork_epoch=0)
    spe = spec.preset.slots_per_epoch
    injector = FaultInjector(seed)
    net = LocalNetwork(spec, 3, 48, topology="mesh", injector=injector)
    try:
        net.run_slots(4 * spe)               # finality for the anchor
        fin0 = net.nodes[2].harness.chain.finalized_checkpoint()[0]
        _chk(result, "anchor_finalized", fin0 >= 2,
             f"anchor node finalized epoch {fin0}")
        net.partition([0, 1], [2])
        net.run_slots(spe)                   # sides diverge
        with scenario_capture() as trace:
            i3 = net.add_node(anchor_from=2, dial=[2], group=1)
            net.run_slots(spe)
            chain3 = net.nodes[i3].harness.chain
            chain_minor = net.nodes[2].harness.chain
            chain_major = net.nodes[0].harness.chain
            _chk(result, "synced_past_anchor",
                 chain3.head().head_state.slot >
                 fin0 * spe,
                 f"synced node head at slot "
                 f"{chain3.head().head_state.slot}")
            _chk(result, "follows_minority",
                 chain3.head().head_block_root ==
                 chain_minor.head().head_block_root,
                 "synced node sits on the minority head")
            _chk(result, "minority_is_fork",
                 chain3.head().head_block_root !=
                 chain_major.head().head_block_root,
                 "minority head differs from the majority head")
            net.heal()
            net.run_slots(2 * spe)
            converged = net._wait_convergence(timeout=20.0)
        result.trace = trace
        _chk(result, "healed_converged", converged,
             "all four nodes agree after heal")
        _chk(result, "reorged_to_majority",
             chain3.head().head_block_root ==
             chain_major.head().head_block_root,
             "synced node re-orged onto the majority chain")
        _envelope_checks(result, net, trace, max_head_lag=2)
    finally:
        net.stop()
    return result
