"""State-level harness: produce blocks/attestations against a bare state.

The state-transition core of the reference's BeaconChainHarness
(beacon_chain/src/test_utils.rs:611): extend a chain of blocks with full
attestation participation using deterministic keys, without fork
choice/store/network. The full chain harness (chain/harness.py) builds on it.
"""
from __future__ import annotations

import numpy as np

from ..containers import get_types
from ..containers.state import BeaconState
from ..crypto import bls
from ..specs.chain_spec import ChainSpec, ForkName, compute_signing_root
from ..specs.constants import (
    DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER, DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
)
from ..ssz import hash_tree_root, htr, uint64
from ..state_transition import (
    BlockProcessingError, VerifySignatures, interop_genesis_state,
    per_block_processing, process_slots,
)
from ..state_transition.block import get_expected_withdrawals
from ..state_transition.helpers import (
    committee_cache, compute_epoch_at_slot, compute_start_slot_at_epoch,
    get_beacon_proposer_index, get_domain,
)


class StateHarness:
    def __init__(self, spec: ChainSpec, validator_count: int = 64,
                 genesis_time: int = 0):
        self.spec = spec
        self.T = get_types(spec.preset)
        self.secret_keys = [bls.keygen_interop(i)
                            for i in range(validator_count)]
        self.state = interop_genesis_state(spec, self.secret_keys,
                                           genesis_time=genesis_time)
        self.genesis_state = self.state.copy()

    # -- signing -------------------------------------------------------------

    def sign_block(self, state: BeaconState, block) -> object:
        epoch = compute_epoch_at_slot(block.slot, state.slots_per_epoch)
        domain = get_domain(state, DOMAIN_BEACON_PROPOSER, epoch)
        signing_root = compute_signing_root(htr(block), domain)
        sig = bls.sign(self.secret_keys[block.proposer_index], signing_root)
        fork = state.spec.fork_name_at_slot(block.slot)
        return self.T.SignedBeaconBlock[fork](message=block, signature=sig)

    def randao_reveal(self, state: BeaconState, slot: int,
                      proposer_index: int) -> bytes:
        epoch = compute_epoch_at_slot(slot, state.slots_per_epoch)
        domain = get_domain(state, DOMAIN_RANDAO, epoch)
        signing_root = compute_signing_root(
            hash_tree_root(uint64, epoch), domain)
        return bls.sign(self.secret_keys[proposer_index], signing_root)

    # -- attestations --------------------------------------------------------

    def attestation_data(self, state: BeaconState, slot: int,
                         index: int, head_root: bytes):
        T = self.T
        epoch = compute_epoch_at_slot(slot, state.slots_per_epoch)
        epoch_start = compute_start_slot_at_epoch(epoch,
                                                  state.slots_per_epoch)
        if epoch_start == slot or state.slot <= epoch_start:
            target_root = head_root
        else:
            target_root = state.get_block_root_at_slot(epoch_start)
        return T.AttestationData(
            slot=slot, index=index, beacon_block_root=head_root,
            source=state.current_justified_checkpoint,
            target=T.Checkpoint(epoch=epoch, root=target_root))

    def produce_attestations(self, state: BeaconState, slot: int,
                             head_root: bytes) -> list:
        """One fully-aggregated attestation per committee at `slot`.

        `state` must be at `slot` (or later within the epoch).
        """
        T = self.T
        epoch = compute_epoch_at_slot(slot, state.slots_per_epoch)
        cache = committee_cache(state, epoch)
        electra = state.fork_name >= ForkName.ELECTRA
        out = []
        for index in range(cache.committees_per_slot):
            committee = cache.committee(slot, index)
            data = self.attestation_data(
                state, slot, 0 if electra else index, head_root)
            domain = get_domain(state, DOMAIN_BEACON_ATTESTER, epoch)
            signing_root = compute_signing_root(htr(data), domain)
            sigs = [bls.sign(self.secret_keys[int(v)], signing_root)
                    for v in committee]
            agg = bls.aggregate_signatures(sigs)
            if electra:
                committee_bits = [i == index
                                  for i in range(
                                      self.T.preset.max_committees_per_slot)]
                att = T.AttestationElectra(
                    aggregation_bits=[True] * len(committee), data=data,
                    signature=agg, committee_bits=committee_bits)
            else:
                att = T.Attestation(
                    aggregation_bits=[True] * len(committee), data=data,
                    signature=agg)
            out.append(att)
        return out

    # -- sync aggregate ------------------------------------------------------

    def produce_sync_aggregate(self, state: BeaconState, block_slot: int,
                               head_root: bytes):
        T = self.T
        previous_slot = max(block_slot, 1) - 1
        epoch = compute_epoch_at_slot(previous_slot, state.slots_per_epoch)
        domain = get_domain(state, DOMAIN_SYNC_COMMITTEE, epoch)
        signing_root = compute_signing_root(head_root, domain)
        committee = state.current_sync_committee
        sigs, bits = [], []
        for pk in committee.pubkeys:
            idx = state.validators.index_of(pk)
            if idx is not None:
                sigs.append(bls.sign(self.secret_keys[idx], signing_root))
                bits.append(True)
            else:
                bits.append(False)
        agg = (bls.aggregate_signatures(sigs) if sigs
               else bls.INFINITY_SIGNATURE)
        return T.SyncAggregate(sync_committee_bits=bits,
                               sync_committee_signature=agg)

    # -- block production ----------------------------------------------------

    def produce_block_on_state(self, state: BeaconState, slot: int,
                               attestations: list | None = None,
                               deposits: list | None = None,
                               exits: list | None = None,
                               graffiti: bytes = b"\x00" * 32):
        """Advance `state` to `slot` and build+apply+sign a block on it.

        Returns (signed_block, post_state). Mirrors the 3-phase structure of
        beacon_chain.rs:4810 produce_block_on_state (packing, payload,
        completion) with the op pool replaced by explicit arguments.
        """
        T = self.T
        if state.slot < slot:
            process_slots(state, slot)
        fork = state.fork_name
        proposer_index = get_beacon_proposer_index(state)
        parent_root = htr(state.latest_block_header)

        body_cls = T.BeaconBlockBody[fork]
        body = body_cls(
            randao_reveal=self.randao_reveal(state, slot, proposer_index),
            eth1_data=state.eth1_data, graffiti=graffiti,
            attestations=list(attestations or []),
            deposits=list(deposits or []),
            voluntary_exits=list(exits or []))
        if fork >= ForkName.ALTAIR:
            body.sync_aggregate = self.produce_sync_aggregate(
                state, slot, parent_root)
        if fork >= ForkName.BELLATRIX:
            body.execution_payload = self._stub_payload(state, fork)

        block = T.BeaconBlock[fork](
            slot=slot, proposer_index=proposer_index,
            parent_root=parent_root, state_root=b"\x00" * 32, body=body)

        post = state.copy()
        signed = self.sign_block(state, block)
        per_block_processing(post, signed, VerifySignatures.FALSE)
        block.state_root = post.hash_tree_root()
        signed = self.sign_block(state, block)  # re-sign with state root
        return signed, post

    def _stub_payload(self, state: BeaconState, fork: ForkName):
        """Minimal valid local payload (mock-EL style)."""
        from ..state_transition.block import compute_timestamp_at_slot
        cls = self.T.ExecutionPayload[fork]
        parent_hash = (state.latest_execution_payload_header.block_hash
                       if state.fork_name >= ForkName.BELLATRIX
                       else b"\x00" * 32)
        kw = dict(
            parent_hash=parent_hash,
            prev_randao=state.get_randao_mix(state.current_epoch()),
            block_number=state.latest_execution_payload_header.block_number + 1,
            timestamp=compute_timestamp_at_slot(state, state.slot),
            block_hash=htr(self.T.Checkpoint(
                epoch=state.slot, root=parent_hash)),
            base_fee_per_gas=7,
        )
        if fork >= ForkName.CAPELLA:
            withdrawals, _ = get_expected_withdrawals(state)
            kw["withdrawals"] = withdrawals
        payload = cls(**kw)
        return payload

    # -- chain extension -----------------------------------------------------

    def extend_chain(self, num_blocks: int, attest: bool = True):
        """Produce `num_blocks` blocks with full attestations (one per slot),
        applying them to self.state. Returns the signed blocks."""
        blocks = []
        for _ in range(num_blocks):
            slot = self.state.slot + 1
            atts = []
            if attest and slot > 1:
                # attestations for the previous slot's head
                head_root = htr(self.state.latest_block_header)
                hdr = self.state.latest_block_header
                if hdr.state_root == b"\x00" * 32:
                    hdr = self.T.BeaconBlockHeader(
                        slot=hdr.slot, proposer_index=hdr.proposer_index,
                        parent_root=hdr.parent_root,
                        state_root=self.state.hash_tree_root(),
                        body_root=hdr.body_root)
                    head_root = htr(hdr)
                atts = self.produce_attestations(
                    self.state, self.state.slot, head_root)
            signed, post = self.produce_block_on_state(
                self.state, slot, attestations=atts)
            self.state = post
            blocks.append(signed)
        return blocks
