"""Test utilities: state harness, deterministic keys, mock services.

Equivalent of the reference's test infrastructure (SURVEY.md §4):
BeaconChainHarness (beacon_chain/src/test_utils.rs:611), deterministic
interop keypairs, TestingSlotClock, MockExecutionLayer.
"""
from .state_harness import StateHarness
