"""Multi-node in-process simulator.

Equivalent of /root/reference/testing/simulator (basic_sim.rs:29,
local_network.rs:107, checks.rs): N beacon nodes (production objects) on
real TCP loopback with validators split across per-node validator clients,
asserting liveness, full participation, sync and finalization — the
"multi-node without a real cluster" tier of SURVEY.md §4.

Run directly:  python -m lighthouse_tpu.testing.simulator --nodes 3
"""
from __future__ import annotations

import argparse
import sys
import time
from dataclasses import dataclass, field

from ..api import ApiBackend, BeaconApiServer
from ..chain import BeaconChainHarness
from ..crypto import bls
from ..network import NetworkService
from ..specs import minimal_spec
from ..validator_client import (
    BeaconNodeFallback, ValidatorClient, ValidatorStore,
)
from ..validator_client.http_client import BeaconNodeHttpClient


@dataclass
class LocalNode:
    harness: BeaconChainHarness
    network: NetworkService
    backend: ApiBackend
    vc: ValidatorClient | None = None
    api_server: object | None = None     # BeaconApiServer in HTTP mode
    dead: bool = False


class GossipingBackend(ApiBackend):
    """API publish also floods the gossip network (http_api/src/
    publish_blocks.rs -> network channel behavior).  Block broadcast goes
    through the backend's publish_fn hook so the round-4
    broadcast-validation ordering applies (gossip mode broadcasts after
    gossip checks; consensus mode only after full import)."""

    def __init__(self, chain, network: NetworkService):
        super().__init__(chain)
        self.network = network
        self.publish_fn = network.publish_block

    def publish_attestation(self, attestation) -> None:
        super().publish_attestation(attestation)
        self.network.publish_attestation(attestation)

    def publish_sync_committee_message(self, msg) -> None:
        super().publish_sync_committee_message(msg)
        self.network.publish_sync_committee_message(msg)


@dataclass
class CheckResult:
    name: str
    ok: bool
    detail: str = ""


class LocalNetwork:
    """node_test_rig LocalNetwork equivalent."""

    def __init__(self, spec, node_count: int, validator_count: int = 64,
                 use_http: bool = False):
        """`use_http=True` drives every VC through a REAL per-node HTTP
        API server (BeaconNodeHttpClient -> BeaconApiServer -> backend),
        with every OTHER node's URL as a fallback — the reference's
        fallback_sim topology; block publication then takes the real
        POST /eth/v1/beacon/blocks path (publish_blocks.rs role) instead
        of an in-process shortcut."""
        bls.set_backend("fake")
        self.spec = spec
        self.validator_count = validator_count
        self.use_http = use_http
        self.nodes: list[LocalNode] = []
        first_port = None
        for i in range(node_count):
            h = BeaconChainHarness(spec, validator_count)
            net = NetworkService(h.chain)
            backend = GossipingBackend(h.chain, net)
            net.start()
            node = LocalNode(h, net, backend)
            if use_http:
                node.api_server = BeaconApiServer(backend)
                node.api_server.start()
            self.nodes.append(node)
            if first_port is None:
                first_port = net.port
            else:
                net.dial("127.0.0.1", first_port)
        # split validators across nodes, each slice driven by that node's VC
        per = validator_count // node_count
        for i, node in enumerate(self.nodes):
            store = ValidatorStore(
                spec, node.harness.chain.genesis_validators_root)
            lo = i * per
            hi = validator_count if i == node_count - 1 else (i + 1) * per
            for sk in node.harness.secret_keys[lo:hi]:
                store.add_validator(sk)
            if use_http:
                # own node first, every other node as failover
                order = [node] + [n for n in self.nodes if n is not node]
                clients = [BeaconNodeHttpClient(
                    f"http://127.0.0.1:{n.api_server.port}", spec,
                    timeout=5.0) for n in order]
                node.vc = ValidatorClient(spec, store,
                                          BeaconNodeFallback(clients))
            else:
                node.vc = ValidatorClient(
                    spec, store, BeaconNodeFallback([node.backend]))

    def kill_node(self, i: int) -> None:
        """Fault injection (fallback_sim.rs role): the node's API server
        and network die.  Its VC KEEPS RUNNING — in HTTP mode its duties
        fail over to the surviving nodes' URLs, which is the behavior
        the fallback simulation exists to prove."""
        node = self.nodes[i]
        node.dead = True
        if node.api_server is not None:
            node.api_server.stop()
        node.network.stop()

    @property
    def live_nodes(self) -> list[LocalNode]:
        live = [n for n in self.nodes if not n.dead]
        if not live:
            raise RuntimeError("no live nodes left in the simulation")
        return live

    def _wait_convergence(self, timeout: float = 5.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            heads = {n.harness.chain.recompute_head()
                     for n in self.live_nodes}
            if len(heads) == 1:
                return
            time.sleep(0.02)

    def _run_duty(self, node: LocalNode, fn, *args) -> None:
        """Dead/HTTP duty policy in ONE place: a dead node's VC runs
        only when HTTP failover exists, and only a dead node's errors
        are swallowed — a live node's duty failure must stay loud."""
        if node.dead:
            if not self.use_http:
                return                 # no failover path without HTTP
            try:
                fn(*args)
            except Exception:
                return                 # dead-primary hiccup: next slot
        else:
            fn(*args)

    def run_slots(self, num_slots: int) -> None:
        """Each slot mirrors the real duty schedule: propose at 0s,
        attest + sync-sign at slot/3 (after block propagation),
        aggregate at 2*slot/3.  A dead node's chain stops, but its VC
        keeps running — in HTTP mode its duties fail over to the
        surviving nodes' APIs (fallback_sim behavior)."""
        def propose(node, slot):
            vc = node.vc
            epoch = slot // self.spec.preset.slots_per_epoch
            if epoch not in vc._duties or epoch + 1 not in vc._duties:
                vc.update_duties(epoch)
            vc.propose_if_due(slot)

        def attest(node, slot):
            node.vc.attest(slot)
            node.vc.sync_committee_duty(slot)

        for _ in range(num_slots):
            for node in self.live_nodes:
                node.harness.advance_slot()
            slot = self.live_nodes[0].harness.chain.slot()
            for node in self.nodes:
                self._run_duty(node, propose, node, slot)
            self._wait_convergence()
            for node in self.nodes:
                self._run_duty(node, attest, node, slot)
            for node in self.nodes:
                self._run_duty(node, node.vc.aggregate, slot)
            self._wait_convergence()

    # -- checks (testing/simulator/src/checks.rs) ----------------------------

    def checks(self, min_epochs: int) -> list[CheckResult]:
        out = []
        live = self.live_nodes
        heads = {n.harness.chain.head().head_block_root for n in live}
        out.append(CheckResult("all_nodes_agree_on_head", len(heads) == 1,
                               f"{len(heads)} distinct heads"))
        slot = live[0].harness.chain.slot()
        head_slot = live[0].harness.chain.head().head_state.slot
        out.append(CheckResult(
            "liveness", head_slot >= slot - 1,
            f"head {head_slot} vs clock {slot}"))
        fin = live[0].harness.chain.finalized_checkpoint()[0]
        out.append(CheckResult(
            "finalization", fin >= max(0, min_epochs - 2),
            f"finalized epoch {fin}"))
        blocks_per_node = [n.vc.published_blocks for n in self.nodes]
        out.append(CheckResult(
            "all_nodes_proposed", all(b > 0 for b in blocks_per_node),
            f"{blocks_per_node}"))
        # sync-aggregate participation on recent blocks
        chain = live[0].harness.chain
        body = chain.head().head_block.message.body
        if hasattr(body, "sync_aggregate"):
            bits = body.sync_aggregate.sync_committee_bits
            rate = sum(1 for b in bits if b) / max(1, len(bits))
            out.append(CheckResult("sync_participation", rate > 0.5,
                                   f"{rate:.2f}"))
        return out

    def stop(self) -> None:
        for n in self.nodes:
            if not n.dead:
                n.network.stop()
            if n.api_server is not None and not n.dead:
                n.api_server.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args(argv)
    spec = minimal_spec(altair_fork_epoch=0)
    net = LocalNetwork(spec, args.nodes, args.validators)
    try:
        net.run_slots(args.epochs * spec.preset.slots_per_epoch)
        results = net.checks(args.epochs)
    finally:
        net.stop()
    ok = True
    for r in results:
        print(f"[{'PASS' if r.ok else 'FAIL'}] {r.name}: {r.detail}")
        ok &= r.ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
