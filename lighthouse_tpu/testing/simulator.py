"""Multi-node in-process simulator.

Equivalent of /root/reference/testing/simulator (basic_sim.rs:29,
local_network.rs:107, checks.rs): N beacon nodes (production objects) on
real TCP loopback with validators split across per-node validator clients,
asserting liveness, full participation, sync and finalization — the
"multi-node without a real cluster" tier of SURVEY.md §4.

The adversarial tier (ISSUE 7) layers on top: a shared ``FaultInjector``
swaps every node's transport for a ``FaultyTransport`` so scenarios
(testing/scenarios.py) can ``partition()``/``heal()`` the network, nodes
can run the priority beacon processor with batched gossip verification,
and a per-node slasher can be armed.

Run directly:  python -m lighthouse_tpu.testing.simulator --nodes 3
Scenarios:     python -m lighthouse_tpu.testing.simulator \
                   --scenario partition_heal --seed 7
"""
from __future__ import annotations

import argparse
import sys
import time
from dataclasses import dataclass

from ..api import ApiBackend, BeaconApiServer
from ..chain import BeaconChainHarness
from ..crypto import bls
from ..network import NetworkService
from ..network.faults import FaultInjector, FaultyTransport
from ..network.service import NetworkConfig
from ..specs import minimal_spec
from ..validator_client import (
    BeaconNodeFallback, ValidatorClient, ValidatorStore,
)
from ..validator_client.http_client import BeaconNodeHttpClient


@dataclass
class LocalNode:
    harness: object                      # BeaconChainHarness or anchor shim
    network: NetworkService
    backend: ApiBackend
    vc: ValidatorClient | None = None
    api_server: object | None = None     # BeaconApiServer in HTTP mode
    slasher: object | None = None
    dead: bool = False


class GossipingBackend(ApiBackend):
    """API publish also floods the gossip network (http_api/src/
    publish_blocks.rs -> network channel behavior).  Block broadcast goes
    through the backend's publish_fn hook so the round-4
    broadcast-validation ordering applies (gossip mode broadcasts after
    gossip checks; consensus mode only after full import)."""

    def __init__(self, chain, network: NetworkService):
        super().__init__(chain)
        self.network = network
        self.publish_fn = network.publish_block

    def publish_attestation(self, attestation) -> None:
        super().publish_attestation(attestation)
        self.network.publish_attestation(attestation)

    def publish_aggregate(self, signed_aggregate) -> None:
        super().publish_aggregate(signed_aggregate)
        self.network.publish_aggregate(signed_aggregate)

    def publish_sync_committee_message(self, msg) -> None:
        super().publish_sync_committee_message(msg)
        self.network.publish_sync_committee_message(msg)


@dataclass
class CheckResult:
    name: str
    ok: bool
    detail: str = ""


class _AnchorHarness:
    """Harness shim for a checkpoint-synced node: it owns a chain and a
    clock but no genesis validators of its own."""

    def __init__(self, chain, clock):
        self.chain = chain
        self.clock = clock

    def advance_slot(self) -> None:
        self.clock.advance_slot()
        self.chain.per_slot_task()


class LocalNetwork:
    """node_test_rig LocalNetwork equivalent."""

    def __init__(self, spec, node_count: int, validator_count: int = 64,
                 use_http: bool = False, topology: str = "star",
                 security: str | None = None,
                 injector: FaultInjector | None = None,
                 use_processor: bool = False,
                 batch_gossip_verification: bool = False,
                 with_slasher: bool = False):
        """`use_http=True` drives every VC through a REAL per-node HTTP
        API server (BeaconNodeHttpClient -> BeaconApiServer -> backend),
        with every OTHER node's URL as a fallback — the reference's
        fallback_sim topology; block publication then takes the real
        POST /eth/v1/beacon/blocks path (publish_blocks.rs role) instead
        of an in-process shortcut.

        `topology`: "star" (everyone dials node 0 — the seed layout) or
        "mesh" (full peering — required for partition scenarios, where a
        severed hub would otherwise isolate every spoke at once).
        `injector`: a FaultInjector; each node then runs a
        FaultyTransport labeled "n{i}" so scenarios can cut/degrade
        links.  `with_slasher` arms a per-node slasher fed by gossip
        verification; run_slots drains it into the op pool exactly like
        the production client loop."""
        if topology not in ("star", "mesh"):
            raise ValueError(f"unknown topology {topology!r}")
        bls.set_backend("fake")
        self.spec = spec
        self.validator_count = validator_count
        self.use_http = use_http
        self.topology = topology
        self.security = security
        self.injector = injector
        self.use_processor = use_processor
        self.batch_gossip_verification = batch_gossip_verification
        self.with_slasher = with_slasher
        self.nodes: list[LocalNode] = []
        self.partitions: list[list[int]] | None = None
        self.convergence_failures: list[CheckResult] = []
        for i in range(node_count):
            h = BeaconChainHarness(spec, validator_count)
            node = self._wire_node(h, f"n{i}")
            self.nodes.append(node)
            for j in self._dial_targets(i):
                node.network.dial("127.0.0.1", self.nodes[j].network.port)
        # split validators across nodes, each slice driven by that node's VC
        per = validator_count // node_count
        for i, node in enumerate(self.nodes):
            store = ValidatorStore(
                spec, node.harness.chain.genesis_validators_root)
            lo = i * per
            hi = validator_count if i == node_count - 1 else (i + 1) * per
            for sk in node.harness.secret_keys[lo:hi]:
                store.add_validator(sk)
            node.vc = ValidatorClient(spec, store, self._fallback_for(node))

    # -- construction --------------------------------------------------------

    def _wire_node(self, harness, label: str) -> LocalNode:
        chain = harness.chain
        cfg = NetworkConfig(
            security=self.security,
            batch_gossip_verification=self.batch_gossip_verification)
        processor = None
        if self.use_processor:
            from ..beacon_processor import BeaconProcessor
            processor = BeaconProcessor(num_workers=2)
        transport_factory = None
        if self.injector is not None:
            inj = self.injector
            transport_factory = lambda host, port: FaultyTransport(
                host, port, security=self.security, injector=inj,
                label=label)
        net = NetworkService(chain, cfg, processor=processor,
                             transport_factory=transport_factory,
                             label=label)
        backend = GossipingBackend(chain, net)
        net.start()
        node = LocalNode(harness, net, backend)
        if self.with_slasher:
            from ..slasher import Slasher, SlasherConfig
            node.slasher = Slasher(SlasherConfig(history_length=64))
            chain.slasher = node.slasher
        if self.use_http:
            node.api_server = BeaconApiServer(backend)
            node.api_server.start()
        return node

    def _fallback_for(self, node: LocalNode) -> BeaconNodeFallback:
        if self.use_http:
            # own node first, every other node as failover
            order = [node] + [n for n in self.nodes if n is not node]
            clients = [BeaconNodeHttpClient(
                f"http://127.0.0.1:{n.api_server.port}", self.spec,
                timeout=5.0) for n in order]
            return BeaconNodeFallback(clients)
        return BeaconNodeFallback([node.backend])

    def _dial_targets(self, i: int) -> list[int]:
        if i == 0:
            return []
        return [0] if self.topology == "star" else list(range(i))

    def add_node(self, anchor_from: int, dial: list[int] | None = None,
                 group: int | None = None) -> int:
        """Join a FRESH node mid-run via weak-subjectivity checkpoint
        sync against `anchor_from`'s finalized state (the fresh node has
        no validators — it follows, which is exactly the
        checkpoint-sync-into-partition victim).  `dial` overrides the
        topology's default peers; `group` places the node into an active
        partition group so convergence checks score it correctly."""
        from ..chain import BeaconChainBuilder
        from ..containers.state import BeaconState
        from ..utils.slot_clock import ManualSlotClock
        src = self.nodes[anchor_from].harness.chain
        fin_epoch, fin_root = src.finalized_checkpoint()
        fin_block = src.store.get_block(fin_root)
        fin_state = src.store.get_hot_state(fin_block.message.state_root)
        # serialize round-trip: exactly what a checkpoint provider serves
        state2 = BeaconState.from_ssz_bytes(
            fin_state.serialize(), fin_state.T, self.spec,
            fin_state.fork_name)
        clock = ManualSlotClock(0, self.spec.seconds_per_slot,
                                current_slot=src.slot())
        chain = (BeaconChainBuilder(self.spec)
                 .weak_subjectivity_anchor(state2, fin_block)
                 .slot_clock(clock)
                 .build())
        i = len(self.nodes)
        node = self._wire_node(_AnchorHarness(chain, clock), f"n{i}")
        self.nodes.append(node)
        if group is not None and self.partitions is not None:
            self.partitions[group].append(i)
            if self.injector is not None:
                labels = [[f"n{j}" for j in g] for g in self.partitions]
                self.injector.partition(*labels)
        for j in (dial if dial is not None else self._dial_targets(i)):
            node.network.dial("127.0.0.1", self.nodes[j].network.port)
        return i

    def add_fresh_node(self, dial: list[int] | None = None) -> int:
        """Join a GENESIS-state node mid-run: it shares the network's
        deterministic interop genesis but has imported nothing, so it
        must RANGE-SYNC the whole history from its peers — the
        byzantine-sync victim (ISSUE 11).  Runs no validators.  `dial=[]`
        suppresses dialing so a scenario can tune the node's sync knobs
        before any STATUS exchange triggers `maybe_sync`."""
        h = BeaconChainHarness(self.spec, self.validator_count)
        h.set_slot(int(self.live_nodes[0].harness.chain.slot()))
        i = len(self.nodes)
        node = self._wire_node(h, f"n{i}")
        self.nodes.append(node)
        for j in (dial if dial is not None else self._dial_targets(i)):
            node.network.dial("127.0.0.1", self.nodes[j].network.port)
        return i

    # -- fault control -------------------------------------------------------

    def kill_node(self, i: int) -> None:
        """Fault injection (fallback_sim.rs role): the node's API server
        and network die.  Its VC KEEPS RUNNING — in HTTP mode its duties
        fail over to the surviving nodes' URLs, which is the behavior
        the fallback simulation exists to prove."""
        node = self.nodes[i]
        node.dead = True
        if node.api_server is not None:
            node.api_server.stop()
        node.network.stop()

    def partition(self, *groups) -> None:
        """Split the network into node-index groups; requires the fault
        injector.  Cross-group TCP sessions are closed and re-dials
        refused until heal()."""
        if self.injector is None:
            raise RuntimeError("partition() needs a FaultInjector")
        self.partitions = [list(g) for g in groups]
        self.injector.partition(*[[f"n{i}" for i in g] for g in groups])

    def heal(self, redial: bool = True) -> None:
        """Clear every link fault and (by default) re-establish the
        topology's severed edges."""
        if self.injector is None:
            raise RuntimeError("heal() needs a FaultInjector")
        self.injector.heal()
        self.partitions = None
        if not redial:
            return
        for i, node in enumerate(self.nodes):
            if node.dead:
                continue
            for j in self._dial_targets(i):
                if not self.nodes[j].dead and not self._connected(i, j):
                    node.network.dial("127.0.0.1",
                                      self.nodes[j].network.port)

    def _connected(self, i: int, j: int) -> bool:
        other = self.nodes[j].network.transport.node_id
        return any(p.node_id == other for p in
                   self.nodes[i].network.transport.peers.values())

    @property
    def live_nodes(self) -> list[LocalNode]:
        live = [n for n in self.nodes if not n.dead]
        if not live:
            raise RuntimeError("no live nodes left in the simulation")
        return live

    # -- driving -------------------------------------------------------------

    def _groups(self) -> list[list[LocalNode]]:
        """Live nodes, grouped by the active partition (one group when
        the network is whole)."""
        if self.partitions is None:
            return [self.live_nodes]
        return [[self.nodes[i] for i in g if not self.nodes[i].dead]
                for g in self.partitions]

    def _wait_convergence(self, timeout: float = 5.0) -> bool:
        """Wait until every partition group internally agrees on a head.
        A timeout is RECORDED (convergence_failures) and reported —
        silently proceeding made partition regressions invisible."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            converged = True
            for group in self._groups():
                heads = {n.harness.chain.recompute_head() for n in group}
                if len(heads) > 1:
                    converged = False
                    break
            if converged:
                return True
            time.sleep(0.02)
        detail = []
        for gi, group in enumerate(self._groups()):
            heads = {n.harness.chain.recompute_head() for n in group}
            detail.append(f"group{gi}: {len(heads)} heads")
        self.convergence_failures.append(
            CheckResult("convergence", False,
                        f"timeout {timeout}s; " + ", ".join(detail)))
        return False

    def _run_duty(self, node: LocalNode, fn, *args) -> None:
        """Dead/HTTP duty policy in ONE place: a dead node's VC runs
        only when HTTP failover exists, and only a dead node's
        CONNECTION-LEVEL errors are swallowed — a live node's duty
        failure, and any non-transport error, must stay loud."""
        if node.dead:
            if not self.use_http:
                return                 # no failover path without HTTP
            try:
                fn(*args)
            except (OSError, TimeoutError):
                return                 # dead-primary hiccup: next slot
        else:
            fn(*args)

    def _tick_faults(self) -> None:
        if self.injector is not None:
            self.injector.tick()

    def _pump_slashers(self) -> None:
        """Production-loop parity (client/builder.py slot task): drain
        each armed slasher and pack provable records into the op pool."""
        from ..slasher import record_to_operation
        for node in self.live_nodes:
            if node.slasher is None:
                continue
            chain = node.harness.chain
            for rec in node.slasher.process_queued(chain.epoch()):
                op = record_to_operation(rec, chain.T)
                if op is None:
                    continue
                if hasattr(op, "signed_header_1"):
                    chain.op_pool.insert_proposer_slashing(op)
                else:
                    chain.op_pool.insert_attester_slashing(op)

    def run_slots(self, num_slots: int, mid_slot=None) -> None:
        """Each slot mirrors the real duty schedule: propose at 0s,
        attest + sync-sign at slot/3 (after block propagation),
        aggregate at 2*slot/3.  A dead node's chain stops, but its VC
        keeps running — in HTTP mode its duties fail over to the
        surviving nodes' APIs (fallback_sim behavior).  The fault
        injector's scenario clock advances once per duty phase.
        `mid_slot(slot)` runs after block propagation and BEFORE the
        attestation phase — the window where adversarial gossip lands on
        mainnet (scenarios inject floods here)."""
        def propose(node, slot):
            vc = node.vc
            epoch = slot // self.spec.preset.slots_per_epoch
            if epoch not in vc._duties or epoch + 1 not in vc._duties:
                vc.update_duties(epoch)
            vc.propose_if_due(slot)

        def attest(node, slot):
            node.vc.attest(slot)
            node.vc.sync_committee_duty(slot)

        for _ in range(num_slots):
            for node in self.live_nodes:
                node.harness.advance_slot()
            slot = self.live_nodes[0].harness.chain.slot()
            for node in self.nodes:
                if node.vc is not None:
                    self._run_duty(node, propose, node, slot)
            self._tick_faults()
            self._wait_convergence()
            if mid_slot is not None:
                mid_slot(slot)
            for node in self.nodes:
                if node.vc is not None:
                    self._run_duty(node, attest, node, slot)
            for node in self.nodes:
                if node.vc is not None:
                    self._run_duty(node, node.vc.aggregate, slot)
            self._tick_faults()
            self._wait_convergence()
            self._pump_slashers()

    # -- checks (testing/simulator/src/checks.rs) ----------------------------

    def checks(self, min_epochs: int) -> list[CheckResult]:
        out = []
        groups = self._groups()
        for gi, group in enumerate(groups):
            heads = {n.harness.chain.head().head_block_root
                     for n in group}
            name = ("all_nodes_agree_on_head" if len(groups) == 1
                    else f"group{gi}_agrees_on_head")
            out.append(CheckResult(name, len(heads) == 1,
                                   f"{len(heads)} distinct heads"))
        ref = groups[0][0].harness.chain
        slot = ref.slot()
        head_slot = ref.head().head_state.slot
        out.append(CheckResult(
            "liveness", head_slot >= slot - 1,
            f"head {head_slot} vs clock {slot}"))
        fin = ref.finalized_checkpoint()[0]
        out.append(CheckResult(
            "finalization", fin >= max(0, min_epochs - 2),
            f"finalized epoch {fin}"))
        blocks_per_node = [n.vc.published_blocks for n in self.nodes
                           if n.vc is not None]
        out.append(CheckResult(
            "all_nodes_proposed", all(b > 0 for b in blocks_per_node),
            f"{blocks_per_node}"))
        out.append(CheckResult(
            "convergence_clean", not self.convergence_failures,
            f"{len(self.convergence_failures)} timeouts"))
        # sync-aggregate participation on recent blocks
        body = ref.head().head_block.message.body
        if hasattr(body, "sync_aggregate"):
            bits = body.sync_aggregate.sync_committee_bits
            rate = sum(1 for b in bits if b) / max(1, len(bits))
            out.append(CheckResult("sync_participation", rate > 0.5,
                                   f"{rate:.2f}"))
        return out

    def stop(self) -> None:
        for n in self.nodes:
            if not n.dead:
                n.network.stop()
            if n.api_server is not None and not n.dead:
                n.api_server.stop()


def write_stitched_trace(path: str, spans=None) -> str:
    """Dump the span ring (the whole in-process fleet shares one) as a
    stitched Chrome trace: one pid per node label, graftpath flow arrows
    for the cross-node publish->deliver/import edges — loads in Perfetto
    as a fleet, not a soup (obs/causal.py, ISSUE 13)."""
    import json
    from ..obs import causal, tracing
    doc = causal.stitched_chrome_trace(
        tracing.snapshot() if spans is None else spans)
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--validators", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--http", action="store_true",
                    help="drive VCs through real per-node HTTP APIs")
    ap.add_argument("--scenario", default=None,
                    help="run a named adversarial scenario "
                         "(see testing/scenarios.py) instead of the "
                         "plain liveness sim")
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-injection seed (scenarios only)")
    ap.add_argument("--dump-trace", default=None, metavar="PATH",
                    help="after the run, write the stitched cross-node "
                         "Chrome trace (one pid per node) to PATH")
    args = ap.parse_args(argv)
    if args.scenario:
        from .scenarios import run_scenario, scenario_names
        if args.scenario == "list":
            for name in scenario_names():
                print(name)
            return 0
        result = run_scenario(args.scenario, seed=args.seed)
        print(result.render())
        if args.dump_trace:
            print(f"stitched trace -> "
                  f"{write_stitched_trace(args.dump_trace)}")
        return 0 if result.ok else 1
    spec = minimal_spec(altair_fork_epoch=0)
    net = LocalNetwork(spec, args.nodes, args.validators,
                       use_http=args.http)
    try:
        net.run_slots(args.epochs * spec.preset.slots_per_epoch)
        results = net.checks(args.epochs)
    finally:
        net.stop()
    if args.dump_trace:
        print(f"stitched trace -> {write_stitched_trace(args.dump_trace)}")
    ok = True
    for r in results:
        print(f"[{'PASS' if r.ok else 'FAIL'}] {r.name}: {r.detail}")
        ok &= r.ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
