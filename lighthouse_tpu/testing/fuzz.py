"""Randomized SSZ fuzzing + field-level state diffing.

Analogs of two reference facilities (VERDICT r4 missing #5):
- the `arbitrary` derives on all consensus types (workspace
  Cargo.toml:110, consensus/types `arbitrary` feature): `arbitrary(typ)`
  builds a random value of ANY SSZ type by walking its type structure,
  for round-trip properties (serialize -> deserialize -> identical bytes
  and root) and malformed-decode fuzzing (`mutate`);
- `compare_fields` (common/compare_fields): `compare_containers` walks
  two container values and returns the paths that differ; `state_diff`
  does the same for BeaconState via its per-field serializations.

Decode-fuzz contract: `deserialize(typ, mutate(valid_bytes))` must
either raise DeserializeError or return a value that re-serializes
canonically — any other exception type, crash, or non-canonical accept
is a codec bug.
"""
from __future__ import annotations

import random

from ..ssz.codec import DeserializeError, deserialize, serialize
from ..ssz.types import (
    Bitlist, Bitvector, Boolean, ByteList, ByteVector, Container, List,
    SSZType, UInt, Union, UnionValue, Vector, default_value,
)

MAX_LIST_FUZZ = 4       # keep generated lists small: shape, not volume


def arbitrary(typ: SSZType, rng: random.Random, depth: int = 0):
    """A random value of any SSZ type (bounded recursion)."""
    if isinstance(typ, Boolean):
        return rng.random() < 0.5
    if isinstance(typ, UInt):
        # bias to edge values: 0, max, small, random
        roll = rng.random()
        top = (1 << (8 * typ.byte_len)) - 1
        if roll < 0.25:
            return 0
        if roll < 0.5:
            return top
        if roll < 0.75:
            return rng.randrange(0, 256)
        return rng.randrange(0, top + 1)
    if isinstance(typ, ByteVector):
        return bytes(rng.getrandbits(8) for _ in range(typ.length))
    if isinstance(typ, ByteList):
        n = rng.randrange(0, min(typ.limit, 2 * MAX_LIST_FUZZ) + 1)
        return bytes(rng.getrandbits(8) for _ in range(n))
    if isinstance(typ, Bitvector):
        return [rng.random() < 0.5 for _ in range(typ.length)]
    if isinstance(typ, Bitlist):
        n = rng.randrange(0, min(typ.limit, 8 * MAX_LIST_FUZZ) + 1)
        return [rng.random() < 0.5 for _ in range(n)]
    if isinstance(typ, Vector):
        return [arbitrary(typ.elem, rng, depth + 1)
                for _ in range(typ.length)]
    if isinstance(typ, List):
        if depth > 6:
            return []
        n = rng.randrange(0, min(typ.limit, MAX_LIST_FUZZ) + 1)
        return [arbitrary(typ.elem, rng, depth + 1) for _ in range(n)]
    if isinstance(typ, Container):
        kwargs = {}
        for name, ftyp in typ.fields:
            kwargs[name] = (arbitrary(ftyp, rng, depth + 1)
                            if depth <= 8 else default_value(ftyp))
        return typ.cls(**kwargs)
    if isinstance(typ, Union):
        sel = rng.randrange(len(typ.options))
        opt = typ.options[sel]
        val = None if opt is None else arbitrary(opt, rng, depth + 1)
        return UnionValue(sel, val)
    raise TypeError(f"arbitrary: unhandled SSZ type {typ!r}")


def mutate(data: bytes, rng: random.Random) -> bytes:
    """One random structural corruption of a serialization."""
    if not data:
        return bytes([rng.getrandbits(8)])
    op = rng.randrange(5)
    buf = bytearray(data)
    i = rng.randrange(len(buf))
    if op == 0:                          # bit flip
        buf[i] ^= 1 << rng.randrange(8)
    elif op == 1:                        # truncate
        del buf[rng.randrange(len(buf)):]
    elif op == 2:                        # extend with junk
        buf += bytes(rng.getrandbits(8)
                     for _ in range(1 + rng.randrange(8)))
    elif op == 3:                        # byte splice (offset confusion)
        j = rng.randrange(len(buf))
        buf[i], buf[j] = buf[j], buf[i]
        buf[i] = rng.getrandbits(8)
    else:                                # zero a 4-byte window (offsets)
        buf[i:i + 4] = b"\x00" * min(4, len(buf) - i)
    return bytes(buf)


def fuzz_decode_one(typ: SSZType, data: bytes) -> str:
    """-> 'rejected' | 'accepted' (canonically) — raises on codec bugs."""
    try:
        val = deserialize(typ, data)
    except DeserializeError:
        return "rejected"
    # accepted: must re-serialize to EXACTLY the accepted bytes
    # (SSZ decoding is bijective on valid encodings; a non-canonical
    # accept means two wire forms map to one value)
    out = serialize(typ, val)
    if out != data:
        raise AssertionError(
            f"non-canonical accept: {data.hex()} != {out.hex()}")
    return "accepted"


# ---------------------------------------------------------------------------
# field-level diffing (common/compare_fields analog)
# ---------------------------------------------------------------------------

def compare_containers(a, b, typ: SSZType, path: str = "") -> list[str]:
    """Paths at which two values of `typ` differ (leaf-level)."""
    diffs: list[str] = []
    if isinstance(typ, Container):
        for name, ftyp in typ.fields:
            diffs += compare_containers(getattr(a, name),
                                        getattr(b, name), ftyp,
                                        f"{path}.{name}" if path else name)
        return diffs
    if isinstance(typ, (Vector, List)):
        la, lb = list(a), list(b)
        if len(la) != len(lb):
            return [f"{path}.len({len(la)}!={len(lb)})"]
        for i, (xa, xb) in enumerate(zip(la, lb)):
            diffs += compare_containers(xa, xb, typ.elem,
                                        f"{path}[{i}]")
        return diffs
    if isinstance(a, (bytes, bytearray)) or not isinstance(typ, Union):
        if (bytes(a) if isinstance(a, (bytes, bytearray)) else a) != \
                (bytes(b) if isinstance(b, (bytes, bytearray)) else b):
            return [path]
        return []
    if a.selector != b.selector or a.value != b.value:
        return [path]
    return []


def state_diff(a, b) -> list[str]:
    """Differing BeaconState field names via per-field serializations
    (the compare_fields debugging workflow for the SoA state)."""
    from ..containers.state import active_field_specs
    if a.fork_name != b.fork_name:
        return [f"fork({a.fork_name}!={b.fork_name})"]
    out = []
    for f in active_field_specs(a.T, a.fork_name):
        pa, _ = a._field_serialize(f)
        pb, _ = b._field_serialize(f)
        if pa != pb:
            out.append(f.name)
    return out
