"""Park-and-replay queue for early/unresolvable work.

Equivalent of beacon_processor/src/work_reprocessing_queue.rs (:1-60):
- early-arriving gossip blocks are parked until their slot starts and
  re-enter the processor's priority queues at the boundary;
- attestations/aggregates referencing an unknown block root are parked
  and replayed the moment that block imports (the reference replays via
  the `BlockImported` reprocess event);
- future-slot attestations are parked until their slot;
- buckets are bounded, and unresolved by-root parks expire after
  EXPIRY_SLOTS so a junk root can't pin memory forever.

The queue holds `Work` items and re-enters them through the submitter
(BeaconProcessor.submit), so replayed work flows through the same
priority scheduling as fresh gossip.
"""
from __future__ import annotations

import threading
from collections import defaultdict


class ReprocessQueue:
    EXPIRY_SLOTS = 64          # by-root parks older than this are dropped
    MAX_FUTURE_SLOTS = 64      # refuse parks this far past the clock

    def __init__(self, submit):
        self._submit = submit                 # BeaconProcessor.submit
        self._closed = False
        self._by_slot: dict[int, list] = defaultdict(list)
        # root -> (parked_at_slot, [work, ...])
        self._by_root: dict[bytes, tuple[int, list]] = {}
        self._lock = threading.Lock()
        self.max_per_bucket = 1024
        # Global bound across ALL by-root buckets: UNKNOWN_HEAD parks are
        # taken before any signature check, so an attacker gossiping random
        # roots must not open unbounded buckets inside the expiry window
        # (reference: work_reprocessing_queue.rs MAXIMUM_QUEUED_ATTESTATIONS).
        self.max_by_root_total = 16384
        self._by_root_count = 0
        self.parked_total = 0
        self.replayed_total = 0
        self.expired_total = 0
        self.refused_total = 0

    def close(self) -> None:
        """Sever the injected submitter: called from the owning
        BeaconProcessor's stop(), so a slot tick or late block import
        racing the teardown drops its replays instead of landing them in
        the stopped processor's queues."""
        self._closed = True

    def park_until_slot(self, slot: int, work,
                        current_slot: int | None = None) -> None:
        """Parks are clock-bounded: future_slot is raised BEFORE any
        signature check, so attacker-chosen far-future slots must not pin
        memory (each distinct slot would otherwise open a fresh bucket)."""
        if current_slot is not None and \
                slot > current_slot + self.MAX_FUTURE_SLOTS:
            with self._lock:
                self.refused_total += 1
            return
        with self._lock:
            bucket = self._by_slot[slot]
            if len(bucket) < self.max_per_bucket:
                bucket.append(work)
                self.parked_total += 1

    def park_until_block(self, block_root: bytes, work,
                         current_slot: int = 0) -> None:
        with self._lock:
            if self._by_root_count >= self.max_by_root_total:
                self.refused_total += 1
                return
            parked_at, bucket = self._by_root.get(block_root,
                                                  (current_slot, []))
            if len(bucket) < self.max_per_bucket:
                bucket.append(work)
                self.parked_total += 1
                self._by_root_count += 1
            else:
                self.refused_total += 1       # full bucket: drop, visibly
            self._by_root[block_root] = (parked_at, bucket)

    def on_slot(self, slot: int) -> int:
        """Replay everything parked for slots <= slot; expire stale
        by-root parks (their block never arrived)."""
        with self._lock:
            due = [w for s in list(self._by_slot)
                   if s <= slot for w in self._by_slot.pop(s)]
            for root in list(self._by_root):
                parked_at, bucket = self._by_root[root]
                if parked_at + self.EXPIRY_SLOTS < slot:
                    self._by_root.pop(root)
                    self.expired_total += len(bucket)
                    self._by_root_count -= len(bucket)
        if self._closed:
            return 0                  # owner stopping: drop, don't submit
        for w in due:
            self._submit(w)
        if due:
            from ..api import metrics_defs as M
            M.count("beacon_processor_reprocess_total", len(due))
        with self._lock:
            self.replayed_total += len(due)
        return len(due)

    def on_block_imported(self, block_root: bytes) -> int:
        with self._lock:
            _at, due = self._by_root.pop(block_root, (0, []))
            self._by_root_count -= len(due)
        if self._closed:
            return 0                  # owner stopping: drop, don't submit
        for w in due:
            self._submit(w)
        if due:
            from ..api import metrics_defs as M
            M.count("beacon_processor_reprocess_total", len(due))
        with self._lock:
            self.replayed_total += len(due)
        return len(due)

    @property
    def parked(self) -> int:
        with self._lock:
            return (sum(len(b) for b in self._by_slot.values())
                    + sum(len(b) for _a, b in self._by_root.values()))
