"""Park-and-replay queue for early/unresolvable work.

Equivalent of beacon_processor/src/work_reprocessing_queue.rs: early-arriving
gossip (future-slot attestations/blocks) and attestations for unknown blocks
are parked and re-enqueued when their slot arrives or their block is
imported.
"""
from __future__ import annotations

import threading
from collections import defaultdict


class ReprocessQueue:
    def __init__(self, submit):
        self._submit = submit                 # BeaconProcessor.submit
        self._by_slot: dict[int, list] = defaultdict(list)
        self._by_root: dict[bytes, list] = defaultdict(list)
        self._lock = threading.Lock()
        self.max_per_bucket = 1024

    def park_until_slot(self, slot: int, work) -> None:
        with self._lock:
            bucket = self._by_slot[slot]
            if len(bucket) < self.max_per_bucket:
                bucket.append(work)

    def park_until_block(self, block_root: bytes, work) -> None:
        with self._lock:
            bucket = self._by_root[block_root]
            if len(bucket) < self.max_per_bucket:
                bucket.append(work)

    def on_slot(self, slot: int) -> int:
        """Replay everything parked for slots <= slot."""
        with self._lock:
            due = [w for s in list(self._by_slot)
                   if s <= slot for w in self._by_slot.pop(s)]
        for w in due:
            self._submit(w)
        return len(due)

    def on_block_imported(self, block_root: bytes) -> int:
        with self._lock:
            due = self._by_root.pop(block_root, [])
        for w in due:
            self._submit(w)
        return len(due)
