"""The processor itself."""
from __future__ import annotations

import enum
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from ..obs import tracing


class WorkType(enum.Enum):
    # priority order (beacon_processor/src/lib.rs queue drain order)
    CHAIN_SEGMENT_BACKFILL = 0
    GOSSIP_BLOCK = 1
    GOSSIP_BLOB_SIDECAR = 2
    RPC_BLOCK = 3
    CHAIN_SEGMENT = 4
    GOSSIP_AGGREGATE = 5
    GOSSIP_AGGREGATE_BATCH = 6
    GOSSIP_ATTESTATION = 7
    GOSSIP_ATTESTATION_BATCH = 8
    STATUS = 9
    GOSSIP_VOLUNTARY_EXIT = 10
    GOSSIP_PROPOSER_SLASHING = 11
    GOSSIP_ATTESTER_SLASHING = 12
    API_REQUEST = 13


#: queues drained in this order each scheduling round
PRIORITY_ORDER = [
    WorkType.GOSSIP_BLOCK, WorkType.GOSSIP_BLOB_SIDECAR, WorkType.RPC_BLOCK,
    WorkType.CHAIN_SEGMENT, WorkType.STATUS, WorkType.GOSSIP_AGGREGATE,
    WorkType.GOSSIP_ATTESTATION, WorkType.GOSSIP_VOLUNTARY_EXIT,
    WorkType.GOSSIP_PROPOSER_SLASHING, WorkType.GOSSIP_ATTESTER_SLASHING,
    WorkType.API_REQUEST, WorkType.CHAIN_SEGMENT_BACKFILL,
]

#: per-queue caps (scaled by validator count in the reference, lib.rs:97-130)
DEFAULT_CAPS = {
    WorkType.GOSSIP_ATTESTATION: 16384,
    WorkType.GOSSIP_AGGREGATE: 4096,
    WorkType.GOSSIP_BLOCK: 1024,
    WorkType.GOSSIP_BLOB_SIDECAR: 1024,
    WorkType.RPC_BLOCK: 1024,
    WorkType.CHAIN_SEGMENT: 64,
    WorkType.CHAIN_SEGMENT_BACKFILL: 64,
}


@dataclass
class Work:
    kind: WorkType
    run: Callable[[], Any]
    batchable_payload: Any = None  # set for attestation work, enables batching
    #: (trace_id, span_id) captured at submit time so the worker's spans
    #: join the submitting thread's trace (graftscope queue-hop rule)
    trace_ctx: Any = None
    #: perf_counter at submit — the worker's span reports the queue wait
    #: (enqueue -> execution start) so the critical path can split
    #: queue-wait from service time (obs/critpath.py)
    enqueued_at: float = 0.0


class BeaconProcessor:
    """Manager + bounded blocking worker pool. Gossip attestation/aggregate
    queues are drained opportunistically into batch work items
    (lib.rs:561)."""

    MAX_BATCH = 64

    def __init__(self, num_workers: int = 4,
                 batch_handler: Callable | None = None,
                 aggregate_batch_handler: Callable | None = None):
        from .reprocess import ReprocessQueue
        from ..utils.threads import ThreadGroup
        self.queues: dict[WorkType, deque] = {w: deque() for w in WorkType}
        self.reprocess = ReprocessQueue(self.submit)
        self.caps = dict(DEFAULT_CAPS)
        self.batch_handler = batch_handler
        self.aggregate_batch_handler = aggregate_batch_handler
        self._idle = threading.Semaphore(num_workers)
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._stop = False
        self.num_workers = num_workers
        self._workers = ThreadGroup("beacon_processor")
        self._manager = threading.Thread(target=self._run, daemon=True,
                                         name="beacon_processor.manager")
        self.dropped = 0
        self.processed = 0
        self.high_water = 0     # max total pending ever seen (scenarios)
        # graftwatch flight dumps include per-queue depths
        from ..obs import graftwatch
        graftwatch.register_processor(self)

    def start(self) -> None:
        self._manager.start()

    def stop(self, join: bool = True) -> None:
        """Stop the manager loop; by default JOIN it and the in-flight
        workers so no processor thread outlives the chain/network it
        touches (clean-shutdown discipline, task_executor/src/lib.rs)."""
        self._stop = True
        self.reprocess.close()
        self._event.set()
        if join:
            if self._manager.is_alive() and \
                    self._manager is not threading.current_thread():
                self._manager.join(timeout=2)
            self._workers.join_all(timeout=2)

    def submit(self, work: Work) -> bool:
        if work.trace_ctx is None:
            work.trace_ctx = tracing.capture()
        if not work.enqueued_at:
            work.enqueued_at = time.perf_counter()
        with self._lock:
            q = self.queues[work.kind]
            cap = self.caps.get(work.kind, 4096)
            shed = len(q) >= cap
            if shed:
                # drop oldest (gossip) — lossy under overload by design
                q.popleft()
                self.dropped += 1
            q.append(work)
            pending = sum(len(qq) for qq in self.queues.values())
            if pending > self.high_water:
                self.high_water = pending
        from ..api import metrics_defs as M
        if shed:
            M.count("beacon_processor_work_dropped_total")
        M.count("beacon_processor_work_events_total")
        M.gauge("beacon_processor_queue_length", pending)
        self._event.set()
        return True

    def _next_work(self) -> Work | list[Work] | None:
        with self._lock:
            for kind in PRIORITY_ORDER:
                q = self.queues[kind]
                if not q:
                    continue
                if kind in (WorkType.GOSSIP_ATTESTATION,
                            WorkType.GOSSIP_AGGREGATE) and len(q) > 1:
                    batch = []
                    while q and len(batch) < self.MAX_BATCH:
                        batch.append(q.popleft())
                    return batch
                return q.popleft()
        return None

    def _run(self) -> None:
        while not self._stop:
            work = self._next_work()
            if work is None:
                self._event.wait(timeout=0.05)
                self._event.clear()
                continue
            self._idle.acquire()
            self._workers.spawn(self._execute, work,
                                name="beacon_processor.worker")

    def _execute(self, work) -> None:
        first = work[0] if isinstance(work, list) else work
        batch = len(work) if isinstance(work, list) else 1
        from ..api import metrics_defs as M
        idle = getattr(self._idle, "_value", None)
        if idle is not None:
            M.gauge("beacon_processor_workers_active",
                    self.num_workers - idle)
        # re-attach the submitter's trace so the queue hop doesn't break
        # the block's gossip->db-write trace; batches adopt the first
        # item's context (they are one fused device call anyway)
        with tracing.attach(first.trace_ctx), \
                tracing.span("processor_work", work_kind=first.kind.name,
                             batch=batch) as s:
            if first.enqueued_at:
                s.annotate(queue_wait_s=round(
                    max(0.0, s.start - first.enqueued_at), 9))
            self._execute_inner(work)

    def _execute_inner(self, work) -> None:
        try:
            if isinstance(work, list):
                kind = work[0].kind
                handler = (self.batch_handler
                           if kind == WorkType.GOSSIP_ATTESTATION
                           else self.aggregate_batch_handler)
                if handler is not None:
                    payloads = [w.batchable_payload for w in work
                                if w.batchable_payload is not None]
                    if payloads:
                        handler(payloads)
                    # replayed (parked) items carry no payload — they
                    # re-run their full verification closure
                    for w in work:
                        if w.batchable_payload is None:
                            w.run()
                else:
                    for w in work:
                        w.run()
                with self._lock:
                    self.processed += len(work)
            else:
                handler = (self.batch_handler
                           if work.kind == WorkType.GOSSIP_ATTESTATION
                           else self.aggregate_batch_handler
                           if work.kind == WorkType.GOSSIP_AGGREGATE
                           else None)
                if handler is not None and work.batchable_payload is not None:
                    # a lone gossip item is a batch of one — its run() is
                    # a no-op placeholder and the payload must still reach
                    # the handler
                    handler([work.batchable_payload])
                else:
                    work.run()
                with self._lock:
                    self.processed += 1
        except Exception:
            import logging
            logging.getLogger("lighthouse_tpu.processor").exception(
                "work item failed")
        finally:
            self._idle.release()
            self._event.set()

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Test helper: block until all queues drained and workers idle."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                empty = all(not q for q in self.queues.values())
            if empty:
                got = 0
                for _ in range(self.num_workers):
                    if self._idle.acquire(timeout=0.2):
                        got += 1
                for _ in range(got):
                    self._idle.release()
                if got == self.num_workers:
                    return True
            time.sleep(0.01)
        return False
