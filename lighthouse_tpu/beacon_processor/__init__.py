"""Priority work scheduler.

Equivalent of /root/reference/beacon_node/beacon_processor (src/lib.rs:
552-612 Work enum, :758 spawn_manager, work_reprocessing_queue.rs): a
manager drains typed queues in strict priority order into a bounded worker
pool; early-arriving work is parked and replayed; gossip attestations are
opportunistically drained into batches (the TPU batch-verify feeder).
"""
from .processor import BeaconProcessor, Work, WorkType
from .reprocess import ReprocessQueue
