"""Array-backed (SoA) BeaconState with device merkleization.

The reference keeps the BeaconState in `milhouse` persistent trees with lazy
tree-hash caches (consensus/types/src/beacon_state.rs:219-223,339-525 and
`update_tree_hash_cache` :2031-2046). The TPU-native redesign instead keeps
the big per-validator columns as dense numpy/JAX arrays (structure of arrays),
so that:

- epoch processing is vectorized array arithmetic (state_transition/epoch.py),
- merkleization batches onto the TPU hash-tree kernel (ops/sha256.py),
- copies are O(bytes) memcpy of flat arrays, not object graphs.

Small scalar fields stay Python objects. A per-field root cache with explicit
dirty tracking plays the role of milhouse's lazily-flushed tree caches.
"""
from __future__ import annotations

import sys
import time
from dataclasses import dataclass, field as dfield
from typing import Any

import numpy as np

from ..specs.chain_spec import ChainSpec, ForkName
from ..specs.constants import JUSTIFICATION_BITS_LENGTH
from ..ssz import (
    Bitvector, List as SSZList, Root, Vector, hash_tree_root, htr,
    merkleize_chunks, mix_in_length, pack_bytes, serialize, uint8, uint64,
)
from ..ssz.codec import BYTES_PER_LENGTH_OFFSET, DeserializeError, deserialize
from ..utils.hash import ZERO_HASHES, hash_concat

_USE_HOST_HASH = None


def _use_host_hash() -> bool:
    """True when the big-column rehash should run on the HOST (SHA-NI
    C++ batch hasher) instead of the XLA kernels: no accelerator attached
    (CPU backend) and the native library builds.  This mirrors the
    reference's sha2-asm host path; the device kernels stay the TPU
    path."""
    global _USE_HOST_HASH
    if _USE_HOST_HASH is None:
        from ..utils import native_hash as nh
        if nh.get_lib() is None:
            _USE_HOST_HASH = False
        else:
            try:
                import jax
                _USE_HOST_HASH = jax.default_backend() == "cpu"
            except Exception:
                _USE_HOST_HASH = True
    return _USE_HOST_HASH
from .core import Types, get_types
from .cow import CowColumn


def _np_bytes32_root(arr: np.ndarray, limit: int | None,
                     length: int | None = None, device: bool = True) -> bytes:
    """Root of an (N, 32) uint8 array as Vector/List[Bytes32]."""
    from ..ops import sha256 as k
    n = arr.shape[0]
    leaves = (k.chunks_to_words(arr.tobytes()) if n
              else np.zeros((0, 8), np.uint32))
    root = k.words_to_chunks(np.asarray(
        k.merkleize_words(leaves, limit if limit else max(1, n))))
    if length is not None:
        root = mix_in_length(root, length)
    return root


def _np_uint_root(arr: np.ndarray, limit_chunks: int,
                  length: int | None = None) -> bytes:
    """Root of a packed little-endian uint array (uint64/uint8 columns)."""
    from ..ops import sha256 as k
    data = arr.tobytes()
    pad = (-len(data)) % 32
    if pad:
        data += b"\x00" * pad
    leaves = (k.chunks_to_words(data) if data
              else np.zeros((0, 8), np.uint32))
    root = k.words_to_chunks(np.asarray(k.merkleize_words(leaves, limit_chunks)))
    if length is not None:
        root = mix_in_length(root, length)
    return root


@dataclass
class ValidatorView:
    """Scalar view of one validator (mirrors types::Validator)."""
    pubkey: bytes
    withdrawal_credentials: bytes
    effective_balance: int
    slashed: bool
    activation_eligibility_epoch: int
    activation_epoch: int
    exit_epoch: int
    withdrawable_epoch: int


class ValidatorRegistry:
    """SoA validator registry: one numpy column per field.

    Mutations go through setters that mark the root cache dirty — the
    array-oriented analog of milhouse's dirty-leaf tracking.
    """

    COLUMNS = ("pubkeys", "withdrawal_credentials", "effective_balance",
               "slashed", "activation_eligibility_epoch", "activation_epoch",
               "exit_epoch", "withdrawable_epoch")

    def __setattr__(self, name, value):
        # column rebinds (appends, epoch sweeps, test fixtures) land as
        # CoW columns so copy() is chunk-pointer work, not 128 MB memcpy
        if name in ValidatorRegistry.COLUMNS and \
                not isinstance(value, CowColumn):
            value = CowColumn(value)
        object.__setattr__(self, name, value)

    def __init__(self, n: int = 0):
        self.pubkeys = np.zeros((n, 48), dtype=np.uint8)
        self.withdrawal_credentials = np.zeros((n, 32), dtype=np.uint8)
        self.effective_balance = np.zeros(n, dtype=np.uint64)
        self.slashed = np.zeros(n, dtype=bool)
        self.activation_eligibility_epoch = np.zeros(n, dtype=np.uint64)
        self.activation_epoch = np.zeros(n, dtype=np.uint64)
        self.exit_epoch = np.zeros(n, dtype=np.uint64)
        self.withdrawable_epoch = np.zeros(n, dtype=np.uint64)
        self._dirty = True
        self._root_cache: bytes | None = None
        # device-resident incremental merkle tree (ops/merkle_tree): None =
        # rebuild everything; _dirty_rows tracks which validator rows need
        # re-encoding + a dirty-path rehash (milhouse-style O(diff) root)
        self._device_leaves = None   # legacy slot, kept for test/bench resets
        self._device_tree = None
        self._dirty_rows: set[int] | None = None
        # host-native twin (SHA-NI path when no accelerator is attached):
        # incremental merkle tree, shared copy-on-write across copies
        self._host_tree = None
        self._host_shared = False

    def __len__(self) -> int:
        return self.pubkeys.shape[0]

    def mark_dirty(self, row: int | None = None) -> None:
        self._dirty = True
        if row is None:
            self._dirty_rows = None        # full rebuild
        elif self._dirty_rows is not None:
            self._dirty_rows.add(row)

    def mark_dirty_many(self, rows) -> None:
        """Vector form of mark_dirty for chunk-scatter column writes
        (effective-balance hysteresis sweep and friends)."""
        self._dirty = True
        if self._dirty_rows is not None:
            self._dirty_rows.update(
                np.unique(np.asarray(rows, np.int64)).tolist())

    def index_of(self, pubkey: bytes) -> int | None:
        """Pubkey -> validator index (the ValidatorPubkeyCache analog,
        beacon_chain/src/validator_pubkey_cache.rs:20)."""
        cache = getattr(self, "_pk_index", None)
        if cache is None or len(cache) != len(self):
            cache = {self.pubkeys[i].tobytes(): i for i in range(len(self))}
            self._pk_index = cache
        return cache.get(pubkey)

    def pubkey(self, i: int) -> bytes:
        return self.pubkeys[i].tobytes()

    def view(self, i: int) -> ValidatorView:
        return ValidatorView(
            pubkey=self.pubkeys[i].tobytes(),
            withdrawal_credentials=self.withdrawal_credentials[i].tobytes(),
            effective_balance=int(self.effective_balance[i]),
            slashed=bool(self.slashed[i]),
            activation_eligibility_epoch=int(
                self.activation_eligibility_epoch[i]),
            activation_epoch=int(self.activation_epoch[i]),
            exit_epoch=int(self.exit_epoch[i]),
            withdrawable_epoch=int(self.withdrawable_epoch[i]),
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self.view(i)

    def append(self, pubkey: bytes, withdrawal_credentials: bytes,
               effective_balance: int, slashed: bool,
               activation_eligibility_epoch: int, activation_epoch: int,
               exit_epoch: int, withdrawable_epoch: int) -> None:
        self.pubkeys = np.concatenate(
            [self.pubkeys, np.frombuffer(pubkey, np.uint8)[None]])
        self.withdrawal_credentials = np.concatenate(
            [self.withdrawal_credentials,
             np.frombuffer(withdrawal_credentials, np.uint8)[None]])
        for name, v in (("effective_balance", effective_balance),
                        ("activation_eligibility_epoch",
                         activation_eligibility_epoch),
                        ("activation_epoch", activation_epoch),
                        ("exit_epoch", exit_epoch),
                        ("withdrawable_epoch", withdrawable_epoch)):
            col = getattr(self, name)
            setattr(self, name, np.append(col, np.uint64(v)))
        self.slashed = np.append(self.slashed, bool(slashed))
        self.mark_dirty()

    def set_field(self, i: int, name: str, value) -> None:
        col = getattr(self, name)
        if name in ("pubkeys", "withdrawal_credentials"):
            col[i] = np.frombuffer(value, np.uint8)
        else:
            col[i] = value
        self.mark_dirty(int(i))

    def copy(self) -> "ValidatorRegistry":
        out = ValidatorRegistry.__new__(ValidatorRegistry)
        for c in self.COLUMNS:
            object.__setattr__(out, c, getattr(self, c).fork())
        out._dirty = self._dirty
        out._root_cache = self._root_cache
        # share the device tree, flagged so the next update on either copy
        # runs the non-donating program (donation would free buffers the
        # other copy still references); dirty-row sets must not be shared
        out._device_leaves = None
        out._device_tree = (self._device_tree.share()
                            if self._device_tree is not None else None)
        out._dirty_rows = (set(self._dirty_rows)
                           if self._dirty_rows is not None else None)
        # share the host merkle tree copy-on-write: whoever refreshes
        # next copies the levels first
        host = getattr(self, "_host_tree", None)
        out._host_tree = host
        if host is not None:
            self._host_shared = True
        out._host_shared = host is not None
        # pubkeys are append-only and immutable per row, so the
        # pubkey->index dict stays valid for both sides (and is seconds
        # of rebuild at 1M validators) — share it
        pk = getattr(self, "_pk_index", None)
        if pk is not None:
            object.__setattr__(out, "_pk_index", pk)
        return out

    # -- merkleization -------------------------------------------------------

    def _u64_words(self, arr: np.ndarray) -> np.ndarray:
        n = len(self)
        return np.frombuffer(arr.astype("<u8").tobytes(),
                             dtype=">u4").reshape(n, 2).astype(np.uint32)

    def validator_leaf_words(self, rows: np.ndarray | None = None
                             ) -> tuple[np.ndarray, np.ndarray]:
        """(chunks u32[R*8, 8], pk_blocks u32[R, 16]): the 8 field chunks
        per validator with chunk 0 left zero, plus the 64-byte pubkey
        block whose hash fills it — hashed on DEVICE inside the fused
        tree program (ops/merkle_tree, with_pk=True), so no host<->device
        round trip per update."""
        def col(a):
            return a if rows is None else a[rows]

        n = len(self) if rows is None else len(rows)
        # pubkey root preimage: pubkey(48) || zeros(16) as one 64B block
        pk_blocks = np.zeros((n, 64), dtype=np.uint8)
        pk_blocks[:, :48] = col(self.pubkeys)
        pk_words = np.frombuffer(pk_blocks.tobytes(), dtype=">u4").reshape(
            n, 16).astype(np.uint32)
        chunks = np.zeros((n, 8, 8), dtype=np.uint32)
        chunks[:, 1] = np.frombuffer(
            np.ascontiguousarray(col(self.withdrawal_credentials)).tobytes(),
            dtype=">u4").reshape(n, 8).astype(np.uint32)

        def u64w(a):
            return np.frombuffer(
                np.ascontiguousarray(col(a)).astype("<u8").tobytes(),
                dtype=">u4").reshape(n, 2).astype(np.uint32)

        chunks[:, 2, :2] = u64w(self.effective_balance)
        chunks[:, 3, 0] = (col(self.slashed).astype(np.uint32) << 24)
        chunks[:, 4, :2] = u64w(self.activation_eligibility_epoch)
        chunks[:, 5, :2] = u64w(self.activation_epoch)
        chunks[:, 6, :2] = u64w(self.exit_epoch)
        chunks[:, 7, :2] = u64w(self.withdrawable_epoch)
        return chunks.reshape(n * 8, 8), pk_words

    def validator_leaf_bytes(self, rows: np.ndarray | None = None
                             ) -> np.ndarray:
        """u8[R, 8, 32]: the 8 field chunks per validator with the pubkey
        pre-hashed on the HOST (SHA-NI batch) — the no-accelerator twin of
        validator_leaf_words."""
        from ..utils import native_hash as nh

        def col(a):
            return a if rows is None else a[rows]

        n = len(self) if rows is None else len(rows)
        out = np.zeros((n, 8, 32), dtype=np.uint8)
        pk_blocks = np.zeros((n, 64), dtype=np.uint8)
        pk_blocks[:, :48] = col(self.pubkeys)
        out[:, 0] = np.frombuffer(
            nh.hash64_batch(pk_blocks.tobytes()),
            dtype=np.uint8).reshape(n, 32)
        out[:, 1] = col(self.withdrawal_credentials)

        def u64b(a):
            return np.frombuffer(
                np.ascontiguousarray(col(a)).astype("<u8").tobytes(),
                dtype=np.uint8).reshape(n, 8)

        out[:, 2, :8] = u64b(self.effective_balance)
        out[:, 3, 0] = col(self.slashed).astype(np.uint8)
        out[:, 4, :8] = u64b(self.activation_eligibility_epoch)
        out[:, 5, :8] = u64b(self.activation_epoch)
        out[:, 6, :8] = u64b(self.exit_epoch)
        out[:, 7, :8] = u64b(self.withdrawable_epoch)
        return out

    def _validator_roots(self, rows: np.ndarray | None = None) -> np.ndarray:
        """u8[R, 32]: per-validator hash-tree-roots (3 SHA-NI levels over
        the 8 field chunks), host-side."""
        from ..utils import native_hash as nh
        buf = self.validator_leaf_bytes(rows).tobytes()
        for _ in range(3):
            buf = nh.hash64_batch(buf)
        n = len(self) if rows is None else len(rows)
        return np.frombuffer(buf, np.uint8).reshape(n, 32)

    def _host_tree_root(self, registry_limit: int) -> bytes:
        """Host rehash with incremental update_tree_hash_cache semantics:
        an all-levels SHA-NI tree over the per-validator roots, re-hashing
        only dirty validators' paths."""
        from ..utils import native_hash as nh
        n = len(self)
        tree = getattr(self, "_host_tree", None)
        dirty = self._dirty_rows
        if tree is None or dirty is None or tree.n != n:
            self._host_tree = nh.HostTree(self._validator_roots(),
                                          registry_limit)
            self._host_shared = False
        elif dirty:
            rows = np.fromiter(dirty, dtype=np.int64)
            rows.sort()
            if getattr(self, "_host_shared", False):
                from .cow import OVERLAY_MAX_LEAVES
                if len(rows) <= OVERLAY_MAX_LEAVES:
                    # fork fan-out: resolve dirty rows against the SHARED
                    # tree read-only (no ~2x-leaf-bytes level clone per
                    # fork); the dirty set stays pending
                    return mix_in_length(
                        nh.overlay_root(self._host_tree, rows,
                                        self._validator_roots(rows)), n)
                self._host_tree = self._host_tree.copy()
                self._host_shared = False
            self._host_tree.update(rows, self._validator_roots(rows))
        self._dirty_rows = set()
        self._device_tree = None     # consumed the dirty set
        return mix_in_length(self._host_tree.root(), n)

    def _device_root_words(self, registry_limit: int):
        """Incremental device tree root: full build when the tree is stale
        (size change / wholesale mutation), else a fused dirty-path update
        (ops/merkle_tree.DeviceTree: scatter + O(dirty * depth) rehash +
        zero caps in ONE compiled program)."""
        from ..ops.merkle_tree import DeviceTree
        n = len(self)
        tree = self._device_tree
        if tree is None or self._dirty_rows is None or tree.n != n:
            tree = DeviceTree(n, registry_limit, pre_levels=3, with_pk=True)
            chunks, pk = self.validator_leaf_words()
            tree.build(chunks, pk)
            self._device_tree = tree
        elif self._dirty_rows:
            rows = np.fromiter(self._dirty_rows, dtype=np.int64)
            rows.sort()
            chunks, pk = self.validator_leaf_words(rows)
            tree.update(rows, chunks, pk)
        self._dirty_rows = set()
        self._host_tree = None       # consumed the dirty set
        return tree.root_words

    def hash_tree_root(self, registry_limit: int) -> bytes:
        if not self._dirty and self._root_cache is not None:
            return self._root_cache
        import sys
        import time
        t0 = time.perf_counter()
        from ..ops import sha256 as k
        n = len(self)
        if n == 0:
            depth = (registry_limit - 1).bit_length()
            root = mix_in_length(ZERO_HASHES[depth], 0)
        elif _use_host_hash():
            root = self._host_tree_root(registry_limit)
        else:
            root_words = self._device_root_words(registry_limit)
            root = mix_in_length(
                k.words_to_chunks(np.asarray(root_words)), n)
        self._root_cache = root
        self._dirty = False
        m = sys.modules.get("lighthouse_tpu.api.metrics")
        if m is not None:
            m.observe("validator_registry_tree_hash_seconds",
                      time.perf_counter() - t0)
        return root

    def serialize(self) -> bytes:
        """SSZ List[Validator] body: 121 bytes per validator, fixed size."""
        n = len(self)
        out = np.zeros((n, 121), dtype=np.uint8)
        out[:, 0:48] = self.pubkeys
        out[:, 48:80] = self.withdrawal_credentials
        out[:, 80:88] = np.frombuffer(
            self.effective_balance.astype("<u8").tobytes(),
            np.uint8).reshape(n, 8)
        out[:, 88] = self.slashed.astype(np.uint8)
        for off, name in ((89, "activation_eligibility_epoch"),
                          (97, "activation_epoch"), (105, "exit_epoch"),
                          (113, "withdrawable_epoch")):
            out[:, off:off + 8] = np.frombuffer(
                getattr(self, name).astype("<u8").tobytes(),
                np.uint8).reshape(n, 8)
        return out.tobytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ValidatorRegistry":
        if len(data) % 121:
            raise DeserializeError("validator registry size not multiple of 121")
        n = len(data) // 121
        arr = np.frombuffer(data, np.uint8).reshape(n, 121)
        out = cls(n)
        out.pubkeys = arr[:, 0:48].copy()
        out.withdrawal_credentials = arr[:, 48:80].copy()
        out.effective_balance = np.frombuffer(
            arr[:, 80:88].tobytes(), "<u8").copy()
        out.slashed = arr[:, 88].astype(bool)
        for off, name in ((89, "activation_eligibility_epoch"),
                          (97, "activation_epoch"), (105, "exit_epoch"),
                          (113, "withdrawable_epoch")):
            setattr(out, name, np.frombuffer(
                arr[:, off:off + 8].tobytes(), "<u8").copy())
        return out

    @classmethod
    def from_views(cls, views) -> "ValidatorRegistry":
        out = cls(0)
        for v in views:
            out.append(v.pubkey, v.withdrawal_credentials,
                       v.effective_balance, v.slashed,
                       v.activation_eligibility_epoch, v.activation_epoch,
                       v.exit_epoch, v.withdrawable_epoch)
        return out


class BalancesColumn:
    """Device-resident packed-uint column with dirty-chunk scatter — the
    List[uintN, VALIDATOR_REGISTRY_LIMIT] analog of the registry's
    milhouse-style leaf cache (32/itemsize elements per 32-byte chunk).

    Parametrized over the element dtype (round 5): uint64 carries
    balances and inactivity_scores, uint8 the participation columns —
    every n-sized state column now shares this incremental tree.

    Steady-state rehash after k point-mutations moves only
    ceil(k/per_chunk) chunks host->device; the merkle sweep itself is
    all-device.
    """

    def __init__(self, values: np.ndarray, dtype=np.uint64):
        self.dtype = np.dtype(dtype)
        self.per_chunk = 32 // self.dtype.itemsize
        self.values = np.ascontiguousarray(values, dtype=self.dtype)
        self._device_leaves = None   # legacy slot, kept for test/bench resets
        self._device_tree = None
        self._host_tree = None
        self._host_shared = False
        self._dirty_chunks: set[int] | None = None  # None = full rebuild
        self._root_cache: bytes | None = None

    def __len__(self) -> int:
        return self.values.shape[0]

    def fork(self, values: np.ndarray) -> "BalancesColumn":
        """A second owner over a copied values array: trees are shared
        copy-on-write (the host tree clones on next update; the device
        tree switches to the non-donating program)."""
        out = BalancesColumn.__new__(BalancesColumn)
        out.dtype = self.dtype
        out.per_chunk = self.per_chunk
        out.values = np.ascontiguousarray(values, dtype=self.dtype)
        out._device_leaves = None
        out._device_tree = (self._device_tree.share()
                            if self._device_tree is not None else None)
        out._host_tree = self._host_tree
        if self._host_tree is not None:
            self._host_shared = True
        out._host_shared = self._host_tree is not None
        out._dirty_chunks = (set(self._dirty_chunks)
                             if self._dirty_chunks is not None else None)
        out._root_cache = self._root_cache
        return out

    def _chunk_bytes(self, chunks: np.ndarray | None = None) -> np.ndarray:
        """u8[C, 32] packed chunk bytes (per_chunk elements per chunk),
        for the whole column or a chunk subset — the single source of the
        chunk layout for both the host and device paths."""
        n = len(self)
        pc = self.per_chunk
        le = self.dtype.newbyteorder("<")
        if chunks is None:
            n_chunks = (n + pc - 1) // pc
            padded = np.zeros(n_chunks * pc, dtype=self.dtype)
            padded[:n] = self.values
        else:
            padded = np.zeros((len(chunks), pc), dtype=self.dtype)
            for j, c in enumerate(chunks):
                vals = self.values[c * pc:c * pc + pc]
                padded[j, :len(vals)] = vals
        return np.frombuffer(padded.astype(le).tobytes(),
                             np.uint8).reshape(-1, 32)

    def _chunk_words(self, chunks: np.ndarray | None = None) -> np.ndarray:
        """u32[C, 8] big-endian words of the packed chunks."""
        from ..ops import sha256 as k
        return k.chunks_to_words(self._chunk_bytes(chunks).tobytes())

    def mark_dirty(self, i: int) -> None:
        """Record an already-applied mutation of element ``i`` (the one
        place the invalidation invariant lives)."""
        self._root_cache = None
        if self._dirty_chunks is not None:
            self._dirty_chunks.add(int(i) // self.per_chunk)

    def set_many(self, rows: np.ndarray, values: np.ndarray) -> None:
        self.values[rows] = values
        self.mark_dirty_many(rows)

    def mark_dirty_many(self, rows) -> None:
        """Vector form of mark_dirty: one unique+divide over the row set
        instead of a per-row set.add (the attestation hot path touches a
        whole committee at once)."""
        self._root_cache = None
        if self._dirty_chunks is not None:
            chunks = np.unique(np.asarray(rows, np.int64) // self.per_chunk)
            self._dirty_chunks.update(chunks.tolist())

    def set(self, i: int, value: int) -> None:
        self.values[i] = value
        self.mark_dirty(i)

    def replace(self, values: np.ndarray) -> None:
        """Wholesale column replacement (epoch-processing rewards sweep)."""
        self.values = np.ascontiguousarray(values, dtype=self.dtype)
        self._root_cache = None
        self._dirty_chunks = None

    def _device_root_words(self, limit_chunks: int):
        """Incremental device tree root over the packed-u64 chunk leaves
        (same fused build/update programs as the validator registry)."""
        from ..ops.merkle_tree import DeviceTree
        pc = self.per_chunk
        n_chunks = (len(self) + pc - 1) // pc
        tree = self._device_tree
        if tree is None or self._dirty_chunks is None or tree.n != n_chunks:
            tree = DeviceTree(n_chunks, limit_chunks)
            tree.build(self._chunk_words())
            self._device_tree = tree
        elif self._dirty_chunks:
            idx = np.fromiter(self._dirty_chunks, dtype=np.int64)
            idx.sort()
            tree.update(idx, self._chunk_words(idx))
        self._dirty_chunks = set()
        self._host_tree = None       # consumed the dirty set
        return tree.root_words

    def hash_tree_root(self, registry_limit: int) -> bytes:
        if self._root_cache is not None:
            return self._root_cache
        from ..ops import sha256 as k
        n = len(self)
        limit_chunks = (registry_limit * self.dtype.itemsize + 31) // 32
        if n == 0:
            depth = (limit_chunks - 1).bit_length()
            root = mix_in_length(ZERO_HASHES[depth], 0)
        elif _use_host_hash():
            from ..utils import native_hash as nh
            n_chunks = (n + self.per_chunk - 1) // self.per_chunk
            tree = getattr(self, "_host_tree", None)
            if tree is None or self._dirty_chunks is None \
                    or tree.n != n_chunks:
                self._host_tree = nh.HostTree(self._chunk_bytes(),
                                              limit_chunks)
                self._host_shared = False
            elif self._dirty_chunks:
                if self._host_shared:
                    self._host_tree = self._host_tree.copy()
                    self._host_shared = False
                idx = np.fromiter(self._dirty_chunks, dtype=np.int64)
                idx.sort()
                self._host_tree.update(idx, self._chunk_bytes(idx))
            self._dirty_chunks = set()
            self._device_tree = None
            root = mix_in_length(self._host_tree.root(), n)
        else:
            root_words = self._device_root_words(limit_chunks)
            root = mix_in_length(k.words_to_chunks(np.asarray(root_words)), n)
        self._root_cache = root
        return root


# ---------------------------------------------------------------------------
# Field schema
# ---------------------------------------------------------------------------
# kind: 'ssz'      — generic SSZ value, type in `typ`
#       'ssz_list' — python list of containers, elem type in `typ`, limit
#       'roots_vec'— (N,32) uint8 numpy Vector[Root]
#       'roots_list'—(N,32) uint8 numpy List[Root] (limit)
#       'u64_vec'  — numpy uint64 Vector
#       'u64_list' — numpy uint64 List (limit)
#       'u8_list'  — numpy uint8 List (limit)  [participation flags]
#       'validators' — ValidatorRegistry

@dataclass
class FieldSpec:
    name: str
    kind: str
    typ: Any = None
    limit: int | None = None
    since: ForkName = ForkName.PHASE0
    until: ForkName | None = None  # exclusive


def state_field_specs(T: Types) -> list[FieldSpec]:
    p = T.preset
    F = ForkName
    vrl = p.validator_registry_limit
    return [
        FieldSpec("genesis_time", "ssz", uint64),
        FieldSpec("genesis_validators_root", "ssz", Root),
        FieldSpec("slot", "ssz", uint64),
        FieldSpec("fork", "ssz", T.Fork.ssz_type),
        FieldSpec("latest_block_header", "ssz", T.BeaconBlockHeader.ssz_type),
        FieldSpec("block_roots", "roots_vec", limit=p.slots_per_historical_root),
        FieldSpec("state_roots", "roots_vec", limit=p.slots_per_historical_root),
        FieldSpec("historical_roots", "roots_list",
                  limit=p.historical_roots_limit),
        FieldSpec("eth1_data", "ssz", T.Eth1Data.ssz_type),
        FieldSpec("eth1_data_votes", "ssz_list", T.Eth1Data.ssz_type,
                  limit=T.eth1_votes_limit),
        FieldSpec("eth1_deposit_index", "ssz", uint64),
        FieldSpec("validators", "validators", limit=vrl),
        FieldSpec("balances", "u64_list", limit=vrl),
        FieldSpec("randao_mixes", "roots_vec",
                  limit=p.epochs_per_historical_vector),
        FieldSpec("slashings", "u64_vec", limit=p.epochs_per_slashings_vector),
        FieldSpec("previous_epoch_attestations", "ssz_list",
                  T.PendingAttestation.ssz_type, limit=T.pending_att_limit,
                  until=F.ALTAIR),
        FieldSpec("current_epoch_attestations", "ssz_list",
                  T.PendingAttestation.ssz_type, limit=T.pending_att_limit,
                  until=F.ALTAIR),
        FieldSpec("previous_epoch_participation", "u8_list", limit=vrl,
                  since=F.ALTAIR),
        FieldSpec("current_epoch_participation", "u8_list", limit=vrl,
                  since=F.ALTAIR),
        FieldSpec("justification_bits", "ssz",
                  Bitvector(JUSTIFICATION_BITS_LENGTH)),
        FieldSpec("previous_justified_checkpoint", "ssz",
                  T.Checkpoint.ssz_type),
        FieldSpec("current_justified_checkpoint", "ssz",
                  T.Checkpoint.ssz_type),
        FieldSpec("finalized_checkpoint", "ssz", T.Checkpoint.ssz_type),
        FieldSpec("inactivity_scores", "u64_list", limit=vrl, since=F.ALTAIR),
        FieldSpec("current_sync_committee", "ssz", T.SyncCommittee.ssz_type,
                  since=F.ALTAIR),
        FieldSpec("next_sync_committee", "ssz", T.SyncCommittee.ssz_type,
                  since=F.ALTAIR),
        FieldSpec("latest_execution_payload_header", "payload_header",
                  since=F.BELLATRIX),
        FieldSpec("next_withdrawal_index", "ssz", uint64, since=F.CAPELLA),
        FieldSpec("next_withdrawal_validator_index", "ssz", uint64,
                  since=F.CAPELLA),
        FieldSpec("historical_summaries", "ssz_list",
                  T.HistoricalSummary.ssz_type,
                  limit=p.historical_roots_limit, since=F.CAPELLA),
        FieldSpec("deposit_requests_start_index", "ssz", uint64,
                  since=F.ELECTRA),
        FieldSpec("deposit_balance_to_consume", "ssz", uint64,
                  since=F.ELECTRA),
        FieldSpec("exit_balance_to_consume", "ssz", uint64, since=F.ELECTRA),
        FieldSpec("earliest_exit_epoch", "ssz", uint64, since=F.ELECTRA),
        FieldSpec("consolidation_balance_to_consume", "ssz", uint64,
                  since=F.ELECTRA),
        FieldSpec("earliest_consolidation_epoch", "ssz", uint64,
                  since=F.ELECTRA),
        FieldSpec("pending_deposits", "ssz_list", T.PendingDeposit.ssz_type,
                  limit=p.pending_deposits_limit, since=F.ELECTRA),
        FieldSpec("pending_partial_withdrawals", "ssz_list",
                  T.PendingPartialWithdrawal.ssz_type,
                  limit=p.pending_partial_withdrawals_limit, since=F.ELECTRA),
        FieldSpec("pending_consolidations", "ssz_list",
                  T.PendingConsolidation.ssz_type,
                  limit=p.pending_consolidations_limit, since=F.ELECTRA),
    ]


def active_field_specs(T: Types, fork: ForkName) -> list[FieldSpec]:
    return [f for f in state_field_specs(T)
            if f.since <= fork and (f.until is None or fork < f.until)]


# n-sized packed columns with incremental trees:
# field -> (cache attr, element dtype) — bound as hashed CowColumns by
# __setattr__; the legacy *_cache mirror attrs now point at the column
# itself (tests reset them; the root path no longer depends on them)
_COLUMN_CACHES = {
    "balances": ("_balances_cache", np.uint64),
    "inactivity_scores": ("_inactivity_cache", np.uint64),
    "previous_epoch_participation": ("_prev_part_cache", np.uint8),
    "current_epoch_participation": ("_curr_part_cache", np.uint8),
}

# fixed-length vector columns, CoW-wrapped (non-hashed) so copy() stays
# O(chunks) — randao_mixes alone is 2 MB/copy at mainnet shape; their
# roots remain full recomputes (_np_*_root) like before
_VEC_COLUMNS = {
    "block_roots": np.uint8,
    "state_roots": np.uint8,
    "randao_mixes": np.uint8,
    "slashings": np.uint64,
}


class BeaconState:
    """One class for all forks; fields outside the active fork are None.

    The balances column carries an incremental tree-hash cache (the
    update_tree_hash_cache discipline, reference consensus/types/src/
    beacon_state.rs:2031-2046): point mutations MUST go through
    ``increase_balance``/``decrease_balance`` (state_transition/helpers)
    or call ``mark_balances_dirty``; wholesale rebinds
    (``state.balances = arr``) are caught by ``__setattr__`` and trigger
    a full rebuild."""

    # legacy mirror attrs: now the bound CowColumn itself (tests null
    # them; the root path reads the field directly)
    _balances_cache: "CowColumn | None" = None
    _inactivity_cache: "CowColumn | None" = None
    _prev_part_cache: "CowColumn | None" = None
    _curr_part_cache: "CowColumn | None" = None

    def __setattr__(self, name, value):
        if name in _COLUMN_CACHES:
            attr, dtype = _COLUMN_CACHES[name]
            # n-sized columns live as hashed CoW columns: writes through
            # the column API feed one dirty set for both copy and hash
            if value is not None and not isinstance(value, CowColumn):
                value = CowColumn(value, dtype=dtype, hashed=True)
            object.__setattr__(self, attr, value)
        elif name in _VEC_COLUMNS and value is not None and \
                not isinstance(value, CowColumn):
            value = CowColumn(value, dtype=_VEC_COLUMNS[name])
        object.__setattr__(self, name, value)

    def mark_balances_dirty(self, index: int) -> None:
        """Compatibility hook — writes through the column API already
        record themselves; keeps the discipline explicit at call sites."""
        col = self.balances
        if isinstance(col, CowColumn):
            col.mark_dirty(int(index))

    def mark_participation_dirty(self, indices, current: bool) -> None:
        """In-place participation-flag mutations (process_attestation)
        report the touched rows here, mirroring the balances
        discipline (idempotent over the column's own write tracking)."""
        col = (self.current_epoch_participation if current
               else self.previous_epoch_participation)
        if isinstance(col, CowColumn):
            col.mark_dirty_many(indices)

    def rotate_participation(self) -> None:
        """Epoch rotation: previous <- current (the CowColumn carries
        its primed incremental tree across, O(1)), current <- zeros."""
        self.previous_epoch_participation = self.current_epoch_participation
        self.current_epoch_participation = np.zeros(
            len(self.validators), np.uint8)

    def __init__(self, T: Types, spec: ChainSpec, fork_name: ForkName):
        self.T = T
        self.spec = spec
        self.fork_name = fork_name
        p = T.preset
        self.genesis_time = 0
        self.genesis_validators_root = b"\x00" * 32
        self.slot = 0
        self.fork = T.Fork()
        self.latest_block_header = T.BeaconBlockHeader()
        self.block_roots = np.zeros((p.slots_per_historical_root, 32),
                                    np.uint8)
        self.state_roots = np.zeros((p.slots_per_historical_root, 32),
                                    np.uint8)
        self.historical_roots: list[bytes] = []
        self.eth1_data = T.Eth1Data()
        self.eth1_data_votes: list = []
        self.eth1_deposit_index = 0
        self.validators = ValidatorRegistry()
        self.balances = np.zeros(0, np.uint64)
        self.randao_mixes = np.zeros((p.epochs_per_historical_vector, 32),
                                     np.uint8)
        self.slashings = np.zeros(p.epochs_per_slashings_vector, np.uint64)
        self.justification_bits = [False] * JUSTIFICATION_BITS_LENGTH
        self.previous_justified_checkpoint = T.Checkpoint()
        self.current_justified_checkpoint = T.Checkpoint()
        self.finalized_checkpoint = T.Checkpoint()
        # phase0
        self.previous_epoch_attestations: list | None = None
        self.current_epoch_attestations: list | None = None
        # altair+
        self.previous_epoch_participation: np.ndarray | None = None
        self.current_epoch_participation: np.ndarray | None = None
        self.inactivity_scores: np.ndarray | None = None
        self.current_sync_committee = None
        self.next_sync_committee = None
        # bellatrix+
        self.latest_execution_payload_header = None
        # capella+
        self.next_withdrawal_index = None
        self.next_withdrawal_validator_index = None
        self.historical_summaries: list | None = None
        # electra+
        self.deposit_requests_start_index = None
        self.deposit_balance_to_consume = None
        self.exit_balance_to_consume = None
        self.earliest_exit_epoch = None
        self.consolidation_balance_to_consume = None
        self.earliest_consolidation_epoch = None
        self.pending_deposits: list | None = None
        self.pending_partial_withdrawals: list | None = None
        self.pending_consolidations: list | None = None

        self._init_fork_fields(fork_name)

    def _init_fork_fields(self, fork: ForkName) -> None:
        F = ForkName
        T = self.T
        n = len(self.validators)
        if fork == F.PHASE0:
            self.previous_epoch_attestations = []
            self.current_epoch_attestations = []
        if fork >= F.ALTAIR:
            self.previous_epoch_attestations = None
            self.current_epoch_attestations = None
            if self.previous_epoch_participation is None:
                self.previous_epoch_participation = np.zeros(n, np.uint8)
                self.current_epoch_participation = np.zeros(n, np.uint8)
                self.inactivity_scores = np.zeros(n, np.uint64)
            if self.current_sync_committee is None:
                self.current_sync_committee = T.SyncCommittee()
                self.next_sync_committee = T.SyncCommittee()
        if fork >= F.BELLATRIX and self.latest_execution_payload_header is None:
            self.latest_execution_payload_header = \
                T.ExecutionPayloadHeader[max(fork, F.BELLATRIX)]()
        if fork >= F.CAPELLA and self.next_withdrawal_index is None:
            self.next_withdrawal_index = 0
            self.next_withdrawal_validator_index = 0
            self.historical_summaries = []
        if fork >= F.ELECTRA and self.deposit_requests_start_index is None:
            from ..specs.constants import UNSET_DEPOSIT_REQUESTS_START_INDEX
            self.deposit_requests_start_index = \
                UNSET_DEPOSIT_REQUESTS_START_INDEX
            self.deposit_balance_to_consume = 0
            self.exit_balance_to_consume = 0
            self.earliest_exit_epoch = 0
            self.consolidation_balance_to_consume = 0
            self.earliest_consolidation_epoch = 0
            self.pending_deposits = []
            self.pending_partial_withdrawals = []
            self.pending_consolidations = []

    # -- epoch helpers -------------------------------------------------------
    @property
    def slots_per_epoch(self) -> int:
        return self.T.preset.slots_per_epoch

    def current_epoch(self) -> int:
        return self.slot // self.slots_per_epoch

    def previous_epoch(self) -> int:
        cur = self.current_epoch()
        return cur - 1 if cur > 0 else 0

    def get_randao_mix(self, epoch: int) -> bytes:
        p = self.T.preset
        return self.randao_mixes[epoch % p.epochs_per_historical_vector].tobytes()

    def set_randao_mix(self, epoch: int, value: bytes) -> None:
        p = self.T.preset
        self.randao_mixes[epoch % p.epochs_per_historical_vector] = \
            np.frombuffer(value, np.uint8)

    def get_block_root_at_slot(self, slot: int) -> bytes:
        p = self.T.preset
        assert slot < self.slot <= slot + p.slots_per_historical_root
        return self.block_roots[slot % p.slots_per_historical_root].tobytes()

    def get_block_root(self, epoch: int) -> bytes:
        return self.get_block_root_at_slot(epoch * self.slots_per_epoch)

    # -- copy ----------------------------------------------------------------
    def copy(self) -> "BeaconState":
        t0 = time.perf_counter()
        out = BeaconState.__new__(BeaconState)
        out.T, out.spec, out.fork_name = self.T, self.spec, self.fork_name
        for f in active_field_specs(self.T, self.fork_name):
            v = getattr(self, f.name)
            if isinstance(v, CowColumn):
                v = v.fork()
            elif isinstance(v, np.ndarray):
                v = v.copy()
            elif isinstance(v, ValidatorRegistry):
                v = v.copy()
            elif isinstance(v, list):
                # ssz_list entries are frozen (the STF rebinds, never
                # mutates elements in place): share them, copy the spine
                v = list(v)
            elif hasattr(v, "copy") and not isinstance(v, (bytes, int)):
                v = v.copy()
            setattr(out, f.name, v)
        # fields not in the active fork
        for f in state_field_specs(self.T):
            if not hasattr(out, f.name):
                setattr(out, f.name, None)
        m = sys.modules.get("lighthouse_tpu.api.metrics_defs")
        if m is not None:
            m.observe("state_copy_seconds", time.perf_counter() - t0)
        return out

    # -- merkleization -------------------------------------------------------
    def _field_root(self, f: FieldSpec) -> bytes:
        v = getattr(self, f.name)
        if f.kind == "ssz":
            return hash_tree_root(f.typ, v)
        if f.kind == "payload_header":
            return htr(v)
        if f.kind == "ssz_list":
            roots = [hash_tree_root(f.typ, e) for e in v]
            return mix_in_length(merkleize_chunks(roots, f.limit), len(v))
        if f.kind == "roots_vec":
            return _np_bytes32_root(v, f.limit)
        if f.kind == "roots_list":
            arr = (np.frombuffer(b"".join(v), np.uint8).reshape(-1, 32)
                   if v else np.zeros((0, 32), np.uint8))
            return _np_bytes32_root(arr, f.limit, length=len(v))
        if f.kind == "u64_vec":
            return _np_uint_root(v, (f.limit * 8 + 31) // 32)
        if f.kind == "u64_list":
            if isinstance(v, CowColumn):
                # incremental root off the column's own dirty-leaf set —
                # the same bookkeeping its writes feed (no identity-keyed
                # cache invalidation anymore)
                return v.hash_tree_root(f.limit)
            return _np_uint_root(v, (f.limit * 8 + 31) // 32, length=len(v))
        if f.kind == "u8_list":
            if isinstance(v, CowColumn):
                return v.hash_tree_root(f.limit)
            return _np_uint_root(v, (f.limit + 31) // 32, length=len(v))
        if f.kind == "validators":
            return v.hash_tree_root(f.limit)
        raise TypeError(f.kind)

    def hash_tree_root(self) -> bytes:
        # graftscope: the state root is a north-star hot spot — every
        # computation lands in tree_hash_root_seconds and the active trace
        from ..obs import tracing
        with tracing.span("tree_hash", slot=int(self.slot)):
            specs = active_field_specs(self.T, self.fork_name)
            roots = [self._field_root(f) for f in specs]
            return merkleize_chunks(roots,
                                    1 << (len(roots) - 1).bit_length())

    # -- serialization -------------------------------------------------------
    def _field_serialize(self, f: FieldSpec) -> tuple[bytes, bool]:
        """Returns (payload, is_fixed)."""
        from ..ssz.codec import is_fixed_size
        v = getattr(self, f.name)
        if f.kind == "ssz":
            return serialize(f.typ, v), is_fixed_size(f.typ)
        if f.kind == "payload_header":
            t = type(v).ssz_type
            return serialize(t, v), is_fixed_size(t)
        if f.kind == "ssz_list":
            return serialize(SSZList(f.typ, f.limit), v), False
        if f.kind == "roots_vec":
            return v.tobytes(), True
        if f.kind == "roots_list":
            return b"".join(v), False
        if f.kind in ("u64_vec",):
            return v.astype("<u8").tobytes(), True
        if f.kind == "u64_list":
            return v.astype("<u8").tobytes(), False
        if f.kind == "u8_list":
            return v.astype(np.uint8).tobytes(), False
        if f.kind == "validators":
            return v.serialize(), False
        raise TypeError(f.kind)

    def serialize(self) -> bytes:
        parts = [self._field_serialize(f)
                 for f in active_field_specs(self.T, self.fork_name)]
        fixed_len = sum(len(p) if fixed else BYTES_PER_LENGTH_OFFSET
                        for p, fixed in parts)
        out = bytearray()
        offset = fixed_len
        for payload, fixed in parts:
            if fixed:
                out += payload
            else:
                out += offset.to_bytes(4, "little")
                offset += len(payload)
        for payload, fixed in parts:
            if not fixed:
                out += payload
        return bytes(out)

    @classmethod
    def from_ssz_bytes(cls, data: bytes, T: Types, spec: ChainSpec,
                       fork_name: ForkName) -> "BeaconState":
        from ..ssz.codec import is_fixed_size, fixed_size
        state = cls(T, spec, fork_name)
        specs = active_field_specs(T, fork_name)
        pos = 0
        fixed_items: list[tuple[FieldSpec, bytes | int]] = []
        offsets: list[int] = []
        for f in specs:
            if f.kind == "ssz":
                fixed = is_fixed_size(f.typ)
                size = fixed_size(f.typ) if fixed else None
            elif f.kind == "payload_header":
                t = type(getattr(state, f.name)).ssz_type
                fixed = is_fixed_size(t)
                size = fixed_size(t) if fixed else None
            elif f.kind in ("roots_vec",):
                fixed, size = True, f.limit * 32
            elif f.kind == "u64_vec":
                fixed, size = True, f.limit * 8
            else:
                fixed, size = False, None
            if fixed:
                fixed_items.append((f, data[pos:pos + size]))
                pos += size
            else:
                off = int.from_bytes(data[pos:pos + 4], "little")
                fixed_items.append((f, off))
                offsets.append(off)
                pos += 4
        offsets.append(len(data))
        oi = 0
        for f, raw in fixed_items:
            if isinstance(raw, int):
                chunk = data[offsets[oi]:offsets[oi + 1]]
                oi += 1
            else:
                chunk = raw
            cls._field_deserialize(state, f, chunk)
        return state

    @staticmethod
    def _field_deserialize(state: "BeaconState", f: FieldSpec,
                           data: bytes) -> None:
        if f.kind == "ssz":
            setattr(state, f.name, deserialize(f.typ, data))
        elif f.kind == "payload_header":
            t = type(getattr(state, f.name)).ssz_type
            setattr(state, f.name, deserialize(t, data))
        elif f.kind == "ssz_list":
            setattr(state, f.name,
                    deserialize(SSZList(f.typ, f.limit), data))
        elif f.kind == "roots_vec":
            setattr(state, f.name,
                    np.frombuffer(data, np.uint8).reshape(-1, 32).copy())
        elif f.kind == "roots_list":
            setattr(state, f.name,
                    [data[i:i + 32] for i in range(0, len(data), 32)])
        elif f.kind == "u64_vec":
            setattr(state, f.name, np.frombuffer(data, "<u8").copy())
        elif f.kind == "u64_list":
            setattr(state, f.name, np.frombuffer(data, "<u8").copy())
        elif f.kind == "u8_list":
            setattr(state, f.name, np.frombuffer(data, np.uint8).copy())
        elif f.kind == "validators":
            setattr(state, f.name, ValidatorRegistry.from_bytes(data))
        else:
            raise TypeError(f.kind)


def new_state(spec: ChainSpec, fork_name: ForkName = ForkName.PHASE0
              ) -> BeaconState:
    return BeaconState(get_types(spec.preset), spec, fork_name)
