"""Chunk-granular copy-on-write state columns (ROADMAP item 2).

The reference client keeps BeaconState in `milhouse` persistent trees so
cloning is O(mutations) structural sharing; our SoA columns paid O(bytes)
memcpy per copy instead — 604 ms at 1M validators, ~26% of block import
(PERF_MODEL.md §8).  ``CowColumn`` closes that gap for dense numpy
columns: the data lives in fixed-size row chunks (``CHUNK_ROWS`` rows)
shared by reference across forks, with a per-chunk refcount cell so a
write materializes only its own chunk and ``fork()`` is O(chunks)
pointer work.

One dirty-bookkeeping layer feeds both copy and hash: every write path
funnels through ``__setitem__``/``_scatter``, which privatize the CoW
chunk *and* record the touched 32-byte merkle leaves for the incremental
tree (the BalancesColumn/HostTree machinery, now driven without any
identity-keyed cache).  Forked columns share their host merkle tree;
small dirty sets are resolved against it with a read-only overlay walk
(``native_hash.overlay_root``) so 32 live forks never clone tree levels.

Writes MUST go through the column API (``col[rows] = v``, ``set_field``,
``mark_dirty*``); grabbing the backing array and writing it in place
bypasses both the refcounts and the dirty set — graftlint's
``cow-discipline`` rule flags that pattern.
"""
from __future__ import annotations

import sys

import numpy as np

from ..utils.hash import ZERO_HASHES

#: rows per CoW chunk.  4096 rows keeps fork() at ~245 cells per 1M-row
#: u64 column (32 KB/chunk) and divides every merkle-leaf width in use
#: (4 u64 rows or 32 u8 rows per 32-byte leaf), so a leaf never spans
#: two CoW chunks and dirty-leaf reads stay chunk-direct.
CHUNK_ROWS = 4096

#: max dirty leaves resolved via the read-only overlay walk against a
#: *shared* host tree; larger deltas clone the tree once and update it
#: in place (the canonical-chain steady state).
OVERLAY_MAX_LEAVES = 2048

#: process-wide CoW accounting, mirrored into graftscope counters when
#: the metrics module is loaded (bench.py fork_fanout reads the deltas).
STATS = {"chunks_materialized": 0, "chunks_shared": 0, "rebases": 0,
         "bytes_materialized": 0, "bytes_shared": 0}


def _count_metric(name: str, amount: int) -> None:
    m = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if m is not None:
        m.count(name, amount)


def _mix_in_length(root: bytes, length: int) -> bytes:
    from ..ssz import mix_in_length
    return mix_in_length(root, length)


class CowColumn(np.lib.mixins.NDArrayOperatorsMixin):
    """A dense numpy column with chunk-granular copy-on-write forks.

    Reads behave like the wrapped ndarray (ufuncs, fancy indexing,
    ``astype``/``tobytes``/``sum``/iteration); ``np.asarray(col)``
    yields a read-only view so the only write path is the column API.
    ``hashed=True`` adds the incremental packed-uint merkle tree
    (u64/u8 1-D columns only), fed by the same writes.
    """

    def __init__(self, values, dtype=None, hashed: bool = False):
        arr = np.ascontiguousarray(values, dtype=dtype)
        if not arr.flags.writeable or arr.base is not None:
            arr = arr.copy()
        self.dtype = arr.dtype
        self._n = int(arr.shape[0])
        self._row_shape = arr.shape[1:]
        self._base = arr
        nb = (self._n + CHUNK_ROWS - 1) // CHUNK_ROWS
        self._chunks = [arr[c * CHUNK_ROWS:(c + 1) * CHUNK_ROWS]
                        for c in range(nb)]
        self._rc = [[1] for _ in range(nb)]
        self._contig = True    # every chunk is a view of _base
        self._owned = True     # sole owner of every chunk AND _base
        self._hashed = bool(hashed)
        if hashed:
            assert arr.ndim == 1 and 32 % self.dtype.itemsize == 0, \
                "hashed columns are packed 1-D uint columns"
            self._per_leaf = 32 // self.dtype.itemsize
        else:
            self._per_leaf = 0
        # merkle state (hashed mode): dirty set at 32-byte-leaf
        # granularity, None = full rebuild
        self._dirty_leaves: set[int] | None = None
        self._root_cache: bytes | None = None
        self._host_tree = None
        self._host_shared = False
        self._device_tree = None

    def __del__(self):
        try:
            for cell in self._rc:
                cell[0] -= 1
        except Exception:
            pass

    # -- fork / ownership ----------------------------------------------------

    def fork(self) -> "CowColumn":
        """O(chunks) second owner: chunks shared by reference, refcount
        cells shared by identity, merkle trees shared copy-on-write."""
        out = object.__new__(type(self))
        out.dtype = self.dtype
        out._n = self._n
        out._row_shape = self._row_shape
        out._base = self._base
        out._chunks = list(self._chunks)
        out._rc = list(self._rc)
        for cell in self._rc:
            cell[0] += 1
        out._contig = self._contig
        self._owned = False
        out._owned = False
        out._hashed = self._hashed
        out._per_leaf = self._per_leaf
        out._dirty_leaves = (set(self._dirty_leaves)
                             if self._dirty_leaves is not None else None)
        out._root_cache = self._root_cache
        out._device_tree = (self._device_tree.share()
                            if self._device_tree is not None else None)
        out._host_tree = self._host_tree
        if self._host_tree is not None:
            self._host_shared = True
        out._host_shared = self._host_tree is not None
        STATS["chunks_shared"] += len(self._chunks)
        STATS["bytes_shared"] += sum(c.nbytes for c in self._chunks)
        _count_metric("state_cow_chunks_shared", len(self._chunks))
        return out

    def _writable_chunk(self, c: int) -> np.ndarray:
        """Chunk ``c`` safe to write in place: privatizes (copies) it
        first when another fork still references the cell."""
        cell = self._rc[c]
        if cell[0] > 1:
            cell[0] -= 1
            self._chunks[c] = self._chunks[c].copy()
            self._rc[c] = [1]
            self._contig = False
            STATS["chunks_materialized"] += 1
            STATS["bytes_materialized"] += self._chunks[c].nbytes
            _count_metric("state_cow_chunks_materialized", 1)
        return self._chunks[c]

    def _rebase(self) -> None:
        """Compact into a fresh exclusively-owned dense base (whole-array
        reads and generic writes land here)."""
        if self._contig:
            base = self._base.copy()
        else:
            base = np.empty((self._n,) + self._row_shape, self.dtype)
            for c, ch in enumerate(self._chunks):
                o = c * CHUNK_ROWS
                base[o:o + ch.shape[0]] = ch
        for cell in self._rc:
            cell[0] -= 1
        nb = len(self._chunks)
        self._base = base
        self._chunks = [base[c * CHUNK_ROWS:(c + 1) * CHUNK_ROWS]
                        for c in range(nb)]
        self._rc = [[1] for _ in range(nb)]
        self._contig = True
        self._owned = True
        STATS["rebases"] += 1

    def _own_all(self) -> None:
        if not self._owned:
            self._rebase()

    def _array(self) -> np.ndarray:
        """Dense backing for whole-array READS (may still be shared —
        callers must not write it; writers go through _own_all)."""
        if not self._contig:
            self._rebase()
        return self._base

    # -- ndarray duck surface ------------------------------------------------

    @property
    def shape(self):
        return (self._n,) + self._row_shape

    @property
    def ndim(self) -> int:
        return 1 + len(self._row_shape)

    @property
    def size(self) -> int:
        n = self._n
        for d in self._row_shape:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def __len__(self) -> int:
        return self._n

    def __iter__(self):
        return iter(np.asarray(self))

    def __repr__(self):
        return (f"CowColumn(n={self._n}, dtype={self.dtype}, "
                f"chunks={len(self._chunks)}, contig={self._contig}, "
                f"owned={self._owned}, hashed={self._hashed})")

    def __array__(self, dtype=None, copy=None):
        a = self._array()
        if dtype is not None and np.dtype(dtype) != a.dtype:
            return a.astype(dtype)
        if copy:
            return a.copy()
        v = a.view()
        v.flags.writeable = False
        return v

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if kwargs.get("out") is not None:
            return NotImplemented
        conv = [x._array() if isinstance(x, CowColumn) else x
                for x in inputs]
        return getattr(ufunc, method)(*conv, **kwargs)

    def astype(self, dtype, *args, **kwargs):
        return self._array().astype(dtype, *args, **kwargs)

    def copy(self) -> np.ndarray:
        """A plain private ndarray snapshot (fork() is the CoW copy)."""
        return self._array().copy()

    def tobytes(self) -> bytes:
        return self._array().tobytes()

    def sum(self, *args, **kwargs):
        return self._array().sum(*args, **kwargs)

    def any(self, *args, **kwargs):
        return self._array().any(*args, **kwargs)

    def all(self, *args, **kwargs):
        return self._array().all(*args, **kwargs)

    def min(self, *args, **kwargs):
        return self._array().min(*args, **kwargs)

    def max(self, *args, **kwargs):
        return self._array().max(*args, **kwargs)

    # -- reads ---------------------------------------------------------------

    def _gather(self, rows) -> np.ndarray:
        rows = np.asarray(rows)
        if self._contig:
            return self._base[rows]
        if rows.ndim != 1:
            return self._array()[rows]
        if rows.size == 0:
            return np.empty((0,) + self._row_shape, self.dtype)
        if rows.min() < 0:
            return self._array()[rows]
        cs = rows // CHUNK_ROWS
        uniq = np.unique(cs)
        if len(uniq) > 32:
            # scattered over most of the column: densify once
            return self._array()[rows]
        out = np.empty((len(rows),) + self._row_shape, self.dtype)
        for c in uniq:
            m = cs == c
            out[m] = self._chunks[c][rows[m] - c * CHUNK_ROWS]
        return out

    def __getitem__(self, key):
        if isinstance(key, (int, np.integer)):
            i = int(key)
            if i < 0:
                i += self._n
            c, o = divmod(i, CHUNK_ROWS)
            row = self._chunks[c][o]
            if isinstance(row, np.ndarray):
                row = row.view()
                row.flags.writeable = False
            return row
        if isinstance(key, list):
            key = np.asarray(key)
        if isinstance(key, np.ndarray) and key.dtype != np.bool_ \
                and np.issubdtype(key.dtype, np.integer):
            return self._gather(key)
        if isinstance(key, tuple) and len(key) == 2 \
                and isinstance(key[1], (int, np.integer)) \
                and isinstance(key[0], (list, np.ndarray)):
            rows = np.asarray(key[0])
            if rows.dtype != np.bool_ and np.issubdtype(rows.dtype,
                                                        np.integer):
                return self._gather(rows)[:, key[1]].copy()
        out = self._array()[key]
        if isinstance(out, np.ndarray) and out.base is not None:
            out = out.copy()
        return out

    # -- writes (the one dirty-bookkeeping layer) ----------------------------

    def _touch_row(self, i: int) -> None:
        self._root_cache = None
        if self._hashed and self._dirty_leaves is not None:
            self._dirty_leaves.add(i // self._per_leaf)

    def _touch_rows(self, rows: np.ndarray) -> None:
        self._root_cache = None
        if self._hashed and self._dirty_leaves is not None:
            leaves = np.unique(rows // self._per_leaf)
            self._dirty_leaves.update(leaves.tolist())
            if 2 * len(self._dirty_leaves) > self._leaf_count():
                self._dirty_leaves = None     # full rebuild is cheaper

    def _touch_all(self) -> None:
        self._root_cache = None
        self._dirty_leaves = None

    def mark_dirty(self, i: int | None = None) -> None:
        """Compatibility hook for callers that already wrote through the
        column API (idempotent) — or who replaced everything (i=None)."""
        if i is None:
            self._touch_all()
        else:
            self._touch_row(int(i))

    def mark_dirty_many(self, rows) -> None:
        self._touch_rows(np.asarray(rows, np.int64))

    def _scatter(self, rows: np.ndarray, value) -> None:
        if rows.size == 0:
            return
        rows = rows.astype(np.int64, copy=False)
        if self._owned and self._contig:
            self._base[rows] = value
        else:
            value = np.asarray(value)
            per_row = value.ndim >= 1 and value.shape[0] == rows.shape[0]
            cs = rows // CHUNK_ROWS
            for c in np.unique(cs):
                m = cs == c
                ch = self._writable_chunk(int(c))
                ch[rows[m] - int(c) * CHUNK_ROWS] = \
                    value[m] if per_row else value
        self._touch_rows(rows)

    def __setitem__(self, key, value) -> None:
        if isinstance(key, (int, np.integer)):
            i = int(key)
            if i < 0:
                i += self._n
            c, o = divmod(i, CHUNK_ROWS)
            self._writable_chunk(c)[o] = value
            self._touch_row(i)
            return
        if isinstance(key, list):
            key = np.asarray(key)
        if isinstance(key, np.ndarray) and key.dtype != np.bool_ \
                and np.issubdtype(key.dtype, np.integer):
            self._scatter(key, value)
            return
        self._own_all()
        self._base[key] = value
        self._touch_all()

    # -- incremental merkleization (hashed mode) -----------------------------

    def _leaf_count(self) -> int:
        return (self._n + self._per_leaf - 1) // self._per_leaf

    def _leaf_bytes(self, leaves=None) -> np.ndarray:
        """u8[L, 32] packed leaf bytes for the whole column or a leaf
        subset — chunk-direct reads (a leaf never spans CoW chunks)."""
        pl = self._per_leaf
        le = self.dtype.newbyteorder("<")
        if leaves is None:
            padded = np.zeros(self._leaf_count() * pl, dtype=self.dtype)
            padded[:self._n] = self._array()
        else:
            padded = np.zeros((len(leaves), pl), dtype=self.dtype)
            for j, lf in enumerate(np.asarray(leaves, np.int64).tolist()):
                s = lf * pl
                e = min(self._n, s + pl)
                c, o = divmod(s, CHUNK_ROWS)
                padded[j, :e - s] = self._chunks[c][o:o + (e - s)]
        return np.frombuffer(padded.astype(le).tobytes(),
                             np.uint8).reshape(-1, 32)

    def _leaf_words(self, leaves=None) -> np.ndarray:
        from ..ops import sha256 as k
        return k.chunks_to_words(self._leaf_bytes(leaves).tobytes())

    def _device_root_words(self, limit_chunks: int):
        from ..ops.merkle_tree import DeviceTree
        L = self._leaf_count()
        tree = self._device_tree
        if tree is None or self._dirty_leaves is None or tree.n != L:
            tree = DeviceTree(L, limit_chunks)
            tree.build(self._leaf_words())
            self._device_tree = tree
        elif self._dirty_leaves:
            idx = np.fromiter(self._dirty_leaves, dtype=np.int64)
            idx.sort()
            tree.update(idx, self._leaf_words(idx))
        self._dirty_leaves = set()
        self._host_tree = None       # consumed the dirty set
        return tree.root_words

    def hash_tree_root(self, registry_limit: int) -> bytes:
        if not self._hashed:
            raise TypeError("non-hashed CowColumn has no incremental root")
        if self._root_cache is not None:
            return self._root_cache
        from ..ops import sha256 as k
        from . import state as _state
        n = self._n
        limit_chunks = (registry_limit * self.dtype.itemsize + 31) // 32
        if n == 0:
            depth = (limit_chunks - 1).bit_length()
            root = _mix_in_length(ZERO_HASHES[depth], 0)
        elif _state._use_host_hash():
            from ..utils import native_hash as nh
            L = self._leaf_count()
            tree = self._host_tree
            if tree is None or self._dirty_leaves is None or tree.n != L:
                self._host_tree = nh.HostTree(self._leaf_bytes(),
                                              limit_chunks)
                self._host_shared = False
                self._dirty_leaves = set()
                self._device_tree = None
            elif self._dirty_leaves:
                idx = np.fromiter(self._dirty_leaves, dtype=np.int64)
                idx.sort()
                if self._host_shared and len(idx) <= OVERLAY_MAX_LEAVES:
                    # fork fan-out path: resolve the dirty set against
                    # the SHARED tree read-only — no level cloning, the
                    # dirty set stays pending
                    root = _mix_in_length(
                        nh.overlay_root(self._host_tree, idx,
                                        self._leaf_bytes(idx)), n)
                    self._root_cache = root
                    return root
                if self._host_shared:
                    self._host_tree = self._host_tree.copy()
                    self._host_shared = False
                self._host_tree.update(idx, self._leaf_bytes(idx))
                self._dirty_leaves = set()
                self._device_tree = None
            root = _mix_in_length(self._host_tree.root(), n)
        else:
            root = _mix_in_length(
                k.words_to_chunks(
                    np.asarray(self._device_root_words(limit_chunks))), n)
        self._root_cache = root
        return root
