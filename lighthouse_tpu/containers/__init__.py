"""Consensus containers for every fork (phase0 → electra).

Equivalent of /root/reference/consensus/types (22.6k LoC): SSZ containers,
multi-fork variants (superstruct → per-fork classes in a preset-keyed
registry), and the array-backed SoA BeaconState.

Because container shapes depend on the compile-time preset (the reference's
`EthSpec` typenum trait, consensus/types/src/eth_spec.rs:53-161), all types
are built by ``get_types(preset)`` — a cached factory returning a namespace of
container classes and per-fork registries.
"""
from .core import get_types, Types
from .state import BeaconState, ValidatorRegistry, ValidatorView
