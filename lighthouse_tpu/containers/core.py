"""Container class factory, parameterized by preset.

Field orders are root-determining; they follow the consensus specs exactly
(reference: consensus/types/src/*.rs per-fork superstruct variants).

NOTE: no `from __future__ import annotations` here — the @container decorator
reads SSZ type *instances* out of __annotations__, so they must not be
stringified.
"""
import functools
from types import SimpleNamespace

from ..specs.chain_spec import ForkName
from ..specs.constants import (
    BYTES_PER_FIELD_ELEMENT, DEPOSIT_CONTRACT_TREE_DEPTH,
    JUSTIFICATION_BITS_LENGTH, SYNC_COMMITTEE_SUBNET_COUNT,
)
from ..specs.presets import Preset
from ..ssz import (
    Bitlist, Bitvector, ByteList, ByteVector, Bytes4, Bytes20, Bytes32,
    Bytes48, Bytes96, List, Root, Vector, boolean, container, uint8, uint64,
    uint256,
)

Types = SimpleNamespace


def get_types(preset: Preset) -> Types:
    return _build_types_cached(preset.name, preset)


@functools.lru_cache(maxsize=8)
def _build_types_cached(name: str, preset: Preset) -> Types:
    return _build_types(preset)


def _build_types(p: Preset) -> Types:
    T = SimpleNamespace(preset=p)

    # -- misc dependent sizes ------------------------------------------------
    max_validators_per_slot = (p.max_validators_per_committee
                               * p.max_committees_per_slot)
    eth1_votes_limit = p.epochs_per_eth1_voting_period * p.slots_per_epoch
    pending_att_limit = p.max_attestations * p.slots_per_epoch

    # -- fork-independent ----------------------------------------------------
    @container
    class Fork:
        previous_version: Bytes4
        current_version: Bytes4
        epoch: uint64

    @container
    class ForkData:
        current_version: Bytes4
        genesis_validators_root: Root

    @container
    class Checkpoint:
        epoch: uint64
        root: Root

    @container
    class Validator:
        pubkey: Bytes48
        withdrawal_credentials: Bytes32
        effective_balance: uint64
        slashed: boolean
        activation_eligibility_epoch: uint64
        activation_epoch: uint64
        exit_epoch: uint64
        withdrawable_epoch: uint64

    @container
    class AttestationData:
        slot: uint64
        index: uint64
        beacon_block_root: Root
        source: Checkpoint.ssz_type
        target: Checkpoint.ssz_type

    @container
    class IndexedAttestation:
        attesting_indices: List(uint64, p.max_validators_per_committee)
        data: AttestationData.ssz_type
        signature: Bytes96

    @container
    class IndexedAttestationElectra:
        attesting_indices: List(uint64, max_validators_per_slot)
        data: AttestationData.ssz_type
        signature: Bytes96

    @container
    class PendingAttestation:
        aggregation_bits: Bitlist(p.max_validators_per_committee)
        data: AttestationData.ssz_type
        inclusion_delay: uint64
        proposer_index: uint64

    @container
    class Eth1Data:
        deposit_root: Root
        deposit_count: uint64
        block_hash: Bytes32

    @container
    class HistoricalBatch:
        block_roots: Vector(Root, p.slots_per_historical_root)
        state_roots: Vector(Root, p.slots_per_historical_root)

    @container
    class HistoricalSummary:
        block_summary_root: Root
        state_summary_root: Root

    @container
    class DepositMessage:
        pubkey: Bytes48
        withdrawal_credentials: Bytes32
        amount: uint64

    @container
    class DepositData:
        pubkey: Bytes48
        withdrawal_credentials: Bytes32
        amount: uint64
        signature: Bytes96

    @container
    class Deposit:
        proof: Vector(Bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1)
        data: DepositData.ssz_type

    @container
    class BeaconBlockHeader:
        slot: uint64
        proposer_index: uint64
        parent_root: Root
        state_root: Root
        body_root: Root

    @container
    class SignedBeaconBlockHeader:
        message: BeaconBlockHeader.ssz_type
        signature: Bytes96

    @container
    class ProposerSlashing:
        signed_header_1: SignedBeaconBlockHeader.ssz_type
        signed_header_2: SignedBeaconBlockHeader.ssz_type

    @container
    class AttesterSlashing:
        attestation_1: IndexedAttestation.ssz_type
        attestation_2: IndexedAttestation.ssz_type

    @container
    class AttesterSlashingElectra:
        attestation_1: IndexedAttestationElectra.ssz_type
        attestation_2: IndexedAttestationElectra.ssz_type

    @container
    class Attestation:
        aggregation_bits: Bitlist(p.max_validators_per_committee)
        data: AttestationData.ssz_type
        signature: Bytes96

    @container
    class AttestationElectra:
        aggregation_bits: Bitlist(max_validators_per_slot)
        data: AttestationData.ssz_type
        signature: Bytes96
        committee_bits: Bitvector(p.max_committees_per_slot)

    @container
    class VoluntaryExit:
        epoch: uint64
        validator_index: uint64

    @container
    class SignedVoluntaryExit:
        message: VoluntaryExit.ssz_type
        signature: Bytes96

    @container
    class SigningData:
        object_root: Root
        domain: Bytes32

    @container
    class SyncAggregate:
        sync_committee_bits: Bitvector(p.sync_committee_size)
        sync_committee_signature: Bytes96

    @container
    class SyncCommittee:
        pubkeys: Vector(Bytes48, p.sync_committee_size)
        aggregate_pubkey: Bytes48

    @container
    class SyncCommitteeMessage:
        slot: uint64
        beacon_block_root: Root
        validator_index: uint64
        signature: Bytes96

    @container
    class SyncCommitteeContribution:
        slot: uint64
        beacon_block_root: Root
        subcommittee_index: uint64
        aggregation_bits: Bitvector(p.sync_committee_size
                                    // SYNC_COMMITTEE_SUBNET_COUNT)
        signature: Bytes96

    @container
    class ContributionAndProof:
        aggregator_index: uint64
        contribution: SyncCommitteeContribution.ssz_type
        selection_proof: Bytes96

    @container
    class SignedContributionAndProof:
        message: ContributionAndProof.ssz_type
        signature: Bytes96

    @container
    class SyncAggregatorSelectionData:
        slot: uint64
        subcommittee_index: uint64

    @container
    class Withdrawal:
        index: uint64
        validator_index: uint64
        address: Bytes20
        amount: uint64

    @container
    class BLSToExecutionChange:
        validator_index: uint64
        from_bls_pubkey: Bytes48
        to_execution_address: Bytes20

    @container
    class SignedBLSToExecutionChange:
        message: BLSToExecutionChange.ssz_type
        signature: Bytes96

    # -- electra operations --------------------------------------------------
    @container
    class DepositRequest:
        pubkey: Bytes48
        withdrawal_credentials: Bytes32
        amount: uint64
        signature: Bytes96
        index: uint64

    @container
    class WithdrawalRequest:
        source_address: Bytes20
        validator_pubkey: Bytes48
        amount: uint64

    @container
    class ConsolidationRequest:
        source_address: Bytes20
        source_pubkey: Bytes48
        target_pubkey: Bytes48

    @container
    class ExecutionRequests:
        deposits: List(DepositRequest.ssz_type,
                       p.max_deposit_requests_per_payload)
        withdrawals: List(WithdrawalRequest.ssz_type,
                          p.max_withdrawal_requests_per_payload)
        consolidations: List(ConsolidationRequest.ssz_type,
                             p.max_consolidation_requests_per_payload)

    @container
    class PendingDeposit:
        pubkey: Bytes48
        withdrawal_credentials: Bytes32
        amount: uint64
        signature: Bytes96
        slot: uint64

    @container
    class PendingPartialWithdrawal:
        validator_index: uint64
        amount: uint64
        withdrawable_epoch: uint64

    @container
    class PendingConsolidation:
        source_index: uint64
        target_index: uint64

    # -- execution payloads (per fork) ---------------------------------------
    Transactions = List(ByteList(p.max_bytes_per_transaction),
                        p.max_transactions_per_payload)

    payload_base = dict(
        parent_hash=Bytes32, fee_recipient=Bytes20, state_root=Bytes32,
        receipts_root=Bytes32, logs_bloom=ByteVector(p.bytes_per_logs_bloom),
        prev_randao=Bytes32, block_number=uint64, gas_limit=uint64,
        gas_used=uint64, timestamp=uint64,
        extra_data=ByteList(p.max_extra_data_bytes),
        base_fee_per_gas=uint256, block_hash=Bytes32,
    )

    def payload_cls(cls_name: str, extra: dict):
        ns = dict(payload_base); ns.update(extra)
        cls = type(cls_name, (), {"__annotations__": ns})
        return container(cls)

    ExecutionPayloadBellatrix = payload_cls(
        "ExecutionPayloadBellatrix", dict(transactions=Transactions))
    ExecutionPayloadCapella = payload_cls(
        "ExecutionPayloadCapella",
        dict(transactions=Transactions,
             withdrawals=List(Withdrawal.ssz_type,
                              p.max_withdrawals_per_payload)))
    ExecutionPayloadDeneb = payload_cls(
        "ExecutionPayloadDeneb",
        dict(transactions=Transactions,
             withdrawals=List(Withdrawal.ssz_type,
                              p.max_withdrawals_per_payload),
             blob_gas_used=uint64, excess_blob_gas=uint64))

    header_extra = dict(transactions_root=Root)
    ExecutionPayloadHeaderBellatrix = payload_cls(
        "ExecutionPayloadHeaderBellatrix", dict(transactions_root=Root))
    ExecutionPayloadHeaderCapella = payload_cls(
        "ExecutionPayloadHeaderCapella",
        dict(transactions_root=Root, withdrawals_root=Root))
    ExecutionPayloadHeaderDeneb = payload_cls(
        "ExecutionPayloadHeaderDeneb",
        dict(transactions_root=Root, withdrawals_root=Root,
             blob_gas_used=uint64, excess_blob_gas=uint64))

    ExecutionPayload = {
        ForkName.BELLATRIX: ExecutionPayloadBellatrix,
        ForkName.CAPELLA: ExecutionPayloadCapella,
        ForkName.DENEB: ExecutionPayloadDeneb,
        ForkName.ELECTRA: ExecutionPayloadDeneb,
    }
    ExecutionPayloadHeader = {
        ForkName.BELLATRIX: ExecutionPayloadHeaderBellatrix,
        ForkName.CAPELLA: ExecutionPayloadHeaderCapella,
        ForkName.DENEB: ExecutionPayloadHeaderDeneb,
        ForkName.ELECTRA: ExecutionPayloadHeaderDeneb,
    }

    # -- block bodies / blocks per fork --------------------------------------
    body_phase0 = dict(
        randao_reveal=Bytes96, eth1_data=Eth1Data.ssz_type,
        graffiti=Bytes32,
        proposer_slashings=List(ProposerSlashing.ssz_type,
                                p.max_proposer_slashings),
        attester_slashings=List(AttesterSlashing.ssz_type,
                                p.max_attester_slashings),
        attestations=List(Attestation.ssz_type, p.max_attestations),
        deposits=List(Deposit.ssz_type, p.max_deposits),
        voluntary_exits=List(SignedVoluntaryExit.ssz_type,
                             p.max_voluntary_exits),
    )

    def body_cls(cls_name, extra):
        ns = dict(body_phase0); ns.update(extra)
        return container(type(cls_name, (), {"__annotations__": ns}))

    BeaconBlockBodyPhase0 = body_cls("BeaconBlockBodyPhase0", {})
    BeaconBlockBodyAltair = body_cls(
        "BeaconBlockBodyAltair",
        dict(sync_aggregate=SyncAggregate.ssz_type))
    BeaconBlockBodyBellatrix = body_cls(
        "BeaconBlockBodyBellatrix",
        dict(sync_aggregate=SyncAggregate.ssz_type,
             execution_payload=ExecutionPayloadBellatrix.ssz_type))
    BeaconBlockBodyCapella = body_cls(
        "BeaconBlockBodyCapella",
        dict(sync_aggregate=SyncAggregate.ssz_type,
             execution_payload=ExecutionPayloadCapella.ssz_type,
             bls_to_execution_changes=List(
                 SignedBLSToExecutionChange.ssz_type,
                 p.max_bls_to_execution_changes)))
    BeaconBlockBodyDeneb = body_cls(
        "BeaconBlockBodyDeneb",
        dict(sync_aggregate=SyncAggregate.ssz_type,
             execution_payload=ExecutionPayloadDeneb.ssz_type,
             bls_to_execution_changes=List(
                 SignedBLSToExecutionChange.ssz_type,
                 p.max_bls_to_execution_changes),
             blob_kzg_commitments=List(Bytes48,
                                       p.max_blob_commitments_per_block)))
    electra_ns = dict(body_phase0)
    electra_ns.update(
        attester_slashings=List(AttesterSlashingElectra.ssz_type,
                                p.max_attester_slashings_electra),
        attestations=List(AttestationElectra.ssz_type,
                          p.max_attestations_electra),
        sync_aggregate=SyncAggregate.ssz_type,
        execution_payload=ExecutionPayloadDeneb.ssz_type,
        bls_to_execution_changes=List(SignedBLSToExecutionChange.ssz_type,
                                      p.max_bls_to_execution_changes),
        blob_kzg_commitments=List(Bytes48, p.max_blob_commitments_per_block),
        execution_requests=ExecutionRequests.ssz_type,
    )
    BeaconBlockBodyElectra = container(
        type("BeaconBlockBodyElectra", (), {"__annotations__": electra_ns}))

    BeaconBlockBody = {
        ForkName.PHASE0: BeaconBlockBodyPhase0,
        ForkName.ALTAIR: BeaconBlockBodyAltair,
        ForkName.BELLATRIX: BeaconBlockBodyBellatrix,
        ForkName.CAPELLA: BeaconBlockBodyCapella,
        ForkName.DENEB: BeaconBlockBodyDeneb,
        ForkName.ELECTRA: BeaconBlockBodyElectra,
    }

    BeaconBlock = {}
    SignedBeaconBlock = {}
    for fork, body in BeaconBlockBody.items():
        blk = container(type(f"BeaconBlock{fork.name.title()}", (), {
            "__annotations__": dict(
                slot=uint64, proposer_index=uint64, parent_root=Root,
                state_root=Root, body=body.ssz_type)}))
        sblk = container(type(f"SignedBeaconBlock{fork.name.title()}", (), {
            "__annotations__": dict(message=blk.ssz_type,
                                    signature=Bytes96)}))
        blk.fork_name = fork
        sblk.fork_name = fork
        BeaconBlock[fork] = blk
        SignedBeaconBlock[fork] = sblk

    # -- blinded blocks (builder/MEV flow) -----------------------------------
    # Same bodies with execution_payload swapped IN PLACE for its header
    # (field order preserved => identical merkleization up to that leaf),
    # matching the reference's BlindedPayload variants
    # (consensus/types/src/payload.rs; execution_layer/src/lib.rs:807).
    BlindedBeaconBlockBody = {}
    BlindedBeaconBlock = {}
    SignedBlindedBeaconBlock = {}
    for fork, body in BeaconBlockBody.items():
        if fork < ForkName.BELLATRIX:
            continue
        ns = {}
        for fname, ftyp in body.__ssz_fields__.items():
            if fname == "execution_payload":
                ns["execution_payload_header"] = \
                    ExecutionPayloadHeader[fork].ssz_type
            else:
                ns[fname] = ftyp
        bbody = container(type(
            f"BlindedBeaconBlockBody{fork.name.title()}", (),
            {"__annotations__": ns}))
        bblk = container(type(f"BlindedBeaconBlock{fork.name.title()}", (), {
            "__annotations__": dict(
                slot=uint64, proposer_index=uint64, parent_root=Root,
                state_root=Root, body=bbody.ssz_type)}))
        sbblk = container(type(
            f"SignedBlindedBeaconBlock{fork.name.title()}", (), {
                "__annotations__": dict(message=bblk.ssz_type,
                                        signature=Bytes96)}))
        bbody.fork_name = bblk.fork_name = sbblk.fork_name = fork
        BlindedBeaconBlockBody[fork] = bbody
        BlindedBeaconBlock[fork] = bblk
        SignedBlindedBeaconBlock[fork] = sbblk

    # -- aggregation wrappers ------------------------------------------------
    @container
    class AggregateAndProof:
        aggregator_index: uint64
        aggregate: Attestation.ssz_type
        selection_proof: Bytes96

    @container
    class SignedAggregateAndProof:
        message: AggregateAndProof.ssz_type
        signature: Bytes96

    @container
    class AggregateAndProofElectra:
        aggregator_index: uint64
        aggregate: AttestationElectra.ssz_type
        selection_proof: Bytes96

    @container
    class SignedAggregateAndProofElectra:
        message: AggregateAndProofElectra.ssz_type
        signature: Bytes96

    # -- deneb blobs ---------------------------------------------------------
    Blob = ByteVector(BYTES_PER_FIELD_ELEMENT * p.field_elements_per_blob)

    @container
    class BlobSidecar:
        index: uint64
        blob: Blob
        kzg_commitment: Bytes48
        kzg_proof: Bytes48
        signed_block_header: SignedBeaconBlockHeader.ssz_type
        kzg_commitment_inclusion_proof: Vector(
            Bytes32, p.kzg_commitment_inclusion_proof_depth)

    @container
    class BlobIdentifier:
        block_root: Root
        index: uint64

    # -- PeerDAS data columns (fulu; types/src/data_column_sidecar.rs) -------
    # A cell is one column-slice of a blob: field_elements_per_blob /
    # NUMBER_OF_COLUMNS field elements (no RS extension in this miniature —
    # documented in chain/data_columns.py).
    from ..specs.constants import (
        KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH, NUMBER_OF_COLUMNS,
    )
    # cell of the 2x RS-extended blob (spec BYTES_PER_CELL)
    Cell = ByteVector(64 * p.field_elements_per_blob
                      // NUMBER_OF_COLUMNS)

    @container
    class DataColumnSidecar:
        index: uint64
        column: List(Cell, p.max_blob_commitments_per_block)
        kzg_commitments: List(Bytes48, p.max_blob_commitments_per_block)
        kzg_proofs: List(Bytes48, p.max_blob_commitments_per_block)
        signed_block_header: SignedBeaconBlockHeader.ssz_type
        kzg_commitments_inclusion_proof: Vector(
            Bytes32, KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH)

    @container
    class DataColumnIdentifier:
        block_root: Root
        index: uint64

    # -- light client (altair wire forms; branches at the altair..deneb
    # generalized-index depths — current_sync_committee gindex 54 (depth
    # 5), finalized_root gindex 105 (depth 6); types/src/light_client_*.rs)
    @container
    class LightClientHeader:
        beacon: BeaconBlockHeader.ssz_type

    @container
    class SyncCommitteeUpdate:
        next_sync_committee: SyncCommittee.ssz_type
        next_sync_committee_branch: Vector(Bytes32, 5)

    @container
    class LightClientBootstrap:
        header: LightClientHeader.ssz_type
        current_sync_committee: SyncCommittee.ssz_type
        current_sync_committee_branch: Vector(Bytes32, 5)

    @container
    class LightClientUpdate:
        attested_header: LightClientHeader.ssz_type
        next_sync_committee: SyncCommittee.ssz_type
        next_sync_committee_branch: Vector(Bytes32, 5)
        finalized_header: LightClientHeader.ssz_type
        finality_branch: Vector(Bytes32, 6)
        sync_aggregate: SyncAggregate.ssz_type
        signature_slot: uint64

    @container
    class LightClientFinalityUpdate:
        attested_header: LightClientHeader.ssz_type
        finalized_header: LightClientHeader.ssz_type
        finality_branch: Vector(Bytes32, 6)
        sync_aggregate: SyncAggregate.ssz_type
        signature_slot: uint64

    @container
    class LightClientOptimisticUpdate:
        attested_header: LightClientHeader.ssz_type
        sync_aggregate: SyncAggregate.ssz_type
        signature_slot: uint64

    # -- export everything ---------------------------------------------------
    ns = dict(locals())
    for k, v in ns.items():
        if k not in ("T", "p", "ns", "payload_cls", "body_cls",
                     "payload_base", "body_phase0", "electra_ns",
                     "header_extra", "fork", "body", "blk", "sblk", "k", "v",
                     "fname", "ftyp", "bbody", "bblk", "sbblk"):
            setattr(T, k, v)
    T.max_validators_per_slot = max_validators_per_slot
    T.eth1_votes_limit = eth1_votes_limit
    T.pending_att_limit = pending_att_limit
    T.justification_bits_type = Bitvector(JUSTIFICATION_BITS_LENGTH)
    return T
