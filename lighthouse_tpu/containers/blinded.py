"""Blinded-block helpers: payload <-> header, blind / unblind.

The builder (MEV) flow round-trips through REAL SSZ containers now
(VERDICT r2 missing #4): the VC signs a `SignedBlindedBeaconBlock`
whose body carries the `ExecutionPayloadHeader`, and unblinding splices
the full payload back in after checking the header commitment — the
shape of the reference's `BlindedPayload` machinery
(consensus/types/src/payload.rs; execution_layer/src/lib.rs:807
get_payload; beacon_node/execution_layer/src/lib.rs block proposal
unblinding).
"""
from __future__ import annotations

from ..specs.chain_spec import ForkName
from ..ssz import htr
from ..ssz.merkle import hash_tree_root


def payload_to_header(T, fork: ForkName, payload):
    """ExecutionPayload -> ExecutionPayloadHeader (roots for the
    variable-size fields)."""
    H = T.ExecutionPayloadHeader[fork]
    P = type(payload)
    kw = {}
    for name, _typ in H.__ssz_fields__.items():
        if name == "transactions_root":
            kw[name] = hash_tree_root(P.__ssz_fields__["transactions"],
                                      payload.transactions)
        elif name == "withdrawals_root":
            kw[name] = hash_tree_root(P.__ssz_fields__["withdrawals"],
                                      payload.withdrawals)
        else:
            kw[name] = getattr(payload, name)
    return H(**kw)


def blind_block(T, block):
    """BeaconBlock -> BlindedBeaconBlock (same root by construction)."""
    fork = block.fork_name if hasattr(block, "fork_name") else \
        type(block).fork_name
    body = block.body
    BB = T.BlindedBeaconBlockBody[fork]
    kw = {}
    for name in BB.__ssz_fields__:
        if name == "execution_payload_header":
            kw[name] = payload_to_header(T, fork, body.execution_payload)
        else:
            kw[name] = getattr(body, name)
    blinded_body = BB(**kw)
    return T.BlindedBeaconBlock[fork](
        slot=block.slot, proposer_index=block.proposer_index,
        parent_root=block.parent_root, state_root=block.state_root,
        body=blinded_body)


def blind_signed_block(T, signed):
    fork = type(signed).fork_name
    return T.SignedBlindedBeaconBlock[fork](
        message=blind_block(T, signed.message),
        signature=signed.signature)


class UnblindError(Exception):
    pass


def unblind_signed_block(T, signed_blinded, payload):
    """SignedBlindedBeaconBlock + full payload -> SignedBeaconBlock.

    Refuses to splice a payload whose header does not match the one the
    proposer signed (the builder-equivocation check)."""
    fork = type(signed_blinded).fork_name
    msg = signed_blinded.message
    want = msg.body.execution_payload_header
    got = payload_to_header(T, fork, payload)
    if htr(got) != htr(want):
        raise UnblindError("payload does not match the signed header")
    FB = T.BeaconBlockBody[fork]
    kw = {}
    for name in FB.__ssz_fields__:
        if name == "execution_payload":
            kw[name] = payload
        else:
            kw[name] = getattr(msg.body, name)
    block = T.BeaconBlock[fork](
        slot=msg.slot, proposer_index=msg.proposer_index,
        parent_root=msg.parent_root, state_root=msg.state_root,
        body=FB(**kw))
    return T.SignedBeaconBlock[fork](message=block,
                                     signature=signed_blinded.signature)
