"""The walker + per-runner handlers (see package docstring)."""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from pathlib import Path

import yaml

from ..network.snappy import decompress_block
from ..specs import minimal_spec
from ..specs.chain_spec import ChainSpec, ForkName

# runners/handlers we declare as not implemented (reported, not silent).
# `networking` (fulu custody-group math) and electra's renamed-away
# deposit_receipt are the only remaining declared skips — neither is a
# case type the reference executes (testing/ef_tests/src/cases/ has no
# networking case; deposit_receipt became deposit_request).
SKIPPED_HANDLERS = {
    ("operations", "deposit_receipt"),
    ("networking", None),
}

FORK_DIRS = {
    "phase0": ForkName.PHASE0, "altair": ForkName.ALTAIR,
    "bellatrix": ForkName.BELLATRIX, "capella": ForkName.CAPELLA,
    "deneb": ForkName.DENEB, "electra": ForkName.ELECTRA,
    # fulu state containers are not implemented; ONLY its fork-agnostic
    # kzg (cells) runner is executed — every other fulu runner is a
    # declared skip (see _run_all)
    "fulu": ForkName.ELECTRA,
}
FULU_RUNNERS = {"kzg"}


@dataclass
class CaseResult:
    path: str
    ok: bool
    skipped: bool = False
    error: str = ""


@dataclass
class _Case:
    """File access wrapper enforcing the skip-proof discipline."""
    dir: Path
    accessed: set = field(default_factory=set)

    def read(self, name: str) -> bytes:
        p = self.dir / name
        self.accessed.add(name)
        return p.read_bytes()

    def read_ssz(self, name: str) -> bytes:
        return decompress_block(self.read(name))

    def read_yaml(self, name: str):
        return yaml.safe_load(self.read(name))

    def has(self, name: str) -> bool:
        return (self.dir / name).exists()

    def unaccessed(self) -> list[str]:
        return sorted(f for f in os.listdir(self.dir)
                      if (self.dir / f).is_file() and f not in self.accessed)


class EfTestRunner:
    def __init__(self, tests_root: str | Path):
        self.root = Path(tests_root)

    def _spec_for(self, config: str) -> ChainSpec:
        if config in ("minimal", "general"):   # general: spec-independent
            return minimal_spec()
        if config == "mainnet":
            from ..specs import mainnet_spec
            return mainnet_spec()
        raise ValueError(f"unknown config {config!r}")

    def run(self) -> list[CaseResult]:
        # conformance means REAL crypto: a caller that left the fake
        # backend active (chain tests) must not turn signature-rejection
        # vectors into false passes.  Pin python for the run, restore
        # after (the reference's real-vs-fake split is two separate runs).
        from ..crypto import bls
        prev = bls.get_backend().name
        if prev == "fake":
            bls.set_backend("python")
        try:
            return self._run_all()
        finally:
            bls.set_backend(prev)

    def _run_all(self) -> list[CaseResult]:
        results: list[CaseResult] = []
        for config_dir in sorted(self.root.iterdir()):
            if not config_dir.is_dir():
                continue
            try:
                spec = self._spec_for(config_dir.name)
            except ValueError as e:
                results.append(CaseResult(config_dir.name, ok=True,
                                          skipped=True, error=str(e)))
                continue
            for fork_dir in sorted(config_dir.iterdir()):
                fork = FORK_DIRS.get(fork_dir.name)
                if fork is None:
                    continue
                for runner_dir in sorted(fork_dir.iterdir()):
                    if fork_dir.name == "fulu" and \
                            runner_dir.name not in FULU_RUNNERS:
                        for case_dir in runner_dir.glob("*/*/*"):
                            results.append(CaseResult(
                                str(case_dir.relative_to(self.root)),
                                ok=True, skipped=True,
                                error="fulu state containers not "
                                      "implemented"))
                        continue
                    results += self._run_runner(spec, fork, runner_dir)
        return results

    def _run_runner(self, spec, fork, runner_dir: Path) -> list[CaseResult]:
        runner = runner_dir.name
        out: list[CaseResult] = []
        for handler_dir in sorted(runner_dir.iterdir()):
            handler = handler_dir.name
            fn = _HANDLERS.get(runner)
            declared_skip = ((runner, None) in SKIPPED_HANDLERS
                             or (runner, handler) in SKIPPED_HANDLERS)
            for suite_dir in sorted(handler_dir.iterdir()):
                for case_dir in sorted(suite_dir.iterdir()):
                    rel = str(case_dir.relative_to(self.root))
                    if declared_skip or fn is None:
                        out.append(CaseResult(
                            rel, ok=True, skipped=True,
                            error="" if declared_skip
                            else f"no handler for runner {runner!r}"))
                        continue
                    case = _Case(case_dir)
                    try:
                        fn(spec, fork, handler, case)
                        missed = case.unaccessed()
                        if missed:
                            out.append(CaseResult(
                                rel, ok=False,
                                error=f"files not consumed: {missed}"))
                        else:
                            out.append(CaseResult(rel, ok=True))
                    except _DeclaredSkip as e:
                        out.append(CaseResult(rel, ok=True, skipped=True,
                                              error=str(e)))
                    except Exception as e:  # a failing case, not a crash
                        out.append(CaseResult(rel, ok=False,
                                              error=f"{type(e).__name__}: {e}"))
        return out


class _DeclaredSkip(Exception):
    pass


def _expect(fn, expect_valid: bool, what: str) -> None:
    """Run a fork-choice step honoring the EF `valid: false` convention."""
    if expect_valid:
        fn()
        return
    try:
        fn()
    except Exception:
        return
    raise AssertionError(f"invalid {what} step was accepted")


# ---------------------------------------------------------------------------
# handlers
# ---------------------------------------------------------------------------

def _types(spec):
    from ..containers import get_types
    return get_types(spec.preset)


def _load_state(spec, fork, case: _Case, name: str):
    from ..containers.state import BeaconState
    return BeaconState.from_ssz_bytes(case.read_ssz(name), _types(spec),
                                      spec, fork)


def _ssz_type_for(T, fork, name: str):
    from ..ssz import Root, uint64
    simple = {
        "Checkpoint": T.Checkpoint, "Fork": T.Fork, "ForkData": None,
        "AttestationData": T.AttestationData,
        "BeaconBlockHeader": T.BeaconBlockHeader,
        "SignedBeaconBlockHeader": T.SignedBeaconBlockHeader,
        "Attestation": T.Attestation,
        "IndexedAttestation": T.IndexedAttestation,
        "AttesterSlashing": T.AttesterSlashing,
        "ProposerSlashing": T.ProposerSlashing,
        "Deposit": T.Deposit, "DepositData": T.DepositData,
        "VoluntaryExit": T.VoluntaryExit,
        "SignedVoluntaryExit": T.SignedVoluntaryExit,
        "Eth1Data": T.Eth1Data,
        "SyncAggregate": getattr(T, "SyncAggregate", None),
        "SyncCommittee": getattr(T, "SyncCommittee", None),
        "BeaconBlock": T.BeaconBlock[fork],
        "SignedBeaconBlock": T.SignedBeaconBlock[fork],
        "BeaconBlockBody": T.BeaconBlockBody[fork],
    }
    cls = simple.get(name)
    if cls is None:
        raise _DeclaredSkip(f"ssz_static type {name} not mapped")
    return cls


def _h_ssz_static(spec, fork, handler, case: _Case) -> None:
    from ..ssz import deserialize, htr, serialize
    T = _types(spec)
    cls = _ssz_type_for(T, fork, handler)
    raw = case.read_ssz("serialized.ssz_snappy")
    roots = case.read_yaml("roots.yaml")
    if case.has("value.yaml"):
        case.read("value.yaml")    # structural content covered by the root
    obj = deserialize(cls.ssz_type, raw)
    if serialize(cls.ssz_type, obj) != raw:
        raise AssertionError("ssz roundtrip mismatch")
    got = "0x" + htr(obj).hex()
    if got != roots["root"]:
        raise AssertionError(f"root {got} != {roots['root']}")


_OP_FILES = {
    "attestation": ("attestation.ssz_snappy", "Attestation"),
    "attester_slashing": ("attester_slashing.ssz_snappy",
                          "AttesterSlashing"),
    "block_header": ("block.ssz_snappy", "BeaconBlock"),
    "proposer_slashing": ("proposer_slashing.ssz_snappy",
                          "ProposerSlashing"),
    "voluntary_exit": ("voluntary_exit.ssz_snappy", "SignedVoluntaryExit"),
    "deposit": ("deposit.ssz_snappy", "Deposit"),
    "sync_aggregate": ("sync_aggregate.ssz_snappy", "SyncAggregate"),
    "bls_to_execution_change": ("address_change.ssz_snappy",
                                "SignedBLSToExecutionChange"),
    "withdrawals": ("execution_payload.ssz_snappy", "ExecutionPayload"),
    "deposit_request": ("deposit_request.ssz_snappy", "DepositRequest"),
    "withdrawal_request": ("withdrawal_request.ssz_snappy",
                           "WithdrawalRequest"),
    "consolidation_request": ("consolidation_request.ssz_snappy",
                              "ConsolidationRequest"),
}


def _h_operations(spec, fork, handler, case: _Case) -> None:
    from ..ssz import deserialize
    from ..state_transition import block as blk
    from ..state_transition.block import VerifySignatures
    if handler not in _OP_FILES:
        raise _DeclaredSkip(f"operation {handler} not mapped")
    if case.has("meta.yaml"):
        case.read_yaml("meta.yaml")
    fname, tname = _OP_FILES[handler]
    T = _types(spec)
    pre = _load_state(spec, fork, case, "pre.ssz_snappy")
    if tname in ("SignedBLSToExecutionChange", "DepositRequest",
                 "WithdrawalRequest", "ConsolidationRequest"):
        cls = getattr(T, tname, None)
        if cls is None:
            raise _DeclaredSkip(f"no {tname} type")
    elif tname == "ExecutionPayload":
        cls = T.ExecutionPayload[fork]
    else:
        cls = _ssz_type_for(T, fork, tname)
    op = deserialize(cls.ssz_type, case.read_ssz(fname))
    vs = VerifySignatures.TRUE

    def apply():
        if handler == "attestation":
            blk.process_attestation(pre, op, vs)
        elif handler == "attester_slashing":
            blk.process_attester_slashing(pre, op, vs)
        elif handler == "block_header":
            blk.process_block_header(pre, op)
        elif handler == "proposer_slashing":
            blk.process_proposer_slashing(pre, op, vs)
        elif handler == "voluntary_exit":
            blk.process_voluntary_exit(pre, op, vs)
        elif handler == "deposit":
            blk.process_deposit(pre, op)
        elif handler == "sync_aggregate":
            blk.process_sync_aggregate(pre, op, pre.slot, vs)
        elif handler == "bls_to_execution_change":
            blk.process_bls_to_execution_change(pre, op, vs)
        elif handler == "withdrawals":
            blk.process_withdrawals(pre, op)
        elif handler == "deposit_request":
            blk.process_deposit_request(pre, op)
        elif handler == "withdrawal_request":
            blk.process_withdrawal_request(pre, op)
        elif handler == "consolidation_request":
            blk.process_consolidation_request(pre, op)

    if case.has("post.ssz_snappy"):
        apply()
        post = _load_state(spec, fork, case, "post.ssz_snappy")
        if pre.hash_tree_root() != post.hash_tree_root():
            raise AssertionError("post state root mismatch")
    else:
        try:
            apply()
        except Exception:
            return                   # expected invalid
        raise AssertionError("invalid operation was accepted")


def _h_epoch_processing(spec, fork, handler, case: _Case) -> None:
    from ..state_transition import epoch as ep
    from ..state_transition.helpers import get_total_active_balance
    pre = _load_state(spec, fork, case, "pre.ssz_snappy")
    total = get_total_active_balance(pre)

    def ju_fi():
        if fork == ForkName.PHASE0:
            raise _DeclaredSkip("phase0 ju_fi via full epoch only")
        ep.process_justification_and_finalization(pre, total)

    subs = {
        "justification_and_finalization": ju_fi,
        "inactivity_updates": lambda: ep._process_inactivity_updates(pre),
        "rewards_and_penalties": lambda:
            ep._process_rewards_and_penalties_altair(pre, fork, total),
        "registry_updates": lambda: ep._process_registry_updates(pre, fork),
        "slashings": lambda: ep._process_slashings(pre, fork, total),
        "eth1_data_reset": lambda: ep._process_eth1_data_reset(pre),
        "effective_balance_updates": lambda:
            ep._process_effective_balance_updates(pre),
        "slashings_reset": lambda: ep._process_slashings_reset(pre),
        "randao_mixes_reset": lambda: ep._process_randao_mixes_reset(pre),
        "historical_summaries_update": lambda:
            ep._process_historical_update(pre),
        "historical_roots_update": lambda:
            ep._process_historical_update(pre),
        "participation_flag_updates": lambda:
            ep._process_participation_flag_updates(pre),
        "sync_committee_updates": lambda:
            ep._process_sync_committee_updates(pre),
        "pending_deposits": lambda: ep._process_pending_deposits(pre),
        "pending_consolidations": lambda:
            ep._process_pending_consolidations(pre),
    }
    fn = subs.get(handler)
    if fn is None:
        raise _DeclaredSkip(f"epoch sub-processor {handler} not mapped")
    if case.has("post.ssz_snappy"):
        fn()
        post = _load_state(spec, fork, case, "post.ssz_snappy")
        if pre.hash_tree_root() != post.hash_tree_root():
            raise AssertionError("post state root mismatch")
    else:
        try:
            fn()
        except Exception:
            return
        raise AssertionError("invalid epoch case was accepted")


def _state_transition(state, signed_block) -> None:
    """Full spec state_transition incl. state-root validation."""
    from ..state_transition import per_block_processing, process_slots
    if state.slot < signed_block.message.slot:
        process_slots(state, signed_block.message.slot)
    per_block_processing(state, signed_block)
    if signed_block.message.state_root != state.hash_tree_root():
        raise AssertionError("block state_root mismatch")


def _h_sanity(spec, fork, handler, case: _Case) -> None:
    from ..ssz import deserialize
    from ..state_transition import process_slots
    pre = _load_state(spec, fork, case, "pre.ssz_snappy")
    if handler == "slots":
        n = case.read_yaml("slots.yaml")
        process_slots(pre, pre.slot + int(n))
        post = _load_state(spec, fork, case, "post.ssz_snappy")
        if pre.hash_tree_root() != post.hash_tree_root():
            raise AssertionError("post state root mismatch")
        return
    if handler != "blocks":
        raise _DeclaredSkip(f"sanity handler {handler} not mapped")
    meta = case.read_yaml("meta.yaml") if case.has("meta.yaml") else {}
    n_blocks = int(meta.get("blocks_count", 0))
    T = _types(spec)

    def apply_all():
        for i in range(n_blocks):
            raw = case.read_ssz(f"blocks_{i}.ssz_snappy")
            signed = deserialize(T.SignedBeaconBlock[fork].ssz_type, raw)
            _state_transition(pre, signed)

    if case.has("post.ssz_snappy"):
        apply_all()
        post = _load_state(spec, fork, case, "post.ssz_snappy")
        if pre.hash_tree_root() != post.hash_tree_root():
            raise AssertionError("post state root mismatch")
    else:
        try:
            apply_all()
        except Exception:
            # remaining block files count as consumed (case is invalid)
            for i in range(n_blocks):
                name = f"blocks_{i}.ssz_snappy"
                if case.has(name):
                    case.accessed.add(name)
            return
        raise AssertionError("invalid block chain was accepted")


def _h_bls(spec, fork, handler, case: _Case) -> None:
    from ..crypto import bls
    data = case.read_yaml("data.yaml")
    inp, expect = data["input"], data["output"]
    backend = bls.get_backend()
    if backend.name == "fake":
        # conformance needs real crypto, but never leak the switch into
        # the caller's process-global backend
        prev = backend
        backend = bls._make("python")
        assert bls.get_backend() is prev

    def hx(s):
        return bytes.fromhex(s[2:] if s.startswith("0x") else s)

    if handler == "sign":
        got = backend.sign(int(inp["privkey"], 16), hx(inp["message"]))
        ok = (expect is not None and got == hx(expect))
        if expect is None:
            return                  # invalid privkey cases (not generated)
        if not ok:
            raise AssertionError("signature mismatch")
    elif handler == "verify":
        got = backend.verify(hx(inp["pubkey"]), hx(inp["message"]),
                             hx(inp["signature"]))
        if got != bool(expect):
            raise AssertionError(f"verify {got} != {expect}")
    elif handler == "aggregate":
        try:
            got = backend.aggregate_signatures([hx(s) for s in
                                                inp])
        except ValueError:
            got = None
        want = hx(expect) if expect else None
        if got != want:
            raise AssertionError("aggregate mismatch")
    elif handler == "fast_aggregate_verify":
        got = backend.fast_aggregate_verify(
            [hx(p) for p in inp["pubkeys"]], hx(inp["message"]),
            hx(inp["signature"]))
        if got != bool(expect):
            raise AssertionError(f"fast_aggregate_verify {got} != {expect}")
    elif handler == "aggregate_verify":
        got = backend.aggregate_verify(
            [hx(p) for p in inp["pubkeys"]],
            [hx(m) for m in inp["messages"]], hx(inp["signature"]))
        if got != bool(expect):
            raise AssertionError(f"aggregate_verify {got} != {expect}")
    elif handler == "eth_aggregate_pubkeys":
        pks = [hx(p) for p in inp]
        # eth spec: empty input and KeyValidate failures (infinity,
        # off-curve) reject
        if not pks or any(not backend.validate_pubkey(p) for p in pks):
            got = None
        else:
            try:
                got = backend.aggregate_public_keys(pks)
            except Exception:
                got = None
        want = hx(expect) if expect else None
        if got != want:
            raise AssertionError("eth_aggregate_pubkeys mismatch")
    elif handler == "eth_fast_aggregate_verify":
        # eth variant: empty pubkeys + infinity signature -> True
        pks = [hx(p) for p in inp["pubkeys"]]
        sig = hx(inp["signature"])
        if not pks and sig == b"\xc0" + b"\x00" * 95:
            got = True
        else:
            got = backend.fast_aggregate_verify(pks, hx(inp["message"]),
                                                sig)
        if got != bool(expect):
            raise AssertionError(
                f"eth_fast_aggregate_verify {got} != {expect}")
    else:
        raise _DeclaredSkip(f"bls handler {handler} not mapped")


def _run_fc_steps(spec, fork, case: _Case, optimistic: bool) -> None:
    """Shared fork-choice step driver (fork_choice + sync runners).

    `optimistic=True` adds the sync runner's payload-status semantics:
    blocks import with the engine-reported status of their payload
    (default SYNCING/optimistic), and on_payload_info steps propagate
    invalidation through the proto-array."""
    from ..fork_choice import ForkChoice
    from ..fork_choice.proto_array import ExecutionStatus
    from ..ssz import deserialize, htr
    T = _types(spec)
    anchor = _load_state(spec, fork, case, "anchor_state.ssz_snappy")
    anchor_blk_raw = case.read_ssz("anchor_block.ssz_snappy")
    # a genesis anchor block may carry an earlier fork's (empty) body
    anchor_block = None
    for f in [fk for fk in ForkName if fk <= fork][::-1]:
        try:
            anchor_block = deserialize(T.BeaconBlock[f].ssz_type,
                                       anchor_blk_raw)
            break
        except Exception:
            continue
    if anchor_block is None:
        raise AssertionError("anchor block undecodable")
    anchor_root = htr(anchor_block)
    fc = ForkChoice(spec, anchor_root, anchor)
    states = {anchor_root: anchor}
    payload_status: dict[bytes, str] = {}
    hash_to_root: dict[bytes, bytes] = {}      # payload hash -> block root
    current_slot = anchor.slot
    for step in case.read_yaml("steps.yaml"):
        expect_valid = bool(step.get("valid", True))
        if "tick" in step:
            # spec get_current_slot: (time - genesis_time) // spt
            current_slot = max(0, int(step["tick"]) - anchor.genesis_time) \
                // spec.seconds_per_slot
            fc.update_time(current_slot)
        elif "block" in step:
            raw = case.read_ssz(step["block"] + ".ssz_snappy")

            def apply_block():
                signed = deserialize(T.SignedBeaconBlock[fork].ssz_type,
                                     raw)
                parent = states[signed.message.parent_root].copy()
                _state_transition(parent, signed)
                root = htr(signed.message)
                es = ExecutionStatus.IRRELEVANT
                if optimistic:
                    body = signed.message.body
                    bh = body.execution_payload.block_hash \
                        if hasattr(body, "execution_payload") \
                        else b"\x00" * 32
                    hash_to_root[bh] = root
                    status = payload_status.get(bh, "SYNCING")
                    if status == "INVALID":
                        raise AssertionError("invalid payload")
                    es = ExecutionStatus.VALID if status == "VALID" \
                        else ExecutionStatus.OPTIMISTIC
                fc.on_block(current_slot, signed.message, root, parent,
                            execution_status=es)
                states[root] = parent

            _expect(apply_block, expect_valid, "block")
        elif "attestation" in step:
            raw = case.read_ssz(step["attestation"] + ".ssz_snappy")

            def apply_att():
                att = deserialize(T.Attestation.ssz_type, raw)
                from ..state_transition.helpers import (
                    get_indexed_attestation,
                )
                st = states[att.data.beacon_block_root]
                indexed = get_indexed_attestation(st, att)
                fc.on_attestation(current_slot, indexed)

            _expect(apply_att, expect_valid, "attestation")
        elif optimistic and "payload_status" in step:
            bh = bytes.fromhex(step["block_hash"][2:])
            ps = step["payload_status"]
            status = ps["status"]
            payload_status[bh] = status
            root = hash_to_root.get(bh)
            if root is not None:
                if status == "INVALID":
                    lvh = ps.get("latest_valid_hash")
                    fc.on_invalid_execution_payload(
                        root,
                        bytes.fromhex(lvh[2:]) if lvh else None)
                elif status == "VALID":
                    fc.on_valid_execution_payload(root)
        elif "checks" in step:
            checks = step["checks"]
            head = fc.get_head(current_slot)
            known = {"head", "justified_checkpoint", "finalized_checkpoint",
                     "proposer_boost_root", "time", "genesis_time"}
            unknown = set(checks) - known
            if unknown:
                raise _DeclaredSkip(f"checks {sorted(unknown)} not mapped")
            if "head" in checks:
                want = bytes.fromhex(checks["head"]["root"][2:])
                if head != want:
                    raise AssertionError(
                        f"head {head.hex()} != {want.hex()}")
            if "proposer_boost_root" in checks:
                want = bytes.fromhex(checks["proposer_boost_root"][2:])
                if fc.proposer_boost_root != want:
                    raise AssertionError("proposer_boost_root mismatch")
            for key, got in (("justified_checkpoint",
                              fc.justified_checkpoint),
                             ("finalized_checkpoint",
                              fc.finalized_checkpoint)):
                if key in checks:
                    want = checks[key]
                    if got[0] != int(want["epoch"]) or \
                            got[1] != bytes.fromhex(want["root"][2:]):
                        raise AssertionError(f"{key} mismatch")
        else:
            raise _DeclaredSkip(f"fork choice step {step} not mapped")


def _h_fork_choice(spec, fork, handler, case: _Case) -> None:
    _run_fc_steps(spec, fork, case, optimistic=False)


def _h_shuffling(spec, fork, handler, case: _Case) -> None:
    """mapping[i] == compute_shuffled_index(i, count, seed); the whole
    permutation must also match the vectorized whole-list shuffle
    (consensus/swap_or_not_shuffle parity)."""
    import numpy as np
    from ..state_transition.shuffle import (
        compute_shuffled_index, compute_shuffled_indices,
    )
    data = case.read_yaml("mapping.yaml")
    seed = bytes.fromhex(data["seed"][2:])
    count = int(data["count"])
    mapping = [int(x) for x in data["mapping"]]
    rounds = spec.preset.shuffle_round_count
    if count == 0:   # real tarballs include an empty-list case
        if mapping:
            raise AssertionError("count=0 with non-empty mapping")
        return
    for i in (0, count // 2, count - 1):
        got = compute_shuffled_index(i, count, seed, rounds)
        if got != mapping[i]:
            raise AssertionError(f"index {i}: {got} != {mapping[i]}")
    vec = compute_shuffled_indices(count, seed, rounds)
    if list(np.asarray(vec)) != mapping:
        raise AssertionError("vectorized shuffle mismatch")


def _h_kzg(spec, fork, handler, case: _Case) -> None:
    """deneb blob KZG + fulu cells cases over the devnet setup.  Real EF
    tarballs use the mainnet ceremony setup, which is not bundled
    (zero-egress image) — those suites are declared skips, not failures."""
    from ..crypto.kzg import Kzg
    if case.dir.parent.name != "kzg-devnet":
        raise _DeclaredSkip("mainnet trusted setup not bundled")
    global _KZG_DEVNET
    if _KZG_DEVNET is None:
        _KZG_DEVNET = Kzg(devnet_size=16, cells_per_ext_blob=8)
    k = _KZG_DEVNET
    data = case.read_yaml("data.yaml")
    inp, out = data["input"], data["output"]

    def hx(s):
        return bytes.fromhex(s[2:])

    if handler == "blob_to_kzg_commitment":
        got = k.blob_to_kzg_commitment(hx(inp["blob"]))
        if got != hx(out):
            raise AssertionError("commitment mismatch")
    elif handler == "verify_blob_kzg_proof":
        got = k.verify_blob_kzg_proof(hx(inp["blob"]),
                                      hx(inp["commitment"]),
                                      hx(inp["proof"]))
        if got != bool(out):
            raise AssertionError(f"verify {got} != {out}")
    elif handler == "verify_blob_kzg_proof_batch":
        got = k.verify_blob_kzg_proof_batch(
            [hx(b) for b in inp["blobs"]],
            [hx(c) for c in inp["commitments"]],
            [hx(p) for p in inp["proofs"]])
        if got != bool(out):
            raise AssertionError(f"batch verify {got} != {out}")
    elif handler == "compute_cells_and_kzg_proofs":
        cells, proofs = k.compute_cells_and_kzg_proofs(hx(inp["blob"]))
        want_cells = [hx(c) for c in out[0]]
        want_proofs = [hx(p) for p in out[1]]
        if cells != want_cells or proofs != want_proofs:
            raise AssertionError("cells/proofs mismatch")
    elif handler == "verify_cell_kzg_proof_batch":
        got = k.verify_cell_kzg_proof_batch(
            [hx(c) for c in inp["commitments"]],
            [int(i) for i in inp["cell_indices"]],
            [hx(c) for c in inp["cells"]],
            [hx(p) for p in inp["proofs"]])
        if got != bool(out):
            raise AssertionError(f"cell batch verify {got} != {out}")
    elif handler == "recover_cells_and_kzg_proofs":
        cells, proofs = k.recover_cells_and_kzg_proofs(
            [int(i) for i in inp["cell_indices"]],
            [hx(c) for c in inp["cells"]])
        if cells != [hx(c) for c in out[0]] or \
                proofs != [hx(p) for p in out[1]]:
            raise AssertionError("recovered cells/proofs mismatch")
    else:
        raise _DeclaredSkip(f"kzg handler {handler} not mapped")


_KZG_DEVNET = None


def _h_transition(spec, fork, handler, case: _Case) -> None:
    """Fork-boundary transition: apply blocks across the upgrade and
    compare the final state root (testing transition runner layout)."""
    from ..specs import minimal_spec
    from ..specs.chain_spec import FORK_ORDER
    from ..ssz import deserialize
    if spec.config_name != "minimal":
        raise _DeclaredSkip("transition vectors run on minimal only here")
    meta = case.read_yaml("meta.yaml")
    post_fork = ForkName[meta["post_fork"].upper()]
    fork_epoch = int(meta["fork_epoch"])
    overrides = {}
    for f in FORK_ORDER[1:]:           # genesis fork has no epoch knob
        if f < post_fork:
            overrides[f"{f.name.lower()}_fork_epoch"] = 0
        elif f == post_fork:
            overrides[f"{f.name.lower()}_fork_epoch"] = fork_epoch
    tspec = minimal_spec(**overrides)
    pre_fork = FORK_ORDER[FORK_ORDER.index(post_fork) - 1]
    state = _load_state(tspec, pre_fork, case, "pre.ssz_snappy")
    T = _types(tspec)
    fork_block = int(meta.get("fork_block", -1))
    for i in range(int(meta["blocks_count"])):
        raw = case.read_ssz(f"blocks_{i}.ssz_snappy")
        bfork = pre_fork if i <= fork_block else post_fork
        signed = deserialize(T.SignedBeaconBlock[bfork].ssz_type, raw)
        _state_transition(state, signed)
    post = _load_state(tspec, post_fork, case, "post.ssz_snappy")
    if state.hash_tree_root() != post.hash_tree_root():
        raise AssertionError("transition post state root mismatch")


# ---------------------------------------------------------------------------
# round-3 runners (VERDICT r2 missing #2: no declared-skip runners left)
# ---------------------------------------------------------------------------

def _h_finality(spec, fork, handler, case: _Case) -> None:
    """finality runner: identical case shape to sanity/blocks (the
    reference binds it to the SanityBlocks case, handler.rs:532)."""
    _h_sanity(spec, fork, "blocks", case)


def _h_random(spec, fork, handler, case: _Case) -> None:
    """random runner: sanity/blocks shape (handler.rs:421)."""
    _h_sanity(spec, fork, "blocks", case)


def _h_fork(spec, fork, handler, case: _Case) -> None:
    """Fork-upgrade runner: pre-state in the PREVIOUS fork, apply the
    in-place upgrade function, compare roots (cases/fork.rs)."""
    from ..specs.chain_spec import FORK_ORDER
    from ..state_transition import upgrades
    meta = case.read_yaml("meta.yaml")
    post_fork = ForkName[meta["fork"].upper()]
    if post_fork != fork:
        raise AssertionError(f"meta fork {post_fork} != dir fork {fork}")
    pre_fork = FORK_ORDER[FORK_ORDER.index(post_fork) - 1]
    pre = _load_state(spec, pre_fork, case, "pre.ssz_snappy")
    fn = getattr(upgrades, f"upgrade_to_{post_fork.name.lower()}")
    fn(pre)
    post = _load_state(spec, post_fork, case, "post.ssz_snappy")
    if pre.hash_tree_root() != post.hash_tree_root():
        raise AssertionError("fork upgrade post state root mismatch")


def _deltas_type():
    # NB: built via type() because this module has PEP-563 lazy
    # annotations — a class-body annotation would reach @container as a
    # string, not an SSZType
    from ..ssz import List, container, uint64
    return container(type("Deltas", (), {"__annotations__": dict(
        rewards=List(uint64, 1 << 40),
        penalties=List(uint64, 1 << 40))}))


def _h_rewards(spec, fork, handler, case: _Case) -> None:
    """Per-component reward/penalty deltas (cases/rewards.rs): compare
    our vectorized delta computation to the vectors, component-wise."""
    import numpy as np
    from ..ssz import deserialize
    from ..state_transition import epoch as ep
    from ..state_transition.helpers import get_total_active_balance
    Deltas = _deltas_type()
    pre = _load_state(spec, fork, case, "pre.ssz_snappy")
    total = get_total_active_balance(pre)

    def check(name: str, rewards: np.ndarray, penalties: np.ndarray):
        want = deserialize(Deltas.ssz_type,
                           case.read_ssz(f"{name}.ssz_snappy"))
        if list(want.rewards) != [int(x) for x in rewards] or \
                list(want.penalties) != [int(x) for x in penalties]:
            raise AssertionError(f"{name} deltas mismatch")

    if fork == ForkName.PHASE0:
        comp = ep.phase0_reward_deltas(pre, total)
        check("source_deltas", *comp["source"])
        check("target_deltas", *comp["target"])
        check("head_deltas", *comp["head"])
        check("inclusion_delay_deltas", *comp["inclusion_delay"])
        check("inactivity_penalty_deltas", *comp["inactivity"])
    else:
        from ..specs.constants import (
            TIMELY_HEAD_FLAG_INDEX, TIMELY_SOURCE_FLAG_INDEX,
            TIMELY_TARGET_FLAG_INDEX,
        )
        for name, idx in (("source_deltas", TIMELY_SOURCE_FLAG_INDEX),
                          ("target_deltas", TIMELY_TARGET_FLAG_INDEX),
                          ("head_deltas", TIMELY_HEAD_FLAG_INDEX)):
            check(name, *ep.altair_flag_deltas(pre, total, idx))
        check("inactivity_penalty_deltas",
              *ep.altair_inactivity_deltas(pre, pre.fork_name))


def _h_genesis(spec, fork, handler, case: _Case) -> None:
    from ..specs import minimal_spec
    from ..ssz import deserialize
    from ..state_transition import genesis as gen
    T = _types(spec)
    if handler == "validity":
        state = _load_state(spec, fork, case, "genesis.ssz_snappy")
        want = bool(case.read_yaml("is_valid.yaml"))
        got = gen.is_valid_genesis_state(state)
        if got != want:
            raise AssertionError(f"genesis validity {got} != {want}")
        return
    if handler != "initialization":
        raise _DeclaredSkip(f"genesis handler {handler} not mapped")
    if spec.config_name != "minimal":
        raise _DeclaredSkip("genesis initialization runs on minimal only")
    # genesis lands at the case's fork: pin every fork <= it to epoch 0
    # (initialize_beacon_state_from_eth1 derives the genesis fork from
    # the spec, matching the reference's all-fork genesis support)
    tspec = minimal_spec(**{
        f"{f.name.lower()}_fork_epoch": 0
        for f in ForkName if ForkName.PHASE0 < f <= fork})
    eth1 = case.read_yaml("eth1.yaml")
    meta = case.read_yaml("meta.yaml")
    deposits = [deserialize(T.Deposit.ssz_type,
                            case.read_ssz(f"deposits_{i}.ssz_snappy"))
                for i in range(int(meta["deposits_count"]))]
    header = None
    if case.has("execution_payload_header.ssz_snappy"):
        header = deserialize(
            T.ExecutionPayloadHeader[fork].ssz_type,
            case.read_ssz("execution_payload_header.ssz_snappy"))
    state = gen.initialize_beacon_state_from_eth1(
        tspec, bytes.fromhex(eth1["eth1_block_hash"][2:]),
        int(eth1["eth1_timestamp"]), deposits,
        execution_payload_header=header)
    want = _load_state(tspec, fork, case, "state.ssz_snappy")
    if state.hash_tree_root() != want.hash_tree_root():
        raise AssertionError("genesis state root mismatch")


# ssz_generic: case names encode the type (spec-tests layout)

def _ssz_generic_type(handler: str, case_name: str):
    from ..ssz import (
        Bitlist, Bitvector, Boolean, List, UInt, Vector, container, uint8,
        uint16, uint32, uint64, uint128, uint256,
    )
    uints = {8: uint8, 16: uint16, 32: uint32, 64: uint64, 128: uint128,
             256: uint256}
    parts = case_name.split("_")
    if handler == "boolean":
        return Boolean()
    if handler == "uints":
        return uints[int(parts[1])]
    if handler == "basic_vector":
        # vec_{elemtype}_{length}_...
        elem = Boolean() if parts[1] == "bool" else \
            uints[int(parts[1].removeprefix("uint"))]
        return Vector(elem, int(parts[2]))
    if handler == "bitvector":
        return Bitvector(int(parts[1]))
    if handler == "bitlist":
        if parts[1] == "no":          # bitlist_no_delimiter_*
            return Bitlist(64)
        return Bitlist(int(parts[1]))
    if handler == "containers":
        return _ssz_generic_container(parts[0])
    raise _DeclaredSkip(f"ssz_generic handler {handler} not mapped")


def _ssz_generic_container(name: str):
    """The spec-tests container zoo (ssz_generic/containers).  Built via
    type() — see _deltas_type's PEP-563 note."""
    from ..ssz import (
        Bitlist, Bitvector, List, Vector, container, uint8, uint16,
        uint32, uint64,
    )

    def mk(cls_name, **fields):
        return container(type(cls_name, (),
                              {"__annotations__": fields}))

    SingleFieldTestStruct = mk("SingleFieldTestStruct", A=uint8)
    SmallTestStruct = mk("SmallTestStruct", A=uint16, B=uint16)
    FixedTestStruct = mk("FixedTestStruct", A=uint8, B=uint64, C=uint32)
    VarTestStruct = mk("VarTestStruct", A=uint16, B=List(uint16, 1024),
                       C=uint8)
    ComplexTestStruct = mk(
        "ComplexTestStruct", A=uint16, B=List(uint16, 128), C=uint8,
        D=List(uint8, 256), E=VarTestStruct.ssz_type,
        F=Vector(FixedTestStruct.ssz_type, 4),
        G=Vector(VarTestStruct.ssz_type, 2))
    BitsStruct = mk("BitsStruct", A=Bitlist(5), B=Bitvector(2),
                    C=Bitvector(1), D=Bitlist(6), E=Bitvector(8))

    zoo = {c.__name__: c for c in (
        SingleFieldTestStruct, SmallTestStruct, FixedTestStruct,
        VarTestStruct, ComplexTestStruct, BitsStruct)}
    cls = zoo.get(name)
    if cls is None:
        raise _DeclaredSkip(f"ssz_generic container {name} not mapped")
    return cls.ssz_type


def _h_ssz_generic(spec, fork, handler, case: _Case) -> None:
    from ..ssz import deserialize, serialize
    from ..ssz.codec import DeserializeError
    from ..ssz.merkle import hash_tree_root
    suite = case.dir.parent.name        # "valid" | "invalid"
    raw = case.read_ssz("serialized.ssz_snappy")
    if suite == "invalid":
        try:
            # zero-length Vector/Bitvector etc. are invalid TYPES: a
            # construction-time rejection counts as rejecting the case
            typ = _ssz_generic_type(handler, case.dir.name)
            deserialize(typ, raw)
        except (DeserializeError, ValueError, IndexError, AssertionError):
            return
        raise AssertionError("invalid ssz_generic case was accepted")
    typ = _ssz_generic_type(handler, case.dir.name)
    meta = case.read_yaml("meta.yaml")
    if case.has("value.yaml"):
        case.read("value.yaml")         # structure covered by the root
    obj = deserialize(typ, raw)
    if serialize(typ, obj) != raw:
        raise AssertionError("ssz_generic roundtrip mismatch")
    got = "0x" + hash_tree_root(typ, obj).hex()
    if got != meta["root"]:
        raise AssertionError(f"root {got} != {meta['root']}")


def _h_merkle_proof(spec, fork, handler, case: _Case) -> None:
    """single_merkle_proof (incl. the deneb KZG-commitment inclusion
    proof): recompute the branch root bottom-up with plain hashing and
    compare against the object's hash tree root (cases/
    merkle_proof_validity.rs + kzg inclusion variant)."""
    from ..ssz import deserialize, htr
    from ..ssz.merkle_proof import merkle_root_from_branch
    proof = case.read_yaml("proof.yaml")
    leaf = bytes.fromhex(proof["leaf"][2:])
    gindex = int(proof["leaf_index"])
    branch = [bytes.fromhex(b[2:]) for b in proof["branch"]]
    obj_name = case.dir.parent.name
    T = _types(spec)
    if obj_name == "BeaconState":
        root = _load_state(spec, fork, case,
                           "object.ssz_snappy").hash_tree_root()
    elif obj_name == "BeaconBlockBody":
        obj = deserialize(T.BeaconBlockBody[fork].ssz_type,
                          case.read_ssz("object.ssz_snappy"))
        root = htr(obj)
    else:
        raise _DeclaredSkip(f"merkle_proof object {obj_name}")
    got = merkle_root_from_branch(leaf, branch, gindex)
    if got != root:
        raise AssertionError(
            f"merkle proof root {got.hex()} != {root.hex()}")


def _h_light_client(spec, fork, handler, case: _Case) -> None:
    """light_client/single_merkle_proof — the case shape the reference
    binds (handler.rs:799; sync/update-ranking protocol cases are not
    reference case types)."""
    if handler != "single_merkle_proof":
        raise _DeclaredSkip(f"light_client handler {handler} not mapped")
    _h_merkle_proof(spec, fork, handler, case)


def _h_sync(spec, fork, handler, case: _Case) -> None:
    """sync/optimistic: fork-choice steps + engine payload-status
    injections (on_payload_info), driving optimistic import and
    invalidation through the proto-array."""
    _run_fc_steps(spec, fork, case, optimistic=True)


_HANDLERS = {
    "ssz_static": _h_ssz_static,
    "operations": _h_operations,
    "epoch_processing": _h_epoch_processing,
    "sanity": _h_sanity,
    "bls": _h_bls,
    "fork_choice": _h_fork_choice,
    "shuffling": _h_shuffling,
    "kzg": _h_kzg,
    "transition": _h_transition,
    "finality": _h_finality,
    "random": _h_random,
    "fork": _h_fork,
    "rewards": _h_rewards,
    "genesis": _h_genesis,
    "ssz_generic": _h_ssz_generic,
    "merkle_proof": _h_merkle_proof,
    "light_client": _h_light_client,
    "sync": _h_sync,
}
