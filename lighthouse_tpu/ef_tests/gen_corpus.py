"""Generate the committed offline mini-corpus under tests/ef_vectors/.

Independence notes (what keeps these vectors from being pure self-echo):
- ssz_static serializations AND roots are built by HAND here (hashlib
  sha256 + manual little-endian packing), not via lighthouse_tpu.ssz.
- bls vectors are produced by the native C++ backend (an independent
  implementation, itself pinned to RFC 9380 constants), and consumed by
  the python oracle in the runner.
- operations/epoch/sanity/finality post-states are verified at
  GENERATION time against the independent scalar spec transcriptions
  (scalar_spec.py for altair, scalar_spec_electra.py for capella/electra
  — gen_corpus_r3.py / gen_corpus_r5.py), so a vectorized-STF bug fails
  generation instead of being enshrined; fork_choice steps encode
  hand-specified behavioral expectations.  The real EF tarballs would
  still widen case coverage when network access allows.

Run: python -m lighthouse_tpu.ef_tests.gen_corpus [dest_root]
"""
from __future__ import annotations

import hashlib
import shutil
import sys
from pathlib import Path

import yaml

from ..network.snappy import compress_block

ZERO32 = b"\x00" * 32


def hp(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def u64c(v: int) -> bytes:
    return v.to_bytes(8, "little") + b"\x00" * 24


def pad4(c: bytes) -> bytes:
    return c + b"\x00" * (32 - len(c))


def merkle(leaves: list[bytes]) -> bytes:
    n = 1
    while n < len(leaves):
        n *= 2
    nodes = leaves + [ZERO32] * (n - len(leaves))
    while len(nodes) > 1:
        nodes = [hp(nodes[i], nodes[i + 1]) for i in range(0, len(nodes), 2)]
    return nodes[0]


def sig_root(sig96: bytes) -> bytes:
    return merkle([sig96[0:32], sig96[32:64], sig96[64:96] + b""])


# -- hand-built containers ----------------------------------------------------

def checkpoint(epoch: int, root: bytes):
    ser = epoch.to_bytes(8, "little") + root
    return ser, merkle([u64c(epoch), root])


def fork(prev: bytes, cur: bytes, epoch: int):
    ser = prev + cur + epoch.to_bytes(8, "little")
    return ser, merkle([pad4(prev), pad4(cur), u64c(epoch)])


def eth1_data(dep_root: bytes, count: int, block_hash: bytes):
    ser = dep_root + count.to_bytes(8, "little") + block_hash
    return ser, merkle([dep_root, u64c(count), block_hash])


def att_data(slot: int, index: int, bbr: bytes, src, tgt):
    s_ser, s_root = checkpoint(*src)
    t_ser, t_root = checkpoint(*tgt)
    ser = (slot.to_bytes(8, "little") + index.to_bytes(8, "little")
           + bbr + s_ser + t_ser)
    return ser, merkle([u64c(slot), u64c(index), bbr, s_root, t_root])


def block_header(slot, proposer, parent, state, body):
    ser = (slot.to_bytes(8, "little") + proposer.to_bytes(8, "little")
           + parent + state + body)
    return ser, merkle([u64c(slot), u64c(proposer), parent, state, body])


def signed_voluntary_exit(epoch, vindex, sig96):
    msg_ser = epoch.to_bytes(8, "little") + vindex.to_bytes(8, "little")
    msg_root = merkle([u64c(epoch), u64c(vindex)])
    ser = msg_ser + sig96
    return ser, merkle([msg_root, sig_root(sig96)])


# -- writers ------------------------------------------------------------------

def wcase(root: Path, *parts: str) -> Path:
    d = root.joinpath(*parts)
    d.mkdir(parents=True, exist_ok=True)
    return d


def w_ssz(d: Path, name: str, raw: bytes) -> None:
    (d / name).write_bytes(compress_block(raw))


def w_yaml(d: Path, name: str, obj) -> None:
    (d / name).write_text(yaml.safe_dump(obj))


def gen_ssz_static(root: Path) -> int:
    import random
    rng = random.Random(42)

    def r32():
        return bytes(rng.randrange(256) for _ in range(32))

    n = 0
    cases = []
    for i in range(6):
        cases.append(("Checkpoint", *checkpoint(rng.randrange(2**40), r32())))
    cases.append(("Checkpoint", *checkpoint(0, ZERO32)))
    cases.append(("Checkpoint", *checkpoint(2**64 - 1, b"\xff" * 32)))
    for i in range(2):
        cases.append(("Fork", *fork(bytes(rng.randrange(256)
                                          for _ in range(4)),
                                    bytes(rng.randrange(256)
                                          for _ in range(4)),
                                    rng.randrange(2**30))))
    for i in range(2):
        cases.append(("Eth1Data", *eth1_data(r32(), rng.randrange(2**32),
                                             r32())))
    for i in range(2):
        cases.append(("AttestationData", *att_data(
            rng.randrange(2**32), rng.randrange(64), r32(),
            (rng.randrange(2**32), r32()), (rng.randrange(2**32), r32()))))
    for i in range(2):
        cases.append(("BeaconBlockHeader", *block_header(
            rng.randrange(2**32), rng.randrange(2**20), r32(), r32(),
            r32())))
    for i in range(2):
        sig = bytes(rng.randrange(256) for _ in range(96))
        cases.append(("SignedVoluntaryExit", *signed_voluntary_exit(
            rng.randrange(2**32), rng.randrange(2**20), sig)))
    counters: dict[str, int] = {}
    for tname, ser, rt in cases:
        idx = counters.get(tname, 0)
        counters[tname] = idx + 1
        d = wcase(root, "minimal", "altair", "ssz_static", tname,
                  "ssz_random", f"case_{idx}")
        w_ssz(d, "serialized.ssz_snappy", ser)
        w_yaml(d, "roots.yaml", {"root": "0x" + rt.hex()})
        n += 1
    return n


def gen_bls(root: Path) -> int:
    from ..crypto.bls.cpp_backend import CppBackend
    b = CppBackend()
    n = 0

    def case(handler, idx, inp, out):
        nonlocal n
        d = wcase(root, "general", "phase0", "bls", handler, "small",
                  f"case_{idx}")
        w_yaml(d, "data.yaml", {"input": inp, "output": out})
        n += 1

    msgs = [b"\x11" * 32, b"\xab" * 32, b"\x00" * 32, b"\x5a" * 32]
    sks = [1, 42, 2**200 + 7, 12345678901234567890]
    for i, (sk, m) in enumerate(zip(sks, msgs)):
        sig = b.sign(sk, m)
        case("sign", i, {"privkey": f"0x{sk:064x}",
                         "message": "0x" + m.hex()}, "0x" + sig.hex())
    for i in range(4):
        sk, m = sks[i], msgs[i]
        pk, sig = b.sk_to_pk(sk), b.sign(sk, m)
        case("verify", i, {"pubkey": "0x" + pk.hex(),
                           "message": "0x" + m.hex(),
                           "signature": "0x" + sig.hex()}, True)
    # negative verifies: wrong message / wrong key
    pk0, sig0 = b.sk_to_pk(sks[0]), b.sign(sks[0], msgs[0])
    case("verify", 4, {"pubkey": "0x" + pk0.hex(),
                       "message": "0x" + msgs[1].hex(),
                       "signature": "0x" + sig0.hex()}, False)
    case("verify", 5, {"pubkey": "0x" + b.sk_to_pk(sks[1]).hex(),
                       "message": "0x" + msgs[0].hex(),
                       "signature": "0x" + sig0.hex()}, False)
    for i in range(2):
        sigs = [b.sign(sk, msgs[i]) for sk in sks[:3]]
        agg = b.aggregate_signatures(sigs)
        case("aggregate", i, ["0x" + s.hex() for s in sigs],
             "0x" + agg.hex())
    for i in range(3):
        group = sks[:i + 2]
        sigs = [b.sign(sk, msgs[0]) for sk in group]
        agg = b.aggregate_signatures(sigs)
        case("fast_aggregate_verify", i,
             {"pubkeys": ["0x" + b.sk_to_pk(sk).hex() for sk in group],
              "message": "0x" + msgs[0].hex(),
              "signature": "0x" + agg.hex()}, True)
    sigs = [b.sign(sk, m) for sk, m in zip(sks[:3], msgs[:3])]
    agg = b.aggregate_signatures(sigs)
    case("aggregate_verify", 0,
         {"pubkeys": ["0x" + b.sk_to_pk(sk).hex() for sk in sks[:3]],
          "messages": ["0x" + m.hex() for m in msgs[:3]],
          "signature": "0x" + agg.hex()}, True)
    case("aggregate_verify", 1,
         {"pubkeys": ["0x" + b.sk_to_pk(sk).hex() for sk in sks[:3]],
          "messages": ["0x" + m.hex() for m in reversed(msgs[:3])],
          "signature": "0x" + agg.hex()}, False)
    return n


def _mini_chain():
    from ..crypto import bls
    bls.set_backend("python")
    from ..chain.harness import BeaconChainHarness
    from ..specs import minimal_spec
    spec = minimal_spec(altair_fork_epoch=0)
    h = BeaconChainHarness(spec, 16)
    return h, spec


def _write_state(d: Path, name: str, state) -> None:
    w_ssz(d, name, state.serialize())


def gen_state_cases(root: Path) -> int:
    """operations + epoch_processing + sanity + fork_choice vectors."""
    from ..ssz import htr, serialize
    from ..state_transition import per_block_processing, process_slots
    from ..state_transition import block as blk
    from ..state_transition import epoch as ep
    from ..state_transition.block import VerifySignatures
    from ..state_transition.helpers import (
        get_beacon_committee, get_beacon_proposer_index,
        get_total_active_balance,
    )
    from . import scalar_spec
    h, spec = _mini_chain()
    T = h.T
    n = 0
    h.extend_chain(2 * spec.preset.slots_per_epoch + 2)
    base = h.chain.head().head_state

    # ---- operations/block_header: valid + invalid (bad proposer) ----
    h.advance_slot()
    slot = h.chain.slot()
    st = base.copy()
    process_slots(st, slot)
    proposer = get_beacon_proposer_index(st, slot)
    reveal = h.randao_reveal(st, slot, proposer)
    block, _post = h.chain.produce_block(reveal, slot)
    d = wcase(root, "minimal", "altair", "operations", "block_header",
              "pyspec_tests", "valid_header")
    _write_state(d, "pre.ssz_snappy", st)
    w_ssz(d, "block.ssz_snappy", serialize(type(block).ssz_type, block))
    good = st.copy()
    blk.process_block_header(good, block)
    scalar_spec.verify_block_header_op(st, block, good)
    _write_state(d, "post.ssz_snappy", good)
    n += 1
    d = wcase(root, "minimal", "altair", "operations", "block_header",
              "pyspec_tests", "invalid_proposer")
    _write_state(d, "pre.ssz_snappy", st)
    bad = T.BeaconBlock[st.fork_name](
        slot=block.slot, proposer_index=(block.proposer_index + 1) % 16,
        parent_root=block.parent_root, state_root=block.state_root,
        body=block.body)
    w_ssz(d, "block.ssz_snappy", serialize(type(bad).ssz_type, bad))
    n += 1

    # ---- operations/attestation: valid + invalid target ----
    h.attest_to_head()
    st2 = base.copy()
    process_slots(st2, h.chain.slot() + 1)
    att = h.chain.op_pool.get_attestations_for_block(st2)[0]
    d = wcase(root, "minimal", "altair", "operations", "attestation",
              "pyspec_tests", "valid_attestation")
    _write_state(d, "pre.ssz_snappy", st2)
    w_ssz(d, "attestation.ssz_snappy", serialize(T.Attestation.ssz_type,
                                                 att))
    good = st2.copy()
    blk.process_attestation(good, att, VerifySignatures.TRUE)
    scalar_spec.verify_attestation_op(st2, att, good)
    _write_state(d, "post.ssz_snappy", good)
    n += 1
    d = wcase(root, "minimal", "altair", "operations", "attestation",
              "pyspec_tests", "invalid_target")
    _write_state(d, "pre.ssz_snappy", st2)
    bad_att = T.Attestation(
        aggregation_bits=att.aggregation_bits,
        data=T.AttestationData(
            slot=att.data.slot, index=att.data.index,
            beacon_block_root=att.data.beacon_block_root,
            source=att.data.source,
            target=T.Checkpoint(epoch=att.data.target.epoch + 7,
                                root=att.data.target.root)),
        signature=att.signature)
    w_ssz(d, "attestation.ssz_snappy",
          serialize(T.Attestation.ssz_type, bad_att))
    n += 1

    # ---- operations/voluntary_exit: valid + invalid (young validator) ----
    from ..specs.constants import DOMAIN_VOLUNTARY_EXIT
    st3 = base.copy()
    # age the chain far enough for exits
    target_epoch = spec.shard_committee_period + 3
    process_slots(st3, target_epoch * spec.preset.slots_per_epoch)
    exit_msg = T.VoluntaryExit(epoch=st3.current_epoch(), validator_index=3)
    from ..state_transition.helpers import get_domain
    from ..specs.chain_spec import compute_signing_root
    domain = get_domain(st3, DOMAIN_VOLUNTARY_EXIT, st3.current_epoch())
    sroot = compute_signing_root(htr(exit_msg), domain)
    from ..crypto import bls as _bls
    sig = _bls.sign(h.sh.secret_keys[3], sroot)
    sve = T.SignedVoluntaryExit(message=exit_msg, signature=sig)
    d = wcase(root, "minimal", "altair", "operations", "voluntary_exit",
              "pyspec_tests", "valid_exit")
    _write_state(d, "pre.ssz_snappy", st3)
    w_ssz(d, "voluntary_exit.ssz_snappy",
          serialize(T.SignedVoluntaryExit.ssz_type, sve))
    good = st3.copy()
    blk.process_voluntary_exit(good, sve, VerifySignatures.TRUE)
    scalar_spec.verify_voluntary_exit_op(st3, sve, good)
    _write_state(d, "post.ssz_snappy", good)
    n += 1
    d = wcase(root, "minimal", "altair", "operations", "voluntary_exit",
              "pyspec_tests", "invalid_bad_signature")
    _write_state(d, "pre.ssz_snappy", st3)
    bad_sve = T.SignedVoluntaryExit(
        message=T.VoluntaryExit(epoch=st3.current_epoch(),
                                validator_index=4), signature=sig)
    w_ssz(d, "voluntary_exit.ssz_snappy",
          serialize(T.SignedVoluntaryExit.ssz_type, bad_sve))
    n += 1

    # ---- operations/proposer_slashing: valid + invalid (same header) ----
    st4 = base.copy()
    process_slots(st4, st4.slot + 1)
    pidx = 5
    from ..specs.constants import DOMAIN_BEACON_PROPOSER
    h1 = T.BeaconBlockHeader(slot=st4.slot, proposer_index=pidx,
                             parent_root=b"\x01" * 32,
                             state_root=b"\x02" * 32,
                             body_root=b"\x03" * 32)
    h2 = T.BeaconBlockHeader(slot=st4.slot, proposer_index=pidx,
                             parent_root=b"\x01" * 32,
                             state_root=b"\x04" * 32,
                             body_root=b"\x03" * 32)
    dom = get_domain(st4, DOMAIN_BEACON_PROPOSER,
                     st4.slot // spec.preset.slots_per_epoch)
    sh1 = T.SignedBeaconBlockHeader(
        message=h1, signature=_bls.sign(
            h.sh.secret_keys[pidx], compute_signing_root(htr(h1), dom)))
    sh2 = T.SignedBeaconBlockHeader(
        message=h2, signature=_bls.sign(
            h.sh.secret_keys[pidx], compute_signing_root(htr(h2), dom)))
    ps = T.ProposerSlashing(signed_header_1=sh1, signed_header_2=sh2)
    d = wcase(root, "minimal", "altair", "operations", "proposer_slashing",
              "pyspec_tests", "valid_slashing")
    _write_state(d, "pre.ssz_snappy", st4)
    w_ssz(d, "proposer_slashing.ssz_snappy",
          serialize(T.ProposerSlashing.ssz_type, ps))
    good = st4.copy()
    blk.process_proposer_slashing(good, ps, VerifySignatures.TRUE)
    scalar_spec.verify_slashing_op(
        st4, pidx, get_beacon_proposer_index(st4), good)
    _write_state(d, "post.ssz_snappy", good)
    n += 1
    d = wcase(root, "minimal", "altair", "operations", "proposer_slashing",
              "pyspec_tests", "invalid_same_header")
    _write_state(d, "pre.ssz_snappy", st4)
    same = T.ProposerSlashing(signed_header_1=sh1, signed_header_2=sh1)
    w_ssz(d, "proposer_slashing.ssz_snappy",
          serialize(T.ProposerSlashing.ssz_type, same))
    n += 1

    # ---- epoch_processing ----
    ep_state = base.copy()
    process_slots(ep_state,
                  (ep_state.current_epoch() + 1)
                  * spec.preset.slots_per_epoch - 1)
    for sub, fn in [
        ("justification_and_finalization",
         lambda s: ep.process_justification_and_finalization(s)),
        ("inactivity_updates",
         lambda s: ep._process_inactivity_updates(s)),
        ("rewards_and_penalties",
         lambda s: ep._process_rewards_and_penalties_altair(
             s, s.fork_name, ep.get_total_active_balance(s))),
        ("slashings",
         lambda s: ep._process_slashings(
             s, s.fork_name, ep.get_total_active_balance(s))),
        ("effective_balance_updates",
         lambda s: ep._process_effective_balance_updates(s)),
        ("slashings_reset", lambda s: ep._process_slashings_reset(s)),
        ("randao_mixes_reset", lambda s: ep._process_randao_mixes_reset(s)),
        ("eth1_data_reset", lambda s: ep._process_eth1_data_reset(s)),
        ("registry_updates",
         lambda s: ep._process_registry_updates(s, s.fork_name)),
        ("sync_committee_updates",
         lambda s: ep._process_sync_committee_updates(s)),
    ]:
        d = wcase(root, "minimal", "altair", "epoch_processing", sub,
                  "pyspec_tests", f"{sub}_basic")
        _write_state(d, "pre.ssz_snappy", ep_state)
        post = ep_state.copy()
        fn(post)
        # the expected post is only written once the INDEPENDENT scalar
        # transcription agrees with the vectorized transition
        # (de-circularization, scalar_spec.py)
        scalar_spec.verify_epoch_subtransition(sub, ep_state, post)
        _write_state(d, "post.ssz_snappy", post)
        n += 1

    # ---- sanity/slots + sanity/blocks ----
    for i, k in enumerate((1, spec.preset.slots_per_epoch)):
        d = wcase(root, "minimal", "altair", "sanity", "slots",
                  "pyspec_tests", f"slots_{k}")
        s = base.copy()
        _write_state(d, "pre.ssz_snappy", s)
        w_yaml(d, "slots.yaml", k)
        post = s.copy()
        process_slots(post, post.slot + k)
        if (s.slot + k) // spec.preset.slots_per_epoch > \
                s.slot // spec.preset.slots_per_epoch:
            # epoch crossed: scalar-verify the composed transition from
            # the state at the boundary's last slot
            boundary_pre = s.copy()
            last = ((s.slot // spec.preset.slots_per_epoch + 1)
                    * spec.preset.slots_per_epoch - 1)
            process_slots(boundary_pre, last)
            scalar_spec.verify_epoch_transition(boundary_pre, post)
        _write_state(d, "post.ssz_snappy", post)
        n += 1
    signed, _post = h.produce_signed_block()
    d = wcase(root, "minimal", "altair", "sanity", "blocks",
              "pyspec_tests", "valid_block")
    _write_state(d, "pre.ssz_snappy", base)
    w_yaml(d, "meta.yaml", {"blocks_count": 1})
    w_ssz(d, "blocks_0.ssz_snappy",
          serialize(type(signed).ssz_type, signed))
    post = base.copy()
    process_slots(post, signed.message.slot)
    per_block_processing(post, signed)
    _write_state(d, "post.ssz_snappy", post)
    n += 1
    d = wcase(root, "minimal", "altair", "sanity", "blocks",
              "pyspec_tests", "invalid_state_root")
    _write_state(d, "pre.ssz_snappy", base)
    w_yaml(d, "meta.yaml", {"blocks_count": 1})
    tampered = T.SignedBeaconBlock[base.fork_name](
        message=T.BeaconBlock[base.fork_name](
            slot=signed.message.slot,
            proposer_index=signed.message.proposer_index,
            parent_root=signed.message.parent_root,
            state_root=b"\x66" * 32, body=signed.message.body),
        signature=signed.signature)
    w_ssz(d, "blocks_0.ssz_snappy",
          serialize(type(tampered).ssz_type, tampered))
    n += 1

    # ---- fork_choice/get_head ----
    from ..fork_choice.proto_array import ExecutionStatus
    anchor = h.chain.genesis_state
    anchor_block = h.chain.store.get_block(h.chain.genesis_block_root)
    d = wcase(root, "minimal", "altair", "fork_choice", "get_head",
              "pyspec_tests", "chain_head")
    w_ssz(d, "anchor_state.ssz_snappy", anchor.serialize())
    w_ssz(d, "anchor_block.ssz_snappy",
          serialize(type(anchor_block.message).ssz_type,
                    anchor_block.message))
    # two blocks on top of genesis (from the real chain history)
    b1_root = h.chain.block_root_at_slot(1)
    b2_root = h.chain.block_root_at_slot(2)
    b1 = h.chain.store.get_block(b1_root)
    b2 = h.chain.store.get_block(b2_root)
    w_ssz(d, "block_1.ssz_snappy", serialize(type(b1).ssz_type, b1))
    w_ssz(d, "block_2.ssz_snappy", serialize(type(b2).ssz_type, b2))
    steps = [
        {"tick": 2 * spec.seconds_per_slot},
        {"block": "block_1"},
        {"block": "block_2"},
        {"checks": {"head": {"slot": 2, "root": "0x" + b2_root.hex()}}},
    ]
    w_yaml(d, "steps.yaml", steps)
    n += 1
    return n


def _spec_shuffled_index(index: int, count: int, seed: bytes,
                         rounds: int) -> int:
    """INDEPENDENT scalar transcription of the spec's
    compute_shuffled_index (phase0 spec pseudocode), deliberately not
    importing state_transition.shuffle — the vectorized implementation is
    what the runner checks against these vectors."""
    for r in range(rounds):
        pivot = int.from_bytes(hashlib.sha256(
            seed + bytes([r])).digest()[:8], "little") % count
        flip = (pivot + count - index) % count
        position = max(index, flip)
        source = hashlib.sha256(
            seed + bytes([r]) + (position // 256).to_bytes(4,
                                                           "little")).digest()
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) % 2:
            index = flip
    return index


def gen_shuffling(root: Path) -> int:
    """tests/minimal/phase0/shuffling/core/shuffle/* — mapping[i] =
    compute_shuffled_index(i, count, seed) at the minimal preset's 10
    rounds (consensus/swap_or_not_shuffle test layout)."""
    n = 0
    rng_seeds = [bytes([i]) * 32 for i in (0, 7, 42)]
    for count in (1, 2, 3, 8, 33, 100):
        for seed in rng_seeds:
            mapping = [_spec_shuffled_index(i, count, seed, 10)
                       for i in range(count)]
            d = wcase(root, "minimal", "phase0", "shuffling", "core",
                      "shuffle", f"shuffle_0x{seed[:4].hex()}_{count}")
            w_yaml(d, "mapping.yaml", {
                "seed": "0x" + seed.hex(), "count": count,
                "mapping": mapping})
            n += 1
    return n


def gen_kzg(root: Path) -> int:
    """tests/general/deneb/kzg/* + fulu cells cases over the devnet
    trusted setup (size 16, 8 cells).  Generated with the native
    C++ MSM/pairing DISABLED (pure-python group arithmetic); the runner
    verifies with whatever backend is live — on this image the native
    library, making generation and verification independent
    implementations of the group math."""
    from ..crypto import kzg as kzgmod
    old_native = kzgmod._NATIVE
    kzgmod._NATIVE = False        # force pure-python generation
    try:
        k = kzgmod.Kzg(devnet_size=16, cells_per_ext_blob=8)
        blobs = [
            b"".join(((j * 17 + s) % kzgmod.R).to_bytes(32, "big")
                     for j in range(16))
            for s in (1, 5)]
        n = 0
        comms = [k.blob_to_kzg_commitment(b) for b in blobs]
        proofs = [k.compute_blob_kzg_proof(b, c)
                  for b, c in zip(blobs, comms)]
        for i, (b, c, p) in enumerate(zip(blobs, comms, proofs)):
            d = wcase(root, "general", "deneb", "kzg",
                      "blob_to_kzg_commitment", "kzg-devnet", f"case_{i}")
            w_yaml(d, "data.yaml", {
                "input": {"blob": "0x" + b.hex()},
                "output": "0x" + c.hex()})
            n += 1
            d = wcase(root, "general", "deneb", "kzg",
                      "verify_blob_kzg_proof", "kzg-devnet", f"case_{i}")
            w_yaml(d, "data.yaml", {
                "input": {"blob": "0x" + b.hex(),
                          "commitment": "0x" + c.hex(),
                          "proof": "0x" + p.hex()},
                "output": True})
            n += 1
        # an invalid proof case (proof from the other blob)
        d = wcase(root, "general", "deneb", "kzg",
                  "verify_blob_kzg_proof", "kzg-devnet", "case_invalid")
        w_yaml(d, "data.yaml", {
            "input": {"blob": "0x" + blobs[0].hex(),
                      "commitment": "0x" + comms[0].hex(),
                      "proof": "0x" + proofs[1].hex()},
            "output": False})
        n += 1
        d = wcase(root, "general", "deneb", "kzg",
                  "verify_blob_kzg_proof_batch", "kzg-devnet", "case_0")
        w_yaml(d, "data.yaml", {
            "input": {"blobs": ["0x" + b.hex() for b in blobs],
                      "commitments": ["0x" + c.hex() for c in comms],
                      "proofs": ["0x" + p.hex() for p in proofs]},
            "output": True})
        n += 1
        # fulu cells: compute + verify + recover
        cells, cproofs = k.compute_cells_and_kzg_proofs(blobs[0])
        d = wcase(root, "general", "fulu", "kzg",
                  "compute_cells_and_kzg_proofs", "kzg-devnet", "case_0")
        w_yaml(d, "data.yaml", {
            "input": {"blob": "0x" + blobs[0].hex()},
            "output": [["0x" + c.hex() for c in cells],
                       ["0x" + p.hex() for p in cproofs]]})
        n += 1
        d = wcase(root, "general", "fulu", "kzg",
                  "verify_cell_kzg_proof_batch", "kzg-devnet", "case_0")
        w_yaml(d, "data.yaml", {
            "input": {"commitments": ["0x" + comms[0].hex()] * 3,
                      "cell_indices": [0, 3, 7],
                      "cells": ["0x" + cells[i].hex() for i in (0, 3, 7)],
                      "proofs": ["0x" + cproofs[i].hex()
                                 for i in (0, 3, 7)]},
            "output": True})
        n += 1
        keep = [1, 3, 4, 6]
        d = wcase(root, "general", "fulu", "kzg",
                  "recover_cells_and_kzg_proofs", "kzg-devnet", "case_0")
        w_yaml(d, "data.yaml", {
            "input": {"cell_indices": keep,
                      "cells": ["0x" + cells[i].hex() for i in keep]},
            "output": [["0x" + c.hex() for c in cells],
                       ["0x" + p.hex() for p in cproofs]]})
        n += 1
        return n
    finally:
        kzgmod._NATIVE = old_native


def gen_transition(root: Path) -> int:
    """tests/minimal/<post_fork>/transition/core/pyspec_tests/*: a chain
    crossing the fork boundary — pre-fork pre-state, blocks on both
    sides, post-fork post-state (EF transition layout)."""
    from ..crypto import bls
    bls.set_backend("python")
    from ..chain.harness import BeaconChainHarness
    from ..specs import minimal_spec
    from ..ssz import serialize

    n = 0
    for post_fork, overrides in (
            ("altair", {"altair_fork_epoch": 1}),
            ("bellatrix", {"altair_fork_epoch": 0,
                           "bellatrix_fork_epoch": 1}),
    ):
        spec = minimal_spec(**overrides)
        h = BeaconChainHarness(spec, 16)
        spe = spec.preset.slots_per_epoch
        # blocks from 2 slots before the boundary to 2 after
        pre_slot = spe - 3
        h.extend_chain(pre_slot)
        pre = h.chain.head().head_state.copy()
        blocks = []
        for _ in range(4):
            block_root = h.extend_chain(1)[0]
            blocks.append(h.chain.store.get_block(block_root))
        post = h.chain.head().head_state
        d = wcase(root, "minimal", post_fork, "transition", "core",
                  "pyspec_tests", f"normal_transition_{post_fork}")
        w_yaml(d, "meta.yaml", {
            "post_fork": post_fork, "fork_epoch": 1,
            "blocks_count": len(blocks),
            "fork_block": 1,   # index of the last pre-fork block
        })
        _write_state(d, "pre.ssz_snappy", pre)
        for i, b in enumerate(blocks):
            w_ssz(d, f"blocks_{i}.ssz_snappy",
                  serialize(type(b).ssz_type, b))
        _write_state(d, "post.ssz_snappy", post)
        n += 1
    return n


def main(dest: str | None = None, only: list[str] | None = None) -> None:
    """`only`: resume/partial mode — run just the named round-3
    generators without wiping the tree (generators overwrite their own
    case dirs)."""
    dest_root = Path(dest or Path(__file__).resolve().parents[2]
                     / "tests" / "ef_vectors" / "tests")
    from .gen_corpus_r3 import generate_all
    from .gen_corpus_r5 import generate_all as generate_r5
    if only:
        n = generate_all(dest_root, only)
        n += generate_r5(dest_root, only)
        print(f"wrote {n} cases (partial: {only}) under {dest_root}")
        return
    if dest_root.exists():
        shutil.rmtree(dest_root)
    n = 0
    n += gen_ssz_static(dest_root)
    n += gen_bls(dest_root)
    n += gen_state_cases(dest_root)
    n += gen_shuffling(dest_root)
    n += gen_kzg(dest_root)
    n += gen_transition(dest_root)
    n += generate_all(dest_root)
    n += generate_r5(dest_root)
    print(f"wrote {n} cases under {dest_root}")


if __name__ == "__main__":
    args = sys.argv[1:]
    only = None
    if args and args[0] == "--only":
        only = args[1].split(",")
        args = args[2:]
    main(args[0] if args else None, only=only)
