"""Independent SCALAR transcription of the consensus spec (altair).

De-circularizes the self-generated EF corpus (VERDICT r3 "next" #5): the
families whose expected post-states used to be regression pins from the
implementation under test are now verified at GENERATION time against
this module — a direct, loop-by-loop transcription of the spec
pseudocode that deliberately imports NOTHING from
``lighthouse_tpu.state_transition`` (the vectorized implementation being
validated).  A transition bug present since round 1 can no longer be
enshrined as an expected post-state: generation fails when the
vectorized post disagrees with the scalar computation.

What IS shared with the implementation:
- the SSZ container layer (reads/writes of state fields) — validated
  independently by the hand-built ssz_static/ssz_generic vectors;
- ``lighthouse_tpu.ssz.htr`` for state/block roots — validated by the
  same hand-built vectors and the merkle_proof re-hashing family;
- the pure-python BLS oracle for pubkey aggregation — validated by the
  EF bls vectors via the byte-exact C++ backend.

Everything else — committees, shuffling, rewards, justification,
registry churn, slashings, flag updates, sync-committee selection, the
block operations — is recomputed here from the spec pseudocode with
plain ints and loops.
"""
from __future__ import annotations

import hashlib

# independent scalar constants (minimal preset, altair)
WEIGHTS = (14, 26, 14)                 # source, target, head
WEIGHT_DENOM = 64
PROPOSER_WEIGHT = 8
SYNC_REWARD_WEIGHT = 2
BASE_REWARD_FACTOR = 64
INCREMENT = 10**9
MAX_EFFECTIVE = 32 * 10**9
HYSTERESIS_QUOTIENT = 4
HYSTERESIS_DOWN = 1
HYSTERESIS_UP = 5
EPOCHS_PER_ETH1_PERIOD = 4             # minimal
SLOTS_PER_EPOCH = 8                    # minimal
EPOCHS_PER_RANDAO_VECTOR = 64          # minimal EPOCHS_PER_HISTORICAL_VECTOR
EPOCHS_PER_SLASHINGS_VECTOR = 64
SLOTS_PER_HISTORICAL_ROOT = 64
MIN_SEED_LOOKAHEAD = 1
MAX_SEED_LOOKAHEAD = 4
MIN_PER_EPOCH_CHURN = 2                # minimal min_per_epoch_churn_limit = 2
CHURN_QUOTIENT = 32                    # minimal churn_limit_quotient
MIN_ACTIVATION_BALANCE = 16 * 10**9    # ejection balance
SHARD_COMMITTEE_PERIOD = 64
MIN_VALIDATOR_WITHDRAWABILITY_DELAY = 256
EPOCHS_PER_SLASHINGS = 64
PROPORTIONAL_SLASHING_MULT_ALTAIR = 2
MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR = 64
WHISTLEBLOWER_REWARD_QUOTIENT = 512
PROPOSER_REWARD_QUOTIENT = 8
INACTIVITY_SCORE_BIAS = 4
INACTIVITY_SCORE_RECOVERY_RATE = 16
INACTIVITY_PENALTY_QUOTIENT_ALTAIR = 3 * 2**24
MIN_EPOCHS_TO_INACTIVITY_PENALTY = 4
SYNC_COMMITTEE_SIZE = 32               # minimal
EPOCHS_PER_SYNC_COMMITTEE_PERIOD = 8   # minimal
SHUFFLE_ROUNDS = 10                    # minimal
DOMAIN_BEACON_ATTESTER = 1
DOMAIN_SYNC_COMMITTEE = 7
TIMELY_SOURCE, TIMELY_TARGET, TIMELY_HEAD = 1, 2, 4
MAX_RANDOM_BYTE = 255


def sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def isqrt(n: int) -> int:
    x = n
    y = (x + 1) // 2
    while y < x:
        x = y
        y = (x + n // x) // 2
    return x


# ---------------------------------------------------------------------------
# scalar state views
# ---------------------------------------------------------------------------

def vrows(state) -> list[dict]:
    """Plain-python rows of the validator registry."""
    v = state.validators
    return [{
        "effective_balance": int(v.effective_balance[i]),
        "slashed": bool(v.slashed[i]),
        "activation_eligibility_epoch": int(
            v.activation_eligibility_epoch[i]),
        "activation_epoch": int(v.activation_epoch[i]),
        "exit_epoch": int(v.exit_epoch[i]),
        "withdrawable_epoch": int(v.withdrawable_epoch[i]),
    } for i in range(len(v))]


def is_active(row: dict, epoch: int) -> bool:
    return row["activation_epoch"] <= epoch < row["exit_epoch"]


def current_epoch(state) -> int:
    return int(state.slot) // SLOTS_PER_EPOCH


def active_indices(rows, epoch: int) -> list[int]:
    return [i for i, r in enumerate(rows) if is_active(r, epoch)]


def total_active_balance(state, rows=None) -> int:
    rows = rows if rows is not None else vrows(state)
    epoch = current_epoch(state)
    tot = sum(r["effective_balance"] for r in rows if is_active(r, epoch))
    return max(INCREMENT, tot)


def get_randao_mix(state, epoch: int) -> bytes:
    return bytes(state.randao_mixes[epoch % EPOCHS_PER_RANDAO_VECTOR])


def get_seed(state, epoch: int, domain: int) -> bytes:
    mix = get_randao_mix(
        state, epoch + EPOCHS_PER_RANDAO_VECTOR - MIN_SEED_LOOKAHEAD - 1)
    return sha(domain.to_bytes(4, "little") + epoch.to_bytes(8, "little")
               + mix)


def shuffled_index(index: int, count: int, seed: bytes) -> int:
    """compute_shuffled_index, straight from the phase0 pseudocode."""
    assert index < count
    for rnd in range(SHUFFLE_ROUNDS):
        pivot = int.from_bytes(
            sha(seed + rnd.to_bytes(1, "little"))[:8], "little") % count
        flip = (pivot + count - index) % count
        position = max(index, flip)
        source = sha(seed + rnd.to_bytes(1, "little")
                     + (position // 256).to_bytes(4, "little"))
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) % 2:
            index = flip
    return index


def get_committee(state, rows, slot: int, index: int) -> list[int]:
    """get_beacon_committee via scalar shuffle."""
    epoch = slot // SLOTS_PER_EPOCH
    active = active_indices(rows, epoch)
    seed = get_seed(state, epoch, DOMAIN_BEACON_ATTESTER)
    per_slot = max(1, min(
        4,                               # minimal max_committees_per_slot
        len(active) // SLOTS_PER_EPOCH // 4))  # target_committee_size 4
    count = per_slot * SLOTS_PER_EPOCH
    i = (slot % SLOTS_PER_EPOCH) * per_slot + index
    n = len(active)
    start = n * i // count
    end = n * (i + 1) // count
    return [active[shuffled_index(pos, n, seed)]
            for pos in range(start, end)]


def committees_per_slot(rows, epoch: int) -> int:
    active = active_indices(rows, epoch)
    return max(1, min(4, len(active) // SLOTS_PER_EPOCH // 4))


# ---------------------------------------------------------------------------
# epoch processing (altair), field by field
# ---------------------------------------------------------------------------

def unslashed_participating_indices(state, rows, flag_bit: int,
                                    epoch: int) -> list[int]:
    cur = current_epoch(state)
    part = (state.current_epoch_participation if epoch == cur
            else state.previous_epoch_participation)
    return [i for i, r in enumerate(rows)
            if is_active(r, epoch) and not r["slashed"]
            and int(part[i]) & flag_bit]


def justification_and_finalization(state) -> dict:
    """Expected {justification_bits, previous/current_justified,
    finalized} after process_justification_and_finalization."""
    epoch = current_epoch(state)
    if epoch <= 1:
        return {
            "bits": list(state.justification_bits),
            "previous_justified": (int(state.current_justified_checkpoint
                                       .epoch),
                                   bytes(state.current_justified_checkpoint
                                         .root)),
            "current_justified": (int(state.current_justified_checkpoint
                                      .epoch),
                                  bytes(state.current_justified_checkpoint
                                        .root)),
            "finalized": (int(state.finalized_checkpoint.epoch),
                          bytes(state.finalized_checkpoint.root)),
        }
    rows = vrows(state)
    total = total_active_balance(state, rows)
    prev_target = sum(
        rows[i]["effective_balance"] for i in
        unslashed_participating_indices(state, rows, TIMELY_TARGET,
                                        epoch - 1))
    cur_target = sum(
        rows[i]["effective_balance"] for i in
        unslashed_participating_indices(state, rows, TIMELY_TARGET, epoch))

    def block_root_at_epoch_start(e):
        slot = e * SLOTS_PER_EPOCH
        return bytes(state.block_roots[slot % SLOTS_PER_HISTORICAL_ROOT])

    bits = list(state.justification_bits)
    old_prev_j = (int(state.previous_justified_checkpoint.epoch),
                  bytes(state.previous_justified_checkpoint.root))
    old_cur_j = (int(state.current_justified_checkpoint.epoch),
                 bytes(state.current_justified_checkpoint.root))
    prev_j = old_cur_j
    cur_j = old_cur_j
    bits = [False] + bits[:3]
    if prev_target * 3 >= total * 2:
        cur_j = (epoch - 1, block_root_at_epoch_start(epoch - 1))
        bits[1] = True
    if cur_target * 3 >= total * 2:
        cur_j = (epoch, block_root_at_epoch_start(epoch))
        bits[0] = True
    fin = (int(state.finalized_checkpoint.epoch),
           bytes(state.finalized_checkpoint.root))
    # the four finalization rules operate on the OLD justified checkpoints
    if all(bits[1:4]) and old_prev_j[0] + 3 == epoch:
        fin = old_prev_j
    if all(bits[1:3]) and old_prev_j[0] + 2 == epoch:
        fin = old_prev_j
    if all(bits[0:3]) and old_cur_j[0] + 2 == epoch:
        fin = old_cur_j
    if all(bits[0:2]) and old_cur_j[0] + 1 == epoch:
        fin = old_cur_j
    return {"bits": bits, "previous_justified": prev_j,
            "current_justified": cur_j, "finalized": fin}


def inactivity_updates(state) -> list[int]:
    """Expected inactivity_scores."""
    epoch = current_epoch(state)
    scores = [int(s) for s in state.inactivity_scores]
    if epoch == 0:
        return scores
    rows = vrows(state)
    target = set(unslashed_participating_indices(
        state, rows, TIMELY_TARGET, epoch - 1))
    leaking = (epoch - int(state.finalized_checkpoint.epoch)
               > MIN_EPOCHS_TO_INACTIVITY_PENALTY)
    out = list(scores)
    for i, r in enumerate(rows):
        if not (is_active(r, epoch - 1)
                or (r["slashed"] and epoch - 1 < r["withdrawable_epoch"])):
            continue                    # eligible set per spec
        if i in target:
            out[i] -= min(1, out[i])
        else:
            out[i] += INACTIVITY_SCORE_BIAS
        if not leaking:
            out[i] -= min(INACTIVITY_SCORE_RECOVERY_RATE, out[i])
    return out


def base_reward_per_increment(total: int) -> int:
    return INCREMENT * BASE_REWARD_FACTOR // isqrt(total)


def rewards_and_penalties(state) -> list[int]:
    """Expected balances after process_rewards_and_penalties."""
    epoch = current_epoch(state)
    balances = [int(b) for b in state.balances]
    if epoch == 0:
        return balances
    rows = vrows(state)
    total = total_active_balance(state, rows)
    brpi = base_reward_per_increment(total)
    leaking = (epoch - int(state.finalized_checkpoint.epoch)
               > MIN_EPOCHS_TO_INACTIVITY_PENALTY)
    eligible = [i for i, r in enumerate(rows)
                if is_active(r, epoch - 1)
                or (r["slashed"] and epoch - 1 < r["withdrawable_epoch"])]
    out = list(balances)
    for flag_i, (bit, weight) in enumerate(
            zip((TIMELY_SOURCE, TIMELY_TARGET, TIMELY_HEAD), WEIGHTS)):
        participating = set(unslashed_participating_indices(
            state, rows, bit, epoch - 1))
        part_incs = sum(rows[i]["effective_balance"] // INCREMENT
                        for i in participating)
        active_incs = total // INCREMENT
        for i in eligible:
            base = (rows[i]["effective_balance"] // INCREMENT) * brpi
            if i in participating:
                if not leaking:
                    num = base * weight * part_incs
                    out[i] += num // (active_incs * WEIGHT_DENOM)
            elif bit != TIMELY_HEAD:
                out[i] -= base * weight // WEIGHT_DENOM
    # inactivity penalties
    target = set(unslashed_participating_indices(
        state, rows, TIMELY_TARGET, epoch - 1))
    scores = [int(s) for s in state.inactivity_scores]
    for i in eligible:
        if i not in target:
            num = rows[i]["effective_balance"] * scores[i]
            out[i] -= num // (INACTIVITY_SCORE_BIAS
                              * INACTIVITY_PENALTY_QUOTIENT_ALTAIR)
    return [max(0, b) for b in out]


def churn_limit(rows, epoch: int) -> int:
    return max(MIN_PER_EPOCH_CHURN,
               len(active_indices(rows, epoch)) // CHURN_QUOTIENT)


def exit_epoch_and_update(rows, epoch: int, exiting: list[int]
                          ) -> list[tuple[int, int, int]]:
    """initiate_validator_exit for each index in order; returns
    (index, exit_epoch, withdrawable_epoch) updates."""
    out = []
    exit_epochs = [r["exit_epoch"] for r in rows
                   if r["exit_epoch"] != 2**64 - 1]
    for idx in exiting:
        candidates = exit_epochs + [epoch + 1 + MAX_SEED_LOOKAHEAD]
        exit_q = max(candidates)
        churn = sum(1 for e in exit_epochs if e == exit_q)
        if churn >= churn_limit(rows, epoch):
            exit_q += 1
        exit_epochs.append(exit_q)
        out.append((idx, exit_q,
                    exit_q + MIN_VALIDATOR_WITHDRAWABILITY_DELAY))
        rows[idx]["exit_epoch"] = exit_q
        rows[idx]["withdrawable_epoch"] = \
            exit_q + MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    return out


def registry_updates(state) -> list[dict]:
    """Expected registry rows after process_registry_updates (altair)."""
    rows = vrows(state)
    epoch = current_epoch(state)
    # eligibility
    for r in rows:
        if (r["activation_eligibility_epoch"] == 2**64 - 1
                and r["effective_balance"] == MAX_EFFECTIVE):
            r["activation_eligibility_epoch"] = epoch + 1
    # ejections
    ejected = [i for i, r in enumerate(rows)
               if is_active(r, epoch)
               and r["effective_balance"] <= MIN_ACTIVATION_BALANCE]
    exit_epoch_and_update(rows, epoch, ejected)
    # activation queue: eligible, finalized-confirmed, ordered
    fin = int(state.finalized_checkpoint.epoch)
    queue = sorted(
        (i for i, r in enumerate(rows)
         if r["activation_eligibility_epoch"] <= fin
         and r["activation_epoch"] == 2**64 - 1),
        key=lambda i: (rows[i]["activation_eligibility_epoch"], i))
    for i in queue[:churn_limit(rows, epoch)]:
        rows[i]["activation_epoch"] = epoch + 1 + MAX_SEED_LOOKAHEAD
    return rows


def slashings_penalties(state) -> list[int]:
    """Expected balances after process_slashings."""
    rows = vrows(state)
    epoch = current_epoch(state)
    total = total_active_balance(state, rows)
    slash_sum = sum(int(s) for s in state.slashings)
    adj = min(slash_sum * PROPORTIONAL_SLASHING_MULT_ALTAIR, total)
    out = [int(b) for b in state.balances]
    for i, r in enumerate(rows):
        if r["slashed"] and epoch + EPOCHS_PER_SLASHINGS // 2 == \
                r["withdrawable_epoch"]:
            inc = INCREMENT
            # spec order: penalty_numerator // total_balance * increment
            # (the earlier transcription divided by total//inc — an
            # increment-factor error masked by the zero-slashings altair
            # vector; caught by the r5 bellatrix transcription)
            penalty_num = r["effective_balance"] // inc * adj
            penalty = penalty_num // total * inc
            out[i] = max(0, out[i] - penalty)
    return out


def effective_balance_updates(state) -> list[int]:
    rows = vrows(state)
    out = []
    for i, r in enumerate(rows):
        bal = int(state.balances[i])
        eff = r["effective_balance"]
        hyst = INCREMENT // HYSTERESIS_QUOTIENT
        if (bal + hyst * HYSTERESIS_DOWN < eff
                or eff + hyst * HYSTERESIS_UP < bal):
            eff = min(bal - bal % INCREMENT, MAX_EFFECTIVE)
        out.append(eff)
    return out


def eth1_data_reset_expected(state):
    next_epoch = current_epoch(state) + 1
    if next_epoch % EPOCHS_PER_ETH1_PERIOD == 0:
        return []                       # votes cleared
    return None                         # unchanged


def slashings_reset_expected(state) -> tuple[int, int]:
    next_epoch = current_epoch(state) + 1
    return (next_epoch % EPOCHS_PER_SLASHINGS_VECTOR, 0)


def randao_mixes_reset_expected(state) -> tuple[int, bytes]:
    epoch = current_epoch(state)
    next_epoch = epoch + 1
    return (next_epoch % EPOCHS_PER_RANDAO_VECTOR,
            get_randao_mix(state, epoch))


def sync_committee_update_expected(state):
    """Expected (pubkeys, aggregate_pubkey) of next_sync_committee after
    process_sync_committee_updates, or None when not at a period
    boundary.  Selection via the scalar shuffle; aggregation via the
    pure-python curve oracle (independent of the vectorized path)."""
    next_epoch = current_epoch(state) + 1
    if next_epoch % EPOCHS_PER_SYNC_COMMITTEE_PERIOD != 0:
        return None
    rows = vrows(state)
    base_epoch = next_epoch + 1
    active = active_indices(rows, base_epoch)
    seed = get_seed(state, base_epoch, DOMAIN_SYNC_COMMITTEE)
    indices = []
    i = 0
    while len(indices) < SYNC_COMMITTEE_SIZE:
        pos = shuffled_index(i % len(active), len(active), seed)
        candidate = active[pos]
        rnd = sha(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        if rows[candidate]["effective_balance"] * MAX_RANDOM_BYTE >= \
                MAX_EFFECTIVE * rnd:
            indices.append(candidate)
        i += 1
    pubkeys = [bytes(state.validators.pubkeys[i]) for i in indices]
    from ..crypto.bls12_381 import g1_decompress, g1_compress
    agg = None
    for pk in pubkeys:
        pt = g1_decompress(pk)
        agg = pt if agg is None else agg.add(pt)
    return pubkeys, g1_compress(agg)


# ---------------------------------------------------------------------------
# generation-time verifiers (called by gen_corpus*)
# ---------------------------------------------------------------------------

class ScalarMismatch(AssertionError):
    pass


def _ck(cond, what):
    if not cond:
        raise ScalarMismatch(f"scalar spec disagrees on {what}")


def verify_epoch_subtransition(sub: str, pre, post) -> None:
    """Check the implementation's post against the scalar expectation for
    one epoch_processing sub-transition (pre = state at the last slot of
    an epoch, post = after running the sub-transition only)."""
    if sub == "effective_balance_updates":
        exp = effective_balance_updates(pre)
        got = [int(x) for x in post.validators.effective_balance]
        _ck(exp == got, "effective balances")
    elif sub == "slashings_reset":
        idx, val = slashings_reset_expected(pre)
        _ck(int(post.slashings[idx]) == val, "slashings reset")
    elif sub == "randao_mixes_reset":
        idx, mix = randao_mixes_reset_expected(pre)
        _ck(bytes(post.randao_mixes[idx]) == mix, "randao mixes reset")
    elif sub == "eth1_data_reset":
        exp = eth1_data_reset_expected(pre)
        if exp is not None:
            _ck(len(post.eth1_data_votes) == 0, "eth1 votes cleared")
        else:
            _ck(len(post.eth1_data_votes) == len(pre.eth1_data_votes),
                "eth1 votes unchanged")
    elif sub == "registry_updates":
        exp = registry_updates(pre)
        for i, r in enumerate(exp):
            v = post.validators
            _ck(int(v.activation_eligibility_epoch[i])
                == r["activation_eligibility_epoch"],
                f"eligibility[{i}]")
            _ck(int(v.activation_epoch[i]) == r["activation_epoch"],
                f"activation[{i}]")
            _ck(int(v.exit_epoch[i]) == r["exit_epoch"], f"exit[{i}]")
            _ck(int(v.withdrawable_epoch[i]) == r["withdrawable_epoch"],
                f"withdrawable[{i}]")
    elif sub == "sync_committee_updates":
        exp = sync_committee_update_expected(pre)
        if exp is not None:
            pubkeys, agg = exp
            got = [bytes(pk) for pk in post.next_sync_committee.pubkeys]
            _ck(got == pubkeys, "next sync committee pubkeys")
            _ck(bytes(post.next_sync_committee.aggregate_pubkey) == agg,
                "next sync committee aggregate")
    elif sub == "justification_and_finalization":
        exp = justification_and_finalization(pre)
        _ck(list(post.justification_bits) == exp["bits"],
            "justification bits")
        _ck((int(post.current_justified_checkpoint.epoch),
             bytes(post.current_justified_checkpoint.root))
            == exp["current_justified"], "current justified")
        _ck((int(post.finalized_checkpoint.epoch),
             bytes(post.finalized_checkpoint.root)) == exp["finalized"],
            "finalized")
    elif sub == "inactivity_updates":
        _ck([int(s) for s in post.inactivity_scores]
            == inactivity_updates(pre), "inactivity scores")
    elif sub == "rewards_and_penalties":
        _ck([int(b) for b in post.balances] == rewards_and_penalties(pre),
            "balances after rewards")
    elif sub == "slashings":
        _ck([int(b) for b in post.balances] == slashings_penalties(pre),
            "balances after slashings")
    else:
        raise ValueError(f"no scalar check for {sub}")


def verify_epoch_transition(pre_last_slot, post) -> None:
    """Scalar check of the COMPOSED epoch transition (sanity/slots across
    a boundary): run the scalar sub-transitions in spec order on plain
    views of `pre` and compare the fields they own against `post`."""
    jf = justification_and_finalization(pre_last_slot)
    _ck(list(post.justification_bits) == jf["bits"], "bits (composed)")
    _ck((int(post.finalized_checkpoint.epoch),
         bytes(post.finalized_checkpoint.root)) == jf["finalized"],
        "finalized (composed)")
    # balances: rewards then slashings use pre-epoch state views
    bal_after_rewards = rewards_and_penalties(pre_last_slot)
    _bal_check_possible = all(
        not (bool(pre_last_slot.validators.slashed[i]))
        for i in range(len(pre_last_slot.validators)))
    if _bal_check_possible:
        # without mid-epoch slashings the slashings step is a no-op and
        # scalar balances must match exactly
        _ck([int(b) for b in post.balances] == bal_after_rewards,
            "balances (composed)")
    _ck([int(x) for x in post.validators.effective_balance]
        == _effective_after(pre_last_slot, bal_after_rewards),
        "effective balances (composed)")


def _effective_after(pre, balances: list[int]) -> list[int]:
    rows = vrows(pre)
    out = []
    for i, r in enumerate(rows):
        bal = balances[i]
        eff = r["effective_balance"]
        hyst = INCREMENT // HYSTERESIS_QUOTIENT
        if (bal + hyst * HYSTERESIS_DOWN < eff
                or eff + hyst * HYSTERESIS_UP < bal):
            eff = min(bal - bal % INCREMENT, MAX_EFFECTIVE)
        out.append(eff)
    return out


# ---------------------------------------------------------------------------
# scalar block operations
# ---------------------------------------------------------------------------

def verify_block_header_op(pre, block, post) -> None:
    """process_block_header: header caching semantics."""
    h = post.latest_block_header
    _ck(int(h.slot) == int(block.slot), "header slot")
    _ck(int(h.proposer_index) == int(block.proposer_index),
        "header proposer")
    _ck(bytes(h.parent_root) == bytes(block.parent_root), "header parent")
    _ck(bytes(h.state_root) == b"\x00" * 32, "header state root zeroed")
    from ..ssz import htr
    _ck(bytes(h.body_root) == htr(block.body), "header body root")


def verify_voluntary_exit_op(pre, signed_exit, post) -> None:
    rows = vrows(pre)
    epoch = current_epoch(pre)
    updates = exit_epoch_and_update(
        rows, epoch, [int(signed_exit.message.validator_index)])
    idx, exit_q, wd = updates[0]
    _ck(int(post.validators.exit_epoch[idx]) == exit_q, "exit epoch")
    _ck(int(post.validators.withdrawable_epoch[idx]) == wd,
        "withdrawable epoch")


def slash_validator_expected(pre, idx: int, whistleblower: int | None,
                             proposer: int) -> dict:
    """Scalar slash_validator: returns expected balance/registry deltas."""
    rows = vrows(pre)
    epoch = current_epoch(pre)
    exit_epoch_and_update(rows, epoch, [idx])
    wd = max(rows[idx]["withdrawable_epoch"],
             epoch + EPOCHS_PER_SLASHINGS_VECTOR)
    eff = rows[idx]["effective_balance"]
    penalty = eff // MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    wb_reward = eff // WHISTLEBLOWER_REWARD_QUOTIENT
    prop_reward = wb_reward * PROPOSER_WEIGHT // WEIGHT_DENOM
    wb = whistleblower if whistleblower is not None else proposer
    return {
        "index": idx, "withdrawable_epoch": wd,
        "exit_epoch": rows[idx]["exit_epoch"],
        "penalty": penalty,
        "proposer": proposer, "proposer_reward": prop_reward,
        "whistleblower": wb,
        "whistleblower_reward": wb_reward - prop_reward,
        "slashings_slot": epoch % EPOCHS_PER_SLASHINGS_VECTOR,
        "slashings_add": eff,
    }


def verify_slashing_op(pre, slashed_index: int, proposer: int,
                       post) -> None:
    exp = slash_validator_expected(pre, slashed_index, None, proposer)
    _ck(bool(post.validators.slashed[slashed_index]), "slashed flag")
    _ck(int(post.validators.withdrawable_epoch[slashed_index])
        == exp["withdrawable_epoch"], "slashed withdrawable")
    _ck(int(post.slashings[exp["slashings_slot"]])
        - int(pre.slashings[exp["slashings_slot"]]) == exp["slashings_add"],
        "slashings accumulator")
    expected_bal = (int(pre.balances[slashed_index]) - exp["penalty"])
    if proposer == slashed_index:
        expected_bal += exp["proposer_reward"] + exp["whistleblower_reward"]
        _ck(int(post.balances[slashed_index]) == expected_bal,
            "self-slash balance")
    else:
        _ck(int(post.balances[slashed_index]) == expected_bal,
            "slashed balance")
        _ck(int(post.balances[proposer]) - int(pre.balances[proposer])
            == exp["proposer_reward"] + exp["whistleblower_reward"],
            "proposer reward")


def attestation_expected(pre, att) -> tuple[list[int], list[int], int]:
    """(participating indices, new flags per index, proposer reward).

    Scalar process_attestation for altair: committee from the scalar
    shuffle, timeliness from inclusion delay, flag updates and the
    proposer reward (spec pseudocode)."""
    rows = vrows(pre)
    data = att.data
    committee = get_committee(pre, rows, int(data.slot), int(data.index))
    bits = list(att.aggregation_bits)
    _ck(len(bits) == len(committee), "aggregation bits length")
    attesting = [v for v, b in zip(committee, bits) if b]
    delay = int(pre.slot) - int(data.slot)
    epoch = current_epoch(pre)
    is_current = int(data.target.epoch) == epoch
    # justified checkpoint matching determines source timeliness
    jc = (pre.current_justified_checkpoint if is_current
          else pre.previous_justified_checkpoint)
    source_ok = (int(data.source.epoch) == int(jc.epoch)
                 and bytes(data.source.root) == bytes(jc.root))
    _ck(source_ok, "attestation source must match justified")
    target_start = int(data.target.epoch) * SLOTS_PER_EPOCH
    target_ok = bytes(data.target.root) == bytes(
        pre.block_roots[target_start % SLOTS_PER_HISTORICAL_ROOT]) \
        if target_start < int(pre.slot) else \
        bytes(data.target.root) == bytes(pre.latest_block_header_root()) \
        if hasattr(pre, "latest_block_header_root") else True
    head_ok = bytes(data.beacon_block_root) == bytes(
        pre.block_roots[int(data.slot) % SLOTS_PER_HISTORICAL_ROOT]) \
        if int(data.slot) < int(pre.slot) else True
    flags = 0
    if source_ok and delay <= isqrt(SLOTS_PER_EPOCH):
        flags |= TIMELY_SOURCE
    if target_ok:                        # altair: within 32 slots, always
        flags |= TIMELY_TARGET
    if head_ok and delay == 1:
        flags |= TIMELY_HEAD
    # proposer reward: sum weights of NEWLY set flags
    part = (pre.current_epoch_participation if is_current
            else pre.previous_epoch_participation)
    total = total_active_balance(pre, rows)
    brpi = base_reward_per_increment(total)
    reward_num = 0
    for v in attesting:
        have = int(part[v])
        for bit, weight in zip((TIMELY_SOURCE, TIMELY_TARGET, TIMELY_HEAD),
                               WEIGHTS):
            if flags & bit and not have & bit:
                base = rows[v]["effective_balance"] // INCREMENT * brpi
                reward_num += base * weight
    prop_reward = (reward_num // WEIGHT_DENOM) * PROPOSER_WEIGHT \
        // (WEIGHT_DENOM - PROPOSER_WEIGHT)
    return attesting, flags, prop_reward


def verify_upgrade(pre, post, expected_prev: bytes, expected_cur: bytes
                   ) -> None:
    """Scalar check of an in-place fork upgrade: version rotation, epoch
    stamping, and preservation of the registry/balances (the upgrade
    functions must only rotate versions and initialize new fields)."""
    _ck(bytes(post.fork.previous_version) == expected_prev,
        "upgrade previous_version")
    _ck(bytes(post.fork.current_version) == expected_cur,
        "upgrade current_version")
    _ck(int(post.fork.epoch) == current_epoch(pre), "upgrade fork epoch")
    _ck(int(post.slot) == int(pre.slot), "upgrade slot unchanged")
    _ck(len(post.validators) == len(pre.validators),
        "upgrade registry size")
    _ck([int(b) for b in post.balances] == [int(b) for b in pre.balances],
        "upgrade balances unchanged")
    _ck([int(x) for x in post.validators.effective_balance]
        == [int(x) for x in pre.validators.effective_balance],
        "upgrade effective balances unchanged")


def verify_genesis_registry(deposit_rows: list[tuple[bytes, bytes, int]],
                            post) -> None:
    """Scalar check of genesis-state registry construction from deposits:
    (pubkey, withdrawal_credentials, amount) rows -> validator rows +
    balances + activations, straight from initialize_beacon_state /
    apply_deposit pseudocode (first-deposit-wins per pubkey)."""
    seen: dict[bytes, int] = {}
    balances: list[int] = []
    rows: list[dict] = []
    for pk, wc, amount in deposit_rows:
        if pk in seen:
            balances[seen[pk]] += amount
            continue
        seen[pk] = len(rows)
        eff = min(amount - amount % INCREMENT, MAX_EFFECTIVE)
        rows.append({"pubkey": pk, "wc": wc, "eff": eff})
        balances.append(amount)
    # genesis activation: validators at max effective balance activate
    for r in rows:
        r["active"] = r["eff"] == MAX_EFFECTIVE
    _ck(len(post.validators) == len(rows), "genesis registry size")
    for i, r in enumerate(rows):
        v = post.validators
        _ck(bytes(v.pubkeys[i]) == r["pubkey"], f"genesis pubkey[{i}]")
        _ck(int(v.effective_balance[i]) == r["eff"],
            f"genesis effective balance[{i}]")
        _ck(int(post.balances[i]) == balances[i], f"genesis balance[{i}]")
        if r["active"]:
            _ck(int(v.activation_epoch[i]) == 0, f"genesis active[{i}]")
    _ck(bytes(post.fork.current_version)
        == bytes(post.fork.previous_version), "genesis fork versions")


def verify_attestation_op(pre, att, post) -> None:
    attesting, flags, prop_reward = attestation_expected(pre, att)
    is_current = int(att.data.target.epoch) == current_epoch(pre)
    pre_part = (pre.current_epoch_participation if is_current
                else pre.previous_epoch_participation)
    post_part = (post.current_epoch_participation if is_current
                 else post.previous_epoch_participation)
    att_set = set(attesting)
    for i in range(len(pre_part)):
        want = int(pre_part[i]) | (flags if i in att_set else 0)
        _ck(int(post_part[i]) == want, f"participation[{i}]")
