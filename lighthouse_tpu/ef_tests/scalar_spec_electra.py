"""Independent SCALAR transcription of the consensus spec — capella+electra.

Extends scalar_spec.py (altair) through the fork-specific state-transition
logic of capella (withdrawals, BLS→execution changes) and electra
(EIP-7251/EIP-7002/EIP-6110: execution-layer requests, balance-churn
accounting, pending deposit/consolidation queues, compounding credentials)
so bellatrix→electra corpus post-states stop being implementation pins
(VERDICT r4 "next" #3).  Same discipline as scalar_spec.py: plain ints,
bytes and loops straight from the spec pseudocode, importing NOTHING from
``lighthouse_tpu.state_transition``.

Shared (documented, independently validated) dependencies:
- hashlib sha256 for the deposit-domain merkle bits (hand-rolled here);
- the pure-python BLS oracle for deposit-signature validity (validated by
  the EF bls vectors against the byte-exact C++ backend).

Reference parity: per_block_processing/process_operations.rs electra
arms, per_epoch_processing/single_pass.rs (registry/balance single-pass),
capella withdrawals processing (process_withdrawals in
per_block_processing.rs).
"""
from __future__ import annotations

import hashlib

from .scalar_spec import (
    INCREMENT, SLOTS_PER_EPOCH, _ck, current_epoch, is_active,
    total_active_balance,
)

FAR_FUTURE = 2**64 - 1
GENESIS_SLOT = 0
MAX_SEED_LOOKAHEAD = 4

# minimal-preset electra values (specs/presets.py MINIMAL_PRESET + minimal
# ChainSpec — transcribed as literals so a preset regression can't
# propagate here)
MIN_ACTIVATION_BALANCE = 32 * 10**9
MAX_EFFECTIVE_ELECTRA = 2048 * 10**9
MIN_PER_EPOCH_CHURN_ELECTRA = 64 * 10**9
MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN = 128 * 10**9
CHURN_QUOTIENT = 32
EJECTION_BALANCE = 16 * 10**9
MIN_VALIDATOR_WITHDRAWABILITY_DELAY = 256
SHARD_COMMITTEE_PERIOD = 64
MAX_WITHDRAWALS_PER_PAYLOAD = 4          # minimal
MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP = 16
MAX_PENDING_PARTIALS_PER_SWEEP = 2     # minimal (mainnet: 8)
MAX_PENDING_DEPOSITS_PER_EPOCH = 16
PENDING_PARTIAL_WITHDRAWALS_LIMIT = 64
PENDING_CONSOLIDATIONS_LIMIT = 64
FULL_EXIT_REQUEST_AMOUNT = 0
UNSET_DEPOSIT_REQUESTS_START_INDEX = 2**64 - 1
MAX_EFFECTIVE_BALANCE = 32 * 10**9       # pre-electra ceiling (capella)
MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA = 4096

BLS_PREFIX = 0x00
ETH1_PREFIX = 0x01
COMPOUNDING_PREFIX = 0x02

HYSTERESIS_QUOTIENT = 4
HYSTERESIS_DOWN = 1
HYSTERESIS_UP = 5


# ---------------------------------------------------------------------------
# plain views
# ---------------------------------------------------------------------------

def vrows_full(state) -> list[dict]:
    """vrows + the byte columns the capella/electra logic reads."""
    v = state.validators
    return [{
        "pubkey": bytes(v.pubkeys[i]),
        "withdrawal_credentials": bytes(v.withdrawal_credentials[i]),
        "effective_balance": int(v.effective_balance[i]),
        "slashed": bool(v.slashed[i]),
        "activation_eligibility_epoch": int(
            v.activation_eligibility_epoch[i]),
        "activation_epoch": int(v.activation_epoch[i]),
        "exit_epoch": int(v.exit_epoch[i]),
        "withdrawable_epoch": int(v.withdrawable_epoch[i]),
    } for i in range(len(v))]


def has_eth1_wc(wc: bytes) -> bool:
    return wc[0] == ETH1_PREFIX


def has_compounding_wc(wc: bytes) -> bool:
    return wc[0] == COMPOUNDING_PREFIX


def has_execution_wc(wc: bytes) -> bool:
    return has_eth1_wc(wc) or has_compounding_wc(wc)


def max_effective_balance_for(row: dict) -> int:
    if has_compounding_wc(row["withdrawal_credentials"]):
        return MAX_EFFECTIVE_ELECTRA
    return MIN_ACTIVATION_BALANCE


def pending_balance_to_withdraw(state, index: int) -> int:
    return sum(int(w.amount) for w in state.pending_partial_withdrawals
               if int(w.validator_index) == index)


# ---------------------------------------------------------------------------
# electra churn accounting (EIP-7251)
# ---------------------------------------------------------------------------

def balance_churn_limit(state) -> int:
    churn = max(MIN_PER_EPOCH_CHURN_ELECTRA,
                total_active_balance(state) // CHURN_QUOTIENT)
    return churn - churn % INCREMENT


def activation_exit_churn_limit(state) -> int:
    return min(MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN,
               balance_churn_limit(state))


def consolidation_churn_limit(state) -> int:
    return balance_churn_limit(state) - activation_exit_churn_limit(state)


def exit_epoch_and_churn(earliest: int, to_consume: int, epoch: int,
                         per_epoch_churn: int, exit_balance: int
                         ) -> tuple[int, int, int]:
    """compute_exit_epoch_and_update_churn as a pure function:
    (earliest_exit_epoch, exit_balance_to_consume) -> (exit_epoch,
    new_earliest, new_to_consume).  Also used for the consolidation
    variant with the consolidation churn."""
    new_earliest = max(earliest, epoch + 1 + MAX_SEED_LOOKAHEAD)
    if earliest < new_earliest:
        balance_to_consume = per_epoch_churn
    else:
        balance_to_consume = to_consume
    if exit_balance > balance_to_consume:
        to_process = exit_balance - balance_to_consume
        additional = (to_process - 1) // per_epoch_churn + 1
        new_earliest += additional
        balance_to_consume += additional * per_epoch_churn
    return new_earliest, new_earliest, balance_to_consume - exit_balance


# ---------------------------------------------------------------------------
# capella/electra withdrawals
# ---------------------------------------------------------------------------

def expected_withdrawals(state, electra: bool
                         ) -> tuple[list[dict], int]:
    """get_expected_withdrawals -> ([{index, validator_index, address,
    amount}], processed_partials)."""
    rows = vrows_full(state)
    balances = [int(b) for b in state.balances]
    epoch = current_epoch(state)
    windex = int(state.next_withdrawal_index)
    vindex = int(state.next_withdrawal_validator_index)
    out: list[dict] = []
    processed_partials = 0
    if electra:
        for w in state.pending_partial_withdrawals:
            if int(w.withdrawable_epoch) > epoch or \
                    len(out) == MAX_PENDING_PARTIALS_PER_SWEEP:
                break
            r = rows[int(w.validator_index)]
            bal = balances[int(w.validator_index)]
            if (r["exit_epoch"] == FAR_FUTURE
                    and r["effective_balance"] >= MIN_ACTIVATION_BALANCE
                    and bal > MIN_ACTIVATION_BALANCE):
                out.append({
                    "index": windex,
                    "validator_index": int(w.validator_index),
                    "address": r["withdrawal_credentials"][12:],
                    "amount": min(bal - MIN_ACTIVATION_BALANCE,
                                  int(w.amount)),
                })
                windex += 1
            processed_partials += 1
    n = len(rows)
    for _ in range(min(n, MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)):
        r = rows[vindex]
        balance = balances[vindex]
        if electra:
            balance -= sum(w["amount"] for w in out
                           if w["validator_index"] == vindex)
            max_eb = max_effective_balance_for(r)
            withdrawable_wc = has_execution_wc(r["withdrawal_credentials"])
        else:
            max_eb = MAX_EFFECTIVE_BALANCE
            withdrawable_wc = has_eth1_wc(r["withdrawal_credentials"])
        if withdrawable_wc and r["withdrawable_epoch"] <= epoch \
                and balance > 0:
            out.append({"index": windex, "validator_index": vindex,
                        "address": r["withdrawal_credentials"][12:],
                        "amount": balance})
            windex += 1
        elif withdrawable_wc and r["effective_balance"] == max_eb \
                and balance > max_eb:
            out.append({"index": windex, "validator_index": vindex,
                        "address": r["withdrawal_credentials"][12:],
                        "amount": balance - max_eb})
            windex += 1
        if len(out) == MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        vindex = (vindex + 1) % n
    return out, processed_partials


def verify_withdrawals_op(pre, payload, post) -> None:
    exp, partials = expected_withdrawals(pre, electra=_is_electra(pre))
    got = list(payload.withdrawals)
    _ck(len(got) == len(exp), "withdrawal count")
    for g, e in zip(got, exp):
        _ck(int(g.index) == e["index"], "withdrawal index")
        _ck(int(g.validator_index) == e["validator_index"],
            "withdrawal validator")
        _ck(bytes(g.address) == e["address"], "withdrawal address")
        _ck(int(g.amount) == e["amount"], "withdrawal amount")
    balances = [int(b) for b in pre.balances]
    for e in exp:
        balances[e["validator_index"]] = max(
            0, balances[e["validator_index"]] - e["amount"])
    _ck([int(b) for b in post.balances] == balances,
        "balances after withdrawals")
    if _is_electra(pre):
        _ck(len(post.pending_partial_withdrawals)
            == len(pre.pending_partial_withdrawals) - partials,
            "pending partials consumed")
    if exp:
        _ck(int(post.next_withdrawal_index) == exp[-1]["index"] + 1,
            "next withdrawal index")
    n = len(pre.validators)
    if len(exp) == MAX_WITHDRAWALS_PER_PAYLOAD:
        want_next = (exp[-1]["validator_index"] + 1) % n
    else:
        want_next = (int(pre.next_withdrawal_validator_index)
                     + MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP) % n
    _ck(int(post.next_withdrawal_validator_index) == want_next,
        "next withdrawal validator")


def _is_electra(state) -> bool:
    return getattr(state, "pending_deposits", None) is not None


# ---------------------------------------------------------------------------
# capella bls_to_execution_change
# ---------------------------------------------------------------------------

def verify_bls_change_op(pre, signed_change, post) -> None:
    change = signed_change.message
    idx = int(change.validator_index)
    wc = bytes(pre.validators.withdrawal_credentials[idx])
    _ck(wc[0] == BLS_PREFIX, "bls change pre-credential")
    _ck(wc[1:] == hashlib.sha256(
        bytes(change.from_bls_pubkey)).digest()[1:], "bls change hash")
    new_wc = bytes(post.validators.withdrawal_credentials[idx])
    _ck(new_wc == bytes([ETH1_PREFIX]) + b"\x00" * 11
        + bytes(change.to_execution_address), "bls change new credential")
    for i in range(len(pre.validators)):
        if i != idx:
            _ck(bytes(post.validators.withdrawal_credentials[i])
                == bytes(pre.validators.withdrawal_credentials[i]),
                "bls change untouched rows")


# ---------------------------------------------------------------------------
# electra operations (EIP-6110 / EIP-7002 / EIP-7251)
# ---------------------------------------------------------------------------

def verify_deposit_request_op(pre, request, post) -> None:
    if int(pre.deposit_requests_start_index) == \
            UNSET_DEPOSIT_REQUESTS_START_INDEX:
        _ck(int(post.deposit_requests_start_index) == int(request.index),
            "deposit_requests_start_index set")
    else:
        _ck(int(post.deposit_requests_start_index)
            == int(pre.deposit_requests_start_index),
            "deposit_requests_start_index unchanged")
    _ck(len(post.pending_deposits) == len(pre.pending_deposits) + 1,
        "pending deposit appended")
    d = post.pending_deposits[-1]
    _ck(bytes(d.pubkey) == bytes(request.pubkey), "pending deposit pubkey")
    _ck(bytes(d.withdrawal_credentials)
        == bytes(request.withdrawal_credentials), "pending deposit wc")
    _ck(int(d.amount) == int(request.amount), "pending deposit amount")
    _ck(int(d.slot) == int(pre.slot), "pending deposit slot")


def _withdrawal_request_expected(pre, request) -> dict | None:
    """None => the request is a no-op; else what it must do."""
    rows = vrows_full(pre)
    epoch = current_epoch(pre)
    amount = int(request.amount)
    pk = bytes(request.validator_pubkey)
    idx = next((i for i, r in enumerate(rows) if r["pubkey"] == pk), None)
    if idx is None:
        return None
    r = rows[idx]
    wc = r["withdrawal_credentials"]
    if not has_execution_wc(wc):
        return None
    if wc[12:] != bytes(request.source_address):
        return None
    if not is_active(r, epoch):
        return None
    if epoch < r["activation_epoch"] + SHARD_COMMITTEE_PERIOD:
        return None
    if r["exit_epoch"] != FAR_FUTURE:
        return None
    pending = pending_balance_to_withdraw(pre, idx)
    if amount == FULL_EXIT_REQUEST_AMOUNT:
        if pending != 0:
            return None
        exit_epoch, new_earliest, new_consume = exit_epoch_and_churn(
            int(pre.earliest_exit_epoch), int(pre.exit_balance_to_consume),
            epoch, activation_exit_churn_limit(pre), r["effective_balance"])
        return {"kind": "full", "index": idx, "exit_epoch": exit_epoch,
                "earliest": new_earliest, "consume": new_consume}
    if len(pre.pending_partial_withdrawals) >= \
            PENDING_PARTIAL_WITHDRAWALS_LIMIT:
        return None
    balance = int(pre.balances[idx])
    if not (has_compounding_wc(wc)
            and r["effective_balance"] >= MIN_ACTIVATION_BALANCE
            and balance - pending > MIN_ACTIVATION_BALANCE):
        return None
    to_withdraw = min(balance - MIN_ACTIVATION_BALANCE - pending, amount)
    exit_epoch, new_earliest, new_consume = exit_epoch_and_churn(
        int(pre.earliest_exit_epoch), int(pre.exit_balance_to_consume),
        epoch, activation_exit_churn_limit(pre), to_withdraw)
    return {"kind": "partial", "index": idx, "amount": to_withdraw,
            "withdrawable": exit_epoch
            + MIN_VALIDATOR_WITHDRAWABILITY_DELAY,
            "earliest": new_earliest, "consume": new_consume}


def verify_withdrawal_request_op(pre, request, post) -> None:
    exp = _withdrawal_request_expected(pre, request)
    if exp is None:
        _ck(pre.hash_tree_root() == post.hash_tree_root(),
            "withdrawal request no-op")
        return
    if exp["kind"] == "full":
        i = exp["index"]
        _ck(int(post.validators.exit_epoch[i]) == exp["exit_epoch"],
            "full exit epoch")
        _ck(int(post.validators.withdrawable_epoch[i])
            == exp["exit_epoch"] + MIN_VALIDATOR_WITHDRAWABILITY_DELAY,
            "full exit withdrawable")
        _ck(int(post.earliest_exit_epoch) == exp["earliest"],
            "earliest exit epoch")
        _ck(int(post.exit_balance_to_consume) == exp["consume"],
            "exit balance to consume")
        return
    _ck(len(post.pending_partial_withdrawals)
        == len(pre.pending_partial_withdrawals) + 1, "partial appended")
    w = post.pending_partial_withdrawals[-1]
    _ck(int(w.validator_index) == exp["index"], "partial index")
    _ck(int(w.amount) == exp["amount"], "partial amount")
    _ck(int(w.withdrawable_epoch) == exp["withdrawable"],
        "partial withdrawable epoch")
    _ck(int(post.earliest_exit_epoch) == exp["earliest"],
        "earliest exit epoch (partial)")
    _ck(int(post.exit_balance_to_consume) == exp["consume"],
        "exit balance to consume (partial)")


def verify_consolidation_request_op(pre, request, post) -> None:
    rows = vrows_full(pre)
    epoch = current_epoch(pre)
    spk = bytes(request.source_pubkey)
    tpk = bytes(request.target_pubkey)
    src = next((i for i, r in enumerate(rows) if r["pubkey"] == spk), None)

    # switch-to-compounding arm
    if spk == tpk:
        valid = (src is not None
                 and has_eth1_wc(rows[src]["withdrawal_credentials"])
                 and rows[src]["withdrawal_credentials"][12:]
                 == bytes(request.source_address)
                 and is_active(rows[src], epoch)
                 and rows[src]["exit_epoch"] == FAR_FUTURE)
        if not valid:
            _ck(pre.hash_tree_root() == post.hash_tree_root(),
                "switch no-op")
            return
        new_wc = bytes(post.validators.withdrawal_credentials[src])
        _ck(new_wc == bytes([COMPOUNDING_PREFIX])
            + rows[src]["withdrawal_credentials"][1:], "switched credential")
        balance = int(pre.balances[src])
        if balance > MIN_ACTIVATION_BALANCE:
            excess = balance - MIN_ACTIVATION_BALANCE
            _ck(int(post.balances[src]) == MIN_ACTIVATION_BALANCE,
                "excess balance removed")
            d = post.pending_deposits[-1]
            _ck(int(d.amount) == excess and bytes(d.pubkey) == spk
                and int(d.slot) == GENESIS_SLOT, "excess queued")
        else:
            _ck(int(post.balances[src]) == balance, "balance unchanged")
        return

    tgt = next((i for i, r in enumerate(rows) if r["pubkey"] == tpk), None)
    ok = (consolidation_churn_limit(pre) > MIN_ACTIVATION_BALANCE
          and len(pre.pending_consolidations) < PENDING_CONSOLIDATIONS_LIMIT
          and src is not None and tgt is not None and src != tgt)
    if ok:
        sr, tr = rows[src], rows[tgt]
        ok = (has_execution_wc(sr["withdrawal_credentials"])
              and has_compounding_wc(tr["withdrawal_credentials"])
              and sr["withdrawal_credentials"][12:]
              == bytes(request.source_address)
              and is_active(sr, epoch) and is_active(tr, epoch)
              and sr["exit_epoch"] == FAR_FUTURE
              and tr["exit_epoch"] == FAR_FUTURE
              and epoch >= sr["activation_epoch"] + SHARD_COMMITTEE_PERIOD
              and pending_balance_to_withdraw(pre, src) == 0)
    if not ok:
        _ck(pre.hash_tree_root() == post.hash_tree_root(),
            "consolidation no-op")
        return
    exit_epoch, new_earliest, new_consume = exit_epoch_and_churn(
        int(pre.earliest_consolidation_epoch),
        int(pre.consolidation_balance_to_consume),
        epoch, consolidation_churn_limit(pre),
        rows[src]["effective_balance"])
    _ck(int(post.validators.exit_epoch[src]) == exit_epoch,
        "consolidation source exit")
    _ck(int(post.validators.withdrawable_epoch[src])
        == exit_epoch + MIN_VALIDATOR_WITHDRAWABILITY_DELAY,
        "consolidation source withdrawable")
    _ck(int(post.earliest_consolidation_epoch) == new_earliest,
        "earliest consolidation epoch")
    _ck(int(post.consolidation_balance_to_consume) == new_consume,
        "consolidation balance to consume")
    _ck(len(post.pending_consolidations)
        == len(pre.pending_consolidations) + 1, "consolidation appended")
    c = post.pending_consolidations[-1]
    _ck(int(c.source_index) == src and int(c.target_index) == tgt,
        "consolidation indices")


# ---------------------------------------------------------------------------
# electra epoch processing
# ---------------------------------------------------------------------------

def _deposit_signature_valid(state, pubkey: bytes, wc: bytes, amount: int,
                             signature: bytes) -> bool:
    """Deposit-domain proof of possession, hand-rolled merkle + the
    python BLS oracle (shared validated dep)."""
    def hp(a, b):
        return hashlib.sha256(a + b).digest()

    pk_root = hp(pubkey[:32], pubkey[32:48] + b"\x00" * 16)
    msg_root = hp(hp(pk_root, wc),
                  hp(amount.to_bytes(8, "little") + b"\x00" * 24,
                     b"\x00" * 32))
    # deposit domain: genesis fork version + ZERO validators root
    fork_data_root = hp(_genesis_fork_version(state).ljust(32, b"\x00"),
                        b"\x00" * 32)
    domain = bytes([3, 0, 0, 0]) + fork_data_root[:28]
    signing_root = hp(msg_root, domain)
    from ..crypto.bls import PythonBackend
    try:
        return PythonBackend().verify(pubkey, signing_root, signature)
    except Exception:
        return False


def _genesis_fork_version(state) -> bytes:
    return bytes(state.spec.genesis_fork_version)


def pending_deposits_expected(state) -> dict:
    """process_pending_deposits on plain views.  Returns the expected
    queue suffix + postponed list, applied (pubkey, amount) effects and
    the new deposit_balance_to_consume."""
    rows = vrows_full(state)
    next_epoch = current_epoch(state) + 1
    available = int(state.deposit_balance_to_consume) + \
        activation_exit_churn_limit(state)
    processed = 0
    next_index = 0
    postponed = []
    churn_reached = False
    finalized_slot = int(state.finalized_checkpoint.epoch) * SLOTS_PER_EPOCH
    applied: list[tuple[bytes, int]] = []
    pubkeys = {r["pubkey"]: i for i, r in enumerate(rows)}
    for d in state.pending_deposits:
        if int(d.slot) > GENESIS_SLOT and int(state.eth1_deposit_index) < \
                int(state.deposit_requests_start_index):
            break
        if int(d.slot) > finalized_slot:
            break
        if next_index >= MAX_PENDING_DEPOSITS_PER_EPOCH:
            break
        i = pubkeys.get(bytes(d.pubkey))
        exited = i is not None and rows[i]["exit_epoch"] < FAR_FUTURE
        withdrawn = i is not None and \
            rows[i]["withdrawable_epoch"] < next_epoch
        if withdrawn:
            applied.append((bytes(d.pubkey), int(d.amount)))
        elif exited:
            postponed.append(d)
        else:
            if processed + int(d.amount) > available:
                churn_reached = True
                break
            processed += int(d.amount)
            applied.append((bytes(d.pubkey), int(d.amount)))
        next_index += 1
    return {
        "queue": list(state.pending_deposits)[next_index:] + postponed,
        "applied": applied,
        "to_consume": (available - processed) if churn_reached else 0,
    }


def verify_pending_deposits_sub(pre, post) -> None:
    exp = pending_deposits_expected(pre)
    _ck(len(post.pending_deposits) == len(exp["queue"]),
        "pending deposit queue length")
    for got, want in zip(post.pending_deposits, exp["queue"]):
        _ck(bytes(got.pubkey) == bytes(want.pubkey)
            and int(got.amount) == int(want.amount)
            and int(got.slot) == int(want.slot), "pending deposit queue")
    _ck(int(post.deposit_balance_to_consume) == exp["to_consume"],
        "deposit balance to consume")
    # balance effects: top-ups for known keys; new validators for unknown
    # keys with valid signatures
    balances = [int(b) for b in pre.balances]
    rows = vrows_full(pre)
    known = {r["pubkey"]: i for i, r in enumerate(rows)}
    for pk, amount in exp["applied"]:
        if pk in known:
            balances[known[pk]] += amount
        else:
            dep = next(d for d in pre.pending_deposits
                       if bytes(d.pubkey) == pk)
            if _deposit_signature_valid(
                    pre, pk, bytes(dep.withdrawal_credentials),
                    int(dep.amount), bytes(dep.signature)):
                known[pk] = len(balances)
                balances.append(amount)
    _ck([int(b) for b in post.balances] == balances,
        "balances after pending deposits")
    _ck(len(post.validators) == len(balances), "registry growth")


def verify_pending_consolidations_sub(pre, post) -> None:
    rows = vrows_full(pre)
    next_epoch = current_epoch(pre) + 1
    balances = [int(b) for b in pre.balances]
    next_index = 0
    for c in pre.pending_consolidations:
        src = rows[int(c.source_index)]
        if src["slashed"]:
            next_index += 1
            continue
        if src["withdrawable_epoch"] > next_epoch:
            break
        moved = min(balances[int(c.source_index)], src["effective_balance"])
        balances[int(c.source_index)] -= moved
        balances[int(c.target_index)] += moved
        next_index += 1
    _ck(len(post.pending_consolidations)
        == len(pre.pending_consolidations) - next_index,
        "pending consolidations consumed")
    _ck([int(b) for b in post.balances] == balances,
        "balances after consolidations")


def effective_balance_updates_electra(state) -> list[int]:
    rows = vrows_full(state)
    balances = [int(b) for b in state.balances]
    hyst = INCREMENT // HYSTERESIS_QUOTIENT
    down, up = hyst * HYSTERESIS_DOWN, hyst * HYSTERESIS_UP
    out = []
    for r, b in zip(rows, balances):
        eb = r["effective_balance"]
        max_eb = max_effective_balance_for(r)
        if b + down < eb or eb + up < b:
            eb = min(b - b % INCREMENT, max_eb)
        out.append(eb)
    return out


def registry_updates_electra(state) -> list[dict]:
    """Single pass: eligibility, ejections (serial churn accounting),
    activations without a per-epoch cap (churn moved to deposit
    processing)."""
    rows = vrows_full(state)
    epoch = current_epoch(state)
    finalized = int(state.finalized_checkpoint.epoch)
    out = [dict(r) for r in rows]
    earliest = int(state.earliest_exit_epoch)
    consume = int(state.exit_balance_to_consume)
    churn = activation_exit_churn_limit(state)
    for i, r in enumerate(out):
        if r["activation_eligibility_epoch"] == FAR_FUTURE and \
                r["effective_balance"] >= MIN_ACTIVATION_BALANCE:
            r["activation_eligibility_epoch"] = epoch + 1
    for i, r in enumerate(out):
        if is_active(rows[i], epoch) and \
                r["effective_balance"] <= EJECTION_BALANCE and \
                r["exit_epoch"] == FAR_FUTURE:
            exit_epoch, earliest, consume = exit_epoch_and_churn(
                earliest, consume, epoch, churn, r["effective_balance"])
            r["exit_epoch"] = exit_epoch
            r["withdrawable_epoch"] = exit_epoch + \
                MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    for r, orig in zip(out, rows):
        if orig["activation_eligibility_epoch"] <= finalized and \
                orig["activation_epoch"] == FAR_FUTURE:
            r["activation_epoch"] = epoch + 1 + MAX_SEED_LOOKAHEAD
    return out


def verify_registry_updates_electra(pre, post) -> None:
    exp = registry_updates_electra(pre)
    v = post.validators
    for i, r in enumerate(exp):
        _ck(int(v.activation_eligibility_epoch[i])
            == r["activation_eligibility_epoch"], f"eligibility[{i}]")
        _ck(int(v.activation_epoch[i]) == r["activation_epoch"],
            f"activation[{i}]")
        _ck(int(v.exit_epoch[i]) == r["exit_epoch"], f"exit[{i}]")
        _ck(int(v.withdrawable_epoch[i]) == r["withdrawable_epoch"],
            f"withdrawable[{i}]")


def registry_updates_deneb(state) -> list[dict]:
    """Pre-electra registry updates with the EIP-7514 activation-churn
    cap: activations per epoch = min(MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT
    = 4 on minimal, validator churn limit)."""
    from .scalar_spec import active_indices, registry_updates, vrows
    rows = registry_updates(state)          # altair semantics first
    epoch = current_epoch(state)
    orig = vrows(state)
    churn = max(2, len(active_indices(orig, epoch)) // CHURN_QUOTIENT)
    cap = min(4, churn)                     # minimal preset cap
    fin = int(state.finalized_checkpoint.epoch)
    queue = sorted(
        (i for i, r in enumerate(orig)
         if r["activation_eligibility_epoch"] <= fin
         and r["activation_epoch"] == FAR_FUTURE),
        key=lambda i: (orig[i]["activation_eligibility_epoch"], i))
    for k, i in enumerate(queue):
        rows[i]["activation_epoch"] = (
            epoch + 1 + MAX_SEED_LOOKAHEAD if k < cap else FAR_FUTURE)
    return rows


def slashings_penalties_pre_electra(state, multiplier: int) -> list[int]:
    """The pre-electra slashings formula (bellatrix/capella/deneb use
    multiplier 3, altair 2): penalty = (eb // INC) * adjusted // total
    * INC — integer-division order matters and differs from electra's
    per-increment variant below."""
    rows = vrows_full(state)
    epoch = current_epoch(state)
    total = total_active_balance(state)
    adjusted = min(sum(int(s) for s in state.slashings) * multiplier,
                   total)
    target = epoch + 32                # EPOCHS_PER_SLASHINGS_VECTOR // 2
    out = []
    for i, r in enumerate(rows):
        b = int(state.balances[i])
        if r["slashed"] and r["withdrawable_epoch"] == target:
            penalty = (r["effective_balance"] // INCREMENT) * adjusted \
                // total * INCREMENT
            b = max(0, b - penalty)
        out.append(b)
    return out


def slashings_penalties_electra(state) -> list[int]:
    rows = vrows_full(state)
    epoch = current_epoch(state)
    total = total_active_balance(state)
    adjusted = min(sum(int(s) for s in state.slashings) * 3, total)
    per_increment = adjusted // (total // INCREMENT)
    target = epoch + 32  # EPOCHS_PER_SLASHINGS_VECTOR // 2 (minimal: 64/2)
    out = []
    for i, r in enumerate(rows):
        b = int(state.balances[i])
        if r["slashed"] and r["withdrawable_epoch"] == target:
            penalty = (r["effective_balance"] // INCREMENT) * per_increment
            b = max(0, b - penalty)
        out.append(b)
    return out


def verify_slashings_electra(pre, post) -> None:
    _ck([int(b) for b in post.balances] == slashings_penalties_electra(pre),
        "balances after electra slashings")
