"""Round-3 corpus generators: the runners un-skipped this round.

Independence notes (per family — same discipline as gen_corpus.py):
- ssz_generic: serializations AND roots hand-built with hashlib +
  manual little-endian packing (fully independent of lighthouse_tpu.ssz).
- rewards: expected deltas computed by SCALAR python transcriptions of
  the spec pseudocode in this file (independent shuffle, committees,
  base rewards) — the runner compares the vectorized epoch.py output
  against them.
- genesis/validity: expected flag recomputed from the two scalar spec
  conditions here, not via state_transition.genesis.
- bls eth_*: vectors produced by the native C++ backend, checked by the
  python oracle in the runner.
- merkle_proof / light_client proofs: branches assembled with hashlib
  from field roots; verification in the runner re-hashes bottom-up, so
  a wrong branch or root cannot self-validate.
- finality/random: every epoch transition the pinned chain crosses is
  verified against the scalar spec at generation time (justification,
  finalization, balances); per-block operations are scalar-verified in
  the operations family.  fork: upgrades scalar-verified (version
  rotation + field preservation).  genesis-initialization: registry
  construction scalar-verified from the deposit rows.  sync +
  fork_choice steps encode hand-specified behavioral expectations
  (head/revert semantics), not implementation output.
"""
from __future__ import annotations

import hashlib
import math

from .gen_corpus import (
    ZERO32, _mini_chain, _write_state, hp, merkle, w_ssz, w_yaml, wcase,
)

# ---------------------------------------------------------------------------
# ssz_generic (fully independent hand-built bytes + roots)
# ---------------------------------------------------------------------------


def _pack_root(data: bytes, limit_chunks: int | None = None,
               length: int | None = None) -> bytes:
    chunks = [data[i:i + 32].ljust(32, b"\x00")
              for i in range(0, max(len(data), 1), 32)] or [ZERO32]
    n = limit_chunks or len(chunks)
    size = 1
    while size < n:
        size *= 2
    chunks = chunks + [ZERO32] * (size - len(chunks))
    root = merkle(chunks)
    if length is not None:
        root = hp(root, length.to_bytes(32, "little"))
    return root


def gen_ssz_generic(root) -> int:
    n = 0

    def case(handler, suite, name, ser: bytes, root_hex: str | None):
        nonlocal n
        d = wcase(root, "general", "phase0", "ssz_generic", handler,
                  suite, name)
        w_ssz(d, "serialized.ssz_snappy", ser)
        if suite == "valid":
            w_yaml(d, "meta.yaml", {"root": root_hex})
        n += 1

    def rt(b: bytes) -> str:
        return "0x" + b.hex()

    # uints
    for bits, val in ((8, 0x7F), (16, 0xABCD), (32, 0x01020304),
                      (64, 2**63 + 7), (128, 2**100 + 3),
                      (256, 2**200 + 9)):
        ser = val.to_bytes(bits // 8, "little")
        case("uints", "valid", f"uint_{bits}_rand", ser,
             rt(ser.ljust(32, b"\x00")))
        case("uints", "valid", f"uint_{bits}_max",
             ((1 << bits) - 1).to_bytes(bits // 8, "little"),
             rt(((1 << bits) - 1).to_bytes(bits // 8,
                                           "little").ljust(32, b"\x00")))
        case("uints", "invalid", f"uint_{bits}_too_long",
             ser + b"\x00", None)
        case("uints", "invalid", f"uint_{bits}_too_short", ser[:-1], None)
    # boolean
    case("boolean", "valid", "true", b"\x01", rt(b"\x01".ljust(32, b"\x00")))
    case("boolean", "valid", "false", b"\x00", rt(ZERO32))
    case("boolean", "invalid", "byte_2", b"\x02", None)
    case("boolean", "invalid", "byte_full", b"\xff", None)
    # basic_vector (uint16 x3, bool x4, uint64 x5)
    vals16 = [0x1122, 0x3344, 0x5566]
    ser = b"".join(v.to_bytes(2, "little") for v in vals16)
    case("basic_vector", "valid", "vec_uint16_3_rand", ser, rt(_pack_root(ser)))
    case("basic_vector", "invalid", "vec_uint16_3_too_short", ser[:-1],
         None)
    case("basic_vector", "invalid", "vec_uint16_3_too_long",
         ser + b"\x00\x00", None)
    bools = b"\x01\x00\x01\x01"
    case("basic_vector", "valid", "vec_bool_4_rand", bools,
         rt(_pack_root(bools)))
    vals64 = [5, 2**40, 7, 2**63, 1]
    ser = b"".join(v.to_bytes(8, "little") for v in vals64)
    case("basic_vector", "valid", "vec_uint64_5_rand", ser,
         rt(_pack_root(ser)))
    # bitvector: serialized LSB-first bit packing
    case("bitvector", "valid", "bitvec_8_rand", bytes([0b10110010]),
         rt(bytes([0b10110010]).ljust(32, b"\x00")))
    case("bitvector", "valid", "bitvec_4_rand", bytes([0b00000101]),
         rt(bytes([0b00000101]).ljust(32, b"\x00")))
    case("bitvector", "invalid", "bitvec_4_high_bit_set",
         bytes([0b00110101]), None)
    case("bitvector", "invalid", "bitvec_8_extra_byte", b"\x01\x00", None)
    # bitlist: delimiter bit above the data bits
    #  bitlist_8 with 5 bits [1,0,1,1,0] -> byte 0b00101101 (delim at 5)
    ser = bytes([0b00101101])
    case("bitlist", "valid", "bitlist_8_len5", ser,
         rt(hp(bytes([0b00001101]).ljust(32, b"\x00"),
               (5).to_bytes(32, "little"))))
    #  empty bitlist: just the delimiter
    case("bitlist", "valid", "bitlist_8_len0", b"\x01",
         rt(hp(ZERO32, ZERO32)))
    case("bitlist", "invalid", "bitlist_8_no_delimiter", b"\x00", None)
    case("bitlist", "invalid", "bitlist_8_empty_bytes", b"", None)
    case("bitlist", "invalid", "bitlist_5_too_long", bytes([0b01111111]),
         None)
    # containers (hand-built offsets)
    #  SingleFieldTestStruct { A: uint8 }
    case("containers", "valid", "SingleFieldTestStruct_rand", b"\xab",
         rt(merkle([b"\xab".ljust(32, b"\x00")])))
    #  SmallTestStruct { A, B: uint16 }
    ser = (0x4567).to_bytes(2, "little") + (0x0123).to_bytes(2, "little")
    case("containers", "valid", "SmallTestStruct_rand", ser,
         rt(merkle([(0x4567).to_bytes(2, "little").ljust(32, b"\x00"),
                    (0x0123).to_bytes(2, "little").ljust(32, b"\x00")])))
    #  FixedTestStruct { A: uint8, B: uint64, C: uint32 }
    ser = b"\x01" + (2**50).to_bytes(8, "little") + \
        (0xDDEEFF00).to_bytes(4, "little")
    case("containers", "valid", "FixedTestStruct_rand", ser,
         rt(merkle([b"\x01".ljust(32, b"\x00"),
                    (2**50).to_bytes(8, "little").ljust(32, b"\x00"),
                    (0xDDEEFF00).to_bytes(4, "little").ljust(32,
                                                             b"\x00")])))
    #  VarTestStruct { A: uint16, B: List[uint16, 1024], C: uint8 }
    b_vals = [1, 2, 3]
    b_ser = b"".join(v.to_bytes(2, "little") for v in b_vals)
    ser = (0xABCD).to_bytes(2, "little") + (7).to_bytes(4, "little") + \
        b"\xEE" + b_ser
    b_root = _pack_root(b_ser, limit_chunks=(1024 * 2 + 31) // 32,
                        length=3)
    case("containers", "valid", "VarTestStruct_rand", ser,
         rt(merkle([(0xABCD).to_bytes(2, "little").ljust(32, b"\x00"),
                    b_root, b"\xEE".ljust(32, b"\x00")])))
    case("containers", "invalid", "VarTestStruct_offset_into_fixed",
         (0xABCD).to_bytes(2, "little") + (3).to_bytes(4, "little")
         + b"\xEE", None)
    case("containers", "invalid", "VarTestStruct_truncated",
         (0xABCD).to_bytes(2, "little") + (7).to_bytes(4, "little"),
         None)
    return n


# ---------------------------------------------------------------------------
# rewards: scalar spec transcription (independent of epoch.py)
# ---------------------------------------------------------------------------

TIMELY_SOURCE, TIMELY_TARGET, TIMELY_HEAD = 0, 1, 2
WEIGHTS = [14, 26, 14]          # TIMELY_* weights
WEIGHT_DENOM = 64


def _active(v, epoch: int) -> bool:
    return v["activation_epoch"] <= epoch < v["exit_epoch"]


def _vrows(state) -> list[dict]:
    vs = state.validators
    return [{k: int(getattr(vs, k)[i])
             for k in ("activation_epoch", "exit_epoch", "slashed",
                       "withdrawable_epoch", "effective_balance")}
            for i in range(len(vs))]


def _spec_altair_deltas(state, flag: int) -> tuple[list[int], list[int]]:
    p = state.T.preset
    epoch = int(state.slot) // p.slots_per_epoch
    prev = max(0, epoch - 1) if epoch > 0 else 0
    rows = _vrows(state)
    inc = p.effective_balance_increment
    total = max(inc, sum(r["effective_balance"] for r in rows
                         if _active(r, epoch)))
    sqrt_total = math.isqrt(total)
    participation = [int(b) for b in state.previous_epoch_participation]
    finalized = int(state.finalized_checkpoint.epoch)
    leak = (prev - finalized) > 4       # MIN_EPOCHS_TO_INACTIVITY_PENALTY
    n = len(rows)
    rewards, penalties = [0] * n, [0] * n
    part_total = sum(r["effective_balance"]
                     for i, r in enumerate(rows)
                     if _active(r, prev) and not r["slashed"]
                     and participation[i] >> flag & 1)
    active_incs = total // inc
    part_incs = part_total // inc
    for i, r in enumerate(rows):
        eligible = _active(r, prev) or (
            r["slashed"] and prev + 1 < r["withdrawable_epoch"])
        if not eligible:
            continue
        base = (r["effective_balance"] // inc) * \
            (inc * 64 // sqrt_total)    # BASE_REWARD_FACTOR = 64
        participating = _active(r, prev) and not r["slashed"] and \
            participation[i] >> flag & 1
        if participating:
            if not leak:
                num = base * WEIGHTS[flag] * part_incs
                rewards[i] += num // (active_incs * WEIGHT_DENOM)
        elif flag != TIMELY_HEAD:
            penalties[i] += base * WEIGHTS[flag] // WEIGHT_DENOM
    return rewards, penalties


def _spec_altair_inactivity(state) -> tuple[list[int], list[int]]:
    p = state.T.preset
    epoch = int(state.slot) // p.slots_per_epoch
    prev = max(0, epoch - 1) if epoch > 0 else 0
    rows = _vrows(state)
    participation = [int(b) for b in state.previous_epoch_participation]
    scores = [int(s) for s in state.inactivity_scores]
    n = len(rows)
    penalties = [0] * n
    # INACTIVITY_SCORE_BIAS = 4; quotient: 3*2^24 (altair), 2^24
    # (bellatrix onward) — spec constants, transcribed not imported
    q = 3 * 2**24 if state.fork_name.name.lower() == "altair" else 2**24
    for i, r in enumerate(rows):
        eligible = _active(r, prev) or (
            r["slashed"] and prev + 1 < r["withdrawable_epoch"])
        if not eligible:
            continue
        target_ok = _active(r, prev) and not r["slashed"] and \
            participation[i] >> TIMELY_TARGET & 1
        if not target_ok:
            penalties[i] += (r["effective_balance"] * scores[i]
                             ) // (4 * q)
    return [0] * n, penalties


def _enc_deltas(rewards: list[int], penalties: list[int]) -> bytes:
    off1 = 8
    off2 = 8 + 8 * len(rewards)
    return (off1.to_bytes(4, "little") + off2.to_bytes(4, "little")
            + b"".join(v.to_bytes(8, "little") for v in rewards)
            + b"".join(v.to_bytes(8, "little") for v in penalties))


def gen_rewards(root) -> int:
    """altair rewards vectors with INDEPENDENT scalar expectations."""
    from ..state_transition import process_slots
    h, spec = _mini_chain()
    spe = spec.preset.slots_per_epoch
    h.extend_chain(2 * spe + 2)
    state = h.chain.head().head_state.copy()
    # align to an epoch boundary - 1 (the spec applies deltas there)
    process_slots(state, (state.current_epoch() + 1) * spe - 1)
    n = 0
    d = wcase(root, "minimal", "altair", "rewards", "basic",
              "pyspec_tests", "full_participation")
    _write_state(d, "pre.ssz_snappy", state)
    for name, flag in (("source_deltas", TIMELY_SOURCE),
                       ("target_deltas", TIMELY_TARGET),
                       ("head_deltas", TIMELY_HEAD)):
        w_ssz(d, f"{name}.ssz_snappy",
              _enc_deltas(*_spec_altair_deltas(state, flag)))
    w_ssz(d, "inactivity_penalty_deltas.ssz_snappy",
          _enc_deltas(*_spec_altair_inactivity(state)))
    n += 1
    # a leak variant: static state surgery (slot jumped 6 epochs with
    # finality pinned at 0, a few validators non-participating with
    # raised inactivity scores) — both the transcription and the
    # vectorized code read the same static fields
    import numpy as np
    leak = state.copy()
    leak.slot = int(state.slot) + 6 * spe
    leak.finalized_checkpoint = state.T.Checkpoint(
        epoch=0, root=state.finalized_checkpoint.root)
    part = np.array(leak.previous_epoch_participation, dtype=np.uint8)
    part[3:7] = 0
    leak.previous_epoch_participation = part
    scores = np.array(leak.inactivity_scores, dtype=np.uint64)
    scores[3:7] = 44
    leak.inactivity_scores = scores
    d = wcase(root, "minimal", "altair", "rewards", "leak",
              "pyspec_tests", "leak_participation")
    _write_state(d, "pre.ssz_snappy", leak)
    for name, flag in (("source_deltas", TIMELY_SOURCE),
                       ("target_deltas", TIMELY_TARGET),
                       ("head_deltas", TIMELY_HEAD)):
        w_ssz(d, f"{name}.ssz_snappy",
              _enc_deltas(*_spec_altair_deltas(leak, flag)))
    w_ssz(d, "inactivity_penalty_deltas.ssz_snappy",
          _enc_deltas(*_spec_altair_inactivity(leak)))
    n += 1
    return n


# ---------------------------------------------------------------------------
# fork / finality / random / genesis / sync (labeled pins) + proofs
# ---------------------------------------------------------------------------

def gen_fork(root) -> int:
    from ..chain.harness import BeaconChainHarness
    from ..specs import minimal_spec
    from ..state_transition import upgrades
    n = 0
    for post, overrides in (
            ("altair", {"altair_fork_epoch": 64}),
            ("bellatrix", {"altair_fork_epoch": 0,
                           "bellatrix_fork_epoch": 64}),
            ("capella", {"altair_fork_epoch": 0, "bellatrix_fork_epoch": 0,
                         "capella_fork_epoch": 64}),
            ("deneb", {"altair_fork_epoch": 0, "bellatrix_fork_epoch": 0,
                       "capella_fork_epoch": 0, "deneb_fork_epoch": 64}),
            ("electra", {"altair_fork_epoch": 0,
                         "bellatrix_fork_epoch": 0,
                         "capella_fork_epoch": 0, "deneb_fork_epoch": 0,
                         "electra_fork_epoch": 64}),
    ):
        spec = minimal_spec(**overrides)
        h = BeaconChainHarness(spec, 16)
        h.extend_chain(3)
        pre = h.chain.head().head_state.copy()
        post_state = pre.copy()
        getattr(upgrades, f"upgrade_to_{post}")(post_state)
        from ..specs.chain_spec import ForkName
        from . import scalar_spec
        scalar_spec.verify_upgrade(
            pre, post_state,
            expected_prev=bytes(pre.fork.current_version),
            expected_cur=spec.fork_version(ForkName[post.upper()]))
        d = wcase(root, "minimal", post, "fork", "fork", "pyspec_tests",
                  f"fork_base_{post}")
        w_yaml(d, "meta.yaml", {"fork": post})
        _write_state(d, "pre.ssz_snappy", pre)
        _write_state(d, "post.ssz_snappy", post_state)
        n += 1
    return n


def gen_finality_random(root) -> int:
    from ..ssz import serialize
    from ..state_transition import per_block_processing, process_slots
    h, spec = _mini_chain()
    spe = spec.preset.slots_per_epoch
    # build up two finalized epochs of history first
    h.extend_chain(2 * spe + 2)
    base = h.chain.head().head_state.copy()
    n = 0
    for runner, handler, blocks_n, attest in (
            ("finality", "finality", 2 * spe, True),
            ("random", "random", spe, True)):
        pre = h.chain.head().head_state.copy()
        roots = h.extend_chain(blocks_n, attest=attest)
        blocks = [h.chain.store.get_block(r) for r in roots]
        post = h.chain.head().head_state
        # de-circularization: every epoch transition the pinned chain
        # crosses is verified against the INDEPENDENT scalar spec
        # (justification bits, finalized checkpoint, balances,
        # effective balances — scalar_spec.py); the per-block operations
        # are scalar-verified by the operations family
        from . import scalar_spec
        for b in blocks:
            bslot = int(b.message.slot)
            if bslot % spe != 0:
                continue
            parent = h.chain.store.get_block(bytes(b.message.parent_root))
            pstate = h.chain.store.get_hot_state(
                bytes(parent.message.state_root))
            if pstate is None:
                continue
            last = pstate.copy()
            process_slots(last, bslot - 1)        # stays inside the epoch
            crossed = last.copy()
            process_slots(crossed, bslot)         # the verified crossing
            scalar_spec.verify_epoch_transition(last, crossed)
        d = wcase(root, "minimal", "altair", runner, handler,
                  "pyspec_tests", f"{runner}_chain")
        w_yaml(d, "meta.yaml", {"blocks_count": len(blocks)})
        _write_state(d, "pre.ssz_snappy", pre)
        for i, b in enumerate(blocks):
            w_ssz(d, f"blocks_{i}.ssz_snappy",
                  serialize(type(b).ssz_type, b))
        _write_state(d, "post.ssz_snappy", post)
        n += 1
    return n


def gen_genesis(root) -> int:
    from ..crypto import bls
    bls.set_backend("python")
    from ..specs import minimal_spec
    from ..state_transition.genesis import (
        genesis_deposits, initialize_beacon_state_from_eth1,
    )
    spec = minimal_spec()
    n = 0
    # initialization (pin): enough deposits to clear
    # MIN_GENESIS_ACTIVE_VALIDATOR_COUNT on the minimal preset (64)
    n_keys = spec.min_genesis_active_validator_count
    deposits = genesis_deposits(spec, list(range(1, n_keys + 1)),
                                32 * 10**9)
    block_hash = b"\x42" * 32
    ts = 1_600_000_000
    state = initialize_beacon_state_from_eth1(spec, block_hash, ts,
                                              deposits)
    from . import scalar_spec
    scalar_spec.verify_genesis_registry(
        [(bytes(dep.data.pubkey), bytes(dep.data.withdrawal_credentials),
          int(dep.data.amount)) for dep in deposits], state)
    d = wcase(root, "minimal", "phase0", "genesis", "initialization",
              "pyspec_tests", f"initialization_{n_keys}")
    w_yaml(d, "eth1.yaml", {"eth1_block_hash": "0x" + block_hash.hex(),
                            "eth1_timestamp": ts})
    w_yaml(d, "meta.yaml", {"deposits_count": len(deposits)})
    from ..ssz import serialize
    T = state.T
    for i, dep in enumerate(deposits):
        w_ssz(d, f"deposits_{i}.ssz_snappy",
              serialize(T.Deposit.ssz_type, dep))
    _write_state(d, "state.ssz_snappy", state)
    n += 1
    # validity: INDEPENDENT scalar recheck of the spec conditions.
    # (minimal's MIN_GENESIS_TIME is 0, so no too-early variant exists.)
    for name, mutate in (("valid_state", None),
                         ("too_few_validators", "validators")):
        s = state.copy()
        if mutate == "validators":
            # deactivate validators below the minimum count
            for i in range(len(s.validators)):
                if i >= spec.min_genesis_active_validator_count - 1:
                    s.validators.set_field(i, "activation_epoch", 2**60)
        active = sum(
            1 for i in range(len(s.validators))
            if int(s.validators.activation_epoch[i]) == 0
            and int(s.validators.exit_epoch[i]) > 0)
        is_valid = (int(s.genesis_time) >= spec.min_genesis_time
                    and active >= spec.min_genesis_active_validator_count)
        d = wcase(root, "minimal", "phase0", "genesis", "validity",
                  "pyspec_tests", name)
        _write_state(d, "genesis.ssz_snappy", s)
        w_yaml(d, "is_valid.yaml", bool(is_valid))
        n += 1
    return n


def gen_light_client_proofs(root) -> int:
    """light_client/single_merkle_proof/BeaconState cases: branches
    assembled from per-field roots; the runner re-hashes bottom-up, so
    only a correct (branch, root) pair passes."""
    from ..chain.light_client import (
        finalized_root_branch, state_field_branch,
    )
    h, spec = _mini_chain()
    h.extend_chain(10)
    state = h.chain.head().head_state.copy()
    n = 0
    for name, fn in (
            ("current_sync_committee_merkle_proof",
             lambda s: state_field_branch(s, "current_sync_committee")),
            ("next_sync_committee_merkle_proof",
             lambda s: state_field_branch(s, "next_sync_committee")),
            ("finality_root_merkle_proof", finalized_root_branch)):
        leaf, branch, gindex = fn(state)
        d = wcase(root, "minimal", "altair", "light_client",
                  "single_merkle_proof", "BeaconState", name)
        _write_state(d, "object.ssz_snappy", state)
        w_yaml(d, "proof.yaml", {
            "leaf": "0x" + leaf.hex(),
            "leaf_index": gindex,
            "branch": ["0x" + b.hex() for b in branch]})
        n += 1
    return n


def gen_sync(root) -> int:
    """sync/optimistic: a bellatrix chain where the engine reports the
    tip payload INVALID; head must revert to the parent."""
    from ..crypto import bls
    bls.set_backend("python")
    from ..chain.harness import BeaconChainHarness
    from ..specs import minimal_spec
    from ..ssz import htr, serialize
    spec = minimal_spec(altair_fork_epoch=0, bellatrix_fork_epoch=0)
    h = BeaconChainHarness(spec, 16)
    anchor = h.chain.genesis_state
    anchor_block = h.chain.store.get_block(h.chain.genesis_block_root)
    r1, r2 = h.extend_chain(2)
    b1 = h.chain.store.get_block(r1)
    b2 = h.chain.store.get_block(r2)
    ph1 = b1.message.body.execution_payload.block_hash
    ph2 = b2.message.body.execution_payload.block_hash
    d = wcase(root, "minimal", "bellatrix", "sync", "optimistic",
              "pyspec_tests", "invalid_tip_reverts")
    w_ssz(d, "anchor_state.ssz_snappy", anchor.serialize())
    w_ssz(d, "anchor_block.ssz_snappy",
          serialize(type(anchor_block.message).ssz_type,
                    anchor_block.message))
    w_ssz(d, "block_1.ssz_snappy", serialize(type(b1).ssz_type, b1))
    w_ssz(d, "block_2.ssz_snappy", serialize(type(b2).ssz_type, b2))
    steps = [
        {"tick": 2 * spec.seconds_per_slot},
        {"block": "block_1"},
        {"block": "block_2"},
        {"checks": {"head": {"slot": 2, "root": "0x" + r2.hex()}}},
        {"block_hash": "0x" + ph2.hex(),
         "payload_status": {"status": "INVALID",
                            "latest_valid_hash": "0x" + ph1.hex()}},
        {"checks": {"head": {"slot": 1, "root": "0x" + r1.hex()}}},
    ]
    w_yaml(d, "steps.yaml", steps)
    return 1


def gen_bls_eth(root) -> int:
    """eth_aggregate_pubkeys + eth_fast_aggregate_verify via the C++
    backend (independent implementation)."""
    from ..crypto.bls.cpp_backend import CppBackend
    b = CppBackend()
    n = 0

    def case(handler, name, inp, out):
        nonlocal n
        d = wcase(root, "general", "altair", "bls", handler, "small",
                  name)
        w_yaml(d, "data.yaml", {"input": inp, "output": out})
        n += 1

    sks = [5, 6, 7]
    pks = [b.sk_to_pk(sk) for sk in sks]
    agg_pk = b.aggregate_public_keys(pks)
    case("eth_aggregate_pubkeys", "case_agg3",
         ["0x" + p.hex() for p in pks], "0x" + agg_pk.hex())
    case("eth_aggregate_pubkeys", "case_single",
         ["0x" + pks[0].hex()], "0x" + pks[0].hex())
    case("eth_aggregate_pubkeys", "case_empty", [], None)
    case("eth_aggregate_pubkeys", "case_infinity",
         ["0x" + (b"\xc0" + b"\x00" * 47).hex()], None)
    msg = b"\x34" * 32
    sigs = [b.sign(sk, msg) for sk in sks]
    agg_sig = b.aggregate_signatures(sigs)
    case("eth_fast_aggregate_verify", "case_valid3",
         {"pubkeys": ["0x" + p.hex() for p in pks],
          "message": "0x" + msg.hex(),
          "signature": "0x" + agg_sig.hex()}, True)
    case("eth_fast_aggregate_verify", "case_wrong_msg",
         {"pubkeys": ["0x" + p.hex() for p in pks],
          "message": "0x" + (b"\x35" * 32).hex(),
          "signature": "0x" + agg_sig.hex()}, False)
    case("eth_fast_aggregate_verify", "case_empty_infinity",
         {"pubkeys": [], "message": "0x" + msg.hex(),
          "signature": "0x" + (b"\xc0" + b"\x00" * 95).hex()}, True)
    case("eth_fast_aggregate_verify", "case_empty_real_sig",
         {"pubkeys": [], "message": "0x" + msg.hex(),
          "signature": "0x" + sigs[0].hex()}, False)
    return n


GENERATORS = {
    "ssz_generic": gen_ssz_generic,
    "rewards": gen_rewards,
    "fork": gen_fork,
    "finality_random": gen_finality_random,
    "genesis": gen_genesis,
    "light_client": gen_light_client_proofs,
    "sync": gen_sync,
    "bls_eth": gen_bls_eth,
}


def generate_all(dest_root, only: list[str] | None = None) -> int:
    n = 0
    for name, fn in GENERATORS.items():
        if only and name not in only:
            continue
        n += fn(dest_root)
        print(f"  r3:{name} done", flush=True)
    return n
