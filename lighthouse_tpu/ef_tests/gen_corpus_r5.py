"""Round-5 corpus generators: capella + electra operations and electra
epoch-processing families (VERDICT r4 "next" #3).

Every post-state written here is verified at GENERATION time against the
independent scalar transcription in scalar_spec_electra.py — the same
de-circularization discipline as the altair families (gen_corpus_r3.py):
a fork-specific STF bug (withdrawal sweep, churn accounting, pending
queues) cannot be enshrined as an expected post-state because generation
fails when the vectorized implementation disagrees with the scalar spec.

Reference parity targets: process_operations.rs electra arms,
capella::process_withdrawals, per_epoch_processing/single_pass.rs.
"""
from __future__ import annotations

from . import scalar_spec_electra as sse
from .gen_corpus import _write_state, w_ssz, wcase

ETH = 10**9


# ---------------------------------------------------------------------------
# state builders
# ---------------------------------------------------------------------------

def _spec(last_fork: str, n_extra: dict | None = None):
    from ..specs.chain_spec import minimal_spec
    epochs = {"altair_fork_epoch": 0}
    if last_fork in ("bellatrix", "capella", "deneb", "electra"):
        epochs["bellatrix_fork_epoch"] = 0
    if last_fork in ("capella", "deneb", "electra"):
        epochs["capella_fork_epoch"] = 0
    if last_fork in ("deneb", "electra"):
        epochs["deneb_fork_epoch"] = 0
    if last_fork == "electra":
        epochs["electra_fork_epoch"] = 0
    epochs.update(n_extra or {})
    return minimal_spec(**epochs)


def _genesis(last_fork: str, n: int):
    from ..crypto import bls
    bls.set_backend("python")
    from ..state_transition.genesis import interop_genesis_state
    spec = _spec(last_fork)
    keys = [bls.keygen_interop(i) for i in range(n)]
    state = interop_genesis_state(spec, keys, genesis_time=0)
    return state, keys, spec


def _set_wc(state, idx: int, prefix: int, address: bytes | None = None):
    """Give validator `idx` an execution credential with `address`
    (default: 20 bytes derived from the index)."""
    address = address or bytes([0xAA, idx % 256] * 10)
    wc = bytes([prefix]) + b"\x00" * 11 + address
    state.validators.set_field(idx, "withdrawal_credentials", wc)
    return address


def _set_balance(state, idx: int, amount: int):
    state.balances[idx] = amount
    state.mark_balances_dirty(idx)


def _age(state, epoch: int):
    """Jump the clock so current_epoch() == epoch (operations/epoch
    vectors only need field consistency, not a replayed chain)."""
    state.slot = epoch * state.slots_per_epoch


def _age_last_slot(state, epoch: int):
    """Last slot of `epoch` — where epoch sub-transitions run."""
    state.slot = (epoch + 1) * state.slots_per_epoch - 1


def _deposit_sig(spec, sk: int, pubkey: bytes, wc: bytes, amount: int
                 ) -> bytes:
    from ..crypto import bls
    from ..specs.chain_spec import compute_domain, compute_signing_root
    from ..specs.constants import DOMAIN_DEPOSIT
    from ..containers import get_types
    from ..ssz import htr
    T = get_types(spec.preset)
    domain = compute_domain(DOMAIN_DEPOSIT, spec.genesis_fork_version,
                            b"\x00" * 32)
    msg = T.DepositMessage(pubkey=pubkey, withdrawal_credentials=wc,
                           amount=amount)
    return bls.sign(sk, compute_signing_root(htr(msg), domain))


# ---------------------------------------------------------------------------
# electra operations
# ---------------------------------------------------------------------------

def gen_electra_operations(root) -> int:
    from ..containers import get_types
    from ..ssz import serialize
    from ..state_transition import block as blk
    n = 0

    def case(handler, name):
        return wcase(root, "minimal", "electra", "operations", handler,
                     "pyspec_tests", name)

    # ---- deposit_request ------------------------------------------------
    state, keys, spec = _genesis("electra", 16)
    T = get_types(spec.preset)
    _age(state, 3)
    new_sk = 10**6 + 7
    from ..crypto import bls
    new_pk = bls.sk_to_pk(new_sk)
    new_wc = b"\x02" + b"\x00" * 11 + b"\xbb" * 20
    req = T.DepositRequest(
        pubkey=new_pk, withdrawal_credentials=new_wc, amount=32 * ETH,
        signature=_deposit_sig(spec, new_sk, new_pk, new_wc, 32 * ETH),
        index=77)
    d = case("deposit_request", "sets_start_index_and_queues")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "deposit_request.ssz_snappy",
          serialize(T.DepositRequest.ssz_type, req))
    post = state.copy()
    blk.process_deposit_request(post, req)
    sse.verify_deposit_request_op(state, req, post)
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # second request: start index already set
    req2 = T.DepositRequest(
        pubkey=bytes(state.validators.pubkeys[2]),
        withdrawal_credentials=b"\x01" + b"\x00" * 31, amount=1 * ETH,
        signature=b"\x00" * 96, index=78)
    d = case("deposit_request", "top_up_keeps_start_index")
    _write_state(d, "pre.ssz_snappy", post)
    w_ssz(d, "deposit_request.ssz_snappy",
          serialize(T.DepositRequest.ssz_type, req2))
    post2 = post.copy()
    blk.process_deposit_request(post2, req2)
    sse.verify_deposit_request_op(post, req2, post2)
    _write_state(d, "post.ssz_snappy", post2)
    n += 1

    # ---- withdrawal_request --------------------------------------------
    state, keys, spec = _genesis("electra", 16)
    T = get_types(spec.preset)
    _age(state, sse.SHARD_COMMITTEE_PERIOD + 3)
    addr5 = _set_wc(state, 5, sse.ETH1_PREFIX)
    addr6 = _set_wc(state, 6, sse.COMPOUNDING_PREFIX)
    _set_balance(state, 6, 40 * ETH)

    # full exit
    req = T.WithdrawalRequest(source_address=addr5,
                              validator_pubkey=bytes(
                                  state.validators.pubkeys[5]),
                              amount=sse.FULL_EXIT_REQUEST_AMOUNT)
    d = case("withdrawal_request", "full_exit_via_churn")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "withdrawal_request.ssz_snappy",
          serialize(T.WithdrawalRequest.ssz_type, req))
    post = state.copy()
    blk.process_withdrawal_request(post, req)
    sse.verify_withdrawal_request_op(state, req, post)
    assert int(post.validators.exit_epoch[5]) != sse.FAR_FUTURE
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # partial withdrawal (compounding, excess balance)
    req = T.WithdrawalRequest(source_address=addr6,
                              validator_pubkey=bytes(
                                  state.validators.pubkeys[6]),
                              amount=5 * ETH)
    d = case("withdrawal_request", "partial_withdrawal_queued")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "withdrawal_request.ssz_snappy",
          serialize(T.WithdrawalRequest.ssz_type, req))
    post = state.copy()
    blk.process_withdrawal_request(post, req)
    sse.verify_withdrawal_request_op(state, req, post)
    assert len(post.pending_partial_withdrawals) == 1
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # wrong source address: no-op (post == pre)
    req = T.WithdrawalRequest(source_address=b"\xde" * 20,
                              validator_pubkey=bytes(
                                  state.validators.pubkeys[5]),
                              amount=sse.FULL_EXIT_REQUEST_AMOUNT)
    d = case("withdrawal_request", "wrong_source_address_noop")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "withdrawal_request.ssz_snappy",
          serialize(T.WithdrawalRequest.ssz_type, req))
    post = state.copy()
    blk.process_withdrawal_request(post, req)
    sse.verify_withdrawal_request_op(state, req, post)
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # ---- consolidation_request -----------------------------------------
    # 192 validators: total 6144 ETH -> balance churn 192 ETH, activation
    # churn 128 ETH, consolidation churn 64 ETH > MIN_ACTIVATION
    state, keys, spec = _genesis("electra", 192)
    T = get_types(spec.preset)
    _age(state, sse.SHARD_COMMITTEE_PERIOD + 5)
    src_addr = _set_wc(state, 7, sse.ETH1_PREFIX)
    _set_wc(state, 9, sse.COMPOUNDING_PREFIX)
    req = T.ConsolidationRequest(
        source_address=src_addr,
        source_pubkey=bytes(state.validators.pubkeys[7]),
        target_pubkey=bytes(state.validators.pubkeys[9]))
    d = case("consolidation_request", "valid_consolidation")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "consolidation_request.ssz_snappy",
          serialize(T.ConsolidationRequest.ssz_type, req))
    post = state.copy()
    blk.process_consolidation_request(post, req)
    sse.verify_consolidation_request_op(state, req, post)
    assert len(post.pending_consolidations) == 1
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # switch to compounding (source == target, eth1 creds, excess balance)
    sw_addr = _set_wc(state, 11, sse.ETH1_PREFIX)
    _set_balance(state, 11, 34 * ETH)
    req = T.ConsolidationRequest(
        source_address=sw_addr,
        source_pubkey=bytes(state.validators.pubkeys[11]),
        target_pubkey=bytes(state.validators.pubkeys[11]))
    d = case("consolidation_request", "switch_to_compounding")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "consolidation_request.ssz_snappy",
          serialize(T.ConsolidationRequest.ssz_type, req))
    post = state.copy()
    blk.process_consolidation_request(post, req)
    sse.verify_consolidation_request_op(state, req, post)
    assert bytes(post.validators.withdrawal_credentials[11])[0] == 0x02
    assert len(post.pending_deposits) == 1    # the 2 ETH excess
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # insufficient consolidation churn (small registry): no-op
    small, _k, spec16 = _genesis("electra", 16)
    T16 = get_types(spec16.preset)
    _age(small, sse.SHARD_COMMITTEE_PERIOD + 5)
    a = _set_wc(small, 1, sse.ETH1_PREFIX)
    _set_wc(small, 2, sse.COMPOUNDING_PREFIX)
    req = T16.ConsolidationRequest(
        source_address=a,
        source_pubkey=bytes(small.validators.pubkeys[1]),
        target_pubkey=bytes(small.validators.pubkeys[2]))
    d = case("consolidation_request", "insufficient_churn_noop")
    _write_state(d, "pre.ssz_snappy", small)
    w_ssz(d, "consolidation_request.ssz_snappy",
          serialize(T16.ConsolidationRequest.ssz_type, req))
    post = small.copy()
    blk.process_consolidation_request(post, req)
    sse.verify_consolidation_request_op(small, req, post)
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # ---- withdrawals (electra: partial sweep + regular sweep) ----------
    state, keys, spec = _genesis("electra", 16)
    T = get_types(spec.preset)
    _age(state, 10)
    _set_wc(state, 3, sse.COMPOUNDING_PREFIX)
    _set_balance(state, 3, 40 * ETH)
    state.pending_partial_withdrawals = [
        T.PendingPartialWithdrawal(validator_index=3, amount=4 * ETH,
                                   withdrawable_epoch=9)]
    # a fully-withdrawable validator for the sweep arm
    _set_wc(state, 0, sse.ETH1_PREFIX)
    state.validators.set_field(0, "withdrawable_epoch", 8)
    state.validators.set_field(0, "exit_epoch", 7)
    from ..specs.chain_spec import ForkName
    expected, _p = blk.get_expected_withdrawals(state)
    payload = T.ExecutionPayload[ForkName.ELECTRA](withdrawals=expected)
    d = case("withdrawals", "partial_sweep_and_full_withdrawal")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "execution_payload.ssz_snappy",
          serialize(T.ExecutionPayload[ForkName.ELECTRA].ssz_type, payload))
    post = state.copy()
    blk.process_withdrawals(post, payload)
    sse.verify_withdrawals_op(state, payload, post)
    assert len(post.pending_partial_withdrawals) == 0
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # invalid: payload withdrawal amount tampered -> must raise
    bad = [T.Withdrawal(index=int(w.index),
                        validator_index=int(w.validator_index),
                        address=bytes(w.address),
                        amount=int(w.amount) + 1) for w in expected]
    payload_bad = T.ExecutionPayload[ForkName.ELECTRA](withdrawals=bad)
    d = case("withdrawals", "invalid_tampered_amount")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "execution_payload.ssz_snappy",
          serialize(T.ExecutionPayload[ForkName.ELECTRA].ssz_type,
                    payload_bad))
    n += 1
    return n


# ---------------------------------------------------------------------------
# capella operations
# ---------------------------------------------------------------------------

def gen_capella_operations(root) -> int:
    from ..containers import get_types
    from ..crypto import bls
    from ..specs.chain_spec import (
        ForkName, compute_domain, compute_signing_root,
    )
    from ..specs.constants import DOMAIN_BLS_TO_EXECUTION_CHANGE
    from ..ssz import htr, serialize
    from ..state_transition import block as blk
    from ..state_transition.block import VerifySignatures
    n = 0

    def case(handler, name):
        return wcase(root, "minimal", "capella", "operations", handler,
                     "pyspec_tests", name)

    state, keys, spec = _genesis("capella", 16)
    T = get_types(spec.preset)
    _age(state, 10)
    # full withdrawal: exited validator with eth1 creds
    _set_wc(state, 2, sse.ETH1_PREFIX)
    state.validators.set_field(2, "withdrawable_epoch", 9)
    state.validators.set_field(2, "exit_epoch", 8)
    # partial withdrawal: active with balance above 32 ETH
    _set_wc(state, 4, sse.ETH1_PREFIX)
    _set_balance(state, 4, 35 * ETH)
    expected, _p = blk.get_expected_withdrawals(state)
    assert len(expected) == 2, "capella sweep should find full+partial"
    payload = T.ExecutionPayload[ForkName.CAPELLA](withdrawals=expected)
    d = case("withdrawals", "full_and_partial_sweep")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "execution_payload.ssz_snappy",
          serialize(T.ExecutionPayload[ForkName.CAPELLA].ssz_type, payload))
    post = state.copy()
    blk.process_withdrawals(post, payload)
    sse.verify_withdrawals_op(state, payload, post)
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # invalid: missing withdrawal -> raise
    payload_bad = T.ExecutionPayload[ForkName.CAPELLA](
        withdrawals=expected[:1])
    d = case("withdrawals", "invalid_missing_withdrawal")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "execution_payload.ssz_snappy",
          serialize(T.ExecutionPayload[ForkName.CAPELLA].ssz_type,
                    payload_bad))
    n += 1

    # ---- bls_to_execution_change ---------------------------------------
    idx = 8
    pk = bls.sk_to_pk(keys[idx])          # interop: wc == 00||sha(pk)[1:]
    change = T.BLSToExecutionChange(
        validator_index=idx, from_bls_pubkey=pk,
        to_execution_address=b"\xcc" * 20)
    domain = compute_domain(DOMAIN_BLS_TO_EXECUTION_CHANGE,
                            spec.genesis_fork_version,
                            state.genesis_validators_root)
    sig = bls.sign(keys[idx], compute_signing_root(htr(change), domain))
    signed = T.SignedBLSToExecutionChange(message=change, signature=sig)
    d = case("bls_to_execution_change", "valid_change")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "address_change.ssz_snappy",
          serialize(T.SignedBLSToExecutionChange.ssz_type, signed))
    post = state.copy()
    blk.process_bls_to_execution_change(post, signed,
                                        VerifySignatures.TRUE)
    sse.verify_bls_change_op(state, signed, post)
    _write_state(d, "post.ssz_snappy", post)
    n += 1

    # invalid: from_bls_pubkey does not hash to the credential
    wrong = T.BLSToExecutionChange(
        validator_index=idx, from_bls_pubkey=bls.sk_to_pk(keys[0]),
        to_execution_address=b"\xcc" * 20)
    signed_bad = T.SignedBLSToExecutionChange(
        message=wrong, signature=sig)
    d = case("bls_to_execution_change", "invalid_pubkey_hash")
    _write_state(d, "pre.ssz_snappy", state)
    w_ssz(d, "address_change.ssz_snappy",
          serialize(T.SignedBLSToExecutionChange.ssz_type, signed_bad))
    n += 1
    return n


# ---------------------------------------------------------------------------
# electra epoch processing
# ---------------------------------------------------------------------------

def gen_electra_epoch(root) -> int:
    from ..containers import get_types
    from ..crypto import bls
    from ..state_transition import epoch as ep
    n = 0

    def case(handler, name):
        return wcase(root, "minimal", "electra", "epoch_processing",
                     handler, "pyspec_tests", name)

    def run(handler, name, pre, fn, verify):
        nonlocal n
        d = case(handler, name)
        _write_state(d, "pre.ssz_snappy", pre)
        post = pre.copy()
        fn(post)
        verify(pre, post)
        _write_state(d, "post.ssz_snappy", post)
        n += 1

    # ---- pending_deposits ----------------------------------------------
    state, keys, spec = _genesis("electra", 16)
    T = get_types(spec.preset)
    _age_last_slot(state, 6)
    state.finalized_checkpoint = T.Checkpoint(epoch=4, root=b"\x11" * 32)
    new_sk = 10**6 + 19
    new_pk = bls.sk_to_pk(new_sk)
    new_wc = b"\x02" + b"\x00" * 11 + b"\xdd" * 20
    state.pending_deposits = [
        # top-up for a known key (no signature needed)
        T.PendingDeposit(pubkey=bytes(state.validators.pubkeys[3]),
                         withdrawal_credentials=b"\x00" * 32,
                         amount=1 * ETH, signature=b"\x00" * 96, slot=0),
        # brand-new validator with a valid deposit signature
        T.PendingDeposit(pubkey=new_pk, withdrawal_credentials=new_wc,
                         amount=32 * ETH,
                         signature=_deposit_sig(spec, new_sk, new_pk,
                                                new_wc, 32 * ETH),
                         slot=0),
        # not yet finalized: slot beyond the finalized checkpoint
        T.PendingDeposit(pubkey=bytes(state.validators.pubkeys[4]),
                         withdrawal_credentials=b"\x00" * 32,
                         amount=1 * ETH, signature=b"\x00" * 96,
                         slot=45),
    ]
    run("pending_deposits", "top_up_new_validator_and_unfinalized",
        state, ep._process_pending_deposits,
        sse.verify_pending_deposits_sub)

    # churn limit: deposits beyond the per-epoch balance churn stay queued
    state2 = state.copy()
    state2.pending_deposits = [
        T.PendingDeposit(pubkey=bytes(state2.validators.pubkeys[i]),
                         withdrawal_credentials=b"\x00" * 32,
                         amount=70 * ETH, signature=b"\x00" * 96, slot=0)
        for i in (1, 2, 5)]          # 210 ETH > 128 ETH activation churn
    run("pending_deposits", "churn_limit_carries_balance",
        state2, ep._process_pending_deposits,
        sse.verify_pending_deposits_sub)

    # postponed: deposit for an exiting-but-not-withdrawable validator
    state3 = state.copy()
    state3.pending_deposits = [
        T.PendingDeposit(pubkey=bytes(state3.validators.pubkeys[7]),
                         withdrawal_credentials=b"\x00" * 32,
                         amount=2 * ETH, signature=b"\x00" * 96, slot=0)]
    state3.validators.set_field(7, "exit_epoch", 20)
    state3.validators.set_field(7, "withdrawable_epoch", 276)
    run("pending_deposits", "exiting_validator_postponed",
        state3, ep._process_pending_deposits,
        sse.verify_pending_deposits_sub)

    # ---- pending_consolidations ----------------------------------------
    state, keys, spec = _genesis("electra", 16)
    T = get_types(spec.preset)
    _age_last_slot(state, 30)
    # consolidation ready: source withdrawable at next epoch
    state.validators.set_field(1, "exit_epoch", 25)
    state.validators.set_field(1, "withdrawable_epoch", 31)
    # slashed source: skipped without transfer
    state.validators.set_field(2, "slashed", True)
    state.validators.set_field(2, "exit_epoch", 25)
    state.validators.set_field(2, "withdrawable_epoch", 31)
    # not yet withdrawable: processing stops here
    state.validators.set_field(3, "exit_epoch", 30)
    state.validators.set_field(3, "withdrawable_epoch", 40)
    state.pending_consolidations = [
        T.PendingConsolidation(source_index=1, target_index=10),
        T.PendingConsolidation(source_index=2, target_index=10),
        T.PendingConsolidation(source_index=3, target_index=11),
    ]
    run("pending_consolidations", "apply_skip_slashed_and_break",
        state, ep._process_pending_consolidations,
        sse.verify_pending_consolidations_sub)

    # ---- effective_balance_updates (compounding ceiling) ---------------
    state, keys, spec = _genesis("electra", 16)
    _age_last_slot(state, 5)
    _set_wc(state, 0, sse.COMPOUNDING_PREFIX)
    _set_balance(state, 0, 100 * ETH)     # rises to 100 ETH effective
    _set_wc(state, 1, sse.ETH1_PREFIX)
    _set_balance(state, 1, 100 * ETH)     # capped at 32 ETH effective
    _set_balance(state, 2, 31 * ETH + int(0.7 * ETH))  # hysteresis: hold

    def verify_ebu(pre, post):
        from .scalar_spec import _ck
        _ck([int(x) for x in post.validators.effective_balance]
            == sse.effective_balance_updates_electra(pre),
            "electra effective balances")

    run("effective_balance_updates", "compounding_vs_eth1_ceilings",
        state, ep._process_effective_balance_updates, verify_ebu)

    # ---- registry_updates ----------------------------------------------
    from ..specs.chain_spec import ForkName
    state, keys, spec = _genesis("electra", 16)
    T = get_types(spec.preset)
    _age_last_slot(state, 8)
    state.finalized_checkpoint = T.Checkpoint(epoch=7, root=b"\x22" * 32)
    # new depositors awaiting eligibility + activation
    for i in (3, 4):
        state.validators.set_field(i, "activation_eligibility_epoch", 5)
        state.validators.set_field(i, "activation_epoch",
                                   sse.FAR_FUTURE)
    # ejectable: effective balance at the ejection floor
    state.validators.set_field(6, "effective_balance", 16 * ETH)

    def run_ru(st):
        ep._process_registry_updates(st, ForkName.ELECTRA)

    def verify_ru(pre, post):
        sse.verify_registry_updates_electra(pre, post)

    run("registry_updates", "activation_ejection_churn", state, run_ru,
        verify_ru)

    # ---- slashings (per-increment penalty) -----------------------------
    state, keys, spec = _genesis("electra", 16)
    _age_last_slot(state, 40)
    epoch = 40
    target = epoch + 32                    # EPOCHS_PER_SLASHINGS_VECTOR/2
    for i in (2, 9):
        state.validators.set_field(i, "slashed", True)
        state.validators.set_field(i, "withdrawable_epoch", target)
    state.slashings[3] = 64 * ETH

    def run_sl(st):
        from ..state_transition.helpers import get_total_active_balance
        ep._process_slashings(st, ForkName.ELECTRA,
                              get_total_active_balance(st))

    run("slashings", "per_increment_penalty", state, run_sl,
        sse.verify_slashings_electra)
    return n


def gen_electra_sanity(root) -> int:
    """electra sanity/slots: the COMPOSED epoch transition on an electra
    state (pending queues + compounding balances + electra registry),
    scalar-verified piecewise at generation time."""
    from ..containers import get_types
    from ..state_transition import process_slots
    from .gen_corpus import w_yaml
    n = 0
    state, _keys, spec = _genesis("electra", 16)
    T = get_types(spec.preset)
    # the GENESIS-epoch boundary: rewards/justification/inactivity are
    # skipped by spec, so the composed transition's balance effects come
    # EXACTLY from the electra queues — piecewise scalar-checkable
    _age_last_slot(state, 0)
    # make the boundary DO electra-specific work: a queued finalized
    # deposit, a due consolidation, and a compounding balance excess
    state.pending_deposits = [
        T.PendingDeposit(pubkey=bytes(state.validators.pubkeys[2]),
                         withdrawal_credentials=b"\x00" * 32,
                         amount=3 * ETH, signature=b"\x00" * 96, slot=0)]
    state.validators.set_field(4, "exit_epoch", 0)
    state.validators.set_field(4, "withdrawable_epoch", 1)
    state.pending_consolidations = [
        T.PendingConsolidation(source_index=4, target_index=5)]
    _set_wc(state, 6, sse.COMPOUNDING_PREFIX)
    _set_balance(state, 6, 80 * ETH)

    # scalar expectations computed on the PRE state (the epoch order
    # runs these sub-transitions before effective-balance updates read
    # the moved balances — so compose them scalar-side too)
    exp_deposits = sse.pending_deposits_expected(state)
    d = wcase(root, "minimal", "electra", "sanity", "slots",
              "pyspec_tests", "epoch_boundary_queues")
    _write_state(d, "pre.ssz_snappy", state)
    w_yaml(d, "slots.yaml", 1)
    post = state.copy()
    process_slots(post, state.slot + 1)
    # piecewise scalar verification of the electra-specific outcomes
    from .scalar_spec import _ck
    _ck(len(post.pending_deposits) == len(exp_deposits["queue"]),
        "sanity: pending deposit queue")
    _ck(int(post.balances[2])
        == int(state.balances[2]) + 3 * ETH, "sanity: deposit applied")
    _ck(len(post.pending_consolidations) == 0,
        "sanity: consolidation consumed")
    _ck(int(post.balances[5]) > int(state.balances[5]),
        "sanity: consolidation moved balance")
    _ck(int(post.validators.effective_balance[6])
        == sse.effective_balance_updates_electra(_pre_eb_state(state,
                                                               post))[6],
        "sanity: compounding effective balance")
    _write_state(d, "post.ssz_snappy", post)
    n += 1
    return n


def _pre_eb_state(pre, post):
    """Effective-balance updates read balances AFTER the earlier epoch
    steps ran; lend the scalar transcription that intermediate view:
    pre-state rows with post-step balances."""
    class _View:
        pass
    v = _View()
    v.validators = pre.validators
    v.balances = post.balances
    v.slot = pre.slot
    return v


def gen_mid_fork_epoch(root) -> int:
    """bellatrix/capella/deneb epoch_processing: the fork-specific
    pieces between altair and electra (bellatrix slashings multiplier,
    capella/deneb effective-balance + registry behavior) — previously
    these forks had NO epoch vectors at all."""
    from ..specs.chain_spec import ForkName
    from ..state_transition import epoch as ep
    from ..state_transition.helpers import get_total_active_balance
    from .scalar_spec import _ck, effective_balance_updates
    n = 0

    def run(fork_dir, handler, name, pre, fn, verify):
        nonlocal n
        d = wcase(root, "minimal", fork_dir, "epoch_processing", handler,
                  "pyspec_tests", name)
        _write_state(d, "pre.ssz_snappy", pre)
        post = pre.copy()
        fn(post)
        verify(pre, post)
        _write_state(d, "post.ssz_snappy", post)
        n += 1

    # bellatrix slashings: multiplier 3 with the pre-electra formula
    state, _k, _spec_ = _genesis("bellatrix", 16)
    _age_last_slot(state, 40)
    for i in (1, 8):
        state.validators.set_field(i, "slashed", True)
        state.validators.set_field(i, "withdrawable_epoch", 40 + 32)
    state.slashings[3] = 48 * ETH

    def run_sl(st):
        ep._process_slashings(st, ForkName.BELLATRIX,
                              get_total_active_balance(st))

    run("bellatrix", "slashings", "multiplier_three", state, run_sl,
        lambda pre, post: _ck(
            [int(b) for b in post.balances]
            == sse.slashings_penalties_pre_electra(pre, 3),
            "bellatrix slashings"))

    # capella effective balances: pre-electra ceiling semantics
    state, _k, _spec_ = _genesis("capella", 16)
    _age_last_slot(state, 5)
    _set_balance(state, 0, 40 * ETH)          # capped at 32 ETH effective
    _set_balance(state, 1, 29 * ETH)          # hysteresis drop

    run("capella", "effective_balance_updates", "pre_electra_ceiling",
        state, ep._process_effective_balance_updates,
        lambda pre, post: _ck(
            [int(x) for x in post.validators.effective_balance]
            == effective_balance_updates(pre), "capella effective"))

    # deneb registry updates: 160 active validators make the validator
    # churn limit 5, so the EIP-7514 activation cap (4 on minimal)
    # BINDS — 6 eligible pending validators, exactly 4 may activate
    state, _k, _spec_ = _genesis("deneb", 160)
    _age_last_slot(state, 8)
    from ..containers import get_types
    T = get_types(_spec_.preset)
    state.finalized_checkpoint = T.Checkpoint(epoch=7, root=b"\x44" * 32)
    for i in (3, 4, 5, 6, 7, 10):
        state.validators.set_field(i, "activation_eligibility_epoch", 5)
        state.validators.set_field(i, "activation_epoch", sse.FAR_FUTURE)
    state.validators.set_field(9, "effective_balance", 16 * ETH)

    def run_ru(st):
        ep._process_registry_updates(st, ForkName.DENEB)

    def verify_ru(pre, post):
        exp = sse.registry_updates_deneb(pre)
        v = post.validators
        for i, r in enumerate(exp):
            _ck(int(v.activation_epoch[i]) == r["activation_epoch"],
                f"deneb activation[{i}]")
            _ck(int(v.exit_epoch[i]) == r["exit_epoch"],
                f"deneb exit[{i}]")
        activated = sum(
            1 for i in (3, 4, 5, 6, 7, 10)
            if int(v.activation_epoch[i]) != sse.FAR_FUTURE)
        _ck(activated == 4, "EIP-7514 cap must bind at exactly 4")

    run("deneb", "registry_updates", "eip7514_activation_cap_binds",
        state, run_ru, verify_ru)
    return n


def generate_all(root, only: list[str] | None = None) -> int:
    gens = {
        "electra_operations": gen_electra_operations,
        "capella_operations": gen_capella_operations,
        "electra_epoch": gen_electra_epoch,
        "electra_sanity": gen_electra_sanity,
        "mid_fork_epoch": gen_mid_fork_epoch,
    }
    n = 0
    for name, fn in gens.items():
        if only and name not in only:
            continue
        n += fn(root)
    return n
