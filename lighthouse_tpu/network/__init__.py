"""Networking (L7).

Equivalent of /root/reference/beacon_node/{lighthouse_network,network}
(61k LoC incl. vendored gossipsub), rebuilt compactly:

- ``transport``: length-prefixed framed TCP with handshake (the libp2p
  TCP+noise+yamux stack's role; encryption TODO round 2)
- ``gossip``: flood-publish pubsub with message-id dedup and validation
  hooks (gossipsub mesh management TODO; topics match types/topics.rs:109)
- ``rpc``: status/goodbye/ping/metadata/blocks_by_range/blocks_by_root with
  zlib-compressed SSZ payloads (SSZ-snappy's role, rpc/protocol.rs:236-266)
- ``peer_manager``: scoring + ban thresholds (peer_manager/peerdb/score.rs)
- ``service``: NetworkService wiring gossip/rpc to the chain + processor
  (network/src/{service,router}.rs)
- ``sync``: range sync + block lookups (network/src/sync/manager.rs)
"""
from .transport import Transport, Peer
from .gossip import GossipEngine, Topic
from .rpc import RpcHandler, StatusMessage
from .peer_manager import PeerManager
from .service import NetworkService, NetworkConfig
from .sync import SyncManager
