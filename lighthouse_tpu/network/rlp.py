"""Recursive Length Prefix (RLP) encoding — the Ethereum wire/identity
serialization used by ENRs (EIP-778) and discv5.

Wire-compatible with the `rlp` crate the reference pulls in for its ENR
handling (ref: beacon_node/lighthouse_network/src/discovery/enr.rs:186 —
the reference's ENRs are RLP records signed per EIP-778).

Items are either bytes (strings) or lists of items.  Integers are
encoded big-endian with no leading zeros (the canonical scalar form the
ENR spec requires); `decode` returns raw bytes, leaving scalar
interpretation to the caller.
"""
from __future__ import annotations


class RlpError(Exception):
    pass


def encode_int(v: int) -> bytes:
    """Canonical scalar: big-endian, no leading zeros, 0 -> empty."""
    if v < 0:
        raise RlpError("negative scalar")
    if v == 0:
        return b""
    return v.to_bytes((v.bit_length() + 7) // 8, "big")


def decode_int(b: bytes) -> int:
    if b[:1] == b"\x00":
        raise RlpError("non-canonical scalar (leading zero)")
    return int.from_bytes(b, "big")


def _encode_length(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    ll = encode_int(length)
    return bytes([offset + 55 + len(ll)]) + ll


def encode(item) -> bytes:
    """item: bytes | int | list (recursively)."""
    if isinstance(item, int):
        item = encode_int(item)
    if isinstance(item, (bytes, bytearray, memoryview)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _encode_length(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(x) for x in item)
        return _encode_length(len(payload), 0xC0) + payload
    raise RlpError(f"cannot RLP-encode {type(item).__name__}")


def _decode_at(data: bytes, pos: int):
    """-> (item, next_pos); item is bytes or list."""
    if pos >= len(data):
        raise RlpError("truncated")
    b0 = data[pos]
    if b0 < 0x80:                       # single byte
        return data[pos:pos + 1], pos + 1
    if b0 < 0xB8:                       # short string
        n = b0 - 0x80
        end = pos + 1 + n
        if end > len(data):
            raise RlpError("truncated string")
        s = data[pos + 1:end]
        if n == 1 and s[0] < 0x80:
            raise RlpError("non-canonical single byte")
        return s, end
    if b0 < 0xC0:                       # long string
        ln = b0 - 0xB7
        if pos + 1 + ln > len(data):
            raise RlpError("truncated length")
        n = decode_int(data[pos + 1:pos + 1 + ln])
        if n < 56:
            raise RlpError("non-canonical long length")
        end = pos + 1 + ln + n
        if end > len(data):
            raise RlpError("truncated string")
        return data[pos + 1 + ln:end], end
    if b0 < 0xF8:                       # short list
        n = b0 - 0xC0
        end = pos + 1 + n
        if end > len(data):
            raise RlpError("truncated list")
        return _decode_list(data, pos + 1, end), end
    ln = b0 - 0xF7                      # long list
    if pos + 1 + ln > len(data):
        raise RlpError("truncated length")
    n = decode_int(data[pos + 1:pos + 1 + ln])
    if n < 56:
        raise RlpError("non-canonical long length")
    end = pos + 1 + ln + n
    if end > len(data):
        raise RlpError("truncated list")
    return _decode_list(data, pos + 1 + ln, end), end


def _decode_list(data: bytes, pos: int, end: int) -> list:
    out = []
    while pos < end:
        item, pos = _decode_at(data, pos)
        out.append(item)
    if pos != end:
        raise RlpError("list payload overrun")
    return out


def decode(data: bytes):
    item, end = _decode_at(bytes(data), 0)
    if end != len(data):
        raise RlpError(f"trailing bytes after RLP item ({len(data)-end})")
    return item
