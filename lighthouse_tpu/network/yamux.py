"""yamux — libp2p's stream multiplexer, real wire format.

Frame header (12 bytes, big-endian), per the hashicorp/yamux spec the
reference's transport stack negotiates (ref: beacon_node/
lighthouse_network/src/service/utils.rs build_transport — yamux over
noise):

    version(1)=0 | type(1) | flags(2) | stream_id(4) | length(4)

Types: 0 Data, 1 WindowUpdate, 2 Ping, 3 GoAway.
Flags: 1 SYN, 2 ACK, 4 FIN, 8 RST.
Stream ids: odd from the connection initiator, even from the responder.
Data frames consume receive window (256 KiB default); WindowUpdate
replenishes it.  Ping carries an opaque 4-byte value in `length`.
"""
from __future__ import annotations

import struct
import threading

VERSION = 0
TYPE_DATA = 0
TYPE_WINDOW_UPDATE = 1
TYPE_PING = 2
TYPE_GOAWAY = 3
FLAG_SYN = 0x1
FLAG_ACK = 0x2
FLAG_FIN = 0x4
FLAG_RST = 0x8
DEFAULT_WINDOW = 256 * 1024
HEADER = struct.Struct(">BBHII")


class YamuxError(Exception):
    pass


class YamuxEOF(YamuxError):
    """Clean half-close: the peer FINished and the buffer is drained."""


class YamuxTimeout(YamuxError):
    """No data within the deadline (stream still open)."""


class YamuxReset(YamuxError):
    """Stream was RST."""


def encode_frame(ftype: int, flags: int, stream_id: int,
                 payload: bytes = b"", length: int | None = None) -> bytes:
    """Data frames: length = len(payload).  Other types carry `length`
    as a bare value (window delta / ping opaque / goaway code)."""
    n = len(payload) if length is None else length
    return HEADER.pack(VERSION, ftype, flags, stream_id, n) + payload


def decode_header(hdr12: bytes) -> tuple[int, int, int, int]:
    version, ftype, flags, stream_id, length = HEADER.unpack(hdr12)
    if version != VERSION:
        raise YamuxError(f"bad yamux version {version}")
    if ftype > TYPE_GOAWAY:
        raise YamuxError(f"bad yamux type {ftype}")
    return ftype, flags, stream_id, length


class Stream:
    """One logical stream: buffered inbound data + flow-control window."""

    def __init__(self, session: "Session", stream_id: int):
        self.session = session
        self.id = stream_id
        self.recv_buf = bytearray()
        self.recv_closed = False
        self.send_closed = False
        self.reset = False
        self.send_window = DEFAULT_WINDOW
        self.recv_window = DEFAULT_WINDOW
        self.cv = threading.Condition()

    # -- app side -------------------------------------------------------------

    def write(self, data: bytes) -> None:
        if self.send_closed or self.reset:
            raise YamuxError("write on closed stream")
        off = 0
        while off < len(data):
            with self.cv:
                while self.send_window == 0 and not self.reset:
                    self.cv.wait(timeout=5)
                if self.reset:
                    raise YamuxError("stream reset")
                n = min(self.send_window, len(data) - off, 16384)
                self.send_window -= n
            self.session._send(encode_frame(TYPE_DATA, 0, self.id,
                                            data[off:off + n]))
            off += n

    def read(self, max_bytes: int = 1 << 20, timeout: float = 10.0
             ) -> bytes:
        """-> b"" on clean EOF or timeout (check recv_closed to tell;
        empty-payload frames notify the condvar, so WAIT IN A LOOP)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        with self.cv:
            while not self.recv_buf and not self.recv_closed \
                    and not self.reset:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                self.cv.wait(timeout=remaining)
            if self.reset:
                raise YamuxReset("stream reset")
            data = bytes(self.recv_buf[:max_bytes])
            del self.recv_buf[:len(data)]
        if data:
            self._replenish(len(data))
        return data

    def read_exact(self, n: int, timeout: float = 10.0) -> bytes:
        import time as _time
        deadline = _time.monotonic() + timeout
        buf = b""
        while len(buf) < n:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise YamuxTimeout(f"stream read timeout ({n} bytes)")
            chunk = self.read(n - len(buf), remaining)
            if not chunk:
                if self.recv_closed:
                    raise YamuxEOF("stream EOF mid-read")
                continue
            buf += chunk
        return buf

    def close(self) -> None:
        """Half-close our sending direction (FIN). Best-effort at
        teardown: the peer (and its socket) may already be gone."""
        with self.cv:
            already = self.send_closed
            self.send_closed = True
        if not already:                 # exactly one FIN, racing closers
            try:
                self.session._send(encode_frame(TYPE_DATA, FLAG_FIN,
                                                self.id))
            except (YamuxError, OSError):
                pass
        self.session._maybe_gc(self)

    def rst(self) -> None:
        # mark + WAKE waiters under the condvar (a blocked read would
        # otherwise sleep out its full timeout), then best-effort RST on
        # the wire — during shutdown the socket may already be closed
        # (round-5 leak: OSError escaping a serve_stream thread)
        with self.cv:
            self.reset = True
            self.cv.notify_all()
        try:
            self.session._send(encode_frame(TYPE_DATA, FLAG_RST, self.id))
        except (YamuxError, OSError):
            pass
        self.session._maybe_gc(self)

    def _replenish(self, n: int) -> None:
        with self.cv:
            self.recv_window -= n
            if self.recv_window > DEFAULT_WINDOW // 2:
                return
            delta = DEFAULT_WINDOW - self.recv_window
            self.recv_window = DEFAULT_WINDOW
        self.session._send(encode_frame(TYPE_WINDOW_UPDATE, 0,
                                        self.id, length=delta))

    # -- session side ---------------------------------------------------------

    def _on_data(self, data: bytes, flags: int) -> None:
        with self.cv:
            if data:
                self.recv_buf += data
            if flags & FLAG_FIN:
                self.recv_closed = True
            if flags & FLAG_RST:
                self.reset = True
            self.cv.notify_all()

    def _on_window(self, delta: int) -> None:
        with self.cv:
            self.send_window += delta
            self.cv.notify_all()


class Session:
    """A yamux session over any reliable byte transport.

    `send_fn(bytes)` writes to the wire; feed inbound bytes through
    `on_bytes`.  `on_stream(stream)` fires for peer-opened streams.
    Typically wrapped around a NoiseSession (see transport.py).
    """

    def __init__(self, send_fn, initiator: bool, on_stream=None,
                 on_ping=None):
        self._send_fn = send_fn
        self._next_id = 1 if initiator else 2
        self.streams: dict[int, Stream] = {}
        self.on_stream = on_stream
        self.on_ping = on_ping
        self._buf = bytearray()
        self._lock = threading.Lock()
        self.closed = False
        self.goaway_code: int | None = None

    def _send(self, frame: bytes) -> None:
        with self._lock:
            if self.closed:
                return
            try:
                self._send_fn(frame)
            except OSError as e:
                # wire gone mid-write (teardown race): the session is
                # dead; surface a protocol error instead of letting the
                # raw OSError escape on a service thread
                self.closed = True
                raise YamuxError("session write failed") from e

    def _maybe_gc(self, st: Stream) -> None:
        """Drop fully-dead streams so long-lived connections (one stream
        per req/resp call) do not leak Stream objects."""
        if st.reset or (st.send_closed and st.recv_closed):
            self.streams.pop(st.id, None)

    # -- opening --------------------------------------------------------------

    def open_stream(self) -> Stream:
        with self._lock:
            sid = self._next_id
            self._next_id += 2
        st = Stream(self, sid)
        self.streams[sid] = st
        self._send(encode_frame(TYPE_DATA, FLAG_SYN, sid))
        return st

    def ping(self, value: int = 0) -> None:
        self._send(encode_frame(TYPE_PING, FLAG_SYN, 0, length=value))

    def goaway(self, code: int = 0) -> None:
        self._send(encode_frame(TYPE_GOAWAY, 0, 0, length=code))
        with self._lock:
            self.closed = True

    # -- inbound pump ---------------------------------------------------------

    def on_bytes(self, data: bytes) -> None:
        """Feed raw wire bytes; dispatches complete frames.

        Framing happens under the session lock (the reassembly buffer is
        shared state); dispatch runs OUTSIDE it — handlers send ACKs and
        window updates through `_send`, which takes the same lock."""
        frames = []
        with self._lock:
            self._buf += data
            while True:
                if len(self._buf) < 12:
                    break
                ftype, flags, sid, length = decode_header(
                    bytes(self._buf[:12]))
                if ftype == TYPE_DATA:
                    if len(self._buf) < 12 + length:
                        break
                    payload = bytes(self._buf[12:12 + length])
                    del self._buf[:12 + length]
                    frames.append((ftype, flags, sid, length, payload))
                else:
                    del self._buf[:12]
                    frames.append((ftype, flags, sid, length, b""))
        for ftype, flags, sid, length, payload in frames:
            if ftype == TYPE_DATA:
                self._dispatch_data(sid, flags, payload)
            else:
                self._dispatch_ctrl(ftype, flags, sid, length)

    def _dispatch_data(self, sid: int, flags: int, payload: bytes) -> None:
        st = self.streams.get(sid)
        if st is None:
            if flags & FLAG_SYN:
                st = Stream(self, sid)
                self.streams[sid] = st
                self._send(encode_frame(TYPE_DATA, FLAG_ACK, sid))
                st._on_data(payload, flags)
                if self.on_stream:
                    self.on_stream(st)
                return
            if not flags & FLAG_RST:       # unknown stream: protocol error
                self._send(encode_frame(TYPE_DATA, FLAG_RST, sid))
            return
        st._on_data(payload, flags)
        if flags & (FLAG_FIN | FLAG_RST):
            self._maybe_gc(st)

    def _dispatch_ctrl(self, ftype: int, flags: int, sid: int,
                       length: int) -> None:
        if ftype == TYPE_WINDOW_UPDATE:
            st = self.streams.get(sid)
            if st is None and flags & FLAG_SYN:
                st = Stream(self, sid)
                self.streams[sid] = st
                self._send(encode_frame(TYPE_WINDOW_UPDATE, FLAG_ACK, sid,
                                        length=0))
                st._on_window(length)
                if self.on_stream:
                    self.on_stream(st)
                return
            if st is not None:
                st._on_window(length)
        elif ftype == TYPE_PING:
            if flags & FLAG_SYN:
                self._send(encode_frame(TYPE_PING, FLAG_ACK, 0,
                                        length=length))
            if self.on_ping:
                self.on_ping(length, flags)
        elif ftype == TYPE_GOAWAY:
            # dispatch runs outside the session lock (see on_bytes), so
            # the closed flag must be flipped under it like everywhere else
            with self._lock:
                self.goaway_code = length
                self.closed = True


class StreamIO:
    """multistream-select adapter over a yamux Stream."""

    def __init__(self, stream: Stream, timeout: float = 10.0):
        self.stream = stream
        self.timeout = timeout

    def read_exact(self, n: int) -> bytes:
        return self.stream.read_exact(n, self.timeout)

    def write(self, data: bytes) -> None:
        self.stream.write(data)
