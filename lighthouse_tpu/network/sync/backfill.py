"""Backfill sync: download history backwards from a checkpoint anchor.

Equivalent of the reference's backfill machine (network/src/sync/
backfill_sync/mod.rs): after checkpoint sync the node holds [anchor, head]
and must recover [genesis, anchor) — batches walk DOWN from the anchor and
every received block must hash-link into the trusted chain
(`expected_root`), which subsumes signature verification the way the
reference's `historical_blocks.rs` chain-linkage does.

Batch downloads pipeline in parallel (fixed descending windows) but are
*verified* strictly newest-first, because linkage is only checkable against
the already-verified chain above.  Empty windows are legitimate (runs of
skipped slots) but an all-empty history down to genesis — which must
contain the genesis block — or an endless run of empty claims is
misbehavior: the peer is penalized and the machine stops (the caller
rotates peers on the next drive).
"""
from __future__ import annotations

import sys

from .batches import Batch, BatchState
from .validation import validate_range_batch


def _count(name: str, amount: float = 1) -> None:
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    count = getattr(md, "count", None)
    if count is not None:
        count(name, amount)


class BackfillSync:
    MAX_EMPTY_WINDOWS = 64
    BATCH_BUFFER = 4

    def __init__(self, ctx, batch_slots: int | None = None):
        self.ctx = ctx
        self.batch_slots = batch_slots or (
            2 * ctx.slots_per_epoch())
        self.batches: dict[int, Batch] = {}
        self.requests: dict[int, int] = {}
        self.next_batch_id = 0
        self.process_ptr = 0
        self.stored = 0
        self.empty_windows = 0
        self.stopped = False
        # [window_low, window_high) spans, high -> low as batch ids grow
        self._spans: dict[int, tuple[int, int]] = {}
        self._req_end: int | None = None      # exclusive top of next window
        # (batch_id, peer) that last advanced the anchor, for fault
        # attribution when the NEXT batch's top block fails to link: a
        # peer that truncated its window's lower edge still hash-links
        # and advances the anchor, leaving the gap inside ITS span
        self._advanced_by: tuple[int, str] | None = None
        self._rewindowed = False              # one re-window per advance

    # -- scheduling ----------------------------------------------------------

    def _anchor(self):
        return self.ctx.backfill_anchor()

    def drive(self, peers: list[str]) -> None:
        """Create/dispatch descending windows to the peer pool."""
        if self.stopped:
            return
        anchor = self._anchor()
        if anchor is None or anchor[0] == 0:
            return
        if self._req_end is None:
            self._req_end = anchor[0]
        cap = self.ctx.max_request_blocks()
        window = min(self.batch_slots, cap)
        while (self._req_end > 0
               and self.next_batch_id < self.process_ptr + self.BATCH_BUFFER):
            high = self._req_end
            low = max(0, high - window)
            bid = self.next_batch_id
            self.batches[bid] = Batch(bid, low, high - low)
            self._spans[bid] = (low, high)
            self.next_batch_id += 1
            self._req_end = low
        for bid in sorted(self.batches):
            batch = self.batches[bid]
            if batch.state != BatchState.AWAITING_DOWNLOAD:
                continue
            busy = {b.peer for b in self.batches.values()
                    if b.state == BatchState.DOWNLOADING}
            pool = [p for p in peers if p not in busy]
            peer = batch.pick_peer(
                pool, salt=batch.download_attempts + batch.id)
            if peer is None:
                return
            req_id = self.ctx.send_range(peer, batch.start_slot, batch.count,
                                         self)
            batch.start_download(peer, req_id)
            self.requests[req_id] = bid

    # -- events --------------------------------------------------------------

    def on_range_response(self, req_id: int, blocks: list | None,
                          reason: str = "timeout") -> None:
        bid = self.requests.pop(req_id, None)
        if bid is None:
            return
        batch = self.batches[bid]
        if blocks is None:
            if reason != "shutdown":        # our close path: no penalty
                self.ctx.penalize(batch.peer, reason)
            if batch.download_failed() == BatchState.FAILED:
                self.stopped = True
            return
        # download-time structural validation: a wrong-range / reordered
        # / miscounted response never reaches the anchor-linkage stage
        # (which could otherwise mis-advance the anchor on junk)
        res = validate_range_batch(blocks, batch.start_slot, batch.count,
                                   block_root=self.ctx.block_root)
        if not res.ok:
            _count("sync_batch_validation_rejects_total")
            note = getattr(self.ctx, "note_validation_reject", None)
            if note is not None:
                note(batch.peer, batch.start_slot, batch.count, res.reason)
            self.ctx.penalize(batch.peer, "bad_segment")
            if batch.download_failed() == BatchState.FAILED:
                self.stopped = True
            return
        batch.downloaded(blocks)
        self._process_ready()

    def _process_ready(self) -> None:
        """Link-verify batches newest-first into the trusted anchor."""
        while not self.stopped:
            batch = self.batches.get(self.process_ptr)
            if batch is None or batch.state != BatchState.AWAITING_PROCESSING:
                return
            blocks = batch.start_processing()
            anchor = self._anchor()
            if anchor is None:
                self.stopped = True
                return
            _, expected_root = anchor
            ok = True
            pairs = []
            for sb in reversed(blocks):
                root = self.ctx.block_root(sb)
                if root != expected_root:
                    ok = False
                    break
                pairs.append((root, sb))
                expected_root = sb.message.parent_root
            # the linked prefix lands as ONE atomic hot batch (graftflow,
            # ISSUE 14) — per-block stores remain for bare test contexts
            store_batch = getattr(self.ctx, "store_backfill_batch", None)
            if store_batch is not None:
                store_batch(pairs)
            else:
                for root, sb in pairs:
                    self.ctx.store_backfill_block(root, sb)
            stored_here = len(pairs)
            if not ok:
                if (stored_here == 0 and self._advanced_by is not None
                        and self._advanced_by[0] != batch.id
                        and not self._rewindowed):
                    # nothing in THIS batch linked: either the batch that
                    # advanced the anchor truncated its lower edge (gap in
                    # ITS span) or this batch is garbage.  Blame is
                    # ambiguous, so — like range_sync's previous-batch
                    # PARENT_UNKNOWN rollback — penalize BOTH peers, then
                    # re-window from the stored anchor so a truncated span
                    # gets re-downloaded.
                    self.ctx.penalize(self._advanced_by[1],
                                      "truncated_batch")
                    # intermediate batches that claimed EMPTY windows are
                    # equally suspect (a falsely-empty claim produces the
                    # same signature); penalize every peer in the
                    # ambiguous span so a liar can't hide behind honest
                    # neighbours
                    blamed = {self._advanced_by[1]}
                    for mid in range(self._advanced_by[0] + 1, batch.id + 1):
                        b = self.batches.get(mid)
                        if b is not None and b.peer is not None \
                                and b.peer not in blamed:
                            blamed.add(b.peer)
                            self.ctx.penalize(b.peer, "bad_segment")
                    self._rewindow()
                    return
                self.ctx.penalize(batch.peer, "bad_segment")
                if batch.processing_failed() == BatchState.FAILED:
                    self.stopped = True
                return
            if blocks:
                self.empty_windows = 0
                self.stored += stored_here
                self._advanced_by = (batch.id, batch.peer)
                self._rewindowed = False
                new_anchor = blocks[0].message.slot
                self.ctx.set_backfill_anchor(new_anchor, expected_root)
                if new_anchor == 0:
                    self.stopped = True       # reached the genesis block
                    return
            else:
                low, _high = self._spans[batch.id]
                self.empty_windows += 1
                if low == 0 or self.empty_windows > self.MAX_EMPTY_WINDOWS:
                    # an empty [0, x) claims there is no genesis block
                    self.ctx.penalize(batch.peer, "empty_batch")
                    self.stopped = True
                    return
            batch.processed()
            _count("sync_backfill_batches_total")
            self.process_ptr += 1

    def _rewindow(self) -> None:
        """Drop all windows (incl. in-flight) and restart from the stored
        anchor, so a span truncated by a lying peer gets re-downloaded."""
        anchor = self._anchor()
        self.batches.clear()
        self._spans.clear()
        self.requests.clear()         # stale responses are ignored
        self.process_ptr = self.next_batch_id
        self._req_end = anchor[0] if anchor else None
        self._rewindowed = True
        # the re-downloaded span re-serves the same legitimately-empty
        # windows; counting them twice could falsely trip
        # MAX_EMPTY_WINDOWS and stop an honest backfill
        self.empty_windows = 0

    @property
    def in_flight(self) -> int:
        return len(self.requests)

    @property
    def complete(self) -> bool:
        anchor = self._anchor()
        return anchor is None or anchor[0] == 0
