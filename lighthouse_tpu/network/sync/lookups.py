"""Block lookups: by-root resolution of unknown blocks and parent chains.

Equivalent of the reference's lookup machinery (network/src/sync/
block_lookups/mod.rs): a gossip block whose parent is unknown — or an
attestation referencing an unknown root — triggers a by-root lookup that
walks parents until it connects to the known chain, then imports the
accumulated segment oldest-first.  Guarantees mirrored from the reference:

- concurrent lookups are deduplicated (a second trigger for the same root
  or for any root already inside a walking chain just adds its peer to the
  pool);
- parent walks are depth-limited (PARENT_DEPTH_TOLERANCE) so a malicious
  peer can't lead us down an endless bogus ancestry — the lookup dies and
  every serving peer is penalized;
- request failures rotate through the lookup's peer pool with bounded
  attempts;
- invalid segments penalize the peers that served the blocks.
"""
from __future__ import annotations

import sys


def _count(name: str, amount: float = 1) -> None:
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    count = getattr(md, "count", None)
    if count is not None:
        count(name, amount)


class Lookup:
    MAX_ATTEMPTS = 4

    def __init__(self, lookup_id: int, root: bytes, peer_id: str,
                 depth_limit: int | None = None):
        self.id = lookup_id
        self.original_root = root
        self.awaiting = root              # next root to fetch
        self.peers: set[str] = {peer_id}
        self.chain: list = []             # (root, block), newest first
        self.served_by: set[str] = set()
        self.attempts = 0
        self.req_id: int | None = None
        self.depth_limit = depth_limit

    def pick_peer(self) -> str | None:
        """Rotate by attempt count so exhausted-pool retries walk the
        pool instead of hammering the same (possibly failed) peer."""
        fresh = sorted(self.peers - self.served_by)
        if fresh:
            return fresh[self.attempts % len(fresh)]
        pool = sorted(self.peers)
        return pool[self.attempts % len(pool)] if pool else None


class BlockLookups:
    PARENT_DEPTH_TOLERANCE = 32
    MAX_CONCURRENT = 64

    def __init__(self, ctx):
        self.ctx = ctx
        self.lookups: dict[int, Lookup] = {}
        self.requests: dict[int, int] = {}    # req_id -> lookup_id
        self._next_id = 0
        self.imported = 0

    # -- triggers ------------------------------------------------------------

    def search(self, root: bytes, peer_id: str,
               max_depth: int | None = None) -> None:
        """Start (or join) a lookup for `root`."""
        if self.ctx.block_known(root):
            return
        for lk in self.lookups.values():
            if lk.awaiting == root or lk.original_root == root or any(
                    r == root for r, _b in lk.chain):
                lk.peers.add(peer_id)
                return
        if len(self.lookups) >= self.MAX_CONCURRENT:
            return
        lk = Lookup(self._next_id, root, peer_id, depth_limit=max_depth)
        self._next_id += 1
        self.lookups[lk.id] = lk
        _count("sync_parent_lookups_total")
        self._request(lk)

    def _request(self, lk: Lookup) -> None:
        peer = lk.pick_peer()
        if peer is None or lk.attempts >= Lookup.MAX_ATTEMPTS:
            self.lookups.pop(lk.id, None)
            return
        lk.attempts += 1
        req_id = self.ctx.send_root(peer, lk.awaiting, self)
        lk.req_id = req_id
        lk.served_by.add(peer)
        self.requests[req_id] = lk.id

    # -- events --------------------------------------------------------------

    def on_root_response(self, req_id: int, block, peer_id: str,
                         reason: str = "timeout") -> None:
        """block=None means error/timeout/empty — rotate peers.  `reason`
        distinguishes peer_gone / decode_error / stall (distinct penalty
        weights) and "shutdown" (our close path: no penalty, no retry)."""
        lid = self.requests.pop(req_id, None)
        if lid is None:
            return
        lk = self.lookups.get(lid)
        if lk is None:
            return
        lk.req_id = None
        if block is None:
            if reason == "shutdown":
                self.lookups.pop(lk.id, None)
                return
            self.ctx.penalize(peer_id, reason)
            self._request(lk)
            return
        if self.ctx.block_root(block) != lk.awaiting:
            # peer answered with a different block than asked
            self.ctx.penalize(peer_id, "bad_segment")
            self._request(lk)
            return
        if block.message.slot <= self.ctx.finalized_slot():
            # an unknown block at/below the finalized slot can never join
            # the canonical chain: remember the root so gossip referencing
            # it is rejected instantly (pre_finalization_cache.rs)
            self.ctx.note_pre_finalization(lk.awaiting)
            self.ctx.penalize(peer_id, "ignore")
            self.lookups.pop(lk.id, None)
            return
        lk.chain.append((lk.awaiting, block))
        parent = block.message.parent_root
        if self.ctx.block_known(parent):
            self._import(lk)
            return
        limit = min(lk.depth_limit or self.PARENT_DEPTH_TOLERANCE,
                    self.PARENT_DEPTH_TOLERANCE)
        if len(lk.chain) >= limit:
            # endless bogus ancestry: drop and penalize every server
            for p in sorted(lk.served_by):
                self.ctx.penalize(p, "bad_segment")
            self.lookups.pop(lk.id, None)
            return
        lk.awaiting = parent
        lk.attempts = 0                    # fresh target, fresh attempts
        self._request(lk)

    def _import(self, lk: Lookup) -> None:
        self.lookups.pop(lk.id, None)
        blocks = [b for _r, b in reversed(lk.chain)]   # oldest first
        imported, err = self.ctx.process_segment(blocks)
        if err is None:
            self.imported += imported
            self.ctx.on_lookup_imported(lk.original_root)
        else:
            for p in sorted(lk.served_by):
                self.ctx.penalize(p, "bad_segment")

    @property
    def in_flight(self) -> int:
        return len(self.requests)
