"""Batch lifecycle state machine shared by range sync and backfill.

Equivalent of the reference's per-batch state machine
(network/src/sync/range_sync/batch.rs: AwaitingDownload -> Downloading ->
AwaitingProcessing -> Processing -> {AwaitingValidation, Failed}), redesigned
as an explicit enum + attempt bookkeeping.  A batch remembers every peer that
served or failed it so retries rotate through the pool, and it permanently
fails after bounded download/processing attempts — the chain then drops and
the pool is penalized by the owner.
"""
from __future__ import annotations

from enum import Enum


class BatchState(Enum):
    AWAITING_DOWNLOAD = "awaiting_download"
    DOWNLOADING = "downloading"
    AWAITING_PROCESSING = "awaiting_processing"
    PROCESSING = "processing"
    PROCESSED = "processed"
    FAILED = "failed"


class Batch:
    """One epoch-aligned span of slots moving through download/processing."""

    MAX_DOWNLOAD_ATTEMPTS = 5
    MAX_PROCESSING_ATTEMPTS = 3

    def __init__(self, batch_id: int, start_slot: int, count: int):
        self.id = batch_id
        self.start_slot = start_slot
        self.count = count
        self.state = BatchState.AWAITING_DOWNLOAD
        self.blocks: list = []
        self.peer: str | None = None          # current / last serving peer
        self.attempted_peers: set[str] = set()
        self.download_attempts = 0
        self.processing_attempts = 0
        self.req_id: int | None = None

    # -- transitions ---------------------------------------------------------

    def start_download(self, peer: str, req_id: int) -> None:
        assert self.state == BatchState.AWAITING_DOWNLOAD, self.state
        self.state = BatchState.DOWNLOADING
        self.peer = peer
        self.req_id = req_id
        self.attempted_peers.add(peer)
        self.download_attempts += 1

    def download_failed(self) -> BatchState:
        """Download error/timeout: back to the queue or FAILED out."""
        assert self.state == BatchState.DOWNLOADING, self.state
        self.req_id = None
        if self.download_attempts >= self.MAX_DOWNLOAD_ATTEMPTS:
            self.state = BatchState.FAILED
        else:
            self.state = BatchState.AWAITING_DOWNLOAD
        return self.state

    def downloaded(self, blocks: list) -> None:
        assert self.state == BatchState.DOWNLOADING, self.state
        self.req_id = None
        self.blocks = blocks
        self.state = BatchState.AWAITING_PROCESSING

    def start_processing(self) -> list:
        assert self.state == BatchState.AWAITING_PROCESSING, self.state
        self.state = BatchState.PROCESSING
        self.processing_attempts += 1
        return self.blocks

    def processed(self) -> None:
        assert self.state == BatchState.PROCESSING, self.state
        self.blocks = []
        self.state = BatchState.PROCESSED

    def processing_failed(self) -> BatchState:
        """Invalid segment: the serving peer lied (or an ancestor batch
        did) — re-download from a different peer, or FAIL the batch after
        MAX_PROCESSING_ATTEMPTS (the owner drops the whole chain)."""
        assert self.state == BatchState.PROCESSING, self.state
        self.blocks = []
        if self.processing_attempts >= self.MAX_PROCESSING_ATTEMPTS:
            self.state = BatchState.FAILED
        else:
            self.state = BatchState.AWAITING_DOWNLOAD
        return self.state

    # -- helpers -------------------------------------------------------------

    def pick_peer(self, pool: list[str], salt: int = 0) -> str | None:
        """Prefer a pool peer that has never touched this batch; fall back
        to any pool peer (the batch may outlive fresh peers).  `salt`
        (seeded on attempt count + batch id by callers) rotates the pick
        so a deterministic `pool[0]` can't retry the same failed peer
        forever."""
        fresh = [p for p in pool if p not in self.attempted_peers]
        if fresh:
            return fresh[salt % len(fresh)]
        return pool[salt % len(pool)] if pool else None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Batch({self.id}, slots=[{self.start_slot},"
                f"{self.start_slot + self.count}), {self.state.value},"
                f" dl={self.download_attempts}, pr={self.processing_attempts})")
