"""SyncManager: event routing + the real network context.

Equivalent of the reference's `SyncManager` task (network/src/sync/
manager.rs:177): owns the three strategies — range sync (range_sync.py),
backfill (backfill.py), block lookups (lookups.py) — and routes network
events to them.  The machines themselves are synchronous and testable with
synthetic events; this module supplies the production context that issues
real req/resp calls over the libp2p transport with a bounded worker pool
(parallel downloads, the blst-multicore analog of the reference's
tokio-concurrent batch requests), decodes SSZ+fork-digest payloads, and
funnels processing into `BeaconChain.process_chain_segment`.

The public entry points keep round-3 call signatures (service.py and the
simulator drive them synchronously): `maybe_sync()`, `backfill()`,
`lookup_unknown_parent()`.
"""
from __future__ import annotations

import random
import sys
import threading
import time
from collections import deque
from concurrent.futures import (
    FIRST_COMPLETED, Future, ThreadPoolExecutor, wait,
)

from ...chain.errors import BlockError
from ...ssz import deserialize, htr, serialize
from .backfill import BackfillSync
from .lookups import BlockLookups
from .range_sync import EPOCHS_PER_BATCH, RangeSync

REQUEST_TIMEOUT = 20.0


def _metrics():
    """metrics_defs, sys.modules-gated (the sync machines run in wire
    tests without the metrics stack loaded).  A module that is still
    mid-import — sync threads can race the api package's first import —
    is treated as absent rather than letting an AttributeError escape
    into the status/pump threads."""
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    return md if hasattr(md, "count") and hasattr(md, "gauge") else None


class _DecodeError(Exception):
    """A response chunk failed SSZ/fork-digest decoding — near-certain
    peer malice, attributed separately from a timeout."""


class PeerBackoff:
    """Jittered exponential re-dispatch backoff + per-peer quarantine.

    Every failed request charges the serving peer a growing, jittered
    delay before sync will dispatch to it again; QUARANTINE_AFTER
    consecutive failures quarantines the peer outright for
    QUARANTINE_SECS (`maybe_sync`/`backfill` skip quarantined peers when
    building pools).  Any success clears the slate.  Seeded RNG keeps
    scenarios deterministic.
    """

    BASE_DELAY = 0.5
    MAX_DELAY = 8.0
    QUARANTINE_AFTER = 3
    QUARANTINE_SECS = 30.0

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._fails: dict[str, int] = {}
        self._delay_until: dict[str, float] = {}
        self._quarantine_until: dict[str, float] = {}
        self._lock = threading.Lock()

    def note_failure(self, peer_id: str) -> float:
        """Record a failed request; returns the backoff delay applied."""
        quarantined = False
        with self._lock:
            n = self._fails.get(peer_id, 0) + 1
            self._fails[peer_id] = n
            delay = min(self.MAX_DELAY, self.BASE_DELAY * 2 ** (n - 1))
            delay *= 0.5 + self._rng.random()
            self._delay_until[peer_id] = time.monotonic() + delay
            if n == self.QUARANTINE_AFTER:
                self._quarantine_until[peer_id] = (
                    time.monotonic() + self.QUARANTINE_SECS)
                quarantined = True
        if quarantined:
            md = _metrics()
            if md is not None:
                md.count("sync_peer_quarantined_total")
        return delay

    def note_success(self, peer_id: str) -> None:
        with self._lock:
            self._fails.pop(peer_id, None)
            self._delay_until.pop(peer_id, None)
            self._quarantine_until.pop(peer_id, None)

    def quarantined(self, peer_id: str) -> bool:
        with self._lock:
            until = self._quarantine_until.get(peer_id)
            if until is None:
                return False
            if time.monotonic() >= until:
                del self._quarantine_until[peer_id]
                return False
            return True

    def delay_remaining(self, peer_id: str) -> float:
        with self._lock:
            until = self._delay_until.get(peer_id)
        if until is None:
            return 0.0
        return max(0.0, until - time.monotonic())

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {
                "failing": dict(self._fails),
                "backoff_remaining": {
                    p: round(max(0.0, t - now), 3)
                    for p, t in self._delay_until.items()
                    if t > now},
                "quarantined": {
                    p: round(max(0.0, t - now), 3)
                    for p, t in self._quarantine_until.items()
                    if t > now},
            }


class _RealSyncContext:
    """Production context: request IO on a worker pool, chain hooks."""

    MAX_WORKERS = 4

    def __init__(self, chain, rpc, peer_manager):
        self.chain = chain
        self.rpc = rpc
        self.peers = peer_manager
        self._digest_map = None
        self._next_req = 0
        self._pool = None
        self._closed = False
        # req_id -> (owner, peer_id, future, kind, deadline)
        self.inflight: dict[int, tuple] = {}
        self.imported_total = 0
        self._lock = threading.Lock()
        # per-request deadline; instance attr so scenarios can tighten it
        self.request_timeout = REQUEST_TIMEOUT
        self.backoff = PeerBackoff()
        # newest-last (peer, start, count, reason) validation rejects,
        # surfaced by the flight recorder's doc["sync"] section
        self.validation_rejects: deque = deque(maxlen=32)

    # -- chain views ---------------------------------------------------------

    def slots_per_epoch(self) -> int:
        return self.chain.spec.preset.slots_per_epoch

    def max_request_blocks(self) -> int:
        return self.chain.spec.max_request_blocks

    def local_status(self) -> tuple[int, int]:
        head = self.chain.head()
        fin_epoch = int(self.chain.fork_choice.finalized_checkpoint[0])
        return head.head_state.slot, fin_epoch

    def block_known(self, root: bytes) -> bool:
        return self.chain.fork_choice.contains_block(root)

    def block_root(self, signed_block) -> bytes:
        return htr(signed_block.message)

    def process_segment(self, blocks: list) -> tuple[int, str | None]:
        # graftflow (chain/replay/, ISSUE 14): epoch-pipelined replay with
        # batched signatures, deferred merkleization and one atomic store
        # commit per epoch — the sequential process_chain_segment stays as
        # its bit-exact oracle
        try:
            n = self.chain.replay_engine().replay_segment(blocks)
        except BlockError as e:
            return 0, e.kind
        with self._lock:
            self.imported_total += n
        return n, None

    def penalize(self, peer_id: str, reason: str) -> None:
        if reason == "shutdown":
            return                      # our own close path, not the peer's
        md = _metrics()
        if md is not None:
            md.count("sync_penalties_total")
            md.count(f"sync_penalties_total_{reason}")
        self.peers.report(peer_id, reason)

    def note_validation_reject(self, peer_id: str, start: int, count: int,
                               reason: str) -> None:
        self.validation_rejects.append(
            {"peer": peer_id, "start": start, "count": count,
             "reason": reason})

    def finalized_slot(self) -> int:
        fin_epoch = int(self.chain.fork_choice.finalized_checkpoint[0])
        return fin_epoch * self.slots_per_epoch()

    def note_pre_finalization(self, root: bytes) -> None:
        self.chain.pre_finalization_cache.insert(root)

    def on_lookup_imported(self, root: bytes) -> None:
        proc = getattr(self.chain, "processor", None)
        if proc is not None and getattr(proc, "reprocess", None) is not None:
            proc.reprocess.on_block_imported(root)

    # -- backfill store hooks ------------------------------------------------

    def backfill_anchor(self):
        return self.chain.store.backfill_anchor()

    def set_backfill_anchor(self, slot: int, root: bytes) -> None:
        self.chain.store.set_backfill_anchor(slot, root)

    def store_backfill_block(self, root: bytes, sb) -> None:
        from ...store import StoreOp
        # hot block first, freezer root second: a crash between the two
        # leaves a re-downloadable gap, never a freezer root pointing at
        # a block the store doesn't have
        self.chain.store.do_atomically([StoreOp.put_block(root, sb)],
                                       fsync=False)
        self.chain.store.freezer_put_block_root(sb.message.slot, root)

    def store_backfill_batch(self, pairs: list) -> None:
        # whole validated batch as ONE atomic hot batch + freezer roots
        # (graftflow backfill commit, same hot-first crash ordering)
        self.chain.replay_engine().backfill_batch(pairs)

    # -- request IO ----------------------------------------------------------

    def _executor(self) -> ThreadPoolExecutor:
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=self.MAX_WORKERS)
        return self._pool

    def close(self) -> None:
        """Shutdown path (task_executor/src/lib.rs:12-28 ordering): no
        new downloads may be submitted once closed — late callers get an
        already-failed future instead of `RuntimeError: cannot schedule
        new futures after shutdown` escaping on a status-exchange thread
        (the round-5 leak)."""
        with self._lock:
            self._closed = True
            pool = self._pool
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    def _submit(self, fn, *args) -> Future:
        with self._lock:
            if self._closed:
                fut: Future = Future()
                fut.set_exception(TimeoutError("sync context closed"))
                return fut
            pool = self._executor()
        try:
            return pool.submit(fn, *args)
        except RuntimeError:            # raced an interpreter-level shutdown
            fut = Future()
            fut.set_exception(TimeoutError("sync context closed"))
            return fut

    def _decode_block(self, hex_payload: str, strict: bool = False):
        try:
            raw = bytes.fromhex(hex_payload)
            dmap = self._digest_map
            if dmap is None:
                dmap = self._digest_map = digest_to_fork(self.chain)
            cls = self.chain.T.SignedBeaconBlock[dmap[raw[:4]]]
            return deserialize(cls.ssz_type, raw[4:])
        except Exception:
            # an undecodable chunk must not masquerade as an empty
            # response (the pre-ISSUE-11 behavior): the fetcher raises so
            # the pump attributes "decode_error" to the serving peer
            if strict:
                raise _DecodeError(hex_payload[:16])
            return None

    def _pace(self, peer_id: str) -> None:
        """Honor this peer's backoff delay inside the worker thread (never
        under a lock); bails out promptly if the context closes."""
        end = time.monotonic() + self.backoff.delay_remaining(peer_id)
        while True:
            left = end - time.monotonic()
            if left <= 0:
                return
            if self._closed:
                raise TimeoutError("sync context closed")
            time.sleep(min(0.1, left))

    def _fetch_range(self, peer_id: str, start: int, count: int):
        self._pace(peer_id)
        peer = self.rpc.transport.peers.get(peer_id)
        if peer is None:
            raise TimeoutError("peer gone")
        resp = self.rpc.request(peer, "beacon_blocks_by_range",
                                {"start_slot": start, "count": count},
                                timeout=self.request_timeout)
        return [self._decode_block(b, strict=True) for b in resp or []]

    def _fetch_root(self, peer_id: str, root: bytes):
        self._pace(peer_id)
        peer = self.rpc.transport.peers.get(peer_id)
        if peer is None:
            raise TimeoutError("peer gone")
        resp = self.rpc.request(peer, "beacon_blocks_by_root",
                                {"roots": [root.hex()]},
                                timeout=self.request_timeout)
        if not resp:
            return None
        return self._decode_block(resp[0], strict=True)

    def _deadline(self, peer_id: str) -> float:
        # the deadline covers the request's own budget PLUS whatever
        # backoff pause the worker will sit out first
        return (time.monotonic() + self.request_timeout
                + self.backoff.delay_remaining(peer_id))

    def send_range(self, peer_id: str, start: int, count: int, owner) -> int:
        # submit BEFORE taking the lock (submission takes it internally),
        # then allocate the id and record the request atomically: a
        # concurrent close() can no longer observe the id without the
        # inflight entry, and a post-close caller records the pre-failed
        # future instead of racing `RuntimeError: cannot schedule new
        # futures after shutdown` on a status-exchange thread
        fut = self._submit(self._fetch_range, peer_id, start, count)
        with self._lock:
            req_id = self._next_req
            self._next_req += 1
            self.inflight[req_id] = (owner, peer_id, fut, "range",
                                     self._deadline(peer_id))
        return req_id

    def send_root(self, peer_id: str, root: bytes, owner) -> int:
        fut = self._submit(self._fetch_root, peer_id, root)
        with self._lock:
            req_id = self._next_req
            self._next_req += 1
            self.inflight[req_id] = (owner, peer_id, fut, "root",
                                     self._deadline(peer_id))
        return req_id

    # -- event pump ----------------------------------------------------------

    @staticmethod
    def _classify(fut) -> tuple[object, str]:
        """(result, failure-reason) for a completed future.  The reason
        only matters when result is None; "shutdown" carries no penalty,
        the rest map to distinct peer_manager SCORES weights."""
        try:
            return fut.result(timeout=0), "timeout"
        except _DecodeError:
            return None, "decode_error"
        except TimeoutError as exc:
            msg = str(exc)
            if msg == "peer gone":
                return None, "peer_gone"
            if msg == "sync context closed":
                return None, "shutdown"
            return None, "timeout"
        except Exception:
            return None, "timeout"

    def pump(self) -> None:
        """Deliver completed request results to their owners until no
        request is in flight.

        Per-request deadline wheel (ISSUE 11): each in-flight request
        carries its own deadline; the pump waits only until the nearest
        one, then expires overdue requests *individually* — failing that
        request alone and penalizing that peer alone.  A slowloris peer
        can no longer mass-fail the honest pool the way the old global
        20 s stall window did (`sync_pump_global_stall_total` is the
        structurally-zero tripwire for that behavior).
        """
        while True:
            with self._lock:
                if not self.inflight:
                    return
                futs = {rec[2]: rid for rid, rec in self.inflight.items()}
                nearest = min(rec[4] for rec in self.inflight.values())
            done, _ = wait(list(futs),
                           timeout=max(0.0, nearest - time.monotonic()),
                           return_when=FIRST_COMPLETED)
            now = time.monotonic()
            deliveries = []                 # (rid, record, expired)
            with self._lock:
                for fut in done:
                    rec = self.inflight.pop(futs[fut], None)
                    if rec is not None:
                        deliveries.append((futs[fut], rec, False))
                for rid, rec in list(self.inflight.items()):
                    if rec[4] <= now:
                        del self.inflight[rid]
                        deliveries.append((rid, rec, True))
            md = _metrics()
            for rid, (owner, peer_id, fut, kind, _dl), expired in deliveries:
                if expired:
                    fut.cancel()
                    if md is not None:
                        md.count("sync_request_deadline_expired_total")
                    result, reason = None, "stall"
                else:
                    result, reason = self._classify(fut)
                if result is None and reason != "shutdown":
                    self.backoff.note_failure(peer_id)
                elif result is not None:
                    self.backoff.note_success(peer_id)
                if kind == "range":
                    owner.on_range_response(rid, result, reason=reason)
                else:
                    owner.on_root_response(rid, result, peer_id,
                                           reason=reason)

    def snapshot(self) -> dict:
        """Flight-recorder view: in-flight requests, backoff/quarantine
        state, and the most recent validation rejects."""
        now = time.monotonic()
        with self._lock:
            inflight = [
                {"req_id": rid, "peer": rec[1], "kind": rec[3],
                 "deadline_in": round(rec[4] - now, 3)}
                for rid, rec in self.inflight.items()]
        return {
            "inflight": inflight,
            "backoff": self.backoff.snapshot(),
            "validation_rejects": list(self.validation_rejects),
            "imported_total": self.imported_total,
            "request_timeout": self.request_timeout,
        }


class SyncManager:
    """Facade over the three sync strategies (manager.rs:177)."""

    def __init__(self, chain, rpc, peer_manager):
        self.chain = chain
        self.rpc = rpc
        self.peers = peer_manager
        self.ctx = _RealSyncContext(chain, rpc, peer_manager)
        self.range = RangeSync(self.ctx)
        self.lookups = BlockLookups(self.ctx)
        self.state = "synced"          # synced | range_syncing (property
        #                                feeds the sync_state gauge)
        # one strategy drives at a time: the service loop, gossip handlers
        # and tests all enter through these methods (manager.rs: the sync
        # manager is a single task; here a lock provides the same
        # exclusion).  Deltas are measured from BEFORE the lock so a
        # caller that waited on a concurrent sync still reports its
        # progress.
        self._drive_lock = threading.RLock()

    @property
    def state(self) -> str:
        return self._state

    @state.setter
    def state(self, value: str) -> None:
        self._state = value
        md = _metrics()
        if md is not None:
            md.gauge("sync_state", 0 if value == "synced" else 1)

    def stop(self) -> None:
        """Refuse new downloads and cancel queued ones; in-flight request
        threads drain into failed results instead of raising into a
        closed transport."""
        self.ctx.close()

    # -- entry points (round-3 signatures) -----------------------------------

    def maybe_sync(self) -> int:
        """Classify STATUS-ahead peers into chains and sync the best one
        to completion (or failure), pumping download events."""
        before = self.ctx.imported_total
        with self._drive_lock:
            while True:
                # (re-)classify peers each pass: when a finalized chain
                # completes, still-ahead peers regroup into head chains
                # (chain_collection.rs re-grouping)
                for p in self.peers.connected():
                    if (p.status is not None and p.score >= 0
                            and not self.ctx.backoff.quarantined(p.node_id)):
                        self.range.add_peer(p.node_id, p.status)
                chain = self.range.drive()
                if chain is None or not self.ctx.inflight:
                    break               # nothing dispatchable remained
                self.state = "range_syncing"
                self.ctx.pump()
            self.state = "synced"
        return self.ctx.imported_total - before

    def backfill(self, batch_slots: int | None = None) -> int:
        """Run the backfill machine against the current peer pool until it
        stops (anchor at genesis, stall, or misbehavior)."""
        with self._drive_lock:
            machine = BackfillSync(self.ctx, batch_slots)
            pool = [p.node_id for p in self.peers.connected()
                    if p.status is not None and p.score >= 0
                    and not p.banned
                    and not self.ctx.backoff.quarantined(p.node_id)]
            if not pool:
                best = self.peers.best_peer_for_sync()
                if best is None:
                    return 0
                pool = [best.node_id]
            while not machine.stopped and not machine.complete:
                machine.drive(pool)
                if not machine.in_flight:
                    break
                self.ctx.pump()
            return machine.stored

    # -- helpers (round-3 compatible) ----------------------------------------

    def snapshot(self) -> dict:
        """Sync-layer view for the flight recorder's doc["sync"]."""
        snap = self.ctx.snapshot()
        snap["state"] = self.state
        return snap

    def _decode_block(self, hex_payload: str):
        return self.ctx._decode_block(hex_payload)

    def _sync_peer_pool(self, min_head: int) -> list:
        """Non-banned, non-negative-score peers whose head is past
        min_head (range peer pool view, used by tests/monitoring)."""
        return [p for p in self.peers.connected()
                if p.status is not None and p.status.head_slot > min_head
                and p.score >= 0]

    def lookup_unknown_parent(self, block_root: bytes, peer_id: str,
                              max_depth: int | None = None) -> int:
        """Resolve an unknown-parent/unknown-root block by walking its
        ancestry (depth-limited in BlockLookups)."""
        before = self.ctx.imported_total
        with self._drive_lock:
            self.lookups.search(block_root, peer_id, max_depth=max_depth)
            self.ctx.pump()
        return self.ctx.imported_total - before


def digest_to_fork(chain) -> dict:
    """4-byte fork-digest -> ForkName, for the chunk context bytes the
    real req/resp protocol leads block chunks with
    (rpc/codec/ssz_snappy.rs context_bytes)."""
    from ...specs.chain_spec import ForkName, compute_fork_digest
    return {compute_fork_digest(chain.spec.fork_version(f),
                                chain.genesis_validators_root): f
            for f in ForkName}


def encode_block(signed_block, chain) -> str:
    """fork-digest context (4B) + SSZ, as one response chunk payload."""
    from ...specs.chain_spec import compute_fork_digest
    digest = compute_fork_digest(
        chain.spec.fork_version(signed_block.fork_name),
        chain.genesis_validators_root)
    return (digest
            + serialize(type(signed_block).ssz_type, signed_block)).hex()
