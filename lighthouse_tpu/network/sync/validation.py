"""Download-time batch validation (ISSUE 11 tentpole 2).

Structural checks a by_range response must pass BEFORE its batch is ever
marked downloaded: they cost O(batch) in pure Python, versus the
O(state-transition) price of letting junk reach `process_segment`.  A
junk server, a wrong-range server, or a count-overflowing server is
caught here and charged `bad_segment` immediately, and the
PARENT_UNKNOWN previous-batch rollback in range_sync keeps precise blame
because a batch that *passed* these checks can only break the chain at
its edges.

Checks, in order (first failure wins):

``count_cap``      at most `count` blocks (the request's own cap);
``out_of_range``   every slot inside the requested [start, start+count);
``not_ascending``  slots strictly ascending (no duplicates, no reorder);
``parent_link``    consecutive blocks hash-link: block[i+1].parent_root
                   == root(block[i]) — skipped slots between them are
                   fine, a fork inside one response is not;
``continuity``     first block's parent_root matches the previous
                   batch's tail root, when the caller knows it.

The module is dependency-free and pure: callers supply `block_root` (the
ctx hook) so the fake-block test harness works unchanged.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ValidationResult:
    ok: bool
    reason: str = ""
    detail: str = ""

    def __bool__(self) -> bool:  # pragma: no cover - convenience
        return self.ok


_OK = ValidationResult(True)


def validate_range_batch(blocks: list, start: int, count: int, *,
                         block_root, prev_tail_root: bytes | None = None,
                         ) -> ValidationResult:
    """Structurally validate a by_range response against its request.

    `blocks` is the decoded response (possibly empty — empty is always
    valid: runs of skipped slots are legitimate).  `prev_tail_root` is
    the root of the last block of the batch immediately below, when the
    caller has it; None skips the continuity check.
    """
    if len(blocks) > count:
        return ValidationResult(
            False, "count_cap",
            f"{len(blocks)} blocks for a {count}-slot request")
    end = start + count
    prev_slot = None
    prev_root = None
    for i, sb in enumerate(blocks):
        slot = int(sb.message.slot)
        if not start <= slot < end:
            return ValidationResult(
                False, "out_of_range",
                f"block {i} at slot {slot} outside [{start}, {end})")
        if prev_slot is not None and slot <= prev_slot:
            return ValidationResult(
                False, "not_ascending",
                f"slot {slot} after slot {prev_slot}")
        if prev_root is not None and sb.message.parent_root != prev_root:
            return ValidationResult(
                False, "parent_link",
                f"block at slot {slot} does not link to the response's "
                f"previous block")
        prev_slot = slot
        prev_root = block_root(sb)
    if (blocks and prev_tail_root is not None
            and blocks[0].message.parent_root != prev_tail_root):
        return ValidationResult(
            False, "continuity",
            f"first block (slot {int(blocks[0].message.slot)}) does not "
            f"link to the previous batch's tail")
    return _OK
