"""Range sync: finalized/head syncing chains with per-chain peer pools.

Equivalent of the reference's range sync (network/src/sync/range_sync/
{range.rs,chain.rs,chain_collection.rs}): peers whose STATUS is ahead of
the local chain are grouped into *chains* keyed by their claimed target
(finalized root for finalized sync, head root for head sync).  One chain
syncs at a time — finalized chains take priority and the best chain is the
one with the most peers.  Each chain pipelines up to BATCH_BUFFER
epoch-aligned batches from its pool, imports them strictly in slot order,
attributes processing failures to the serving peer, retries from other
peers, and fails the chain (penalizing its pool) after bounded attempts.

The machine is synchronous and network-agnostic: it emits requests through
a context object (`ctx.send_range(peer, start, count, owner)`) and consumes
`on_range_response` / `on_download_error` / local processing results — the
test suite drives it with synthetic events exactly like the reference's
sync tests (network/src/sync/block_lookups/tests.rs style).
"""
from __future__ import annotations

from ...chain.errors import PARENT_UNKNOWN
from .batches import Batch, BatchState

EPOCHS_PER_BATCH = 2


class SyncingChain:
    BATCH_BUFFER = 5          # in-flight batches beyond the processing head

    def __init__(self, chain_id: int, kind: str, target_root: bytes,
                 target_slot: int, start_slot: int, batch_slots: int,
                 ctx=None):
        assert kind in ("finalized", "head")
        self.ctx = ctx
        self.id = chain_id
        self.kind = kind
        self.target_root = target_root
        self.target_slot = target_slot
        self.start_slot = start_slot          # first slot to download
        self.batch_slots = batch_slots
        self.peers: set[str] = set()
        self.batches: dict[int, Batch] = {}   # batch_id -> Batch
        self.next_batch_id = 0                # next batch to create
        self.process_ptr = 0                  # next batch to process in order
        self.imported = 0
        self.failed = False
        self.complete = False
        # req_id -> batch_id for in-flight downloads
        self.requests: dict[int, int] = {}

    # -- pool ----------------------------------------------------------------

    def add_peer(self, peer_id: str) -> None:
        self.peers.add(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        self.peers.discard(peer_id)

    @property
    def available_peers(self) -> list[str]:
        busy = {b.peer for b in self.batches.values()
                if b.state == BatchState.DOWNLOADING}
        return sorted(self.peers - busy)

    # -- batch creation / scheduling ----------------------------------------

    def _batch_start(self, batch_id: int) -> int:
        return self.start_slot + batch_id * self.batch_slots

    def _total_batches(self) -> int:
        span = self.target_slot - self.start_slot + 1
        return max(0, -(-span // self.batch_slots))

    def request_batches(self, ctx=None) -> None:
        """Create/dispatch downloads up to BATCH_BUFFER beyond the
        processing pointer, one per available pool peer."""
        ctx = ctx if ctx is not None else self.ctx
        if self.failed or self.complete:
            return
        total = self._total_batches()
        # instantiate lazily
        while (self.next_batch_id < total
               and self.next_batch_id < self.process_ptr + self.BATCH_BUFFER):
            bid = self.next_batch_id
            start = self._batch_start(bid)
            count = min(self.batch_slots, self.target_slot - start + 1)
            self.batches[bid] = Batch(bid, start, count)
            self.next_batch_id += 1
        for bid in sorted(self.batches):
            batch = self.batches[bid]
            if batch.state != BatchState.AWAITING_DOWNLOAD:
                continue
            pool = self.available_peers
            fresh = [p for p in pool if p not in batch.attempted_peers]
            if fresh:
                peer = fresh[0]
            elif self.peers - batch.attempted_peers:
                continue                    # a fresh peer exists but is busy:
                                            # defer rather than re-ask a
                                            # peer that already failed this
            else:
                peer = batch.pick_peer(pool)
                if peer is None:
                    return                  # no free peers right now
            req_id = ctx.send_range(peer, batch.start_slot, batch.count, self)
            batch.start_download(peer, req_id)
            self.requests[req_id] = bid

    # -- event handlers ------------------------------------------------------

    def on_range_response(self, req_id: int, blocks: list | None,
                          ctx=None) -> None:
        """blocks=None means the download failed (error/timeout/decode)."""
        ctx = ctx if ctx is not None else self.ctx
        bid = self.requests.pop(req_id, None)
        if bid is None:
            return                          # stale response for a dropped req
        batch = self.batches[bid]
        if blocks is None:
            ctx.penalize(batch.peer, "timeout")
            if batch.download_failed() == BatchState.FAILED:
                self._fail(ctx)
                return
        else:
            batch.downloaded(blocks)
        self._process_ready(ctx)
        self.request_batches(ctx)

    def _process_ready(self, ctx) -> None:
        """Import batches strictly in order while the frontier is ready."""
        while not self.failed and not self.complete:
            batch = self.batches.get(self.process_ptr)
            if batch is None or batch.state != BatchState.AWAITING_PROCESSING:
                return
            blocks = batch.start_processing()
            imported, err = ctx.process_segment(blocks) if blocks else (0, None)
            if err is None:
                self.imported += imported
                batch.processed()
                self.process_ptr += 1
                if self.process_ptr >= self._total_batches():
                    self._finish(ctx)
                    return
            elif err == PARENT_UNKNOWN and self.process_ptr > 0:
                # the gap is the PREVIOUS batch's fault (a truncated tail
                # is undetectable at download time): roll back and
                # re-download batch k-1, don't blame this batch's peer
                # (range_sync/chain.rs re-downloads the prior batch; the
                # round-3 sync kept the same attribution)
                prev = self.batches[self.process_ptr - 1]
                if prev.peer is not None:
                    ctx.penalize(prev.peer, "ignore")
                if prev.processing_attempts >= Batch.MAX_PROCESSING_ATTEMPTS:
                    self._fail(ctx)
                    return
                redo = Batch(prev.id, prev.start_slot, prev.count)
                redo.processing_attempts = prev.processing_attempts
                redo.attempted_peers = set(prev.attempted_peers)
                self.batches[prev.id] = redo
                batch.state = BatchState.AWAITING_PROCESSING  # retry after
                self.process_ptr -= 1
                self.request_batches(ctx)
                return
            else:
                # the serving peer gave us an unusable segment
                ctx.penalize(batch.peer, "bad_segment")
                if batch.processing_failed() == BatchState.FAILED:
                    self._fail(ctx)
                    return
                self.request_batches(ctx)
                return                      # wait for the re-download

    def _finish(self, ctx) -> None:
        """All batches processed.  An entirely-empty chain whose peers all
        claimed a higher head is a lie — penalize the pool.  But if the
        local head advanced past our start while we synced (gossip imports
        make process_segment return 0 for known blocks), the peers were
        honest and the work just raced."""
        self.complete = True
        if self.imported == 0 and ctx.local_status()[0] < self.start_slot:
            for p in sorted(self.peers):
                ctx.penalize(p, "empty_batch")

    def _fail(self, ctx) -> None:
        self.failed = True
        for p in sorted(self.peers):
            ctx.penalize(p, "ignore")

    @property
    def in_flight(self) -> int:
        return len(self.requests)


class RangeSync:
    """Chain collection: groups STATUS-ahead peers into chains, syncs the
    best one (finalized > head, then most peers), drops completed/failed
    chains (chain_collection.rs behavior)."""

    def __init__(self, ctx, batch_slots: int | None = None):
        self.ctx = ctx
        self.chains: dict[tuple, SyncingChain] = {}
        self.retired: set[tuple] = set()   # completed/failed targets
        self._next_chain_id = 0
        self.batch_slots = batch_slots or (
            EPOCHS_PER_BATCH * ctx.slots_per_epoch())

    # -- peer intake ---------------------------------------------------------

    def add_peer(self, peer_id: str, status) -> None:
        """Classify the peer by its STATUS against our local view: a
        finalized-ahead peer joins a finalized chain; once that target is
        retired (synced or proven bad) a still-head-ahead peer falls
        through to a head chain (our own finality may lag the imported
        blocks' epoch processing)."""
        local_head, local_fin_epoch = self.ctx.local_status()
        spe = self.ctx.slots_per_epoch()
        candidates = []
        if status.finalized_epoch > local_fin_epoch:
            candidates.append(("finalized", status.finalized_root,
                               status.finalized_epoch * spe))
        if status.head_slot > local_head:
            candidates.append(("head", status.head_root, status.head_slot))
        for key in candidates:
            if key in self.retired or key[2] <= local_head:
                continue
            chain = self.chains.get(key)
            if chain is None:
                chain = SyncingChain(
                    self._next_chain_id, key[0], key[1], key[2],
                    start_slot=local_head + 1,
                    batch_slots=self.batch_slots, ctx=self.ctx)
                self._next_chain_id += 1
                self.chains[key] = chain
            chain.add_peer(peer_id)
            return

    def remove_peer(self, peer_id: str) -> None:
        for chain in self.chains.values():
            chain.remove_peer(peer_id)

    # -- scheduling ----------------------------------------------------------

    def best_chain(self) -> SyncingChain | None:
        """Finalized chains beat head chains; more peers beats fewer —
        purging dead chains first (their targets are retired so a stale
        STATUS can't resurrect them)."""
        self.retired |= {k for k, c in self.chains.items()
                         if c.failed or c.complete}
        self.chains = {k: c for k, c in self.chains.items()
                       if not c.failed and not c.complete and c.peers}
        ranked = sorted(
            self.chains.values(),
            key=lambda c: (c.kind != "finalized", -len(c.peers), c.id))
        return ranked[0] if ranked else None

    def drive(self) -> SyncingChain | None:
        """Dispatch requests on the currently-best chain."""
        chain = self.best_chain()
        if chain is not None:
            chain.request_batches(self.ctx)
        return chain

    def on_range_response(self, req_id: int, blocks: list | None) -> None:
        for chain in list(self.chains.values()):
            if req_id in chain.requests:
                chain.on_range_response(req_id, blocks, self.ctx)
                return

    @property
    def syncing(self) -> bool:
        return any(c.in_flight or (not c.complete and not c.failed
                                   and c.peers)
                   for c in self.chains.values())
