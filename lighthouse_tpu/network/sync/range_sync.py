"""Range sync: finalized/head syncing chains with per-chain peer pools.

Equivalent of the reference's range sync (network/src/sync/range_sync/
{range.rs,chain.rs,chain_collection.rs}): peers whose STATUS is ahead of
the local chain are grouped into *chains* keyed by their claimed target
(finalized root for finalized sync, head root for head sync).  One chain
syncs at a time — finalized chains take priority and the best chain is the
one with the most peers.  Each chain pipelines up to BATCH_BUFFER
epoch-aligned batches from its pool, imports them strictly in slot order,
attributes processing failures to the serving peer, retries from other
peers, and fails the chain (penalizing its pool) after bounded attempts.

The machine is synchronous and network-agnostic: it emits requests through
a context object (`ctx.send_range(peer, start, count, owner)`) and consumes
`on_range_response` / `on_download_error` / local processing results — the
test suite drives it with synthetic events exactly like the reference's
sync tests (network/src/sync/block_lookups/tests.rs style).
"""
from __future__ import annotations

import sys

from ...chain.errors import PARENT_UNKNOWN
from .batches import Batch, BatchState
from .validation import validate_range_batch

EPOCHS_PER_BATCH = 2


def _count(name: str, amount: float = 1) -> None:
    """Catalog counter, sys.modules-gated (synthetic-event tests drive
    the machines without the metrics stack).  getattr-guarded so a
    module still mid-import reads as absent."""
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    count = getattr(md, "count", None)
    if count is not None:
        count(name, amount)


class SyncingChain:
    BATCH_BUFFER = 5          # in-flight batches beyond the processing head
    # a pool whose every batch comes back empty while nothing imports is
    # lying about its target (a fake-ahead STATUS): fail fast instead of
    # walking millions of empty slots toward a fabricated head
    MAX_CONSEC_EMPTY = 8

    def __init__(self, chain_id: int, kind: str, target_root: bytes,
                 target_slot: int, start_slot: int, batch_slots: int,
                 ctx=None):
        assert kind in ("finalized", "head")
        self.ctx = ctx
        self.id = chain_id
        self.kind = kind
        self.target_root = target_root
        self.target_slot = target_slot
        self.start_slot = start_slot          # first slot to download
        self.batch_slots = batch_slots
        self.peers: set[str] = set()
        self.batches: dict[int, Batch] = {}   # batch_id -> Batch
        self.next_batch_id = 0                # next batch to create
        self.process_ptr = 0                  # next batch to process in order
        self.imported = 0
        self.failed = False
        self.complete = False
        # req_id -> batch_id for in-flight downloads
        self.requests: dict[int, int] = {}
        self._consec_empty = 0
        # batch_id -> root of the last *processed* block at/below that
        # batch's end (empty batches inherit the tail below them); feeds
        # the download-time continuity check
        self._tail_roots: dict[int, bytes] = {}

    # -- pool ----------------------------------------------------------------

    def add_peer(self, peer_id: str) -> None:
        self.peers.add(peer_id)

    def remove_peer(self, peer_id: str) -> None:
        self.peers.discard(peer_id)

    @property
    def available_peers(self) -> list[str]:
        busy = {b.peer for b in self.batches.values()
                if b.state == BatchState.DOWNLOADING}
        return sorted(self.peers - busy)

    # -- batch creation / scheduling ----------------------------------------

    def _batch_start(self, batch_id: int) -> int:
        return self.start_slot + batch_id * self.batch_slots

    def _total_batches(self) -> int:
        span = self.target_slot - self.start_slot + 1
        return max(0, -(-span // self.batch_slots))

    def request_batches(self, ctx=None) -> None:
        """Create/dispatch downloads up to BATCH_BUFFER beyond the
        processing pointer, one per available pool peer."""
        ctx = ctx if ctx is not None else self.ctx
        if self.failed or self.complete:
            return
        total = self._total_batches()
        # instantiate lazily
        while (self.next_batch_id < total
               and self.next_batch_id < self.process_ptr + self.BATCH_BUFFER):
            bid = self.next_batch_id
            start = self._batch_start(bid)
            count = min(self.batch_slots, self.target_slot - start + 1)
            self.batches[bid] = Batch(bid, start, count)
            self.next_batch_id += 1
        for bid in sorted(self.batches):
            batch = self.batches[bid]
            if batch.state != BatchState.AWAITING_DOWNLOAD:
                continue
            pool = self.available_peers
            fresh = [p for p in pool if p not in batch.attempted_peers]
            # rotate seeded on (attempt, batch id) so a deterministic
            # fresh[0] can't hand every retry to the same failed peer
            salt = batch.download_attempts + batch.id
            if fresh:
                peer = fresh[salt % len(fresh)]
            elif self.peers - batch.attempted_peers:
                continue                    # a fresh peer exists but is busy:
                                            # defer rather than re-ask a
                                            # peer that already failed this
            else:
                peer = batch.pick_peer(pool, salt=salt)
                if peer is None:
                    return                  # no free peers right now
            req_id = ctx.send_range(peer, batch.start_slot, batch.count, self)
            batch.start_download(peer, req_id)
            self.requests[req_id] = bid

    # -- event handlers ------------------------------------------------------

    def on_range_response(self, req_id: int, blocks: list | None,
                          ctx=None, reason: str = "timeout") -> None:
        """blocks=None means the download failed; `reason` says why
        (timeout/stall/peer_gone/decode_error/shutdown) and picks the
        penalty weight — "shutdown" is our own close path and carries
        none."""
        ctx = ctx if ctx is not None else self.ctx
        bid = self.requests.pop(req_id, None)
        if bid is None:
            return                          # stale response for a dropped req
        batch = self.batches[bid]
        if blocks is None:
            ctx.penalize(batch.peer, reason)
            if batch.download_failed() == BatchState.FAILED:
                self._fail(ctx)
                return
        elif not self._validate_download(ctx, batch, blocks):
            return
        else:
            _count("sync_range_batches_downloaded_total")
            batch.downloaded(blocks)
        self._process_ready(ctx)
        self.request_batches(ctx)

    def _validate_download(self, ctx, batch, blocks) -> bool:
        """Download-time structural validation (validation.py): a junk /
        wrong-range / miscounted response is charged `bad_segment` in
        O(batch) and never reaches process_segment.  A continuity break
        against an already-processed previous batch is the *previous*
        batch's truncated tail (this response already proved internally
        linked): roll that batch back instead of blaming this peer.
        Returns True when the caller should accept the download."""
        prev_tail = self._tail_roots.get(batch.id - 1)
        res = validate_range_batch(
            blocks, batch.start_slot, batch.count,
            block_root=ctx.block_root, prev_tail_root=prev_tail)
        if res.ok:
            return True
        note = getattr(ctx, "note_validation_reject", None)
        if res.reason == "continuity" and batch.id > 0:
            prev = self.batches.get(batch.id - 1)
            if (prev is not None and prev.state == BatchState.PROCESSED
                    and prev.peer is not None):
                if note is not None:
                    note(prev.peer, prev.start_slot, prev.count,
                         "continuity")
                ctx.penalize(prev.peer, "truncated_batch")
                self._rollback_processed(prev)
                _count("sync_range_batches_downloaded_total")
                batch.downloaded(blocks)    # this response stands
                self.request_batches(ctx)
                return False
        _count("sync_batch_validation_rejects_total")
        if note is not None:
            note(batch.peer, batch.start_slot, batch.count, res.reason)
        ctx.penalize(batch.peer, "bad_segment")
        if batch.download_failed() == BatchState.FAILED:
            self._fail(ctx)
            return False
        self.request_batches(ctx)
        return False

    def _rollback_processed(self, prev: Batch) -> None:
        """Re-download an already-processed batch whose tail proved
        truncated, preserving its attempt bookkeeping."""
        redo = Batch(prev.id, prev.start_slot, prev.count)
        redo.processing_attempts = prev.processing_attempts
        redo.attempted_peers = set(prev.attempted_peers)
        self.batches[prev.id] = redo
        self._tail_roots.pop(prev.id, None)
        self.process_ptr = min(self.process_ptr, prev.id)

    def _process_ready(self, ctx) -> None:
        """Import batches strictly in order while the frontier is ready."""
        while not self.failed and not self.complete:
            batch = self.batches.get(self.process_ptr)
            if batch is None or batch.state != BatchState.AWAITING_PROCESSING:
                return
            blocks = batch.start_processing()
            imported, err = ctx.process_segment(blocks) if blocks else (0, None)
            if err is None:
                self.imported += imported
                if imported:
                    _count("sync_range_blocks_imported_total", imported)
                if blocks:
                    self._consec_empty = 0
                    self._tail_roots[batch.id] = ctx.block_root(blocks[-1])
                else:
                    self._consec_empty += 1
                    tail = self._tail_roots.get(batch.id - 1)
                    if tail is not None:
                        self._tail_roots[batch.id] = tail
                batch.processed()
                self.process_ptr += 1
                if (self.imported == 0
                        and self._consec_empty >= self.MAX_CONSEC_EMPTY):
                    # every batch empty, nothing imported: the pool's
                    # claimed target is a fabrication (lying STATUS) —
                    # fail fast instead of draining it to the fake head
                    self.failed = True
                    for p in sorted(self.peers):
                        ctx.penalize(p, "empty_batch")
                    return
                if self.process_ptr >= self._total_batches():
                    self._finish(ctx)
                    return
            elif err == PARENT_UNKNOWN and self.process_ptr > 0:
                # download-time validation proved this batch internally
                # linked and in-range, so an unknown parent at its head
                # pins the gap on the PREVIOUS batch's truncated tail:
                # roll back and re-download batch k-1 with precise blame
                # (range_sync/chain.rs re-downloads the prior batch; the
                # round-3 sync penalized "ignore" for want of evidence)
                prev = self.batches[self.process_ptr - 1]
                if prev.peer is not None:
                    ctx.penalize(prev.peer, "truncated_batch")
                if prev.processing_attempts >= Batch.MAX_PROCESSING_ATTEMPTS:
                    self._fail(ctx)
                    return
                redo = Batch(prev.id, prev.start_slot, prev.count)
                redo.processing_attempts = prev.processing_attempts
                redo.attempted_peers = set(prev.attempted_peers)
                self.batches[prev.id] = redo
                self._tail_roots.pop(prev.id, None)
                batch.state = BatchState.AWAITING_PROCESSING  # retry after
                self.process_ptr -= 1
                self.request_batches(ctx)
                return
            else:
                # the serving peer gave us an unusable segment
                ctx.penalize(batch.peer, "bad_segment")
                if batch.processing_failed() == BatchState.FAILED:
                    self._fail(ctx)
                    return
                self.request_batches(ctx)
                return                      # wait for the re-download

    def _finish(self, ctx) -> None:
        """All batches processed.  An entirely-empty chain whose peers all
        claimed a higher head is a lie — penalize the pool.  But if the
        local head advanced past our start while we synced (gossip imports
        make process_segment return 0 for known blocks), the peers were
        honest and the work just raced."""
        self.complete = True
        if self.imported == 0 and ctx.local_status()[0] < self.start_slot:
            for p in sorted(self.peers):
                ctx.penalize(p, "empty_batch")

    def _fail(self, ctx) -> None:
        self.failed = True
        for p in sorted(self.peers):
            ctx.penalize(p, "ignore")

    @property
    def in_flight(self) -> int:
        return len(self.requests)


class RangeSync:
    """Chain collection: groups STATUS-ahead peers into chains, syncs the
    best one (finalized > head, then most peers), drops completed/failed
    chains (chain_collection.rs behavior)."""

    def __init__(self, ctx, batch_slots: int | None = None):
        self.ctx = ctx
        self.chains: dict[tuple, SyncingChain] = {}
        self.retired: set[tuple] = set()   # completed targets
        # failed target -> the pool that failed it.  A FAILED target is
        # only dead to the peers that failed to serve it: a byzantine
        # pool must not be able to poison a real target for honest peers
        # that show up later (ISSUE 11).  Completed targets stay retired
        # for everyone — a stale STATUS can't resurrect them.
        self.failed_from: dict[tuple, set[str]] = {}
        self._next_chain_id = 0
        self.batch_slots = batch_slots or (
            EPOCHS_PER_BATCH * ctx.slots_per_epoch())

    # -- peer intake ---------------------------------------------------------

    def add_peer(self, peer_id: str, status) -> None:
        """Classify the peer by its STATUS against our local view: a
        finalized-ahead peer joins a finalized chain; once that target is
        retired (synced or proven bad) a still-head-ahead peer falls
        through to a head chain (our own finality may lag the imported
        blocks' epoch processing)."""
        local_head, local_fin_epoch = self.ctx.local_status()
        spe = self.ctx.slots_per_epoch()
        candidates = []
        if status.finalized_epoch > local_fin_epoch:
            candidates.append(("finalized", status.finalized_root,
                               status.finalized_epoch * spe))
        if status.head_slot > local_head:
            candidates.append(("head", status.head_root, status.head_slot))
        for key in candidates:
            if key in self.retired or key[2] <= local_head:
                continue
            if peer_id in self.failed_from.get(key, ()):
                continue   # this peer already failed to serve this target
            chain = self.chains.get(key)
            if chain is not None and (chain.failed or chain.complete):
                # purge hasn't run yet — retire the dead chain here so
                # the new peer never lands in a failed pool's blame set
                if chain.complete:
                    self.retired.add(key)
                else:
                    self.failed_from.setdefault(key, set()) \
                        .update(chain.peers)
                del self.chains[key]
                if key in self.retired \
                        or peer_id in self.failed_from.get(key, ()):
                    continue
                chain = None
            if chain is None:
                chain = SyncingChain(
                    self._next_chain_id, key[0], key[1], key[2],
                    start_slot=local_head + 1,
                    batch_slots=self.batch_slots, ctx=self.ctx)
                self._next_chain_id += 1
                self.chains[key] = chain
            chain.add_peer(peer_id)
            return

    def remove_peer(self, peer_id: str) -> None:
        for chain in self.chains.values():
            chain.remove_peer(peer_id)

    # -- scheduling ----------------------------------------------------------

    def best_chain(self) -> SyncingChain | None:
        """Finalized chains beat head chains; more peers beats fewer —
        purging dead chains first.  Completed targets are retired for
        everyone (a stale STATUS can't resurrect them); failed targets
        are retired only from the pool that failed them, so honest
        peers arriving later can still serve the same target."""
        self.retired |= {k for k, c in self.chains.items() if c.complete}
        for k, c in self.chains.items():
            if c.failed and not c.complete:
                self.failed_from.setdefault(k, set()).update(c.peers)
        self.chains = {k: c for k, c in self.chains.items()
                       if not c.failed and not c.complete and c.peers}
        ranked = sorted(
            self.chains.values(),
            key=lambda c: (c.kind != "finalized", -len(c.peers), c.id))
        return ranked[0] if ranked else None

    def drive(self) -> SyncingChain | None:
        """Dispatch requests on the currently-best chain."""
        chain = self.best_chain()
        if chain is not None:
            chain.request_batches(self.ctx)
        return chain

    def on_range_response(self, req_id: int, blocks: list | None,
                          reason: str = "timeout") -> None:
        for chain in list(self.chains.values()):
            if req_id in chain.requests:
                chain.on_range_response(req_id, blocks, self.ctx,
                                        reason=reason)
                return

    @property
    def syncing(self) -> bool:
        return any(c.in_flight or (not c.complete and not c.failed
                                   and c.peers)
                   for c in self.chains.values())
