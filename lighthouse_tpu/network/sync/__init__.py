"""Sync layer: range sync, backfill, block lookups (network/src/sync/).

Round 4 replaced the single-file round-3 sync (a blocking ~265-LoC
`maybe_sync`) with the reference-shaped state machines (VERDICT r3 "next"
#2): per-chain peer pools and batch lifecycles (range_sync.py), a backfill
batch machine (backfill.py), and depth-limited concurrent parent lookups
(lookups.py), all driven by synthetic-event tests in
tests/test_sync_machines.py.
"""
from .batches import Batch, BatchState
from .backfill import BackfillSync
from .lookups import BlockLookups, Lookup
from .manager import SyncManager, digest_to_fork, encode_block
from .range_sync import EPOCHS_PER_BATCH, RangeSync, SyncingChain

__all__ = [
    "Batch", "BatchState", "BackfillSync", "BlockLookups", "Lookup",
    "SyncManager", "digest_to_fork", "encode_block", "EPOCHS_PER_BATCH",
    "RangeSync", "SyncingChain",
]
