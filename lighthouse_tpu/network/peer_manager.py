"""Peer scoring + lifecycle (peer_manager/peerdb/score.rs equivalent)."""
from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field


def _metrics():
    """metrics_defs, sys.modules-gated (wire tests run the network layer
    without the metrics stack); a module still mid-import reads as
    absent so racing network threads never see a half-built module."""
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    return md if hasattr(md, "count") and hasattr(md, "gauge") else None


@dataclass
class PeerInfo:
    node_id: str
    connected_at: float = field(default_factory=time.monotonic)
    score: float = 0.0
    status: object = None          # last StatusMessage
    banned: bool = False


class PeerManager:
    BAN_THRESHOLD = -20.0
    # IGNORE is benign by the gossipsub validation contract (duplicates,
    # not-yet-known head blocks): penalizing it makes every long-lived
    # honest connection drift toward the ban threshold, since aggregates
    # routinely cover already-seen attestations.  Only REJECT (provably
    # invalid) and protocol abuse carry weight.
    # Sync failure reasons carry distinct weights (ISSUE 11 satellite):
    # a peer that *disconnected* mid-request is barely at fault
    # (peer_gone), a stalled request is protocol abuse lighter than junk
    # (stall), and a payload we could not even decode is near-certain
    # malice (decode_error).  "shutdown" is OUR close path and must never
    # reach report() — machines skip the penalty entirely.
    SCORES = {"reject": -5.0, "ignore": 0.0, "accept": 0.1,
              "rate_limited": -1.0, "timeout": -2.0, "bad_segment": -10.0,
              "empty_batch": -3.0, "peer_gone": -0.5, "stall": -3.0,
              "decode_error": -6.0, "truncated_batch": -6.0}

    def __init__(self, target_peers: int = 16):
        self.peers: dict[str, PeerInfo] = {}
        self.target_peers = target_peers
        self._lock = threading.Lock()
        self.on_ban = lambda node_id: None

    def on_connect(self, node_id: str) -> None:
        with self._lock:
            new = node_id not in self.peers
            self.peers.setdefault(node_id, PeerInfo(node_id))
            n = len(self.peers)
        md = _metrics()
        if md is not None:
            if new:
                md.count("libp2p_peer_connect_total")
            md.gauge("libp2p_peers", n)

    def on_disconnect(self, node_id: str) -> None:
        with self._lock:
            gone = self.peers.pop(node_id, None)
            n = len(self.peers)
        md = _metrics()
        if md is not None:
            if gone is not None:
                md.count("libp2p_peer_disconnect_total")
            md.gauge("libp2p_peers", n)

    def set_status(self, node_id: str, status) -> None:
        with self._lock:
            info = self.peers.get(node_id)
            if info:
                info.status = status

    def report(self, node_id: str, event: str) -> None:
        delta = self.SCORES.get(event, 0.0)
        ban = False
        with self._lock:
            info = self.peers.get(node_id)
            if info is None:
                return
            info.score += delta
            if info.score < self.BAN_THRESHOLD and not info.banned:
                info.banned = True
                ban = True
        if ban:
            self.on_ban(node_id)

    def score(self, node_id: str) -> float:
        with self._lock:
            info = self.peers.get(node_id)
            return info.score if info is not None else 0.0

    def connected(self) -> list[PeerInfo]:
        with self._lock:
            return [p for p in self.peers.values() if not p.banned]

    def best_peer_for_sync(self) -> PeerInfo | None:
        best, best_slot = None, -1
        for p in self.connected():
            if p.status is not None and p.status.head_slot > best_slot:
                best, best_slot = p, p.status.head_slot
        return best
