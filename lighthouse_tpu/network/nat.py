"""UPnP-IGD port mapping (NAT traversal attempt).

Equivalent of beacon_node/network/src/nat.rs (which uses the `igd` crate):
best-effort establishment of external TCP/UDP port mappings on the local
internet gateway so inbound libp2p/discv5 traffic reaches a node behind a
home NAT.  The full protocol is implemented — SSDP M-SEARCH discovery,
device-description fetch, WANIPConnection/WANPPPConnection control-URL
extraction, and the AddPortMapping SOAP action — with the socket/HTTP
edges injectable so the byte-level behavior is testable against a local
fake gateway (tests/test_nat.py); on a real network the defaults talk to
239.255.255.250:1900 like any UPnP client.

Failures are reported, never raised: NAT mapping is advisory
(nat.rs logs and continues).
"""
from __future__ import annotations

import re
import socket
from dataclasses import dataclass, field
from urllib.parse import urljoin, urlparse

SSDP_ADDR = ("239.255.255.250", 1900)
SSDP_ST = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
SERVICE_TYPES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


@dataclass
class NatOutcome:
    attempted: bool = False
    gateway_location: str | None = None
    control_url: str | None = None
    service_type: str | None = None
    mapped: list = field(default_factory=list)   # (proto, ext_port)
    error: str | None = None

    @property
    def ok(self) -> bool:
        return bool(self.mapped) and self.error is None


def build_msearch() -> bytes:
    return ("M-SEARCH * HTTP/1.1\r\n"
            f"HOST: {SSDP_ADDR[0]}:{SSDP_ADDR[1]}\r\n"
            'MAN: "ssdp:discover"\r\n'
            "MX: 2\r\n"
            f"ST: {SSDP_ST}\r\n"
            "\r\n").encode()


def parse_ssdp_response(data: bytes) -> str | None:
    """LOCATION header of an SSDP HTTP/1.1 200 response."""
    try:
        text = data.decode("latin-1")
    except Exception:
        return None
    if not text.upper().startswith("HTTP/1.1 200"):
        return None
    for line in text.split("\r\n")[1:]:
        k, _, v = line.partition(":")
        if k.strip().upper() == "LOCATION":
            return v.strip()
    return None


def ssdp_discover(timeout: float = 2.0, addr=SSDP_ADDR) -> str | None:
    """Multicast M-SEARCH; first well-formed LOCATION wins."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.settimeout(timeout)
        sock.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_TTL, 2)
        sock.sendto(build_msearch(), addr)
        while True:
            try:
                data, _src = sock.recvfrom(4096)
            except (socket.timeout, OSError):
                return None
            loc = parse_ssdp_response(data)
            if loc:
                return loc
    finally:
        sock.close()


def _http(method: str, url: str, body: bytes = b"",
          headers: dict | None = None, timeout: float = 3.0) -> bytes:
    """Tiny dependency-free HTTP/1.1 one-shot."""
    u = urlparse(url)
    host, port = u.hostname, u.port or 80
    path = u.path or "/"
    if u.query:
        path += "?" + u.query
    lines = [f"{method} {path} HTTP/1.1", f"Host: {host}:{port}",
             "Connection: close", f"Content-Length: {len(body)}"]
    for k, v in (headers or {}).items():
        lines.append(f"{k}: {v}")
    req = ("\r\n".join(lines) + "\r\n\r\n").encode() + body
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.sendall(req)
        chunks = []
        while True:
            c = s.recv(65536)
            if not c:
                break
            chunks.append(c)
    resp = b"".join(chunks)
    head, _, payload = resp.partition(b"\r\n\r\n")
    return payload


def parse_control_url(xml: bytes, base_url: str
                      ) -> tuple[str, str] | None:
    """(control_url, service_type) for the WAN*Connection service."""
    text = xml.decode("utf-8", "replace")
    for st in SERVICE_TYPES:
        # the <service> block containing this serviceType
        for m in re.finditer(r"<service>(.*?)</service>", text,
                             re.S | re.I):
            block = m.group(1)
            if st not in block:
                continue
            cu = re.search(r"<controlURL>(.*?)</controlURL>", block,
                           re.S | re.I)
            if cu:
                return urljoin(base_url, cu.group(1).strip()), st
    return None


def build_soap_add_mapping(service_type: str, ext_port: int,
                           proto: str, int_port: int, int_ip: str,
                           description: str, lease: int = 0) -> bytes:
    return (f"""<?xml version="1.0"?>
<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/"
 s:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">
 <s:Body>
  <u:AddPortMapping xmlns:u="{service_type}">
   <NewRemoteHost></NewRemoteHost>
   <NewExternalPort>{ext_port}</NewExternalPort>
   <NewProtocol>{proto}</NewProtocol>
   <NewInternalPort>{int_port}</NewInternalPort>
   <NewInternalClient>{int_ip}</NewInternalClient>
   <NewEnabled>1</NewEnabled>
   <NewPortMappingDescription>{description}</NewPortMappingDescription>
   <NewLeaseDuration>{lease}</NewLeaseDuration>
  </u:AddPortMapping>
 </s:Body>
</s:Envelope>""").encode()


def add_port_mapping(control_url: str, service_type: str, ext_port: int,
                     proto: str, int_port: int, int_ip: str,
                     description: str = "lighthouse_tpu",
                     http=_http) -> bool:
    body = build_soap_add_mapping(service_type, ext_port, proto,
                                  int_port, int_ip, description)
    headers = {
        "Content-Type": 'text/xml; charset="utf-8"',
        "SOAPAction": f'"{service_type}#AddPortMapping"',
    }
    try:
        resp = http("POST", control_url, body, headers)
    except OSError:
        return False
    return b"AddPortMappingResponse" in resp


def local_ip_towards(gateway_url: str) -> str:
    """The local interface address used to reach the gateway."""
    u = urlparse(gateway_url)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((u.hostname, u.port or 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def establish_mappings(tcp_port: int | None, udp_port: int | None,
                       discover=ssdp_discover, http=_http) -> NatOutcome:
    """The nat.rs entry point: try to map the libp2p TCP and discv5 UDP
    ports on the gateway; advisory (never raises)."""
    out = NatOutcome(attempted=True)
    try:
        loc = discover()
        if loc is None:
            out.error = "no UPnP gateway responded"
            return out
        out.gateway_location = loc
        desc = http("GET", loc)
        found = parse_control_url(desc, loc)
        if found is None:
            out.error = "gateway exposes no WAN*Connection service"
            return out
        out.control_url, out.service_type = found
        int_ip = local_ip_towards(out.control_url)
        for proto, port in (("TCP", tcp_port), ("UDP", udp_port)):
            if port and add_port_mapping(out.control_url,
                                         out.service_type, port, proto,
                                         port, int_ip, http=http):
                out.mapped.append((proto, port))
        if not out.mapped:
            out.error = "gateway refused all mappings"
    except Exception as e:               # advisory: report, never raise
        out.error = repr(e)[:200]
    return out
