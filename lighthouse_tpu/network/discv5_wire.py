"""discv5 v5.1 wire protocol — the REAL packet format.

Replaces the round-2 private framing (VERDICT r2 missing #1).  Every
byte here follows the devp2p discv5-wire spec the reference's `discv5`
crate implements (ref: beacon_node/lighthouse_network/src/discovery/
mod.rs drives it; boot_node/src/server.rs runs it standalone):

    packet        = masking-iv || masked-header || message
    masked-header = aesctr(masking-key=dest-id[:16], masking-iv, header)
    header        = static-header || authdata
    static-header = "discv5" || version(0x0001) || flag || nonce(12) ||
                    authdata-size(2, BE)

Flags: 0 = ordinary message (authdata = src-id, 32B), 1 = WHOAREYOU
(authdata = id-nonce(16) || enr-seq(8 BE)), 2 = handshake message
(authdata = src-id(32) || sig-size(1) || eph-key-size(1) ||
id-signature || eph-pubkey || record?).

Messages are AES-128-GCM sealed with the session key, nonce =
header.nonce, AD = masking-iv || header; plaintext = message-type ||
rlp(message-data).

Session keys (spec 4.5.2):
    challenge-data = masking-iv || static-header || authdata   (of the
                     WHOAREYOU packet, unmasked)
    secret    = ecdh(dest-pubkey, eph-privkey)      (compressed, 33B)
    kdf-info  = "discovery v5 key agreement" || id-A || id-B
    out       = HKDF-SHA256(secret, salt=challenge-data, info, 32)
    initiator-key, recipient-key = out[:16], out[16:]

id-signature (spec 4.5.3) = ecdsa(sha256("discovery v5 identity proof"
    || challenge-data || eph-pubkey || dest-node-id)).
"""
from __future__ import annotations

import hashlib
import os
import struct

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from . import rlp, secp256k1

PROTOCOL_ID = b"discv5"
VERSION = 1
FLAG_ORDINARY = 0
FLAG_WHOAREYOU = 1
FLAG_HANDSHAKE = 2
MAX_PACKET = 1280
MIN_PACKET = 63

ID_PROOF_TEXT = b"discovery v5 identity proof"
KDF_INFO_TEXT = b"discovery v5 key agreement"

# message types (spec 5)
MSG_PING = 0x01
MSG_PONG = 0x02
MSG_FINDNODE = 0x03
MSG_NODES = 0x04
MSG_TALKREQ = 0x05
MSG_TALKRESP = 0x06


class WireError(Exception):
    pass


def _aes_ctr(key16: bytes, iv16: bytes, data: bytes) -> bytes:
    c = Cipher(algorithms.AES(key16), modes.CTR(iv16)).encryptor()
    return c.update(data) + c.finalize()


def _static_header(flag: int, nonce12: bytes, authdata_size: int) -> bytes:
    return PROTOCOL_ID + struct.pack(">HB", VERSION, flag) + nonce12 + \
        struct.pack(">H", authdata_size)


def _mask(dest_id: bytes, header: bytes, message: bytes,
          iv: bytes | None) -> bytes:
    iv = iv if iv is not None else os.urandom(16)
    return iv + _aes_ctr(dest_id[:16], iv, header) + message


class Header:
    """Decoded packet header (+ the raw bytes AEAD binds as AD)."""

    def __init__(self, flag: int, nonce: bytes, authdata: bytes,
                 iv: bytes, raw: bytes):
        self.flag = flag
        self.nonce = nonce
        self.authdata = authdata
        self.iv = iv
        self.raw = raw          # iv || unmasked header  (the AEAD AD)

    @property
    def challenge_data(self) -> bytes:
        """For WHOAREYOU packets: what handshake crypto binds to."""
        return self.raw


def encode_ordinary(dest_id: bytes, src_id: bytes, nonce12: bytes,
                    key16: bytes, plaintext: bytes,
                    iv: bytes | None = None) -> bytes:
    header = _static_header(FLAG_ORDINARY, nonce12, 32) + src_id
    iv = iv if iv is not None else os.urandom(16)
    ad = iv + header
    ct = AESGCM(key16).encrypt(nonce12, plaintext, ad)
    return _mask(dest_id, header, ct, iv)


def encode_random(dest_id: bytes, src_id: bytes) -> bytes:
    """An ordinary packet with unreadable payload — the session poke
    that elicits WHOAREYOU (spec: random packet)."""
    header = _static_header(FLAG_ORDINARY, os.urandom(12), 32) + src_id
    return _mask(dest_id, header, os.urandom(44), None)


def encode_whoareyou(dest_id: bytes, request_nonce: bytes,
                     id_nonce: bytes, enr_seq: int,
                     iv: bytes | None = None) -> bytes:
    authdata = id_nonce + struct.pack(">Q", enr_seq)
    header = _static_header(FLAG_WHOAREYOU, request_nonce, 24) + authdata
    return _mask(dest_id, header, b"", iv)


def encode_handshake(dest_id: bytes, src_id: bytes, nonce12: bytes,
                     key16: bytes, plaintext: bytes, id_signature: bytes,
                     eph_pubkey: bytes, record_rlp: bytes | None,
                     iv: bytes | None = None) -> bytes:
    authdata = src_id + bytes([len(id_signature), len(eph_pubkey)]) + \
        id_signature + eph_pubkey + (record_rlp or b"")
    header = _static_header(FLAG_HANDSHAKE, nonce12, len(authdata)) + \
        authdata
    iv = iv if iv is not None else os.urandom(16)
    ad = iv + header
    ct = AESGCM(key16).encrypt(nonce12, plaintext, ad)
    return _mask(dest_id, header, ct, iv)


def decode_packet(local_id: bytes, data: bytes) -> tuple[Header, bytes]:
    """Unmask with our node id -> (Header, message ciphertext)."""
    if not MIN_PACKET <= len(data) <= MAX_PACKET:
        raise WireError(f"bad packet size {len(data)}")
    iv = data[:16]
    dec = Cipher(algorithms.AES(local_id[:16]), modes.CTR(iv)).decryptor()
    fixed = dec.update(data[16:16 + 23])
    if fixed[:6] != PROTOCOL_ID:
        raise WireError("bad protocol id")
    version, flag = struct.unpack_from(">HB", fixed, 6)
    if version != VERSION:
        raise WireError(f"bad version {version}")
    if flag not in (FLAG_ORDINARY, FLAG_WHOAREYOU, FLAG_HANDSHAKE):
        raise WireError(f"bad flag {flag}")
    nonce = fixed[9:21]
    (authdata_size,) = struct.unpack_from(">H", fixed, 21)
    if 16 + 23 + authdata_size > len(data):
        raise WireError("truncated authdata")
    authdata = dec.update(data[16 + 23:16 + 23 + authdata_size])
    message = data[16 + 23 + authdata_size:]
    raw = iv + fixed + authdata
    return Header(flag, nonce, authdata, iv, raw), message


def parse_handshake_authdata(authdata: bytes
                             ) -> tuple[bytes, bytes, bytes, bytes]:
    """-> (src_id, id_signature, eph_pubkey, record_rlp)."""
    if len(authdata) < 34:
        raise WireError("handshake authdata too short")
    src_id = authdata[:32]
    sig_size, key_size = authdata[32], authdata[33]
    need = 34 + sig_size + key_size
    if len(authdata) < need:
        raise WireError("handshake authdata truncated")
    sig = authdata[34:34 + sig_size]
    eph = authdata[34 + sig_size:need]
    return src_id, sig, eph, authdata[need:]


def open_message(key16: bytes, header: Header, ciphertext: bytes) -> bytes:
    return AESGCM(key16).decrypt(header.nonce, ciphertext, header.raw)


# -- handshake crypto ---------------------------------------------------------

def id_sign(priv: int, challenge_data: bytes, eph_pubkey: bytes,
            dest_id: bytes) -> bytes:
    digest = hashlib.sha256(ID_PROOF_TEXT + challenge_data + eph_pubkey
                            + dest_id).digest()
    return secp256k1.sign(priv, digest)


def id_verify(static_pub_pt, signature: bytes, challenge_data: bytes,
              eph_pubkey: bytes, dest_id: bytes) -> bool:
    digest = hashlib.sha256(ID_PROOF_TEXT + challenge_data + eph_pubkey
                            + dest_id).digest()
    return secp256k1.verify(static_pub_pt, digest, signature)


def session_keys(secret33: bytes, challenge_data: bytes,
                 initiator_id: bytes, recipient_id: bytes
                 ) -> tuple[bytes, bytes]:
    okm = HKDF(algorithm=hashes.SHA256(), length=32, salt=challenge_data,
               info=KDF_INFO_TEXT + initiator_id + recipient_id
               ).derive(secret33)
    return okm[:16], okm[16:]


# -- message codec (RLP payloads, spec 5) -------------------------------------

def enc_ping(req_id: bytes, enr_seq: int) -> bytes:
    return bytes([MSG_PING]) + rlp.encode([req_id, enr_seq])


def enc_pong(req_id: bytes, enr_seq: int, ip: str, port: int) -> bytes:
    ip_bytes = bytes(int(x) for x in ip.split("."))
    return bytes([MSG_PONG]) + rlp.encode([req_id, enr_seq, ip_bytes, port])


def enc_findnode(req_id: bytes, distances: list[int]) -> bytes:
    return bytes([MSG_FINDNODE]) + rlp.encode([req_id, list(distances)])


def enc_nodes(req_id: bytes, total: int, enr_rlps: list) -> bytes:
    """enr_rlps: decoded RLP item lists (so records nest structurally,
    matching every other implementation's NODES encoding)."""
    return bytes([MSG_NODES]) + rlp.encode([req_id, total, enr_rlps])


def enc_talkreq(req_id: bytes, protocol: bytes, request: bytes) -> bytes:
    return bytes([MSG_TALKREQ]) + rlp.encode([req_id, protocol, request])


def enc_talkresp(req_id: bytes, response: bytes) -> bytes:
    return bytes([MSG_TALKRESP]) + rlp.encode([req_id, response])


def decode_message(plaintext: bytes) -> tuple[int, list]:
    if not plaintext:
        raise WireError("empty message")
    body = rlp.decode(plaintext[1:])
    if not isinstance(body, list) or not body:
        raise WireError("message body not a list")
    return plaintext[0], body
