"""Sync manager: range sync + block lookups.

Equivalent of /root/reference/beacon_node/network/src/sync/manager.rs (:177)
with range sync batches (range_sync/) and parent lookups (block_lookups/):
compare peer status to local finality, download epoch-aligned batches of
blocks by range, import as chain segments (one batched signature check per
epoch chunk), and resolve unknown-parent gossip blocks by root.
"""
from __future__ import annotations

import threading

from ..chain.errors import PARENT_UNKNOWN, BlockError
from ..ssz import deserialize, htr, serialize

EPOCHS_PER_BATCH = 2


class SyncManager:
    # consecutive empty by_range windows tolerated per backfill call
    # before the peer is penalized and rotated
    MAX_EMPTY_WINDOWS = 64

    def __init__(self, chain, rpc, peer_manager):
        self.chain = chain
        self.rpc = rpc
        self.peers = peer_manager
        self.state = "synced"          # synced | range_syncing
        self._digest_map = None        # lazy fork-digest -> ForkName
        self._lock = threading.Lock()

    # -- range sync ----------------------------------------------------------

    MAX_INFLIGHT_BATCHES = 4    # parallel peer-pool downloads

    def _sync_peer_pool(self, min_head: int) -> list:
        """Non-banned, non-negative-score peers whose head is past
        min_head (range_sync/range.rs peer pool)."""
        return [p for p in self.peers.connected()
                if p.status is not None and p.status.head_slot > min_head
                and p.score >= 0]

    def _download_batch(self, peer_info, start: int, count: int):
        peer = self.rpc.transport.peers.get(peer_info.node_id)
        if peer is None:
            raise TimeoutError("peer gone")
        resp = self.rpc.request(peer, "beacon_blocks_by_range",
                                {"start_slot": start, "count": count})
        blocks = [self._decode_block(b) for b in resp or []]
        return [b for b in blocks if b is not None]

    def maybe_sync(self) -> int:
        """If peers are ahead, range-sync toward the best head with
        batches downloaded in PARALLEL from the peer pool and imported in
        order (range_sync/range.rs:27-40 batch pipelining; round 1 pulled
        sequentially from a single peer)."""
        local_head = self.chain.head().head_state.slot
        pool = self._sync_peer_pool(local_head)
        if not pool:
            self.state = "synced"
            return 0
        remote_head = max(p.status.head_slot for p in pool)
        self.state = "range_syncing"
        spe = self.chain.spec.preset.slots_per_epoch
        batch_slots = EPOCHS_PER_BATCH * spe
        spans = []
        start = local_head + 1
        while start <= remote_head:
            count = min(batch_slots, remote_head - start + 1)
            spans.append((start, count))
            start += count
        imported = 0
        from concurrent.futures import ThreadPoolExecutor
        workers = min(self.MAX_INFLIGHT_BATCHES, len(pool), len(spans))
        pool_ex = ThreadPoolExecutor(max_workers=max(1, workers))
        prev_peer = None            # served the batch BEFORE this one
        try:
            futures = {}
            for i, (s, c) in enumerate(spans):
                # batches must cover slots the chosen peer actually has
                eligible = [p for p in pool
                            if p.status.head_slot >= s] or pool
                peer_info = eligible[i % len(eligible)]
                futures[i] = (peer_info,
                              pool_ex.submit(self._download_batch,
                                             peer_info, s, c))
            for i in range(len(spans)):
                peer_info, fut = futures[i]
                try:
                    blocks = fut.result(timeout=20)
                except Exception:
                    self.peers.report(peer_info.node_id, "timeout")
                    # one in-order retry from a different peer
                    others = [p for p in pool
                              if p.node_id != peer_info.node_id]
                    if not others:
                        break
                    retry = others[i % len(others)]
                    try:
                        blocks = self._download_batch(retry, *spans[i])
                        peer_info = retry
                    except Exception:
                        self.peers.report(retry.node_id, "timeout")
                        break
                if blocks:
                    try:
                        imported += self.chain.process_chain_segment(blocks)
                    except BlockError as e:
                        if e.kind == PARENT_UNKNOWN and prev_peer is not None:
                            # likely the EARLIER batch was short/empty —
                            # don't ban this (possibly honest) peer for it
                            self.peers.report(prev_peer.node_id, "ignore")
                        else:
                            self.peers.report(peer_info.node_id,
                                              "bad_segment")
                        break
                # empty batches are legitimate (runs of skipped slots)
                prev_peer = peer_info
        finally:
            # a break must not wait for queued downloads to run to completion
            pool_ex.shutdown(wait=False, cancel_futures=True)
        self.state = "synced"
        return imported

    # -- backfill (checkpoint-sync history, sync/backfill_sync/mod.rs) -------

    def backfill(self, batch_slots: int | None = None) -> int:
        """Download blocks BACKWARDS from the anchor to genesis, verifying
        hash-chain linkage into the trusted anchor (historical_blocks.rs:
        signature verification is subsumed by the parent-root chain into a
        finalized root here; batched sig-recheck is a TODO). Returns blocks
        stored."""
        chain = self.chain
        anchor = chain.store.backfill_anchor()
        if anchor is None:
            return 0
        anchor_slot, expected_root = anchor
        if anchor_slot == 0:
            return 0
        peer_info = self.peers.best_peer_for_sync()
        if peer_info is None:
            return 0
        peer = self.rpc.transport.peers.get(peer_info.node_id)
        if peer is None:
            return 0
        spe = chain.spec.preset.slots_per_epoch
        batch_slots = batch_slots or EPOCHS_PER_BATCH * spe
        max_req = chain.spec.max_request_blocks
        stored = 0
        window = min(batch_slots, max_req)
        req_end = anchor_slot  # exclusive top of the next request window
        empty_windows = 0
        while anchor_slot > 0:
            start = max(0, req_end - window)
            try:
                resp = self.rpc.request(
                    peer, "beacon_blocks_by_range",
                    {"start_slot": start, "count": req_end - start})
            except (TimeoutError, RuntimeError):
                self.peers.report(peer_info.node_id, "timeout")
                break
            blocks = [b for b in (self._decode_block(x) for x in resp or [])
                      if b is not None]
            # verify the batch links into the trusted root, newest first.
            # Because every higher window came back empty, the newest block
            # of this one must be the direct parent of the link chain.
            for sb in reversed(blocks):
                root = htr(sb.message)
                if root != expected_root:
                    self.peers.report(peer_info.node_id, "bad_segment")
                    return stored
                chain.store.put_block(root, sb)
                chain.store.freezer_put_block_root(sb.message.slot, root)
                expected_root = sb.message.parent_root
                stored += 1
            if not blocks:
                # A run of skipped slots can legitimately empty a window:
                # slide the window down (growing it up to the rate-limit
                # cap) and retry.  Never ADVANCE the anchor on a bare
                # empty claim — an all-empty [0, anchor) (which must
                # contain the genesis block) or an endless run of empty
                # claims is misbehavior: penalize and rotate.
                empty_windows += 1
                if start == 0 or empty_windows > self.MAX_EMPTY_WINDOWS:
                    self.peers.report(peer_info.node_id, "empty_batch")
                    break
                req_end = start
                window = min(window * 2, max_req)
                continue
            empty_windows = 0
            window = min(batch_slots, max_req)
            anchor_slot = blocks[0].message.slot
            req_end = anchor_slot
            # complete only when the verified link chain itself reaches the
            # slot-0 genesis block (served by peers since BeaconChain
            # synthesizes + stores it)
            chain.store.set_backfill_anchor(anchor_slot, expected_root)
            if anchor_slot == 0:
                break
        return stored

    # -- block lookups -------------------------------------------------------

    def lookup_unknown_parent(self, block_root: bytes, peer_id: str,
                              max_depth: int = 16) -> int:
        """Walk parents by root until the chain connects, then import
        (block_lookups parent chains)."""
        peer = self.rpc.transport.peers.get(peer_id)
        if peer is None:
            return 0
        chain_blocks = []
        root = block_root
        for _ in range(max_depth):
            if self.chain.fork_choice.contains_block(root):
                break
            try:
                resp = self.rpc.request(peer, "beacon_blocks_by_root",
                                        {"roots": [root.hex()]})
            except (TimeoutError, RuntimeError):
                self.peers.report(peer_id, "timeout")
                return 0
            if not resp:
                return 0
            blk = self._decode_block(resp[0])
            if blk is None:
                return 0
            chain_blocks.append(blk)
            root = blk.message.parent_root
        chain_blocks.reverse()
        try:
            return self.chain.process_chain_segment(chain_blocks)
        except BlockError:
            self.peers.report(peer_id, "bad_segment")
            return 0

    def _decode_block(self, hex_payload: str):
        try:
            raw = bytes.fromhex(hex_payload)
            dmap = self._digest_map
            if dmap is None:
                dmap = self._digest_map = digest_to_fork(self.chain)
            cls = self.chain.T.SignedBeaconBlock[dmap[raw[:4]]]
            return deserialize(cls.ssz_type, raw[4:])
        except Exception:
            return None


def digest_to_fork(chain) -> dict:
    """4-byte fork-digest -> ForkName, for the chunk context bytes the
    real req/resp protocol leads block chunks with
    (rpc/codec/ssz_snappy.rs context_bytes)."""
    from ..specs.chain_spec import ForkName, compute_fork_digest
    return {compute_fork_digest(chain.spec.fork_version(f),
                                chain.genesis_validators_root): f
            for f in ForkName}


def encode_block(signed_block, chain) -> str:
    """fork-digest context (4B) + SSZ, as one response chunk payload."""
    from ..specs.chain_spec import compute_fork_digest
    digest = compute_fork_digest(
        chain.spec.fork_version(signed_block.fork_name),
        chain.genesis_validators_root)
    return (digest
            + serialize(type(signed_block).ssz_type, signed_block)).hex()
