"""The libp2p transport stack — REAL wire protocols end to end.

Connection upgrade path, exactly as the reference builds it
(beacon_node/lighthouse_network/src/service/utils.rs:80-130
build_transport):

    TCP
    └─ multistream-select          "/noise"
       └─ Noise XX                 (noise_xx.py — identity-certified)
          └─ multistream-select    "/yamux/1.0.0"   (inside noise frames)
             └─ yamux session      (yamux.py — SYN/ACK streams, windows)
                ├─ /meshsub/1.2.0 streams: varint-delimited gossipsub
                │    RPC protobufs (gossipsub_pb.py), one long-lived
                │    outbound stream per peer
                └─ /eth2/beacon_chain/req/* streams: one per request
                     (rpc.py — SSZ-snappy with result/context bytes)

Peers are identified by their libp2p peer id (identity multihash of the
secp256k1 identity key, authenticated inside the noise handshake).
"""
from __future__ import annotations

import secrets
import socket
import threading

from ..utils.threads import ThreadGroup
from . import multistream as ms
from . import secp256k1
from .gossipsub_pb import unframe
from .noise_xx import (
    HAVE_CRYPTOGRAPHY, NoiseError, NoiseSession, initiator_handshake,
    peer_id_from_pubkey, responder_handshake,
)
from .plaintext import plaintext_handshake
from .yamux import Session, Stream, StreamIO, YamuxError

PROTO_NOISE = "/noise"
PROTO_PLAINTEXT = "/plaintext/2.0.0"
PROTO_YAMUX = "/yamux/1.0.0"
PROTO_MESHSUB = ["/meshsub/1.2.0", "/meshsub/1.1.0"]


class NodeIdentity:
    """secp256k1 libp2p identity keypair."""

    def __init__(self, priv: int | None = None):
        self.priv = priv or int.from_bytes(secrets.token_bytes(32), "big") \
            % (secp256k1.N - 1) + 1
        self.pub = secp256k1.compress(secp256k1.pubkey(self.priv))
        self.peer_id = peer_id_from_pubkey(self.pub)
        self.node_id = self.peer_id.hex()


class _NoiseIO:
    """Byte-stream view over a NoiseSession (for multistream + yamux)."""

    def __init__(self, sock, session: NoiseSession):
        self.sock = sock
        self.session = session
        self._buf = bytearray()
        self._wlock = threading.Lock()

    def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._buf += self.session.recv(self.sock)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def recv_any(self) -> bytes:
        """One noise frame's plaintext (+ any buffered leftovers)."""
        if self._buf:
            out = bytes(self._buf)
            self._buf.clear()
            return out
        return self.session.recv(self.sock)

    def write(self, data: bytes) -> None:
        with self._wlock:
            self.session.send(self.sock, data)


class Peer:
    """One upgraded connection: noise-authenticated, yamux-multiplexed."""

    def __init__(self, transport: "Transport", sock, addr,
                 io: _NoiseIO, outbound: bool):
        self.transport = transport
        self.sock = sock
        self.addr = addr
        self.io = io
        self.outbound = outbound
        self.node_id = io.session.remote_peer_id.hex()
        self.alive = True
        self.mux = Session(io.write, initiator=outbound,
                           on_stream=self._on_inbound_stream)
        self._gossip_out: Stream | None = None
        self._gossip_lock = threading.Lock()
        self._gossip_in_buf = bytearray()

    # -- outbound streams ------------------------------------------------------

    def open_protocol(self, protocols: list[str],
                      timeout: float = 10.0) -> tuple[Stream, str]:
        st = self.mux.open_stream()
        proto = ms.negotiate_out(StreamIO(st, timeout), protocols)
        return st, proto

    def send_gossip_rpc(self, framed: bytes) -> None:
        """Write one varint-framed gossipsub RPC on the persistent
        meshsub stream (opened lazily)."""
        with self._gossip_lock:
            if self._gossip_out is None or self._gossip_out.reset:
                try:
                    self._gossip_out, _ = self.open_protocol(PROTO_MESHSUB)
                except (ms.MultistreamError, YamuxError, OSError):
                    self._gossip_out = None
                    return
            try:
                self._gossip_out.write(framed)
            except (YamuxError, OSError):
                self._gossip_out = None

    # -- inbound streams -------------------------------------------------------

    def _on_inbound_stream(self, stream: Stream) -> None:
        if not self.alive:
            return          # close() raced the mux callback
        self.transport._threads.spawn(self._serve_stream, stream,
                                      name="peer.serve_stream")

    def _serve_stream(self, stream: Stream) -> None:
        try:
            supported = PROTO_MESHSUB + self.transport.rpc_protocols
            proto = ms.negotiate_in(StreamIO(stream), supported)
        except (ms.MultistreamError, YamuxError, OSError):
            try:
                stream.rst()
            except (YamuxError, OSError):
                pass            # socket already gone at teardown
            return
        if proto in PROTO_MESHSUB:
            self._gossip_read_loop(stream)
        else:
            try:
                self.transport.on_rpc_stream(self, proto, stream)
            except Exception:
                import logging
                logging.getLogger("lighthouse_tpu.network").exception(
                    "rpc stream handler failed (peer %s)", self.node_id)
                try:
                    stream.rst()
                except (YamuxError, OSError):
                    pass

    def _gossip_read_loop(self, stream: Stream) -> None:
        from .gossipsub_pb import MAX_RPC_SIZE, PbError
        buf = bytearray()
        while self.alive and not stream.reset:
            try:
                chunk = stream.read(timeout=30.0)
            except YamuxError:
                return
            if not chunk:
                if stream.recv_closed:
                    return
                continue
            buf += chunk
            if len(buf) > MAX_RPC_SIZE + 10:
                stream.rst()       # oversized frame: peer misbehavior
                return
            while True:
                try:
                    rpc = unframe(buf)
                except PbError:
                    stream.rst()   # malformed frame: stop reading them
                    return
                if rpc is None:
                    break
                try:
                    self.transport.on_gossip_rpc(self, rpc)
                except Exception:
                    import logging
                    logging.getLogger("lighthouse_tpu.network").exception(
                        "gossip handler failed (peer %s)", self.node_id)

    def close(self) -> None:
        self.alive = False
        try:
            self.mux.goaway()
        except Exception:
            pass
        try:
            # close() alone does not wake a recv() blocked in another
            # thread; shutdown() delivers EOF to it first
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class Transport:
    """Listener + dialer; hands upgraded Peers to `on_peer`, gossipsub
    RPCs to `on_gossip_rpc(peer, rpc)`, req/resp streams to
    `on_rpc_stream(peer, protocol, stream)`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 identity: NodeIdentity | None = None,
                 security: str | None = None):
        """`security`: "noise" | "plaintext" | None (auto: noise when the
        cryptography package is available, else the plaintext fallback).
        Both sides of a connection must agree — the chosen protocol is
        what multistream offers, so a mismatch fails the negotiation
        instead of silently downgrading."""
        if security is None:
            security = "noise" if HAVE_CRYPTOGRAPHY else "plaintext"
        if security == "noise" and not HAVE_CRYPTOGRAPHY:
            raise NoiseError("noise security requires the 'cryptography' "
                             "package; use security='plaintext'")
        if security not in ("noise", "plaintext"):
            raise ValueError(f"unknown security mode {security!r}")
        self.security = security
        self.identity = identity or NodeIdentity()
        self.node_id = self.identity.node_id
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind((host, port))
        self.listener.listen(64)
        self.port = self.listener.getsockname()[1]
        self.host = host
        self.on_peer = lambda peer: None
        self.on_gossip_rpc = lambda peer, rpc: None
        self.on_rpc_stream = lambda peer, protocol, stream: None
        self.on_disconnect = lambda peer: None
        #: protocol ids served on inbound streams (set by RpcHandler)
        self.rpc_protocols: list[str] = []
        self.peers: dict[str, Peer] = {}
        self._stop = False
        self._threads = ThreadGroup("transport")

    def start(self) -> None:
        self._threads.spawn(self._accept_loop, name="transport.accept")

    def stop(self) -> None:
        # close the sockets first (unblocks accept/read threads), then
        # join them so no transport thread outlives the transport
        self._stop = True
        try:
            self.listener.close()
        except OSError:
            pass
        for p in list(self.peers.values()):
            p.close()
        self._threads.join_all(timeout=2)

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                sock, addr = self.listener.accept()
            except OSError:
                return
            self._threads.spawn(self._upgrade_in, sock, addr,
                                name="transport.upgrade_in")

    # -- the upgrade path ------------------------------------------------------

    def _security_proto(self) -> str:
        return PROTO_NOISE if self.security == "noise" else PROTO_PLAINTEXT

    def _upgrade_in(self, sock, addr) -> None:
        try:
            sock.settimeout(10)
            proto = ms.negotiate_in(sock, [self._security_proto()])
            session = (responder_handshake(sock, self.identity.priv)
                       if proto == PROTO_NOISE
                       else plaintext_handshake(sock, self.identity.priv))
            io = _NoiseIO(sock, session)
            ms.negotiate_in(io, [PROTO_YAMUX])
            sock.settimeout(None)
            self._register(Peer(self, sock, addr, io, outbound=False))
        except (OSError, ValueError, NoiseError, ms.MultistreamError):
            sock.close()

    def dial(self, host: str, port: int) -> Peer | None:
        try:
            sock = socket.create_connection((host, port), timeout=5)
            sock.settimeout(10)
            proto = ms.negotiate_out(sock, [self._security_proto()])
            session = (initiator_handshake(sock, self.identity.priv)
                       if proto == PROTO_NOISE
                       else plaintext_handshake(sock, self.identity.priv))
            io = _NoiseIO(sock, session)
            ms.negotiate_out(io, [PROTO_YAMUX])
            sock.settimeout(None)
            peer = Peer(self, sock, (host, port), io, outbound=True)
            self._register(peer)
            return peer
        except (OSError, ValueError, NoiseError, ms.MultistreamError):
            return None

    def _register(self, peer: Peer) -> None:
        if self._stop:
            peer.close()    # accept/dial raced stop(): no thread may
            return          # spawn after join_all has run
        self.peers[peer.node_id] = peer
        self._threads.spawn(self._read_loop, peer,
                            name="transport.read_loop")
        self.on_peer(peer)

    def _read_loop(self, peer: Peer) -> None:
        """Pump noise plaintext into the yamux session."""
        try:
            while peer.alive and not self._stop:
                peer.mux.on_bytes(peer.io.recv_any())
                if peer.mux.closed:
                    break
        except (OSError, NoiseError, YamuxError):
            pass
        peer.alive = False
        # a redialed peer may have replaced this entry — only pop ourselves
        if self.peers.get(peer.node_id) is peer:
            self.peers.pop(peer.node_id, None)
            self.on_disconnect(peer)
