"""Framed TCP transport.

Frames: [u32 len][u8 kind][payload]. kind: 0 = handshake, 1 = gossip,
2 = rpc request, 3 = rpc response. Each peer connection runs a reader
thread dispatching into the owning service's handlers.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import uuid


class Peer:
    def __init__(self, sock: socket.socket, addr, node_id: str,
                 outbound: bool):
        self.sock = sock
        self.addr = addr
        self.node_id = node_id
        self.outbound = outbound
        self._send_lock = threading.Lock()
        self.alive = True

    def send_frame(self, kind: int, payload: bytes) -> None:
        frame = struct.pack("<IB", len(payload) + 1, kind) + payload
        with self._send_lock:
            try:
                self.sock.sendall(frame)
            except OSError:
                self.alive = False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class Transport:
    """Listener + dialer; hands connected Peers to `on_peer`, frames to
    `on_frame(peer, kind, payload)`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 node_id: str | None = None):
        self.node_id = node_id or uuid.uuid4().hex[:16]
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind((host, port))
        self.listener.listen(64)
        self.port = self.listener.getsockname()[1]
        self.host = host
        self.on_peer = lambda peer: None
        self.on_frame = lambda peer, kind, payload: None
        self.on_disconnect = lambda peer: None
        self.peers: dict[str, Peer] = {}
        self._stop = False

    def start(self) -> None:
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stop = True
        try:
            self.listener.close()
        except OSError:
            pass
        for p in list(self.peers.values()):
            p.close()

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                sock, addr = self.listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handshake_in,
                             args=(sock, addr), daemon=True).start()

    def _handshake_in(self, sock, addr) -> None:
        try:
            kind, payload = _read_frame(sock)
            if kind != 0:
                sock.close()
                return
            hello = json.loads(payload)
            sock.sendall(_frame(0, json.dumps(
                {"node_id": self.node_id}).encode()))
            peer = Peer(sock, addr, hello["node_id"], outbound=False)
            self._register(peer)
        except (OSError, ValueError, KeyError):
            sock.close()

    def dial(self, host: str, port: int) -> Peer | None:
        try:
            sock = socket.create_connection((host, port), timeout=5)
            sock.sendall(_frame(0, json.dumps(
                {"node_id": self.node_id}).encode()))
            kind, payload = _read_frame(sock)
            if kind != 0:
                sock.close()
                return None
            hello = json.loads(payload)
            peer = Peer(sock, (host, port), hello["node_id"], outbound=True)
            self._register(peer)
            return peer
        except (OSError, ValueError, KeyError):
            return None

    def _register(self, peer: Peer) -> None:
        self.peers[peer.node_id] = peer
        threading.Thread(target=self._read_loop, args=(peer,),
                         daemon=True).start()
        self.on_peer(peer)

    def _read_loop(self, peer: Peer) -> None:
        import logging
        try:
            while peer.alive and not self._stop:
                kind, payload = _read_frame(peer.sock)
                try:
                    self.on_frame(peer, kind, payload)
                except Exception:
                    # a handler bug must not kill the reader / skip cleanup
                    logging.getLogger("lighthouse_tpu.network").exception(
                        "frame handler failed (peer %s)", peer.node_id)
        except (OSError, ValueError):
            pass
        peer.alive = False
        # a redialed peer may have replaced this entry — only pop ourselves
        if self.peers.get(peer.node_id) is peer:
            self.peers.pop(peer.node_id, None)
            self.on_disconnect(peer)


def _frame(kind: int, payload: bytes) -> bytes:
    return struct.pack("<IB", len(payload) + 1, kind) + payload


def _read_frame(sock) -> tuple[int, bytes]:
    hdr = _read_exact(sock, 5)
    (length, kind) = struct.unpack("<IB", hdr)
    if length > 64 * 1024 * 1024:
        raise ValueError("frame too large")
    payload = _read_exact(sock, length - 1)
    return kind, payload


def _read_exact(sock, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise OSError("connection closed")
        out += chunk
    return out
