"""Encrypted framed TCP transport.

Connection setup runs the noise-like handshake (network/noise.py): peers
are identified by sha256(static_pub)[:8] — an AUTHENTICATED id, not a
self-claimed one.  After the handshake every frame is one AEAD envelope:

    [u32 ciphertext_len][ciphertext]
    plaintext = [u8 kind][payload]        kind: 1 gossip, 2 rpc-req,
                                                3 rpc-resp

Per-direction nonce counters + transcript-bound associated data give
ordering/splicing protection; a tampered frame fails AEAD and drops the
connection (ref role: lighthouse_network/src/service/utils.rs noise XX).
"""
from __future__ import annotations

import socket
import struct
import threading

from .noise import (
    HandshakeError, NodeIdentity, initiator_handshake, node_id_of,
    responder_handshake,
)

# Sealed-frame cap: must fit a max-size gossip payload AFTER snappy's
# worst-case ~0.8% expansion on incompressible data, and a full
# max_request_blocks by_range response packed into one frame.
MAX_FRAME = 64 * 1024 * 1024 + 4096


class Peer:
    def __init__(self, sock: socket.socket, addr, node_id: str,
                 channel, outbound: bool):
        self.sock = sock
        self.addr = addr
        self.node_id = node_id
        self.channel = channel
        self.outbound = outbound
        self._send_lock = threading.Lock()
        self.alive = True

    def send_frame(self, kind: int, payload: bytes) -> None:
        with self._send_lock:
            try:
                sealed = self.channel.seal(bytes([kind]) + payload)
                self.sock.sendall(struct.pack("<I", len(sealed)) + sealed)
            except OSError:
                self.alive = False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class Transport:
    """Listener + dialer; hands connected Peers to `on_peer`, frames to
    `on_frame(peer, kind, payload)`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 identity: NodeIdentity | None = None):
        self.identity = identity or NodeIdentity()
        self.node_id = self.identity.node_id
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind((host, port))
        self.listener.listen(64)
        self.port = self.listener.getsockname()[1]
        self.host = host
        self.on_peer = lambda peer: None
        self.on_frame = lambda peer, kind, payload: None
        self.on_disconnect = lambda peer: None
        self.peers: dict[str, Peer] = {}
        self._stop = False

    def start(self) -> None:
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stop = True
        try:
            self.listener.close()
        except OSError:
            pass
        for p in list(self.peers.values()):
            p.close()

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                sock, addr = self.listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handshake_in,
                             args=(sock, addr), daemon=True).start()

    def _handshake_in(self, sock, addr) -> None:
        try:
            sock.settimeout(10)
            channel, remote_static = responder_handshake(
                sock.sendall, lambda n: _read_exact(sock, n), self.identity)
            sock.settimeout(None)
            peer = Peer(sock, addr, node_id_of(remote_static), channel,
                        outbound=False)
            self._register(peer)
        except (OSError, ValueError, HandshakeError):
            sock.close()

    def dial(self, host: str, port: int) -> Peer | None:
        try:
            sock = socket.create_connection((host, port), timeout=5)
            sock.settimeout(10)
            channel, remote_static = initiator_handshake(
                sock.sendall, lambda n: _read_exact(sock, n), self.identity)
            sock.settimeout(None)
            peer = Peer(sock, (host, port), node_id_of(remote_static),
                        channel, outbound=True)
            self._register(peer)
            return peer
        except (OSError, ValueError, HandshakeError):
            return None

    def _register(self, peer: Peer) -> None:
        self.peers[peer.node_id] = peer
        threading.Thread(target=self._read_loop, args=(peer,),
                         daemon=True).start()
        self.on_peer(peer)

    def _read_loop(self, peer: Peer) -> None:
        import logging
        try:
            while peer.alive and not self._stop:
                hdr = _read_exact(peer.sock, 4)
                (length,) = struct.unpack("<I", hdr)
                if length > MAX_FRAME:
                    raise ValueError("frame too large")
                sealed = _read_exact(peer.sock, length)
                plain = peer.channel.open(sealed)  # tampering -> drop conn
                kind, payload = plain[0], plain[1:]
                try:
                    self.on_frame(peer, kind, payload)
                except Exception:
                    # a handler bug must not kill the reader / skip cleanup
                    logging.getLogger("lighthouse_tpu.network").exception(
                        "frame handler failed (peer %s)", peer.node_id)
        except (OSError, ValueError, HandshakeError, IndexError):
            pass
        peer.alive = False
        # a redialed peer may have replaced this entry — only pop ourselves
        if self.peers.get(peer.node_id) is peer:
            self.peers.pop(peer.node_id, None)
            self.on_disconnect(peer)


def _read_exact(sock, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise OSError("connection closed")
        out += chunk
    return out
