"""Noise-like authenticated encryption for the p2p transport.

An XX-pattern-inspired handshake over X25519 + HKDF-SHA256 +
ChaCha20-Poly1305 (all from the `cryptography` package).  Not
wire-compatible with libp2p-noise (that would require the exact Noise
state machine + protobuf payloads); it provides the same properties the
reference gets from it (ref: lighthouse_network/src/service/utils.rs
build_transport — noise XX + yamux):

- ephemeral-ephemeral secrecy (forward secrecy per connection),
- mutual STATIC-key authentication: the responder proves possession of
  its static key by completing message 4 (final keys depend on es), the
  initiator by message 5 (final keys depend on se),
- peer ids DERIVED from the authenticated static key (sha256(pub)[:8]),
  so a peer cannot claim another's id,
- every transport frame AEAD-sealed with per-direction nonce counters
  and the handshake transcript hash bound as associated data.

Handshake (h = rolling sha256 transcript):
  m1  I->R: e_i
  m2  R->I: e_r || Enc(k_ee;     s_r_pub, ad=h)
  m3  I->R:        Enc(k_ee_es;  s_i_pub, ad=h)
  final: k_i2r, k_r2i = HKDF(ee || es || se, info=h)
  m4  R->I: Enc(k_r2i; "fin", ad=h)     (authenticates R)
  m5  I->R: Enc(k_i2r; "fin", ad=h)     (authenticates I)
"""
from __future__ import annotations

import hashlib
import struct

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF
from cryptography.hazmat.primitives import hashes


class HandshakeError(Exception):
    pass


def _hkdf(key_material: bytes, info: bytes, length: int = 32) -> bytes:
    return HKDF(algorithm=hashes.SHA256(), length=length, salt=b"",
                info=info).derive(key_material)


def _pub_bytes(priv: X25519PrivateKey) -> bytes:
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )
    return priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)


def _raw_pub(data: bytes) -> X25519PublicKey:
    return X25519PublicKey.from_public_bytes(data)


class NodeIdentity:
    """Stable static keypair; node_id is derived from (and authenticated
    by) the public key."""

    def __init__(self, static_priv: bytes | None = None):
        self.key = (X25519PrivateKey.from_private_bytes(static_priv)
                    if static_priv else X25519PrivateKey.generate())
        self.pub = _pub_bytes(self.key)
        self.node_id = node_id_of(self.pub)


def node_id_of(static_pub: bytes) -> str:
    return hashlib.sha256(static_pub).digest()[:8].hex()


class SecureChannel:
    """Post-handshake AEAD framing: seal/open with counter nonces."""

    def __init__(self, k_send: bytes, k_recv: bytes, transcript: bytes):
        self._send = ChaCha20Poly1305(k_send)
        self._recv = ChaCha20Poly1305(k_recv)
        self._ad = transcript
        self._ns = 0
        self._nr = 0

    @staticmethod
    def _nonce(n: int) -> bytes:
        return b"\x00\x00\x00\x00" + struct.pack("<Q", n)

    def seal(self, plaintext: bytes) -> bytes:
        n = self._ns
        self._ns += 1
        return self._send.encrypt(self._nonce(n), plaintext, self._ad)

    def open(self, ciphertext: bytes) -> bytes:
        n = self._nr
        self._nr += 1
        try:
            return self._recv.decrypt(self._nonce(n), ciphertext, self._ad)
        except Exception as e:
            raise HandshakeError(f"AEAD open failed: {e}") from None


def _mix(h: bytes, data: bytes) -> bytes:
    return hashlib.sha256(h + data).digest()


_PROTO = b"lighthouse-tpu-noise-v1"


def initiator_handshake(sock_send, sock_recv, identity: NodeIdentity
                        ) -> tuple[SecureChannel, bytes]:
    """Returns (channel, remote_static_pub).  sock_send(bytes)/
    sock_recv(n)->bytes are blocking exact-IO callables."""
    e = X25519PrivateKey.generate()
    h = hashlib.sha256(_PROTO).digest()
    m1 = _pub_bytes(e)
    sock_send(m1)
    h = _mix(h, m1)

    m2 = sock_recv(32 + 48)
    e_r_pub, enc_sr = m2[:32], m2[32:]
    ee = e.exchange(_raw_pub(e_r_pub))
    k_ee = _hkdf(ee, b"k_ee" + h)
    try:
        s_r_pub = ChaCha20Poly1305(k_ee).decrypt(b"\x00" * 12, enc_sr, h)
    except Exception:
        raise HandshakeError("responder static decrypt failed") from None
    h = _mix(h, m2)

    es = e.exchange(_raw_pub(s_r_pub))
    k3 = _hkdf(ee + es, b"k_ee_es" + h)
    m3 = ChaCha20Poly1305(k3).encrypt(b"\x00" * 12, identity.pub, h)
    sock_send(m3)
    h = _mix(h, m3)

    se = identity.key.exchange(_raw_pub(e_r_pub))
    k_i2r = _hkdf(ee + es + se, b"i2r" + h)
    k_r2i = _hkdf(ee + es + se, b"r2i" + h)
    ch = SecureChannel(k_i2r, k_r2i, h)

    fin_r = sock_recv(3 + 16)
    try:
        if ChaCha20Poly1305(k_r2i).decrypt(b"\xff" * 12, fin_r, h) != b"fin":
            raise HandshakeError("bad responder fin")
    except HandshakeError:
        raise
    except Exception:
        raise HandshakeError("responder fin failed") from None
    fin_i = ChaCha20Poly1305(k_i2r).encrypt(b"\xff" * 12, b"fin", h)
    sock_send(fin_i)
    return ch, s_r_pub


def responder_handshake(sock_send, sock_recv, identity: NodeIdentity
                        ) -> tuple[SecureChannel, bytes]:
    e = X25519PrivateKey.generate()
    h = hashlib.sha256(_PROTO).digest()
    m1 = sock_recv(32)
    h = _mix(h, m1)
    ee = e.exchange(_raw_pub(m1))
    e_r_pub = _pub_bytes(e)
    # the initiator derives k_ee with the transcript BEFORE m2 is mixed
    k_ee = _hkdf(ee, b"k_ee" + h)
    enc_sr = ChaCha20Poly1305(k_ee).encrypt(b"\x00" * 12, identity.pub, h)
    m2 = e_r_pub + enc_sr
    sock_send(m2)
    h = _mix(h, m2)

    m3 = sock_recv(32 + 16)
    es = identity.key.exchange(_raw_pub(m1))
    k3 = _hkdf(ee + es, b"k_ee_es" + h)
    try:
        s_i_pub = ChaCha20Poly1305(k3).decrypt(b"\x00" * 12, m3, h)
    except Exception:
        raise HandshakeError("initiator static decrypt failed") from None
    h = _mix(h, m3)

    se = e.exchange(_raw_pub(s_i_pub))
    k_i2r = _hkdf(ee + es + se, b"i2r" + h)
    k_r2i = _hkdf(ee + es + se, b"r2i" + h)
    ch = SecureChannel(k_r2i, k_i2r, h)   # responder sends on r2i

    fin_r = ChaCha20Poly1305(k_r2i).encrypt(b"\xff" * 12, b"fin", h)
    sock_send(fin_r)
    fin_i = sock_recv(3 + 16)
    try:
        if ChaCha20Poly1305(k_i2r).decrypt(b"\xff" * 12, fin_i, h) != b"fin":
            raise HandshakeError("bad initiator fin")
    except HandshakeError:
        raise
    except Exception:
        raise HandshakeError("initiator fin failed") from None
    return ch, s_i_pub
