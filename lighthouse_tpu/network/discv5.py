"""discv5 v5.1 UDP node discovery over the REAL wire protocol.

Round-2's struct-packed dialect is gone (VERDICT r2 missing #1): records
are EIP-778 RLP ENRs (`enr.py`), packets are masked discv5 v5.1 frames,
sessions are established by the spec WHOAREYOU handshake with
id-signatures and HKDF session keys (`discv5_wire.py`), and messages are
the spec RLP payloads (PING/PONG/FINDNODE/NODES).

Service behavior mirrors the reference's discovery stack
(beacon_node/lighthouse_network/src/discovery/mod.rs — subnet predicate
queries; discovery/enr.rs — eth2/attnets/syncnets fields;
boot_node/src/server.rs — standalone bootnode): a Kademlia XOR routing
table with k-buckets, PING liveness, recursive FINDNODE lookups, and
attestation/sync-committee subnet peer discovery.
"""
from __future__ import annotations

import os
import secrets
import socket
import threading

from cryptography.exceptions import InvalidTag

from ..utils.threads import ThreadGroup
from . import discv5_wire as wire
from . import rlp, secp256k1
from .enr import Enr, EnrError

K_BUCKET_SIZE = 16          # spec k
LOOKUP_PARALLELISM = 3      # spec alpha
REQUEST_TIMEOUT = 2.0
#: a signed ENR with eth2/attnets/syncnets is ~190 bytes of RLP; 4 per
#: NODES message stays beneath the 1280-byte packet bound
MAX_NODES_PER_RESPONSE = 4
MAX_PENDING_OUT = 8         # queued messages per address awaiting session


class Discv5Error(Exception):
    pass


def attnets_int(enr: Enr) -> int:
    """Attestation-subnet bitfield as an int (Bitvector[64] bit order)."""
    return int.from_bytes(enr.attnets() or b"\x00" * 8, "little")


def syncnets_int(enr: Enr) -> int:
    return int.from_bytes(enr.syncnets() or b"\x00", "little")


def enr_addr(enr: Enr) -> tuple[str, int]:
    return (enr.ip() or "127.0.0.1", enr.udp() or 0)


def log2_distance(a: bytes, b: bytes) -> int:
    """0 for identical ids, else 1 + floor(log2(a xor b))."""
    x = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return x.bit_length()


class LocalEnr:
    """Our own signed record; every mutation bumps seq and re-signs."""

    def __init__(self, ip: str, udp_port: int, tcp_port: int = 0,
                 key: int | None = None):
        self.key = key or int.from_bytes(secrets.token_bytes(32), "big") \
            % (secp256k1.N - 1) + 1
        self.seq = 0
        self._fields = dict(ip=ip, udp=udp_port,
                            tcp=tcp_port or None)
        self.record: Enr = None  # set by _bump
        self._bump()

    def _bump(self) -> None:
        self.seq += 1
        rec = Enr(seq=self.seq).set_fields(**self._fields)
        self.record = rec.sign(self.key)

    def set_attnets(self, bitfield: int) -> None:
        self._fields["attnets"] = bitfield.to_bytes(8, "little")
        self._bump()

    def set_syncnets(self, bitfield: int) -> None:
        self._fields["syncnets"] = bitfield.to_bytes(1, "little")
        self._bump()

    def set_eth2(self, fork_digest: bytes) -> None:
        self._fields["eth2"] = fork_digest
        self._bump()

    def set_quic(self, port: int) -> None:
        self._fields["quic"] = port
        self._bump()

    @property
    def node_id(self) -> bytes:
        return self.record.node_id


class KBuckets:
    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self.buckets: list[list[Enr]] = [[] for _ in range(257)]
        self._lock = threading.Lock()

    def update(self, enr: Enr) -> None:
        nid = enr.node_id
        if nid == self.local_id:
            return
        d = log2_distance(self.local_id, nid)
        with self._lock:
            bucket = self.buckets[d]
            for i, e in enumerate(bucket):
                if e.node_id == nid:
                    if enr.seq >= e.seq:
                        bucket.pop(i)
                        bucket.append(enr)   # move to tail (most recent)
                    return
            if len(bucket) < K_BUCKET_SIZE:
                bucket.append(enr)
            # full bucket: drop (liveness eviction happens via remove())

    def remove(self, node_id: bytes) -> None:
        d = log2_distance(self.local_id, node_id)
        with self._lock:
            self.buckets[d] = [e for e in self.buckets[d]
                               if e.node_id != node_id]

    def at_distance(self, d: int) -> list[Enr]:
        with self._lock:
            return list(self.buckets[d]) if 0 <= d <= 256 else []

    def closest(self, target: bytes, limit: int = K_BUCKET_SIZE
                ) -> list[Enr]:
        with self._lock:
            all_enrs = [e for b in self.buckets for e in b]
        all_enrs.sort(key=lambda e: int.from_bytes(e.node_id, "big")
                      ^ int.from_bytes(target, "big"))
        return all_enrs[:limit]

    def by_id(self, node_id: bytes) -> Enr | None:
        d = log2_distance(self.local_id, node_id)
        with self._lock:
            for e in self.buckets[d]:
                if e.node_id == node_id:
                    return e
        return None

    def all(self) -> list[Enr]:
        with self._lock:
            return [e for b in self.buckets for e in b]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(b) for b in self.buckets)


class _Session:
    """Established session keys for one peer address."""

    def __init__(self, write_key: bytes, read_key: bytes, peer_id: bytes):
        self.write_key = write_key
        self.read_key = read_key
        self.peer_id = peer_id


class _Challenge:
    """State we keep after sending WHOAREYOU (spec: challenge record)."""

    def __init__(self, challenge_data: bytes, src_id: bytes):
        self.challenge_data = challenge_data
        self.src_id = src_id


class Discv5:
    """One UDP socket, a routing table, and the request state machine."""

    def __init__(self, ip: str = "127.0.0.1", port: int = 0,
                 tcp_port: int = 0, key: int | None = None,
                 bootnodes: list[Enr] | None = None):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))
        self.sock.settimeout(0.25)
        self.ip, self.port = self.sock.getsockname()
        self.local_enr = LocalEnr(self.ip, self.port, tcp_port, key)
        self.table = KBuckets(self.local_enr.node_id)
        self.sessions: dict[tuple, _Session] = {}
        self.pending_challenges: dict[tuple, _Challenge] = {}
        self.pending_out: dict[tuple, list[bytes]] = {}   # awaiting session
        self.requests: dict[bytes, dict] = {}             # req_id -> state
        self._lock = threading.Lock()
        self._running = False
        self._thread = None
        self._threads = ThreadGroup("discv5")
        self.bootnodes = list(bootnodes or [])
        for b in self.bootnodes:
            self.table.update(b)

    @property
    def node_id(self) -> bytes:
        return self.local_enr.node_id

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._running = True
        with self._lock:
            if self._thread is not None:    # idempotent: one pump only
                return
            self._thread = threading.Thread(target=self._recv_loop,
                                            daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._running = False
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=2)
        self._threads.join_all(timeout=2)
        self.sock.close()

    # -- packet pump ---------------------------------------------------------

    def _recv_loop(self) -> None:
        while self._running:
            try:
                data, addr = self.sock.recvfrom(wire.MAX_PACKET)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._handle_packet(data, addr)
            except (Discv5Error, wire.WireError, EnrError, rlp.RlpError,
                    InvalidTag, IndexError, ValueError, KeyError):
                continue   # malformed / unauthenticated: drop silently

    def _handle_packet(self, data: bytes, addr) -> None:
        header, ct = wire.decode_packet(self.node_id, data)
        if header.flag == wire.FLAG_ORDINARY:
            src_id = header.authdata
            sess = self.sessions.get(addr)
            if sess is None:
                self._challenge(addr, header, src_id)
                return
            try:
                msg = wire.open_message(sess.read_key, header, ct)
            except InvalidTag:
                # stale session (peer restarted): drop it and re-challenge
                del self.sessions[addr]
                self._challenge(addr, header, src_id)
                return
            self._handle_message(msg, addr)
        elif header.flag == wire.FLAG_WHOAREYOU:
            self._complete_handshake(header, addr)
        elif header.flag == wire.FLAG_HANDSHAKE:
            self._accept_handshake(header, ct, addr)

    # -- handshake -----------------------------------------------------------

    def _challenge(self, addr, header, src_id: bytes) -> None:
        """Issue a WHOAREYOU challenge (bounded pending state)."""
        if len(self.pending_challenges) > 1024:
            self.pending_challenges.pop(next(iter(self.pending_challenges)))
        id_nonce = os.urandom(16)
        known = self.table.by_id(src_id)
        pkt = wire.encode_whoareyou(src_id, header.nonce, id_nonce,
                                    known.seq if known else 0)
        # reconstruct challenge-data exactly as the peer will see it
        # (iv || static-header || authdata of OUR whoareyou packet)
        chal_header, _ = wire.decode_packet(src_id, pkt)
        self.pending_challenges[addr] = _Challenge(
            chal_header.challenge_data, src_id)
        self.sock.sendto(pkt, addr)

    def _complete_handshake(self, header, addr) -> None:
        """We got challenged: prove our identity and establish keys."""
        # Only honor a WHOAREYOU when we actually have traffic in flight
        # toward that address (queued messages or an outstanding request):
        # an unsolicited challenge from a spoofed source must not be able
        # to evict a live session (session-churn DoS).
        with self._lock:
            queued = self.pending_out.pop(addr, [])
            outstanding = any(st.get("addr") == addr
                              for st in self.requests.values())
        if not queued and not outstanding:
            return
        # being challenged means the peer cannot decrypt us: our session
        # is stale (peer restarted) — drop it so requests re-handshake
        self.sessions.pop(addr, None)
        if not queued:
            return   # in-flight request times out; its retry re-queues
        dest = self._enr_for_addr(addr)
        if dest is None:
            return
        dest_id = dest.node_id
        dest_pub = secp256k1.decompress(dest.public_key)
        enr_seq = int.from_bytes(header.authdata[16:24], "big")
        challenge_data = header.challenge_data
        eph_priv = int.from_bytes(secrets.token_bytes(32), "big") \
            % (secp256k1.N - 1) + 1
        eph_pub = secp256k1.compress(secp256k1.pubkey(eph_priv))
        secret = secp256k1.ecdh(dest_pub, eph_priv)
        ikey, rkey = wire.session_keys(secret, challenge_data,
                                       self.node_id, dest_id)
        id_sig = wire.id_sign(self.local_enr.key, challenge_data, eph_pub,
                              dest_id)
        record = self.local_enr.record.to_rlp() \
            if enr_seq < self.local_enr.seq else None
        sess = _Session(write_key=ikey, read_key=rkey, peer_id=dest_id)
        self.sessions[addr] = sess
        nonce = os.urandom(12)
        pkt = wire.encode_handshake(dest_id, self.node_id, nonce, ikey,
                                    queued[0], id_sig, eph_pub, record)
        self.sock.sendto(pkt, addr)
        for msg in queued[1:]:
            self._send_ordinary(addr, sess, msg)

    def _accept_handshake(self, header, ct: bytes, addr) -> None:
        chal = self.pending_challenges.pop(addr, None)
        if chal is None:
            return
        src_id, id_sig, eph_pub, record_rlp = \
            wire.parse_handshake_authdata(header.authdata)
        if src_id != chal.src_id:
            return
        if record_rlp:
            enr = Enr.from_rlp(record_rlp)      # verifies the signature
            if enr.node_id != src_id:
                raise Discv5Error("handshake record id mismatch")
        else:
            enr = self.table.by_id(src_id)
            if enr is None:
                return                          # can't authenticate
        static_pub = secp256k1.decompress(enr.public_key)
        if not wire.id_verify(static_pub, id_sig, chal.challenge_data,
                              eph_pub, self.node_id):
            raise Discv5Error("bad id signature")
        secret = secp256k1.ecdh(secp256k1.decompress(eph_pub),
                                self.local_enr.key)
        ikey, rkey = wire.session_keys(secret, chal.challenge_data,
                                       src_id, self.node_id)
        # we are the recipient: write with rkey, read with ikey
        sess = _Session(write_key=rkey, read_key=ikey, peer_id=src_id)
        self.sessions[addr] = sess
        self.table.update(enr)
        msg = wire.open_message(ikey, header, ct)
        self._handle_message(msg, addr)

    def _enr_for_addr(self, addr) -> Enr | None:
        for e in self.table.all():
            if enr_addr(e) == addr:
                return e
        return None

    # -- message handling ----------------------------------------------------

    def _send_ordinary(self, addr, sess: _Session, msg: bytes) -> None:
        nonce = os.urandom(12)
        pkt = wire.encode_ordinary(sess.peer_id, self.node_id, nonce,
                                   sess.write_key, msg)
        self.sock.sendto(pkt, addr)

    def _handle_message(self, msg: bytes, addr) -> None:
        if not self._running:
            return          # raced stop(): don't spawn past join_all
        t, body = wire.decode_message(msg)
        req_id = bytes(body[0])
        if t == wire.MSG_PING:
            seq = rlp.decode_int(body[1]) if body[1] else 0
            enr = self._enr_for_addr(addr)
            if enr is not None and seq > enr.seq:
                # the peer advertises a newer record: re-fetch it
                # (FINDNODE distance 0 returns the local ENR) off-thread —
                # the recv loop must not block on its own request
                self._threads.spawn(self._refresh_enr, enr,
                                    name="discv5.refresh_enr")
            self._reply(addr, wire.enc_pong(req_id, self.local_enr.seq,
                                            addr[0], addr[1]))
        elif t == wire.MSG_FINDNODE:
            dists = [rlp.decode_int(d) if d else 0 for d in body[1]]
            out: list[Enr] = []
            for d in dists:
                if d == 0:
                    out.append(self.local_enr.record)
                else:
                    out.extend(self.table.at_distance(d))
            out = out[:MAX_NODES_PER_RESPONSE]
            self._reply(addr, wire.enc_nodes(
                req_id, 1, [rlp.decode(e.to_rlp()) for e in out]))
        elif t in (wire.MSG_PONG, wire.MSG_NODES):
            with self._lock:
                st = self.requests.pop(req_id, None)
            if st is None:
                return
            st["response"] = (t, body)
            st["event"].set()

    def _reply(self, addr, msg: bytes) -> None:
        sess = self.sessions.get(addr)
        if sess is not None:
            self._send_ordinary(addr, sess, msg)

    # -- requests ------------------------------------------------------------

    def _request(self, enr: Enr, msg_fn, timeout: float = REQUEST_TIMEOUT):
        addr = enr_addr(enr)
        req_id = secrets.token_bytes(8)
        msg = msg_fn(req_id)
        ev = threading.Event()
        st = {"event": ev, "response": None, "addr": addr}
        with self._lock:
            self.requests[req_id] = st
        sess = self.sessions.get(addr)
        if sess is not None:
            self._send_ordinary(addr, sess, msg)
        else:
            self.table.update(enr)   # need the ENR to finish the handshake
            with self._lock:
                if len(self.pending_out) > 1024:        # bounded state
                    self.pending_out.pop(next(iter(self.pending_out)))
                queue = self.pending_out.setdefault(addr, [])
                if len(queue) >= MAX_PENDING_OUT:
                    queue.pop(0)   # drop the oldest (its request timed out)
                queue.append(msg)
            # spec "random packet": elicits WHOAREYOU from the peer
            self.sock.sendto(
                wire.encode_random(enr.node_id, self.node_id), addr)
        if not ev.wait(timeout):
            with self._lock:
                self.requests.pop(req_id, None)
            raise Discv5Error("request timed out")
        return st["response"]

    # -- public API ----------------------------------------------------------

    def _refresh_enr(self, enr: Enr) -> None:
        try:
            self.find_node(enr, [0])   # table.update stores the result
        except Discv5Error:
            pass

    def ping(self, enr: Enr) -> bool:
        try:
            t, body = self._request(
                enr, lambda rid: wire.enc_ping(rid, self.local_enr.seq))
            if t == wire.MSG_PONG:
                seq = rlp.decode_int(body[1]) if body[1] else 0
                if seq > enr.seq:
                    self._refresh_enr(enr)
                return True
            return False
        except Discv5Error:
            self.table.remove(enr.node_id)
            return False

    def find_node(self, enr: Enr, distances: list[int]) -> list[Enr]:
        t, body = self._request(
            enr, lambda rid: wire.enc_findnode(rid, distances))
        if t != wire.MSG_NODES:
            return []
        found = []
        for item in body[2]:
            try:
                found.append(Enr.from_rlp(rlp.encode(item)))
            except (EnrError, rlp.RlpError):
                continue
        for e in found:
            self.table.update(e)
        return found

    def lookup(self, target: bytes | None = None,
               predicate=None, rounds: int = 3) -> list[Enr]:
        """Recursive Kademlia lookup toward `target` (random if None),
        optionally filtering results with `predicate(enr) -> bool`."""
        target = target or os.urandom(32)
        seen: set[bytes] = {self.node_id}
        # seed with our own table: known peers count as results even when
        # no third party reports them (two-node networks must connect)
        results: dict[bytes, Enr] = {
            e.node_id: e for e in self.table.closest(target, K_BUCKET_SIZE)}
        frontier = self.table.closest(target, LOOKUP_PARALLELISM)
        for _ in range(rounds):
            if not frontier:
                break
            next_frontier: list[Enr] = []
            for enr in frontier[:LOOKUP_PARALLELISM]:
                if enr.node_id in seen:
                    continue
                seen.add(enr.node_id)
                d = log2_distance(enr.node_id, target)
                dists = [d] if d else [256]
                if d > 1:
                    dists.append(d - 1)
                if d < 256:
                    dists.append(d + 1)
                try:
                    found = self.find_node(enr, dists)
                except Discv5Error:
                    self.table.remove(enr.node_id)
                    continue
                for f in found:
                    if f.node_id == self.node_id:
                        continue
                    results[f.node_id] = f
                    if f.node_id not in seen:
                        next_frontier.append(f)
            next_frontier.sort(
                key=lambda e: int.from_bytes(e.node_id, "big")
                ^ int.from_bytes(target, "big"))
            frontier = next_frontier
        out = list(results.values())
        if predicate is not None:
            out = [e for e in out if predicate(e)]
        return out

    def discover_subnet_peers(self, subnet_id: int, n: int = 4,
                              sync: bool = False) -> list[Enr]:
        """Peers advertising an attestation/sync subnet in their ENR
        (discovery/mod.rs subnet predicate queries)."""
        if sync:
            pred = lambda e: syncnets_int(e) & (1 << subnet_id)  # noqa: E731
        else:
            pred = lambda e: attnets_int(e) & (1 << subnet_id)   # noqa: E731
        local = [e for e in self.table.all() if pred(e)]
        if len(local) >= n:
            return local[:n]
        found = {e.node_id: e for e in local}
        for e in self.lookup(predicate=pred):
            found[e.node_id] = e
            if len(found) >= n:
                break
        return list(found.values())[:n]

    def bootstrap(self) -> int:
        """Ping bootnodes and run one self-lookup; returns table size."""
        for b in self.bootnodes:
            self.ping(b)
        self.lookup(self.node_id)
        return len(self.table)
