"""discv5-style UDP node discovery.

Equivalent of the reference's discv5 stack (lighthouse_network/src/
discovery/mod.rs, discovery/enr.rs; boot_node/src/server.rs): signed ENRs
with an eth2/attnets/syncnets payload, a Kademlia XOR routing table with
k-buckets, encrypted UDP sessions established by a WHOAREYOU challenge
handshake, PING/PONG liveness, FINDNODE/NODES recursive lookups, and
subnet predicates for attestation/sync-committee peer discovery.

Faithful-in-kind, with documented deviations from the discv5 v5.1 wire
spec (we interop only with ourselves, as the reference's vendored
gossipsub interops with libp2p):

- identity scheme: secp256k1 ECDSA like "v4", but node_id =
  sha256(uncompressed pubkey) (keccak is not in hashlib) and the record
  encoding is our own length-prefixed k/v, not RLP;
- session crypto: secp256k1 ECDH -> HKDF-SHA256 -> AES-128-GCM, keyed by
  the WHOAREYOU id-nonce, with an id-signature over the challenge proving
  static-key possession (the same derivation shape as spec section
  "handshake"), but without the masked-header obfuscation layer;
- FINDNODE carries log2-distances and NODES returns ENRs, as in the spec.
"""
from __future__ import annotations

import hashlib
import os
import secrets
import socket
import struct
import threading
import time

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.kdf.hkdf import HKDF
from cryptography.exceptions import InvalidSignature, InvalidTag

K_BUCKET_SIZE = 16          # spec k
LOOKUP_PARALLELISM = 3      # spec alpha
MAX_PACKET = 1280           # discv5 MTU bound
REQUEST_TIMEOUT = 2.0
#: an ENR with attnets/syncnets set is ~170 bytes; 5 of them plus
#: nonce/tag/framing stays under the 1280-byte MTU bound
MAX_NODES_PER_RESPONSE = 5
MAX_PENDING_OUT = 8         # queued messages per address awaiting session

_PK_ORDINARY = 0
_PK_WHOAREYOU = 1
_PK_HANDSHAKE = 2

_MSG_PING = 1
_MSG_PONG = 2
_MSG_FINDNODE = 3
_MSG_NODES = 4


class Discv5Error(Exception):
    pass


# ---------------------------------------------------------------------------
# ENR: signed, versioned node record (discovery/enr.rs build_enr)
# ---------------------------------------------------------------------------

def _enc_kv(items: dict[bytes, bytes]) -> bytes:
    out = b""
    for k in sorted(items):
        v = items[k]
        out += struct.pack(">BH", len(k), len(v)) + k + v
    return out


def _dec_kv(data: bytes) -> dict[bytes, bytes]:
    items, off = {}, 0
    while off < len(data):
        klen, vlen = struct.unpack_from(">BH", data, off)
        off += 3
        k = data[off:off + klen]; off += klen
        v = data[off:off + vlen]; off += vlen
        items[k] = v
    return items


class Enr:
    """A signed node record.  Content keys: ip, udp, tcp, attnets,
    syncnets, eth2 (fork digest), plus the secp256k1 public key."""

    def __init__(self, seq: int, pubkey: bytes, kv: dict[bytes, bytes],
                 signature: bytes):
        self.seq = seq
        self.pubkey = pubkey            # compressed secp256k1 (33 bytes)
        self.kv = kv
        self.signature = signature

    # -- identity ------------------------------------------------------------

    @property
    def node_id(self) -> bytes:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), self.pubkey)
        raw = pub.public_bytes(serialization.Encoding.X962,
                               serialization.PublicFormat.UncompressedPoint)
        return hashlib.sha256(raw).digest()

    @property
    def ip(self) -> str:
        return socket.inet_ntoa(self.kv.get(b"ip", b"\x7f\x00\x00\x01"))

    @property
    def udp_port(self) -> int:
        return struct.unpack(">H", self.kv.get(b"udp", b"\x00\x00"))[0]

    @property
    def tcp_port(self) -> int:
        return struct.unpack(">H", self.kv.get(b"tcp", b"\x00\x00"))[0]

    def attnets(self) -> int:
        """Attestation-subnet bitfield (discovery/enr.rs ATTESTATION_BITFIELD_ENR_KEY)."""
        return int.from_bytes(self.kv.get(b"attnets", b"\x00" * 8), "little")

    def syncnets(self) -> int:
        return int.from_bytes(self.kv.get(b"syncnets", b"\x00"), "little")

    # -- encoding ------------------------------------------------------------

    def _signed_content(self) -> bytes:
        return struct.pack(">Q", self.seq) + self.pubkey + _enc_kv(self.kv)

    def encode(self) -> bytes:
        return struct.pack(">H", len(self.signature)) + self.signature + \
            self._signed_content()

    @classmethod
    def decode(cls, data: bytes) -> "Enr":
        try:
            (siglen,) = struct.unpack_from(">H", data, 0)
            sig = data[2:2 + siglen]
            rest = data[2 + siglen:]
            seq = struct.unpack_from(">Q", rest, 0)[0]
            pubkey = rest[8:41]
            kv = _dec_kv(rest[41:])
            enr = cls(seq, pubkey, kv, sig)
            enr.verify()
            return enr
        except (struct.error, ValueError, IndexError) as e:
            raise Discv5Error(f"bad ENR: {e}") from None

    def verify(self) -> None:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), self.pubkey)
        try:
            pub.verify(self.signature, self._signed_content(),
                       ec.ECDSA(hashes.SHA256()))
        except InvalidSignature:
            raise Discv5Error("ENR signature invalid") from None


class LocalEnr:
    """Our own record + signing key; bump seq on every update."""

    def __init__(self, ip: str, udp_port: int, tcp_port: int = 0,
                 key: ec.EllipticCurvePrivateKey | None = None):
        self.key = key or ec.generate_private_key(ec.SECP256K1())
        self.seq = 0
        self.kv: dict[bytes, bytes] = {
            b"ip": socket.inet_aton(ip),
            b"udp": struct.pack(">H", udp_port),
            b"tcp": struct.pack(">H", tcp_port),
        }
        self._bump()

    @property
    def pubkey(self) -> bytes:
        return self.key.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.CompressedPoint)

    def _bump(self) -> None:
        self.seq += 1
        content = struct.pack(">Q", self.seq) + self.pubkey + \
            _enc_kv(self.kv)
        sig = self.key.sign(content, ec.ECDSA(hashes.SHA256()))
        self.record = Enr(self.seq, self.pubkey, dict(self.kv), sig)

    def set(self, key: bytes, value: bytes) -> None:
        self.kv[key] = value
        self._bump()

    def set_attnets(self, bitfield: int) -> None:
        self.set(b"attnets", bitfield.to_bytes(8, "little"))

    def set_syncnets(self, bitfield: int) -> None:
        self.set(b"syncnets", bitfield.to_bytes(1, "little"))

    @property
    def node_id(self) -> bytes:
        return self.record.node_id


# ---------------------------------------------------------------------------
# Kademlia routing table (k-buckets by XOR log-distance)
# ---------------------------------------------------------------------------

def log2_distance(a: bytes, b: bytes) -> int:
    """0 for identical ids, else 1 + floor(log2(a xor b))."""
    x = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return x.bit_length()


class KBuckets:
    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self.buckets: list[list[Enr]] = [[] for _ in range(257)]
        self._lock = threading.Lock()

    def update(self, enr: Enr) -> None:
        nid = enr.node_id
        if nid == self.local_id:
            return
        d = log2_distance(self.local_id, nid)
        with self._lock:
            bucket = self.buckets[d]
            for i, e in enumerate(bucket):
                if e.node_id == nid:
                    if enr.seq >= e.seq:
                        bucket.pop(i)
                        bucket.append(enr)   # move to tail (most recent)
                    return
            if len(bucket) < K_BUCKET_SIZE:
                bucket.append(enr)
            # full bucket: drop (liveness eviction happens via remove())

    def remove(self, node_id: bytes) -> None:
        d = log2_distance(self.local_id, node_id)
        with self._lock:
            self.buckets[d] = [e for e in self.buckets[d]
                               if e.node_id != node_id]

    def at_distance(self, d: int) -> list[Enr]:
        with self._lock:
            return list(self.buckets[d]) if 0 <= d <= 256 else []

    def closest(self, target: bytes, limit: int = K_BUCKET_SIZE
                ) -> list[Enr]:
        with self._lock:
            all_enrs = [e for b in self.buckets for e in b]
        all_enrs.sort(key=lambda e: int.from_bytes(e.node_id, "big")
                      ^ int.from_bytes(target, "big"))
        return all_enrs[:limit]

    def all(self) -> list[Enr]:
        with self._lock:
            return [e for b in self.buckets for e in b]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(b) for b in self.buckets)


# ---------------------------------------------------------------------------
# Sessions (WHOAREYOU challenge -> ECDH handshake -> AES-GCM)
# ---------------------------------------------------------------------------

class Session:
    def __init__(self, send_key: bytes, recv_key: bytes):
        self.send = AESGCM(send_key)
        self.recv = AESGCM(recv_key)

    def seal(self, msg: bytes, ad: bytes) -> bytes:
        nonce = os.urandom(12)
        return nonce + self.send.encrypt(nonce, msg, ad)

    def open(self, data: bytes, ad: bytes) -> bytes:
        return self.recv.decrypt(data[:12], data[12:], ad)


def _session_keys(ecdh_secret: bytes, id_nonce: bytes,
                  initiator_id: bytes, recipient_id: bytes
                  ) -> tuple[bytes, bytes]:
    """(initiator_key, recipient_key) — spec "kdf(secret, challenge)"."""
    okm = HKDF(algorithm=hashes.SHA256(), length=32,
               salt=id_nonce,
               info=b"discovery v5 key agreement" + initiator_id
               + recipient_id).derive(ecdh_secret)
    return okm[:16], okm[16:]


# ---------------------------------------------------------------------------
# Messages
# ---------------------------------------------------------------------------

def _enc_msg(msg_type: int, req_id: bytes, body: bytes) -> bytes:
    return bytes([msg_type, len(req_id)]) + req_id + body


def _dec_msg(data: bytes) -> tuple[int, bytes, bytes]:
    t, rlen = data[0], data[1]
    return t, data[2:2 + rlen], data[2 + rlen:]


def _enc_enr_list(enrs: list[Enr]) -> bytes:
    out = struct.pack(">B", len(enrs))
    for e in enrs:
        blob = e.encode()
        out += struct.pack(">H", len(blob)) + blob
    return out


def _dec_enr_list(data: bytes) -> list[Enr]:
    (n,) = struct.unpack_from(">B", data, 0)
    off, out = 1, []
    for _ in range(n):
        (blen,) = struct.unpack_from(">H", data, off)
        off += 2
        out.append(Enr.decode(data[off:off + blen]))
        off += blen
    return out


# ---------------------------------------------------------------------------
# The service
# ---------------------------------------------------------------------------

class Discv5:
    """One UDP socket, a routing table, and the request state machine."""

    def __init__(self, ip: str = "127.0.0.1", port: int = 0,
                 tcp_port: int = 0,
                 key: ec.EllipticCurvePrivateKey | None = None,
                 bootnodes: list[Enr] | None = None):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))
        self.sock.settimeout(0.25)
        self.ip, self.port = self.sock.getsockname()
        self.local_enr = LocalEnr(self.ip, self.port, tcp_port, key)
        self.table = KBuckets(self.local_enr.node_id)
        self.sessions: dict[tuple, Session] = {}
        self.pending_challenges: dict[tuple, bytes] = {}
        self.pending_out: dict[tuple, list[bytes]] = {}   # awaiting session
        self.requests: dict[bytes, dict] = {}             # req_id -> state
        self._lock = threading.Lock()
        self._running = False
        self._thread = None
        self.bootnodes = list(bootnodes or [])
        for b in self.bootnodes:
            self.table.update(b)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)
        self.sock.close()

    # -- packet pump ---------------------------------------------------------

    def _recv_loop(self) -> None:
        while self._running:
            try:
                data, addr = self.sock.recvfrom(MAX_PACKET)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._handle_packet(data, addr)
            except (Discv5Error, InvalidTag, InvalidSignature,
                    struct.error, IndexError, ValueError):
                continue   # malformed / unauthenticated: drop silently

    def _send_packet(self, addr, kind: int, payload: bytes) -> None:
        self.sock.sendto(bytes([kind]) + payload, addr)

    def _challenge(self, addr) -> None:
        """Issue a WHOAREYOU challenge (bounded pending state)."""
        if len(self.pending_challenges) > 1024:
            self.pending_challenges.pop(next(iter(self.pending_challenges)))
        nonce = os.urandom(16)
        self.pending_challenges[addr] = nonce
        self._send_packet(addr, _PK_WHOAREYOU, nonce)

    def _handle_packet(self, data: bytes, addr) -> None:
        kind, payload = data[0], data[1:]
        if kind == _PK_ORDINARY:
            sess = self.sessions.get(addr)
            if sess is None:
                self._challenge(addr)
                return
            try:
                msg = sess.open(payload, b"")
            except InvalidTag:
                # stale session (peer restarted): drop it and re-challenge
                del self.sessions[addr]
                self._challenge(addr)
                return
            self._handle_message(msg, addr)
        elif kind == _PK_WHOAREYOU:
            self._complete_handshake(payload, addr)
        elif kind == _PK_HANDSHAKE:
            self._accept_handshake(payload, addr)

    # -- handshake -----------------------------------------------------------

    def _complete_handshake(self, id_nonce: bytes, addr) -> None:
        """We got challenged: prove our identity and establish keys.

        HANDSHAKE payload: our ENR | id-signature | sealed first message.
        Keys ride static-static ECDH bound to the challenge nonce, so a
        spoofed source address cannot decrypt (spec 4.1 handshake).
        """
        # Only honor a WHOAREYOU when we actually have traffic in flight
        # toward that address (queued messages or an outstanding request):
        # an unsolicited challenge from a spoofed source must not be able
        # to evict a live session (session-churn DoS).
        with self._lock:
            queued = self.pending_out.pop(addr, [])
            outstanding = any(st.get("addr") == addr
                              for st in self.requests.values())
        if not queued and not outstanding:
            return
        # being challenged means the peer cannot decrypt us: our session
        # is stale (peer restarted) — drop it so requests re-handshake
        self.sessions.pop(addr, None)
        if not queued:
            return   # in-flight request times out; its retry re-queues
        dest = self._enr_for_addr(addr)
        if dest is None:
            return
        dest_pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), dest.pubkey)
        secret = self.local_enr.key.exchange(ec.ECDH(), dest_pub)
        ikey, rkey = _session_keys(secret, id_nonce,
                                   self.local_enr.node_id, dest.node_id)
        sess = Session(ikey, rkey)
        self.sessions[addr] = sess
        id_sig = self.local_enr.key.sign(
            b"discovery v5 identity proof" + id_nonce,
            ec.ECDSA(hashes.SHA256()))
        enr_blob = self.local_enr.record.encode()
        first = sess.seal(queued[0], b"")
        payload = struct.pack(">HH", len(enr_blob), len(id_sig)) + \
            enr_blob + id_sig + first
        self._send_packet(addr, _PK_HANDSHAKE, payload)
        for msg in queued[1:]:
            self._send_packet(addr, _PK_ORDINARY, sess.seal(msg, b""))

    def _accept_handshake(self, payload: bytes, addr) -> None:
        id_nonce = self.pending_challenges.pop(addr, None)
        if id_nonce is None:
            return
        elen, slen = struct.unpack_from(">HH", payload, 0)
        off = 4
        enr = Enr.decode(payload[off:off + elen]); off += elen
        id_sig = payload[off:off + slen]; off += slen
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), enr.pubkey)
        pub.verify(id_sig, b"discovery v5 identity proof" + id_nonce,
                   ec.ECDSA(hashes.SHA256()))
        secret = self.local_enr.key.exchange(ec.ECDH(), pub)
        ikey, rkey = _session_keys(secret, id_nonce, enr.node_id,
                                   self.local_enr.node_id)
        # we are the recipient: send with rkey, receive with ikey
        sess = Session(rkey, ikey)
        self.sessions[addr] = sess
        self.table.update(enr)
        msg = sess.open(payload[off:], b"")
        self._handle_message(msg, addr)

    def _enr_for_addr(self, addr) -> Enr | None:
        for e in self.table.all():
            if (e.ip, e.udp_port) == addr:
                return e
        return None

    # -- message handling ----------------------------------------------------

    def _handle_message(self, msg: bytes, addr) -> None:
        t, req_id, body = _dec_msg(msg)
        if t == _MSG_PING:
            (seq,) = struct.unpack(">Q", body)
            enr = self._enr_for_addr(addr)
            if enr is not None and seq > enr.seq:
                # the peer advertises a newer record: re-fetch it
                # (FINDNODE distance 0 returns the local ENR) off-thread —
                # the recv loop must not block on its own request
                threading.Thread(target=self._refresh_enr, args=(enr,),
                                 daemon=True).start()
            self._reply(addr, _MSG_PONG, req_id, struct.pack(
                ">Q4sH", self.local_enr.seq, socket.inet_aton(addr[0]),
                addr[1]))
        elif t == _MSG_FINDNODE:
            n = body[0]
            dists = struct.unpack_from(f">{n}H", body, 1)
            out: list[Enr] = []
            for d in dists:
                if d == 0:
                    out.append(self.local_enr.record)
                else:
                    out.extend(self.table.at_distance(d))
            self._reply(addr, _MSG_NODES, req_id,
                        _enc_enr_list(out[:MAX_NODES_PER_RESPONSE]))
        elif t in (_MSG_PONG, _MSG_NODES):
            with self._lock:
                st = self.requests.pop(bytes(req_id), None)
            if st is None:
                return
            st["response"] = (t, body)
            st["event"].set()

    def _reply(self, addr, msg_type: int, req_id: bytes,
               body: bytes) -> None:
        sess = self.sessions.get(addr)
        if sess is None:
            return
        self._send_packet(addr, _PK_ORDINARY,
                          sess.seal(_enc_msg(msg_type, req_id, body), b""))

    # -- requests ------------------------------------------------------------

    def _request(self, enr: Enr, msg_type: int, body: bytes,
                 timeout: float = REQUEST_TIMEOUT):
        addr = (enr.ip, enr.udp_port)
        req_id = secrets.token_bytes(8)
        msg = _enc_msg(msg_type, req_id, body)
        ev = threading.Event()
        st = {"event": ev, "response": None, "addr": addr}
        with self._lock:
            self.requests[req_id] = st
        sess = self.sessions.get(addr)
        if sess is not None:
            self._send_packet(addr, _PK_ORDINARY, sess.seal(msg, b""))
        else:
            self.table.update(enr)   # need the ENR to finish the handshake
            with self._lock:
                if len(self.pending_out) > 1024:        # bounded state
                    self.pending_out.pop(next(iter(self.pending_out)))
                queue = self.pending_out.setdefault(addr, [])
                if len(queue) >= MAX_PENDING_OUT:
                    queue.pop(0)   # drop the oldest (its request timed out)
                queue.append(msg)
            # poke: an undecryptable ORDINARY triggers WHOAREYOU
            self._send_packet(addr, _PK_ORDINARY, os.urandom(28))
        if not ev.wait(timeout):
            with self._lock:
                self.requests.pop(req_id, None)
            raise Discv5Error("request timed out")
        return st["response"]

    # -- public API ----------------------------------------------------------

    def _refresh_enr(self, enr: Enr) -> None:
        try:
            self.find_node(enr, [0])   # table.update stores the result
        except Discv5Error:
            pass

    def ping(self, enr: Enr) -> bool:
        try:
            t, body = self._request(enr, _MSG_PING,
                                    struct.pack(">Q", self.local_enr.seq))
            if t == _MSG_PONG:
                (seq,) = struct.unpack_from(">Q", body, 0)
                if seq > enr.seq:
                    self._refresh_enr(enr)
                return True
            return False
        except Discv5Error:
            self.table.remove(enr.node_id)
            return False

    def find_node(self, enr: Enr, distances: list[int]) -> list[Enr]:
        body = bytes([len(distances)]) + b"".join(
            struct.pack(">H", d) for d in distances)
        t, resp = self._request(enr, _MSG_FINDNODE, body)
        if t != _MSG_NODES:
            return []
        found = _dec_enr_list(resp)
        for e in found:
            self.table.update(e)
        return found

    def lookup(self, target: bytes | None = None,
               predicate=None, rounds: int = 3) -> list[Enr]:
        """Recursive Kademlia lookup toward `target` (random if None),
        optionally filtering results with `predicate(enr) -> bool`."""
        target = target or os.urandom(32)
        seen: set[bytes] = {self.local_enr.node_id}
        # seed with our own table: known peers count as results even when
        # no third party reports them (two-node networks must connect)
        results: dict[bytes, Enr] = {
            e.node_id: e for e in self.table.closest(target, K_BUCKET_SIZE)}
        frontier = self.table.closest(target, LOOKUP_PARALLELISM)
        for _ in range(rounds):
            if not frontier:
                break
            next_frontier: list[Enr] = []
            for enr in frontier[:LOOKUP_PARALLELISM]:
                if enr.node_id in seen:
                    continue
                seen.add(enr.node_id)
                d = log2_distance(enr.node_id, target)
                dists = [d] if d else [256]
                if d > 1:
                    dists.append(d - 1)
                if d < 256:
                    dists.append(d + 1)
                try:
                    found = self.find_node(enr, dists)
                except Discv5Error:
                    self.table.remove(enr.node_id)
                    continue
                for f in found:
                    if f.node_id == self.local_enr.node_id:
                        continue
                    results[f.node_id] = f
                    if f.node_id not in seen:
                        next_frontier.append(f)
            next_frontier.sort(
                key=lambda e: int.from_bytes(e.node_id, "big")
                ^ int.from_bytes(target, "big"))
            frontier = next_frontier
        out = list(results.values())
        if predicate is not None:
            out = [e for e in out if predicate(e)]
        return out

    def discover_subnet_peers(self, subnet_id: int, n: int = 4,
                              sync: bool = False) -> list[Enr]:
        """Peers advertising an attestation/sync subnet in their ENR
        (discovery/mod.rs subnet predicate queries)."""
        if sync:
            pred = lambda e: e.syncnets() & (1 << subnet_id)   # noqa: E731
        else:
            pred = lambda e: e.attnets() & (1 << subnet_id)    # noqa: E731
        local = [e for e in self.table.all() if pred(e)]
        if len(local) >= n:
            return local[:n]
        found = {e.node_id: e for e in local}
        for e in self.lookup(predicate=pred):
            found[e.node_id] = e
            if len(found) >= n:
                break
        return list(found.values())[:n]

    def bootstrap(self) -> int:
        """Ping bootnodes and run one self-lookup; returns table size."""
        for b in self.bootnodes:
            self.ping(b)
        self.lookup(self.local_enr.node_id)
        return len(self.table)
