"""libp2p ``/plaintext/2.0.0``-style security "upgrade" — no encryption.

The real libp2p plaintext 2.0 protocol exchanges each side's identity
public key in an ``Exchange`` protobuf and then passes bytes through
unchanged (libp2p/specs/plaintext/README.md).  We implement that shape —
a single length-prefixed exchange message carrying the compressed
secp256k1 identity key, then a raw byte stream — with one hardening
twist the spec leaves out: the exchange message also carries a signature
over the advertised key, so a peer cannot claim an identity it does not
hold (proof of possession; there is still no transport privacy and no
MITM resistance, which is the point of this mode).

Why it exists: the noise XX upgrade (noise_xx.py) needs the python
``cryptography`` package for X25519/ChaCha20-Poly1305.  The scenario
suite (testing/scenarios.py) must run the full TCP/yamux/gossipsub stack
deterministically on machines without it, so the transport negotiates
``/plaintext/2.0.0`` as a fallback security protocol.  Everything above
the security layer (multistream, yamux, meshsub, req/resp) is byte-for-
byte identical to the noise path.
"""
from __future__ import annotations

import struct

from . import secp256k1
from .noise_xx import (
    NoiseError, _pb_bytes_field, _pb_parse, _identity_key_pb,
    peer_id_from_pubkey,
)

EXCHANGE_PREFIX = b"libp2p-plaintext-exchange:"
MAX_EXCHANGE = 4096


class PlaintextError(NoiseError):
    """Subclass of NoiseError so transport except-clauses need no edits."""


def _send_frame(sock, data: bytes) -> None:
    sock.sendall(struct.pack(">H", len(data)) + data)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise PlaintextError("connection closed during exchange")
        buf += chunk
    return buf


def _recv_frame(sock) -> bytes:
    (n,) = struct.unpack(">H", _recv_exact(sock, 2))
    if n > MAX_EXCHANGE:
        raise PlaintextError("oversized exchange message")
    return _recv_exact(sock, n)


def _make_exchange(identity_priv: int) -> bytes:
    """Exchange { id = 1, pubkey = 2, sig = 3 (our extension) }."""
    pub = secp256k1.compress(secp256k1.pubkey(identity_priv))
    import hashlib
    digest = hashlib.sha256(EXCHANGE_PREFIX + pub).digest()
    sig = secp256k1.sign(identity_priv, digest)
    return (_pb_bytes_field(1, peer_id_from_pubkey(pub))
            + _pb_bytes_field(2, _identity_key_pb(pub))
            + _pb_bytes_field(3, sig))


def _parse_exchange(msg: bytes) -> bytes:
    """-> the peer's compressed secp256k1 identity key (33B), verified."""
    fields = _pb_parse(msg)
    key_pb = _pb_parse(fields[2])
    if key_pb.get(1) != 2:
        raise PlaintextError("identity key is not secp256k1")
    pub33 = key_pb[2]
    import hashlib
    digest = hashlib.sha256(EXCHANGE_PREFIX + pub33).digest()
    if not secp256k1.verify(secp256k1.decompress(pub33), digest,
                            fields.get(3, b"")):
        raise PlaintextError("identity possession signature invalid")
    if fields.get(1) != peer_id_from_pubkey(pub33):
        raise PlaintextError("advertised peer id does not match key")
    return pub33


class PlaintextSession:
    """Same surface as NoiseSession (send/recv/remote_peer_id): raw
    socket pass-through after the identity exchange."""

    RECV_CHUNK = 65536

    def __init__(self, remote_identity: bytes):
        self.remote_identity = remote_identity
        self.remote_peer_id = peer_id_from_pubkey(remote_identity)
        self.handshake_hash = b"\x00" * 32   # no channel binding

    def send(self, sock, data: bytes) -> None:
        sock.sendall(data)

    def recv(self, sock) -> bytes:
        chunk = sock.recv(self.RECV_CHUNK)
        if not chunk:
            raise PlaintextError("connection closed")
        return chunk


def plaintext_handshake(sock, identity_priv: int) -> PlaintextSession:
    """Symmetric: both sides send their exchange, then read the peer's."""
    _send_frame(sock, _make_exchange(identity_priv))
    remote = _parse_exchange(_recv_frame(sock))
    return PlaintextSession(remote)
