"""Peer discovery + standalone bootnode.

Equivalent of the reference's discv5 discovery (lighthouse_network/src/
discovery) and the boot_node binary (boot_node/src/server.rs), over the
framed-TCP transport instead of UDP Kademlia: every node serves a
`discovery_peers` RPC returning its known peer addresses; nodes poll it to
top up toward target_peers. A bootnode is just a NetworkService-less
Transport+RPC that only serves the address book.

Run standalone:  python -m lighthouse_tpu.network.discovery --port 9100
"""
from __future__ import annotations

import argparse
import sys
import threading
import time

from .rpc import RpcHandler
from .transport import Transport


class AddressBook:
    def __init__(self):
        self._addrs: dict[str, tuple[str, int]] = {}
        self._lock = threading.Lock()

    def record(self, node_id: str, host: str, port: int) -> None:
        with self._lock:
            self._addrs[node_id] = (host, port)

    def sample(self, exclude: set[str], limit: int = 16) -> list:
        with self._lock:
            return [[nid, h, p] for nid, (h, p) in self._addrs.items()
                    if nid not in exclude][:limit]


def record_identify(book: AddressBook, peer, payload) -> dict:
    """Shared identify handler (node-side and bootnode-side)."""
    try:
        book.record(peer.node_id, payload["host"], int(payload["port"]))
    except (KeyError, ValueError, TypeError):
        pass
    return {"ok": True}


class Discovery:
    """Attach to a NetworkService: serve + poll peer exchange."""

    def __init__(self, service, listen_port: int | None = None):
        self.service = service
        self.book = AddressBook()
        self.listen_port = listen_port or service.port
        service.rpc.register("discovery_peers", self._handle)
        # learn dialable addresses from peers as they identify themselves
        service.rpc.register(
            "discovery_identify",
            lambda peer, p: record_identify(self.book, peer, p))

    def _handle(self, peer, payload) -> list:
        exclude = {peer.node_id, self.service.transport.node_id}
        return self.book.sample(exclude)

    def advertise(self, peer) -> None:
        """Tell a peer our dialable address."""
        try:
            self.service.rpc.request(peer, "discovery_identify", {
                "host": self.service.transport.host,
                "port": self.listen_port}, timeout=3.0)
        except (TimeoutError, RuntimeError):
            pass

    def discover_once(self) -> int:
        """Ask each connected peer for more peers; dial new ones until
        target_peers. Returns new connections made."""
        svc = self.service
        known = set(svc.transport.peers) | {svc.transport.node_id}
        made = 0
        for peer in list(svc.transport.peers.values()):
            self.advertise(peer)
            try:
                found = svc.rpc.request(peer, "discovery_peers", {},
                                        timeout=3.0)
            except (TimeoutError, RuntimeError):
                continue
            for nid, host, port in found or []:
                if nid in known:
                    continue
                if len(svc.transport.peers) >= svc.peers.target_peers:
                    return made
                if svc.dial(host, int(port)) is not None:
                    known.add(nid)
                    made += 1
        return made


class BootNode:
    """Standalone address-book server (boot_node binary equivalent)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.transport = Transport(host, port)
        self.rpc = RpcHandler(self.transport)
        self.book = AddressBook()
        self.transport.on_frame = \
            lambda peer, kind, payload: self.rpc.handle_frame(peer, kind,
                                                              payload)
        self.rpc.register("discovery_peers",
                          lambda peer, p: self.book.sample({peer.node_id}))
        self.rpc.register(
            "discovery_identify",
            lambda peer, p: record_identify(self.book, peer, p))
        self.rpc.register("status", lambda peer, p: p)  # echo, stay neutral
        self.rpc.register("ping", lambda peer, p: {"seq": 0})

    @property
    def port(self) -> int:
        return self.transport.port

    def start(self) -> None:
        self.transport.start()

    def stop(self) -> None:
        self.transport.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9100)
    args = ap.parse_args(argv)
    node = BootNode(args.host, args.port)
    node.start()
    print(f"bootnode listening on {args.host}:{node.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
