"""Peer discovery: discv5 UDP Kademlia + standalone bootnode.

Equivalent of the reference's discovery service and boot_node binary
(lighthouse_network/src/discovery/mod.rs — discv5 queries feeding dialable
peers to the network; boot_node/src/server.rs — a discv5 server with no
libp2p stack).  The wire layer lives in `discv5.py` (signed ENRs,
WHOAREYOU/ECDH sessions, FINDNODE lookups, subnet predicates); this module
binds it to the NetworkService: our ENR advertises the TCP (noise
transport) port, lookups surface ENRs, and new peers are dialed over TCP
until target_peers.

Run standalone:  python -m lighthouse_tpu.network.discovery --port 9100
"""
from __future__ import annotations

import argparse
import sys
import time

from .discv5 import Discv5
from .enr import Enr


class Discovery:
    """Attach to a NetworkService: discv5 lookups -> TCP dials."""

    def __init__(self, service, udp_port: int = 0,
                 bootnode_enrs: list[Enr] | None = None):
        self.service = service
        self.disc = Discv5(ip=service.transport.host, port=udp_port,
                           tcp_port=service.port,
                           bootnodes=bootnode_enrs)
        # only after the UDP bind succeeded (r5 review)
        service.chain.discovery = self    # /eth/v1/node/identity ENR view
        self.disc.start()
        # addr -> transport peer id of the last successful dial, so a
        # dropped connection can be re-dialed on a later round
        self._dialed: dict[tuple[str, int], str] = {}
        if bootnode_enrs:
            self.disc.bootstrap()

    # -- identity ------------------------------------------------------------

    @property
    def enr(self) -> Enr:
        return self.disc.local_enr.record

    def add_bootnode(self, enr: Enr) -> None:
        self.disc.bootnodes.append(enr)
        self.disc.table.update(enr)

    # -- ENR subnet advertisement (discovery/enr.rs attnets/syncnets) --------

    def update_attnets(self, bitfield: int) -> None:
        self.disc.local_enr.set_attnets(bitfield)

    def update_syncnets(self, bitfield: int) -> None:
        self.disc.local_enr.set_syncnets(bitfield)

    # -- discovery -----------------------------------------------------------

    def _try_dial(self, enr: Enr) -> bool:
        """Dial an ENR's TCP endpoint unless we already hold a live
        connection from a previous dial of that address."""
        svc = self.service
        if not enr.tcp():
            return False   # bootnode-style record: not dialable over TCP
        addr = (enr.ip(), enr.tcp())
        if addr == (svc.transport.host, svc.port):
            return False
        live = self._dialed.get(addr)
        if live is not None and live in svc.transport.peers:
            return False   # still connected
        peer = svc.dial(*addr)
        if peer is None:
            self._dialed.pop(addr, None)   # retry on a later round
            return False
        self._dialed[addr] = peer.node_id
        return True

    def discover_once(self) -> int:
        """One lookup round; dial found peers until target_peers.
        Returns new connections made.  (Runs on the per-slot timer: only
        re-bootstrap — serial bootnode pings with 2 s timeouts — when the
        table is empty, so an unreachable bootnode cannot stall slots.)"""
        svc = self.service
        if len(self.disc.table) == 0 and self.disc.bootnodes:
            self.disc.bootstrap()
        made = 0
        for enr in self.disc.lookup():
            if len(svc.transport.peers) >= svc.peers.target_peers:
                break
            if self._try_dial(enr):
                made += 1
        return made

    def discover_subnet_peers(self, subnet_id: int, n: int = 4,
                              sync: bool = False) -> int:
        """Find + dial peers advertising a subnet in their ENR
        (discovery/mod.rs subnet predicate queries).  Returns dials made."""
        made = 0
        for enr in self.disc.discover_subnet_peers(subnet_id, n=n,
                                                   sync=sync):
            if self._try_dial(enr):
                made += 1
        return made

    def stop(self) -> None:
        self.disc.stop()

    # -- routing-table persistence (network/src/persisted_dht.rs) ------------

    def load_persisted(self, store) -> int:
        """Seed the K-buckets from the database — restart without
        bootnodes (invalid records are dropped at decode)."""
        from .persisted_dht import load_dht
        enrs = load_dht(store)
        for e in enrs:
            self.disc.table.update(e)
        return len(enrs)

    def persist(self, store) -> int:
        """Write the current routing table to the database."""
        from .persisted_dht import persist_dht
        return persist_dht(store, self.disc.table.all())


class BootNode:
    """Standalone discv5 server: routing table only, no beacon stack
    (boot_node/src/server.rs)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.disc = Discv5(ip=host, port=port, tcp_port=0)

    @property
    def enr(self) -> Enr:
        return self.disc.local_enr.record

    @property
    def port(self) -> int:
        return self.disc.port

    def start(self) -> None:
        self.disc.start()

    def stop(self) -> None:
        self.disc.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9100)
    args = ap.parse_args(argv)
    node = BootNode(args.host, args.port)
    node.start()
    print(f"bootnode listening on {args.host}:{node.port} (udp)")
    print(f"enr: {node.enr.to_text()}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
