"""secp256k1 for the identity layer: deterministic ECDSA (RFC 6979) and
ECDH returning the compressed shared point.

Self-contained by design: ENR "v4" signatures (EIP-778) need
deterministic low-s 64-byte r||s signatures over a keccak256 digest, and
discv5 v5.1 session-key agreement needs the *compressed point* of the
ECDH result — neither shape is exposed by the `cryptography` package's
DER/x-only APIs.  Handshake-rate usage only (a few ops per peer), so
pure Python with Jacobian coordinates is plenty.

Ref parity: the reference's ENR/discv5 key handling lives in the
`discv5` + `k256` crates (beacon_node/lighthouse_network/src/discovery/
enr.rs:186 builds/signs records; CombinedKey = k256 ECDSA).
"""
from __future__ import annotations

import hashlib
import hmac

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
_INF = None


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


# Jacobian point arithmetic ---------------------------------------------------

def _to_jac(pt):
    return (pt[0], pt[1], 1) if pt is not _INF else (0, 0, 0)


def _from_jac(j):
    if j[2] == 0:
        return _INF
    zi = _inv(j[2], P)
    zi2 = zi * zi % P
    return (j[0] * zi2 % P, j[1] * zi2 * zi % P)


def _jac_double(j):
    x, y, z = j
    if z == 0 or y == 0:
        return (0, 0, 0)
    s = 4 * x * y * y % P
    m = 3 * x * x % P            # a = 0 for secp256k1
    x2 = (m * m - 2 * s) % P
    y2 = (m * (s - x2) - 8 * pow(y, 4, P)) % P
    z2 = 2 * y * z % P
    return (x2, y2, z2)


def _jac_add(j1, j2):
    if j1[2] == 0:
        return j2
    if j2[2] == 0:
        return j1
    x1, y1, z1 = j1
    x2, y2, z2 = j2
    z1s, z2s = z1 * z1 % P, z2 * z2 % P
    u1, u2 = x1 * z2s % P, x2 * z1s % P
    s1, s2 = y1 * z2s * z2 % P, y2 * z1s * z1 % P
    if u1 == u2:
        if s1 != s2:
            return (0, 0, 0)
        return _jac_double(j1)
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    h2 = h * h % P
    h3 = h2 * h % P
    x3 = (r * r - h3 - 2 * u1 * h2) % P
    y3 = (r * (u1 * h2 - x3) - s1 * h3) % P
    z3 = h * z1 * z2 % P
    return (x3, y3, z3)


def _mul(k: int, pt):
    """Scalar multiple k*pt (affine in/out)."""
    acc = (0, 0, 0)
    add = _to_jac(pt)
    while k:
        if k & 1:
            acc = _jac_add(acc, add)
        add = _jac_double(add)
        k >>= 1
    return _from_jac(acc)


def pubkey(priv: int):
    return _mul(priv, (GX, GY))


# encodings -------------------------------------------------------------------

def compress(pt) -> bytes:
    x, y = pt
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def uncompressed64(pt) -> bytes:
    """x||y without the 0x04 prefix (the ENR node-id input form)."""
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def decompress(data: bytes):
    if len(data) == 65 and data[0] == 4:
        pt = (int.from_bytes(data[1:33], "big"),
              int.from_bytes(data[33:], "big"))
    elif len(data) == 33 and data[0] in (2, 3):
        x = int.from_bytes(data[1:], "big")
        if x >= P:
            raise ValueError("x out of range")
        y2 = (pow(x, 3, P) + 7) % P
        y = pow(y2, (P + 1) // 4, P)
        if y * y % P != y2:
            raise ValueError("not on curve")
        if (y & 1) != (data[0] & 1):
            y = P - y
        pt = (x, y)
    else:
        raise ValueError("bad public key encoding")
    if not on_curve(pt):
        raise ValueError("not on curve")
    return pt


def on_curve(pt) -> bool:
    x, y = pt
    return 0 < x < P and 0 < y < P and \
        (y * y - pow(x, 3, P) - 7) % P == 0


# RFC 6979 deterministic nonce (HMAC-SHA256) ----------------------------------

def _rfc6979_k(priv: int, digest32: bytes) -> int:
    x = priv.to_bytes(32, "big")
    h1 = digest32
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(priv: int, digest32: bytes) -> bytes:
    """Deterministic low-s signature over a 32-byte digest -> r||s (64B).

    Matches libsecp256k1/k256 default signing (RFC 6979 SHA-256 nonce,
    low-s normalized) — required to reproduce EIP-778's sample record.
    """
    z = int.from_bytes(digest32, "big") % N
    while True:
        k = _rfc6979_k(priv, digest32)
        pt = _mul(k, (GX, GY))
        r = pt[0] % N
        if r == 0:
            digest32 = hashlib.sha256(digest32).digest()
            continue
        s = _inv(k, N) * (z + r * priv) % N
        if s == 0:
            digest32 = hashlib.sha256(digest32).digest()
            continue
        if s > N // 2:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def verify(pub_pt, digest32: bytes, sig64: bytes) -> bool:
    if len(sig64) != 64:
        return False
    r = int.from_bytes(sig64[:32], "big")
    s = int.from_bytes(sig64[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return False
    z = int.from_bytes(digest32, "big") % N
    w = _inv(s, N)
    u1, u2 = z * w % N, r * w % N
    pt = _from_jac(_jac_add(_to_jac(_mul(u1, (GX, GY))),
                            _to_jac(_mul(u2, pub_pt))))
    if pt is _INF:
        return False
    return pt[0] % N == r


def ecdh(pub_pt, priv: int) -> bytes:
    """discv5 v5.1 ecdh(): compressed 33-byte shared point."""
    return compress(_mul(priv, pub_pt))
