"""Gossipsub RPC protobufs — the real meshsub wire format.

Hand-rolled proto2 encoding of the RPC schema every libp2p gossipsub
implementation shares (ref: the reference vendors it at
beacon_node/lighthouse_network/gossipsub/src/rpc.proto /
generated/gossipsub/pb/mod.rs; protocol ids /meshsub/1.1.0, /meshsub/
1.2.0 in gossipsub/src/protocol.rs):

    message RPC {
      repeated SubOpts subscriptions = 1;
      repeated Message publish = 2;
      optional ControlMessage control = 3;
    }
    message SubOpts   { bool subscribe = 1; string topic_id = 2; }
    message Message   { bytes from = 1; bytes data = 2; bytes seqno = 3;
                        string topic = 4; bytes signature = 5;
                        bytes key = 6; }
    message ControlMessage {
      repeated ControlIHave ihave = 1;      // topic + message_ids
      repeated ControlIWant iwant = 2;      // message_ids
      repeated ControlGraft graft = 3;      // topic
      repeated ControlPrune prune = 4;      // topic + peers + backoff
      repeated ControlIDontWant idontwant = 5;  // message_ids (v1.2)
    }

On the stream, each RPC is varint-length-delimited.
"""
from __future__ import annotations

from dataclasses import dataclass, field


class PbError(Exception):
    pass


# -- primitive proto wire helpers ---------------------------------------------

def _uvarint(n: int) -> bytes:
    out = b""
    while n >= 0x80:
        out += bytes([(n & 0x7F) | 0x80])
        n >>= 7
    return out + bytes([n])


def _tag_bytes(tag: int, data: bytes) -> bytes:
    return _uvarint((tag << 3) | 2) + _uvarint(len(data)) + data


def _tag_varint(tag: int, v: int) -> bytes:
    return _uvarint(tag << 3) + _uvarint(v)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def eof(self) -> bool:
        return self.pos >= len(self.data)

    def uvarint(self) -> int:
        shift = v = 0
        while True:
            if self.pos >= len(self.data):
                raise PbError("truncated varint")
            b = self.data[self.pos]
            self.pos += 1
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7
            if shift > 63:
                raise PbError("varint overflow")

    def bytes_(self) -> bytes:
        n = self.uvarint()
        if self.pos + n > len(self.data):
            raise PbError("truncated bytes field")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def skip(self, wire_type: int) -> None:
        if wire_type == 0:
            self.uvarint()
        elif wire_type == 2:
            self.bytes_()
        elif wire_type == 5:
            self.pos += 4
        elif wire_type == 1:
            self.pos += 8
        else:
            raise PbError(f"unsupported wire type {wire_type}")


# -- schema dataclasses -------------------------------------------------------

@dataclass
class SubOpts:
    subscribe: bool = True
    topic: str = ""

    def encode(self) -> bytes:
        return _tag_varint(1, 1 if self.subscribe else 0) + \
            _tag_bytes(2, self.topic.encode())

    @classmethod
    def decode(cls, data: bytes) -> "SubOpts":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if tag == 1 and wt == 0:
                out.subscribe = bool(r.uvarint())
            elif tag == 2 and wt == 2:
                out.topic = r.bytes_().decode()
            else:
                r.skip(wt)
        return out


@dataclass
class PubMessage:
    from_peer: bytes = b""
    data: bytes = b""
    seqno: bytes = b""
    topic: str = ""
    signature: bytes = b""
    key: bytes = b""

    def encode(self) -> bytes:
        out = b""
        if self.from_peer:
            out += _tag_bytes(1, self.from_peer)
        if self.data:
            out += _tag_bytes(2, self.data)
        if self.seqno:
            out += _tag_bytes(3, self.seqno)
        out += _tag_bytes(4, self.topic.encode())
        if self.signature:
            out += _tag_bytes(5, self.signature)
        if self.key:
            out += _tag_bytes(6, self.key)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "PubMessage":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if wt != 2:
                r.skip(wt)
                continue
            v = r.bytes_()
            if tag == 1:
                out.from_peer = v
            elif tag == 2:
                out.data = v
            elif tag == 3:
                out.seqno = v
            elif tag == 4:
                out.topic = v.decode()
            elif tag == 5:
                out.signature = v
            elif tag == 6:
                out.key = v
        return out


@dataclass
class ControlIHave:
    topic: str = ""
    message_ids: list[bytes] = field(default_factory=list)

    def encode(self) -> bytes:
        out = _tag_bytes(1, self.topic.encode())
        for mid in self.message_ids:
            out += _tag_bytes(2, mid)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ControlIHave":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if wt != 2:
                r.skip(wt)
                continue
            v = r.bytes_()
            if tag == 1:
                out.topic = v.decode()
            elif tag == 2:
                out.message_ids.append(v)
        return out


@dataclass
class ControlIWant:
    message_ids: list[bytes] = field(default_factory=list)

    def encode(self) -> bytes:
        return b"".join(_tag_bytes(1, m) for m in self.message_ids)

    @classmethod
    def decode(cls, data: bytes) -> "ControlIWant":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if tag == 1 and wt == 2:
                out.message_ids.append(r.bytes_())
            else:
                r.skip(wt)
        return out


# IDONTWANT (gossipsub v1.2) shares ControlIWant's shape
ControlIDontWant = ControlIWant


@dataclass
class ControlGraft:
    topic: str = ""

    def encode(self) -> bytes:
        return _tag_bytes(1, self.topic.encode())

    @classmethod
    def decode(cls, data: bytes) -> "ControlGraft":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if tag == 1 and wt == 2:
                out.topic = r.bytes_().decode()
            else:
                r.skip(wt)
        return out


@dataclass
class PeerInfo:
    peer_id: bytes = b""
    signed_peer_record: bytes = b""

    def encode(self) -> bytes:
        out = b""
        if self.peer_id:
            out += _tag_bytes(1, self.peer_id)
        if self.signed_peer_record:
            out += _tag_bytes(2, self.signed_peer_record)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "PeerInfo":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if wt != 2:
                r.skip(wt)
                continue
            v = r.bytes_()
            if tag == 1:
                out.peer_id = v
            elif tag == 2:
                out.signed_peer_record = v
        return out


@dataclass
class ControlPrune:
    topic: str = ""
    peers: list[PeerInfo] = field(default_factory=list)
    backoff: int = 0

    def encode(self) -> bytes:
        out = _tag_bytes(1, self.topic.encode())
        for p in self.peers:
            out += _tag_bytes(2, p.encode())
        if self.backoff:
            out += _tag_varint(3, self.backoff)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ControlPrune":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if tag == 1 and wt == 2:
                out.topic = r.bytes_().decode()
            elif tag == 2 and wt == 2:
                out.peers.append(PeerInfo.decode(r.bytes_()))
            elif tag == 3 and wt == 0:
                out.backoff = r.uvarint()
            else:
                r.skip(wt)
        return out


@dataclass
class ControlMessage:
    ihave: list[ControlIHave] = field(default_factory=list)
    iwant: list[ControlIWant] = field(default_factory=list)
    graft: list[ControlGraft] = field(default_factory=list)
    prune: list[ControlPrune] = field(default_factory=list)
    idontwant: list[ControlIWant] = field(default_factory=list)

    def encode(self) -> bytes:
        out = b""
        for tag, items in ((1, self.ihave), (2, self.iwant),
                           (3, self.graft), (4, self.prune),
                           (5, self.idontwant)):
            for item in items:
                out += _tag_bytes(tag, item.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ControlMessage":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if wt != 2:
                r.skip(wt)
                continue
            v = r.bytes_()
            if tag == 1:
                out.ihave.append(ControlIHave.decode(v))
            elif tag == 2:
                out.iwant.append(ControlIWant.decode(v))
            elif tag == 3:
                out.graft.append(ControlGraft.decode(v))
            elif tag == 4:
                out.prune.append(ControlPrune.decode(v))
            elif tag == 5:
                out.idontwant.append(ControlIWant.decode(v))
        return out

    def empty(self) -> bool:
        return not (self.ihave or self.iwant or self.graft or self.prune
                    or self.idontwant)


@dataclass
class Rpc:
    subscriptions: list[SubOpts] = field(default_factory=list)
    publish: list[PubMessage] = field(default_factory=list)
    control: ControlMessage | None = None

    def encode(self) -> bytes:
        out = b""
        for s in self.subscriptions:
            out += _tag_bytes(1, s.encode())
        for m in self.publish:
            out += _tag_bytes(2, m.encode())
        if self.control is not None and not self.control.empty():
            out += _tag_bytes(3, self.control.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Rpc":
        r, out = _Reader(data), cls()
        while not r.eof():
            key = r.uvarint()
            tag, wt = key >> 3, key & 7
            if wt != 2:
                r.skip(wt)
                continue
            v = r.bytes_()
            if tag == 1:
                out.subscriptions.append(SubOpts.decode(v))
            elif tag == 2:
                out.publish.append(PubMessage.decode(v))
            elif tag == 3:
                out.control = ControlMessage.decode(v)
        return out


# -- stream framing (varint-delimited RPCs) -----------------------------------

#: one RPC may carry a max-size gossip payload (10 MiB) plus framing slack
MAX_RPC_SIZE = 16 * 1024 * 1024


def frame(rpc: Rpc) -> bytes:
    body = rpc.encode()
    return _uvarint(len(body)) + body


def unframe(buf: bytearray) -> Rpc | None:
    """Consume one complete RPC from `buf`, or return None if partial.
    Raises PbError on an oversized declared length or a malformed body
    (the caller must treat either as peer misbehavior)."""
    r = _Reader(bytes(buf[:10]))
    try:
        n = r.uvarint()
    except PbError:
        if len(buf) >= 10:
            raise                  # 10 bytes cannot fail to hold a varint
        return None
    if n > MAX_RPC_SIZE:
        raise PbError(f"rpc frame too large ({n})")
    if r.pos + n > len(buf):
        return None
    body = bytes(buf[r.pos:r.pos + n])
    del buf[:r.pos + n]
    try:
        return Rpc.decode(body)
    except (UnicodeDecodeError, ValueError) as e:   # bad topic bytes etc.
        raise PbError(f"malformed rpc: {e}") from None
