"""Persist/reload the discv5 routing table across restarts.

Equivalent of beacon_node/network/src/persisted_dht.rs: on shutdown the
known ENRs are written to the hot database under one item key; on startup
they are loaded back into the K-buckets so the node re-enters the network
WITHOUT bootnodes.  Records are stored as their signed RLP encodings, so
a corrupted or tampered entry fails signature verification at decode
time and is dropped rather than poisoning the table.
"""
from __future__ import annotations

from .enr import Enr, EnrError

DHT_DB_KEY = b"dht_enrs"
MAX_PERSISTED = 256


def persist_dht(store, enrs: list) -> int:
    """Write the ENR list (newest-first truncated) as one item."""
    blobs = []
    for e in enrs[:MAX_PERSISTED]:
        rec = getattr(e, "record", e)     # discv5 table holds enr.Enr
        blobs.append(rec.to_rlp())
    out = b"".join(len(b).to_bytes(4, "little") + b for b in blobs)
    store.put_item(DHT_DB_KEY, out)
    return len(blobs)


def load_dht(store) -> list[Enr]:
    """Read persisted ENRs; invalid/tampered records are skipped."""
    raw = store.get_item(DHT_DB_KEY)
    if not raw:
        return []
    out: list[Enr] = []
    view = memoryview(raw)
    off = 0
    while off + 4 <= len(view):
        n = int.from_bytes(view[off:off + 4], "little")
        off += 4
        if n <= 0 or off + n > len(view):
            break
        try:
            out.append(Enr.from_rlp(bytes(view[off:off + n])))
        except (EnrError, ValueError):
            pass                          # signature/shape check failed
        off += n
    return out


def clear_dht(store) -> None:
    store.put_item(DHT_DB_KEY, b"")
