"""Fault-injecting network fabric: link control for adversarial scenarios.

Three pieces (ISSUE 7 tentpole, ROADMAP item 4):

``ScenarioClock``
    A deterministic logical clock.  The scenario engine advances it
    explicitly (one tick per duty phase); nothing in the fabric reads
    wall time, so a run's fault schedule is a pure function of the seed
    and the tick sequence.

``FaultInjector``
    The shared link-control plane.  Every node's ``FaultyTransport``
    registers under a scenario-chosen label; per-directed-link
    ``LinkPolicy`` entries then drop, delay (released on later ticks),
    or reorder gossip RPC frames, and ``partition()`` cuts whole link
    sets — existing cross-partition connections are closed and new
    dials refused, which is how long partitions look on mainnet (TCP
    sessions die; reconnection attempts fail).  ``heal()`` clears every
    policy; re-dialing is the caller's job (LocalNetwork.heal) because
    only it knows the intended topology.

``FaultyTransport``
    A Transport subclass wired to the injector: dials consult the cut
    matrix, inbound upgrades of cut peers are refused post-handshake,
    and each registered peer's ``send_gossip_rpc`` is wrapped with the
    link policy.  Req/resp (sync, status) is intentionally NOT
    per-frame-faulted: a cut link has no connection at all, and a live
    link's RPC integrity is what yamux provides — dropping arbitrary
    mux frames would corrupt the stream state machine rather than model
    a real network fault.
"""
from __future__ import annotations

import random
import threading
from dataclasses import dataclass

from .transport import Transport


class ScenarioClock:
    """Logical tick counter; advanced only by the scenario engine."""

    def __init__(self, start: int = 0):
        self.tick = start

    def advance(self, n: int = 1) -> int:
        self.tick += n
        return self.tick


@dataclass
class LinkPolicy:
    """Fault policy for one directed link (src label -> dst label)."""
    cut: bool = False           # refuse dials, close connections
    drop_rate: float = 0.0      # P(drop) per gossip RPC frame
    delay_ticks: int = 0        # hold frames for N scenario ticks
    reorder: bool = False       # shuffle frames released on the same tick

    @property
    def is_default(self) -> bool:
        return (not self.cut and self.drop_rate == 0.0
                and self.delay_ticks == 0 and not self.reorder)


class FaultInjector:
    """Seeded, deterministic link-control plane shared by every
    FaultyTransport in one scenario."""

    def __init__(self, seed: int = 0, clock: ScenarioClock | None = None):
        self.seed = seed
        self.rng = random.Random(seed)
        self.clock = clock or ScenarioClock()
        self._lock = threading.Lock()
        self._policies: dict[tuple[str, str], LinkPolicy] = {}
        self._transports: dict[str, Transport] = {}
        self._labels: dict[str, str] = {}       # node_id hex -> label
        self._addrs: dict[tuple[str, int], str] = {}
        #: [(release_tick, seq, link, send_fn, frame)]
        self._delayed: list = []
        self._seq = 0
        # counters the scenarios assert on
        self.frames_dropped = 0
        self.frames_delayed = 0
        self.frames_reordered = 0
        self.dials_refused = 0
        self.links_severed = 0

    # -- registration --------------------------------------------------------

    def register(self, label: str, transport: Transport) -> None:
        with self._lock:
            self._transports[label] = transport
            self._labels[transport.node_id] = label
            self._addrs[(transport.host, transport.port)] = label

    def label_of(self, node_id: str) -> str | None:
        return self._labels.get(node_id)

    def label_at(self, host: str, port: int) -> str | None:
        return self._addrs.get((host, port))

    # -- policy --------------------------------------------------------------

    def policy(self, src: str | None, dst: str | None) -> LinkPolicy:
        if src is None or dst is None:
            return _DEFAULT
        return self._policies.get((src, dst), _DEFAULT)

    def set_link(self, src: str, dst: str, policy: LinkPolicy,
                 symmetric: bool = True) -> None:
        with self._lock:
            self._policies[(src, dst)] = policy
            if symmetric:
                self._policies[(dst, src)] = policy
        if policy.cut:
            self._sever(src, dst)
            if symmetric:
                self._sever(dst, src)

    def partition(self, *groups) -> None:
        """Cut every link between nodes in different label groups."""
        cut = LinkPolicy(cut=True)
        for i, ga in enumerate(groups):
            for gb in groups[i + 1:]:
                for a in ga:
                    for b in gb:
                        self.set_link(a, b, cut, symmetric=True)

    def heal(self) -> None:
        """Clear every policy and flush held frames (they were faulted
        while in flight; delivering them now models late arrival)."""
        with self._lock:
            self._policies.clear()
            due, self._delayed = self._delayed, []
        for _tick, _seq, _link, send_fn, frame in sorted(due,
                                                         key=lambda d: d[1]):
            try:
                send_fn(frame)
            except Exception:
                pass    # peer may be gone; gossip is lossy by contract

    def _note_refused(self) -> None:
        with self._lock:
            self.dials_refused += 1

    def _sever(self, src: str, dst: str) -> None:
        """Close existing connections crossing a newly-cut link."""
        t = self._transports.get(src)
        if t is None:
            return
        for peer in list(t.peers.values()):
            if self._labels.get(peer.node_id) == dst:
                with self._lock:
                    self.links_severed += 1
                peer.close()

    # -- the gossip-frame data plane -----------------------------------------

    def on_gossip_frame(self, src: str, dst: str | None, send_fn,
                        frame: bytes) -> None:
        pol = self.policy(src, dst)
        if pol.is_default:
            send_fn(frame)
            return
        with self._lock:
            if pol.cut or (pol.drop_rate and
                           self.rng.random() < pol.drop_rate):
                self.frames_dropped += 1
                return
            if pol.delay_ticks > 0:
                self._seq += 1
                self.frames_delayed += 1
                self._delayed.append((self.clock.tick + pol.delay_ticks,
                                      self._seq, (src, dst), send_fn, frame))
                return
        send_fn(frame)

    def tick(self, n: int = 1) -> int:
        """Advance the scenario clock and release due delayed frames.
        Release order is deterministic: by (due tick, submit order),
        except frames on a reordering link, which are shuffled with the
        seeded RNG within their release batch."""
        released = 0
        for _ in range(n):
            now = self.clock.advance()
            with self._lock:
                due = [d for d in self._delayed if d[0] <= now]
                self._delayed = [d for d in self._delayed if d[0] > now]
                due.sort(key=lambda d: (d[0], d[1]))
                by_link: dict[tuple, list] = {}
                for d in due:
                    by_link.setdefault(d[2], []).append(d)
                batches = []
                for link, items in sorted(by_link.items()):
                    if self.policy(*link).reorder and len(items) > 1:
                        self.rng.shuffle(items)
                        self.frames_reordered += len(items)
                    batches.extend(items)
            for _tick, _seq, _link, send_fn, frame in batches:
                released += 1
                try:
                    send_fn(frame)
                except Exception:
                    pass
        return released

    # -- dial/accept control (used by FaultyTransport) -----------------------

    def refuse_dial(self, src: str, host: str, port: int) -> bool:
        dst = self.label_at(host, port)
        if dst is not None and self.policy(src, dst).cut:
            self._note_refused()
            return True
        return False

    def refuse_peer(self, src: str, node_id: str) -> bool:
        dst = self.label_of(node_id)
        return dst is not None and self.policy(src, dst).cut


_DEFAULT = LinkPolicy()


class FaultyTransport(Transport):
    """Transport with every fault choke point routed through a
    FaultInjector.  Constructed exactly like Transport plus
    (injector, label); registers itself on construction so the
    injector's address/label maps are complete before any dial."""

    def __init__(self, *args, injector: FaultInjector, label: str,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.injector = injector
        self.label = label
        injector.register(label, self)

    def dial(self, host: str, port: int):
        if self.injector.refuse_dial(self.label, host, port):
            return None
        return super().dial(host, port)

    def _register(self, peer) -> None:
        if self.injector.refuse_peer(self.label, peer.node_id):
            # an inbound upgrade (or a raced dial) crossed a cut link:
            # drop it post-handshake, exactly like a firewalled RST
            self.injector._note_refused()
            peer.close()
            return
        raw_send = peer.send_gossip_rpc
        peer.send_gossip_rpc = lambda framed: self.injector.on_gossip_frame(
            self.label, self.injector.label_of(peer.node_id), raw_send,
            framed)
        super()._register(peer)
