"""Fault-injecting network fabric: link control for adversarial scenarios.

Three pieces (ISSUE 7 tentpole, ROADMAP item 4):

``ScenarioClock``
    A deterministic logical clock.  The scenario engine advances it
    explicitly (one tick per duty phase); nothing in the fabric reads
    wall time, so a run's fault schedule is a pure function of the seed
    and the tick sequence.

``FaultInjector``
    The shared link-control plane.  Every node's ``FaultyTransport``
    registers under a scenario-chosen label; per-directed-link
    ``LinkPolicy`` entries then drop, delay (released on later ticks),
    or reorder gossip RPC frames, and ``partition()`` cuts whole link
    sets — existing cross-partition connections are closed and new
    dials refused, which is how long partitions look on mainnet (TCP
    sessions die; reconnection attempts fail).  ``heal()`` clears every
    policy; re-dialing is the caller's job (LocalNetwork.heal) because
    only it knows the intended topology.

``FaultyTransport``
    A Transport subclass wired to the injector: dials consult the cut
    matrix, inbound upgrades of cut peers are refused post-handshake,
    and each registered peer's ``send_gossip_rpc`` is wrapped with the
    link policy.  Req/resp mux frames are still never dropped (that
    would corrupt the yamux state machine, not model a network fault) —
    instead ISSUE 11 adds *application-level* req/resp adversaries:

``PeerBehavior``
    A byzantine req/resp serving policy for one directed link,
    installed with ``injector.set_behavior(server, client, behavior)``
    the way gossip faults use ``set_link``.  The server's
    ``FaultyTransport`` intercepts inbound RPC streams from that client
    and serves them adversarially — ``stall`` (read the request, never
    answer, RST late), ``junk`` (answer with real decodable blocks from
    the WRONG slot range), ``truncate`` (serve then drop the tail of
    the chunk stream), ``trickle`` (slowloris: long pauses between
    chunks), ``lying_status`` (a fake-ahead STATUS) — while every other
    link is served honestly.  This is the fabric the byzantine sync
    scenarios point at range sync and backfill.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from . import rpc as rpc_mod
from .transport import Transport


class ScenarioClock:
    """Logical tick counter; advanced only by the scenario engine."""

    def __init__(self, start: int = 0):
        self.tick = start

    def advance(self, n: int = 1) -> int:
        self.tick += n
        return self.tick


_BEHAVIOR_KINDS = ("stall", "junk", "truncate", "trickle", "lying_status")


@dataclass
class PeerBehavior:
    """Byzantine req/resp serving policy for one directed link
    (server label -> client label).  The server still speaks the wire
    protocol correctly — chunk framing, result codes, snappy — so the
    client's decode succeeds and the *content* defenses (download-time
    validation, deadlines, STATUS sanity) are what must catch it.

    kinds:
      ``stall``        read the request, answer nothing, RST after
                       ``stall_secs`` (or when the peer/stream dies).
      ``junk``         serve real, decodable blocks from the WRONG slot
                       range (request shifted by ``slot_shift``, default
                       the request's own count) — guaranteed
                       ``out_of_range`` at download-time validation.
      ``truncate``     serve honestly but drop the tail of the chunk
                       stream, keeping ``keep_fraction`` of the chunks.
      ``trickle``      slowloris: sleep ``chunk_delay`` between chunks.
      ``lying_status`` answer STATUS with ``status_lie`` fields merged
                       over the honest response (fake-ahead head).
    """
    kind: str
    protocols: tuple = ("beacon_blocks_by_range",)
    stall_secs: float = 8.0
    keep_fraction: float = 0.5
    chunk_delay: float = 0.0
    slot_shift: int | None = None
    status_lie: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in _BEHAVIOR_KINDS:
            raise ValueError(f"unknown behavior kind {self.kind!r}")
        if self.kind == "lying_status" and \
                self.protocols == ("beacon_blocks_by_range",):
            # the default protocol tuple makes no sense for a STATUS liar
            self.protocols = ("status",)


@dataclass
class LinkPolicy:
    """Fault policy for one directed link (src label -> dst label)."""
    cut: bool = False           # refuse dials, close connections
    drop_rate: float = 0.0      # P(drop) per gossip RPC frame
    delay_ticks: int = 0        # hold frames for N scenario ticks
    reorder: bool = False       # shuffle frames released on the same tick

    @property
    def is_default(self) -> bool:
        return (not self.cut and self.drop_rate == 0.0
                and self.delay_ticks == 0 and not self.reorder)


class FaultInjector:
    """Seeded, deterministic link-control plane shared by every
    FaultyTransport in one scenario."""

    def __init__(self, seed: int = 0, clock: ScenarioClock | None = None):
        self.seed = seed
        self.rng = random.Random(seed)
        self.clock = clock or ScenarioClock()
        self._lock = threading.Lock()
        self._policies: dict[tuple[str, str], LinkPolicy] = {}
        self._behaviors: dict[tuple[str, str], PeerBehavior] = {}
        self._transports: dict[str, Transport] = {}
        self._labels: dict[str, str] = {}       # node_id hex -> label
        self._addrs: dict[tuple[str, int], str] = {}
        #: [(release_tick, seq, link, send_fn, frame)]
        self._delayed: list = []
        self._seq = 0
        # counters the scenarios assert on
        self.frames_dropped = 0
        self.frames_delayed = 0
        self.frames_reordered = 0
        self.dials_refused = 0
        self.links_severed = 0
        #: byzantine req/resp serves, by behavior kind
        self.behaviors_served: dict[str, int] = {}

    # -- registration --------------------------------------------------------

    def register(self, label: str, transport: Transport) -> None:
        with self._lock:
            self._transports[label] = transport
            self._labels[transport.node_id] = label
            self._addrs[(transport.host, transport.port)] = label

    def label_of(self, node_id: str) -> str | None:
        return self._labels.get(node_id)

    def label_at(self, host: str, port: int) -> str | None:
        return self._addrs.get((host, port))

    # -- policy --------------------------------------------------------------

    def policy(self, src: str | None, dst: str | None) -> LinkPolicy:
        if src is None or dst is None:
            return _DEFAULT
        return self._policies.get((src, dst), _DEFAULT)

    def set_link(self, src: str, dst: str, policy: LinkPolicy,
                 symmetric: bool = True) -> None:
        with self._lock:
            self._policies[(src, dst)] = policy
            if symmetric:
                self._policies[(dst, src)] = policy
        if policy.cut:
            self._sever(src, dst)
            if symmetric:
                self._sever(dst, src)

    def set_behavior(self, src: str, dst: str,
                     behavior: PeerBehavior | None) -> None:
        """Install (or clear, with None) a byzantine serving behavior on
        the directed link src -> dst: requests FROM dst are served
        adversarially BY src's transport.  Directed only — a byzantine
        server is byzantine toward a chosen victim, not symmetric."""
        with self._lock:
            if behavior is None:
                self._behaviors.pop((src, dst), None)
            else:
                self._behaviors[(src, dst)] = behavior

    def behavior(self, src: str | None, dst: str | None) \
            -> PeerBehavior | None:
        if src is None or dst is None:
            return None
        return self._behaviors.get((src, dst))

    def note_behavior(self, kind: str) -> None:
        with self._lock:
            self.behaviors_served[kind] = \
                self.behaviors_served.get(kind, 0) + 1

    def partition(self, *groups) -> None:
        """Cut every link between nodes in different label groups."""
        cut = LinkPolicy(cut=True)
        for i, ga in enumerate(groups):
            for gb in groups[i + 1:]:
                for a in ga:
                    for b in gb:
                        self.set_link(a, b, cut, symmetric=True)

    def heal(self) -> None:
        """Clear every policy and flush held frames (they were faulted
        while in flight; delivering them now models late arrival)."""
        with self._lock:
            self._policies.clear()
            self._behaviors.clear()
            due, self._delayed = self._delayed, []
        for _tick, _seq, _link, send_fn, frame in sorted(due,
                                                         key=lambda d: d[1]):
            try:
                send_fn(frame)
            except Exception:
                pass    # peer may be gone; gossip is lossy by contract

    def _note_refused(self) -> None:
        with self._lock:
            self.dials_refused += 1

    def _sever(self, src: str, dst: str) -> None:
        """Close existing connections crossing a newly-cut link."""
        t = self._transports.get(src)
        if t is None:
            return
        for peer in list(t.peers.values()):
            if self._labels.get(peer.node_id) == dst:
                with self._lock:
                    self.links_severed += 1
                peer.close()

    # -- the gossip-frame data plane -----------------------------------------

    def on_gossip_frame(self, src: str, dst: str | None, send_fn,
                        frame: bytes) -> None:
        pol = self.policy(src, dst)
        if pol.is_default:
            send_fn(frame)
            return
        with self._lock:
            if pol.cut or (pol.drop_rate and
                           self.rng.random() < pol.drop_rate):
                self.frames_dropped += 1
                return
            if pol.delay_ticks > 0:
                self._seq += 1
                self.frames_delayed += 1
                self._delayed.append((self.clock.tick + pol.delay_ticks,
                                      self._seq, (src, dst), send_fn, frame))
                return
        send_fn(frame)

    def tick(self, n: int = 1) -> int:
        """Advance the scenario clock and release due delayed frames.
        Release order is deterministic: by (due tick, submit order),
        except frames on a reordering link, which are shuffled with the
        seeded RNG within their release batch."""
        released = 0
        for _ in range(n):
            now = self.clock.advance()
            with self._lock:
                due = [d for d in self._delayed if d[0] <= now]
                self._delayed = [d for d in self._delayed if d[0] > now]
                due.sort(key=lambda d: (d[0], d[1]))
                by_link: dict[tuple, list] = {}
                for d in due:
                    by_link.setdefault(d[2], []).append(d)
                batches = []
                for link, items in sorted(by_link.items()):
                    if self.policy(*link).reorder and len(items) > 1:
                        self.rng.shuffle(items)
                        self.frames_reordered += len(items)
                    batches.extend(items)
            for _tick, _seq, _link, send_fn, frame in batches:
                released += 1
                try:
                    send_fn(frame)
                except Exception:
                    pass
        return released

    # -- dial/accept control (used by FaultyTransport) -----------------------

    def refuse_dial(self, src: str, host: str, port: int) -> bool:
        dst = self.label_at(host, port)
        if dst is not None and self.policy(src, dst).cut:
            self._note_refused()
            return True
        return False

    def refuse_peer(self, src: str, node_id: str) -> bool:
        dst = self.label_of(node_id)
        return dst is not None and self.policy(src, dst).cut


_DEFAULT = LinkPolicy()


# -- byzantine req/resp serving ----------------------------------------------

def _interruptible_sleep(peer, stream, secs: float) -> None:
    """Sleep up to `secs` on a server stream thread, waking early when
    the peer or stream dies so scenario teardown never blocks on us."""
    deadline = time.monotonic() + secs
    while time.monotonic() < deadline:
        if not getattr(peer, "alive", False) or stream.reset:
            return
        time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))


def _serve_byzantine(raw_serve, behavior: PeerBehavior, peer,
                     spec, stream) -> None:
    """Serve one inbound RPC stream adversarially.  Wire framing stays
    protocol-correct (the client must successfully DECODE the lie); only
    the content / timing is hostile."""
    if behavior.kind == "stall":
        # read the request so the client believes it was accepted, then
        # go silent; the client's per-request deadline is the defense
        try:
            if spec.name != "metadata":
                rpc_mod.read_payload(stream)
        except Exception:
            pass
        _interruptible_sleep(peer, stream, behavior.stall_secs)
        try:
            stream.rst()
        except Exception:
            pass
        return
    handlers = getattr(getattr(raw_serve, "__self__", None), "handlers", {})
    handler = handlers.get(spec.name)
    if handler is None:
        # no honest handler to pervert — fall back to the real server
        raw_serve(peer, spec.id, stream)
        return
    try:
        req_ssz = b"" if spec.name == "metadata" \
            else rpc_mod.read_payload(stream)
        req = spec.dec_req(req_ssz)
    except Exception:
        try:
            stream.rst()
        except Exception:
            pass
        return
    if behavior.kind == "junk" and isinstance(req, dict) \
            and "start_slot" in req:
        # shift the requested window so the HONEST handler serves real,
        # decodable, hash-linked blocks from the wrong range — the junk
        # that only download-time validation (out_of_range) can catch
        req = dict(req)
        start = int(req["start_slot"])
        shift = behavior.slot_shift if behavior.slot_shift is not None \
            else max(1, int(req.get("count", 1)))
        req["start_slot"] = start - shift if start >= shift \
            else start + shift
    try:
        resp = handler(peer, req)
    except Exception:
        try:
            stream.write(bytes([rpc_mod.RESULT_SERVER_ERROR]))
            rpc_mod.write_payload(stream, b"server error")
            stream.close()
        except Exception:
            pass
        return
    if behavior.kind == "lying_status" and isinstance(resp, dict) \
            and behavior.status_lie:
        resp = {**resp, **behavior.status_lie}
    try:
        if spec.chunked:
            chunks = list(resp or [])
            if behavior.kind == "truncate" and len(chunks) > 1:
                keep = max(1, int(len(chunks) * behavior.keep_fraction))
                chunks = chunks[:keep]
            for chunk_hex in chunks:
                raw = spec.enc_resp(chunk_hex)
                stream.write(bytes([rpc_mod.RESULT_SUCCESS]))
                if spec.context_bytes:
                    stream.write(raw[:4])
                    rpc_mod.write_payload(stream, raw[4:])
                else:
                    rpc_mod.write_payload(stream, raw)
                if behavior.kind == "trickle" and behavior.chunk_delay > 0:
                    _interruptible_sleep(peer, stream,
                                         behavior.chunk_delay)
        elif spec.expect_response or resp:
            stream.write(bytes([rpc_mod.RESULT_SUCCESS]))
            rpc_mod.write_payload(stream, spec.enc_resp(resp))
        stream.close()
    except Exception:
        pass        # client hung up mid-lie; nothing to clean


class FaultyTransport(Transport):
    """Transport with every fault choke point routed through a
    FaultInjector.  Constructed exactly like Transport plus
    (injector, label); registers itself on construction so the
    injector's address/label maps are complete before any dial."""

    def __init__(self, *args, injector: FaultInjector, label: str,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.injector = injector
        self.label = label
        injector.register(label, self)

    # `on_rpc_stream` is a plain attribute on Transport (assigned in
    # __init__, later overwritten by RpcHandler with its bound
    # serve_stream).  Making it a data descriptor here intercepts BOTH
    # assignments, so every inbound req/resp stream can be routed through
    # the injector's behavior table without RpcHandler knowing.
    @property
    def on_rpc_stream(self):
        raw = self._raw_on_rpc_stream
        injector = getattr(self, "injector", None)
        if injector is None:        # mid-super().__init__, before wiring
            return raw

        def serve(peer, protocol_id, stream):
            dst = injector.label_of(peer.node_id)
            behavior = injector.behavior(self.label, dst)
            spec = rpc_mod.BY_ID.get(protocol_id)
            if behavior is None or spec is None \
                    or spec.name not in behavior.protocols:
                raw(peer, protocol_id, stream)
                return
            injector.note_behavior(behavior.kind)
            _serve_byzantine(raw, behavior, peer, spec, stream)

        return serve

    @on_rpc_stream.setter
    def on_rpc_stream(self, fn) -> None:
        # runs during Transport.__init__ (default lambda) before
        # self.injector exists — must not touch injector state
        self._raw_on_rpc_stream = fn

    def dial(self, host: str, port: int):
        if self.injector.refuse_dial(self.label, host, port):
            return None
        return super().dial(host, port)

    def _register(self, peer) -> None:
        if self.injector.refuse_peer(self.label, peer.node_id):
            # an inbound upgrade (or a raced dial) crossed a cut link:
            # drop it post-handshake, exactly like a firewalled RST
            self.injector._note_refused()
            peer.close()
            return
        raw_send = peer.send_gossip_rpc
        peer.send_gossip_rpc = lambda framed: self.injector.on_gossip_frame(
            self.label, self.injector.label_of(peer.node_id), raw_send,
            framed)
        super()._register(peer)
