"""multistream-select 1.0 — libp2p's protocol negotiation wire format.

Every libp2p connection/stream opens with this exchange (ref:
beacon_node/lighthouse_network/src/service/utils.rs build_transport —
the upgrade path core-upgrade::apply uses multistream-select):

    varint-length-prefixed lines, each ending "\\n":
      both sides:  "/multistream/1.0.0\\n"
      initiator:   "<protocol>\\n"
      responder:   echo the protocol to accept, or "na\\n" to refuse.

The varint is unsigned LEB128 and the length INCLUDES the trailing
newline — `/multistream/1.0.0` frames as 0x13 + 19 bytes.
"""
from __future__ import annotations

MULTISTREAM = "/multistream/1.0.0"
NA = "na"


class MultistreamError(Exception):
    pass


def write_uvarint(n: int) -> bytes:
    out = b""
    while n >= 0x80:
        out += bytes([(n & 0x7F) | 0x80])
        n >>= 7
    return out + bytes([n])


def read_uvarint(read_exact) -> int:
    """read_exact(n) -> bytes; decodes one LEB128 varint."""
    shift = v = 0
    while True:
        b = read_exact(1)[0]
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v
        shift += 7
        if shift > 63:
            raise MultistreamError("varint overflow")


def encode_msg(proto: str) -> bytes:
    line = proto.encode() + b"\n"
    return write_uvarint(len(line)) + line


def decode_msg(read_exact) -> str:
    n = read_uvarint(read_exact)
    if n == 0 or n > 1024:
        raise MultistreamError(f"bad message length {n}")
    line = read_exact(n)
    if line[-1:] != b"\n":
        raise MultistreamError("message missing newline")
    return line[:-1].decode()


class _SockIO:
    """Adapts a blocking socket to read_exact/write."""

    def __init__(self, sock):
        self.sock = sock

    def read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise MultistreamError("connection closed mid-negotiation")
            buf += chunk
        return buf

    def write(self, data: bytes) -> None:
        self.sock.sendall(data)


def negotiate_out(io, protocols: list[str]) -> str:
    """Dial side: propose protocols in order; -> the accepted one.
    `io` needs read_exact(n) and write(bytes) (socket via _SockIO, or a
    yamux/noise stream adapter)."""
    if hasattr(io, "recv"):
        io = _SockIO(io)
    io.write(encode_msg(MULTISTREAM))
    hello = decode_msg(io.read_exact)
    if hello != MULTISTREAM:
        raise MultistreamError(f"bad multistream hello {hello!r}")
    for proto in protocols:
        io.write(encode_msg(proto))
        resp = decode_msg(io.read_exact)
        if resp == proto:
            return proto
        if resp != NA:
            raise MultistreamError(f"unexpected response {resp!r}")
    raise MultistreamError(f"all protocols refused: {protocols}")


def negotiate_in(io, supported: list[str], max_proposals: int = 16) -> str:
    """Listen side: accept the first supported proposal."""
    if hasattr(io, "recv"):
        io = _SockIO(io)
    hello = decode_msg(io.read_exact)
    if hello != MULTISTREAM:
        raise MultistreamError(f"bad multistream hello {hello!r}")
    io.write(encode_msg(MULTISTREAM))
    for _ in range(max_proposals):
        proposal = decode_msg(io.read_exact)
        if proposal in supported:
            io.write(encode_msg(proposal))
            return proposal
        io.write(encode_msg(NA))
    raise MultistreamError("too many refused proposals")
