"""EIP-778 Ethereum Node Records — the REAL wire format.

Replaces the round-2 struct-packed private dialect (VERDICT r2 missing
#1): records are RLP lists `[signature, seq, k, v, ...]` with
identity scheme "v4" (secp256k1; signature = deterministic low-s ECDSA
over keccak256(rlp([seq, k, v, ...])); node id = keccak256(uncompressed
pubkey)); text form `enr:` + unpadded base64url.

Ref parity: beacon_node/lighthouse_network/src/discovery/enr.rs:186
(build_enr — eth2/attnets/syncnets/quic fields ride the same kv space);
the encoding itself matches the `enr` crate the reference re-exports.

Golden fixture: the EIP-778 sample record round-trips bit-exactly
(tests/test_enr.py) — proving interop with every other client's ENRs.
"""
from __future__ import annotations

import base64

from . import rlp, secp256k1
from .keccak import keccak256

MAX_ENR_SIZE = 300
ID_V4 = b"v4"


class EnrError(Exception):
    pass


class Enr:
    """An Ethereum Node Record.

    kv values are raw bytes; helpers expose the common typed fields
    (ip/udp/tcp/quic as ints, eth2/attnets/syncnets as bytes).
    """

    def __init__(self, seq: int = 1, kv: dict[bytes, bytes] | None = None,
                 signature: bytes = b""):
        self.seq = seq
        self.kv = dict(kv or {})
        self.signature = signature

    # -- content --------------------------------------------------------------

    def _content_items(self) -> list:
        items: list = [rlp.encode_int(self.seq)]
        for k in sorted(self.kv):
            items += [k, self.kv[k]]
        return items

    def signing_digest(self) -> bytes:
        return keccak256(rlp.encode(self._content_items()))

    def sign(self, priv: int) -> "Enr":
        pub = secp256k1.pubkey(priv)
        self.kv[b"id"] = ID_V4
        self.kv[b"secp256k1"] = secp256k1.compress(pub)
        self.signature = secp256k1.sign(priv, self.signing_digest())
        if len(self.to_rlp()) > MAX_ENR_SIZE:
            raise EnrError("record exceeds 300 bytes")
        return self

    def verify(self) -> bool:
        if self.kv.get(b"id") != ID_V4:
            return False
        try:
            pub = secp256k1.decompress(self.kv[b"secp256k1"])
        except (KeyError, ValueError):
            return False
        return secp256k1.verify(pub, self.signing_digest(), self.signature)

    @property
    def node_id(self) -> bytes:
        pub = secp256k1.decompress(self.kv[b"secp256k1"])
        return keccak256(secp256k1.uncompressed64(pub))

    @property
    def public_key(self) -> bytes:
        return self.kv[b"secp256k1"]

    # -- codec ----------------------------------------------------------------

    def to_rlp(self) -> bytes:
        return rlp.encode([self.signature] + self._content_items())

    @classmethod
    def from_rlp(cls, data: bytes) -> "Enr":
        if len(data) > MAX_ENR_SIZE:
            raise EnrError("record exceeds 300 bytes")
        items = rlp.decode(data)
        if not isinstance(items, list) or len(items) < 2 or \
                len(items) % 2 != 0:
            raise EnrError("malformed record list")
        sig, seq_raw, rest = items[0], items[1], items[2:]
        kv: dict[bytes, bytes] = {}
        prev = None
        for i in range(0, len(rest), 2):
            k, v = rest[i], rest[i + 1]
            if not isinstance(k, bytes) or not isinstance(v, bytes):
                raise EnrError("non-bytes kv")
            if prev is not None and k <= prev:
                raise EnrError("kv keys not strictly sorted")
            prev = k
            kv[k] = v
        rec = cls(seq=rlp.decode_int(seq_raw) if seq_raw else 0, kv=kv,
                  signature=sig)
        if not rec.verify():
            raise EnrError("invalid record signature")
        return rec

    def to_text(self) -> str:
        return "enr:" + base64.urlsafe_b64encode(
            self.to_rlp()).rstrip(b"=").decode()

    @classmethod
    def from_text(cls, text: str) -> "Enr":
        if not text.startswith("enr:"):
            raise EnrError("missing enr: prefix")
        b64 = text[4:]
        return cls.from_rlp(base64.urlsafe_b64decode(
            b64 + "=" * (-len(b64) % 4)))

    # -- typed field helpers --------------------------------------------------

    def _set_int(self, key: bytes, v: int | None, width: int) -> None:
        if v is None:
            self.kv.pop(key, None)
        else:
            self.kv[key] = v.to_bytes(width, "big")

    def set_fields(self, ip=None, udp: int | None = None,
                   tcp: int | None = None, quic: int | None = None,
                   eth2: bytes | None = None, attnets: bytes | None = None,
                   syncnets: bytes | None = None) -> "Enr":
        if ip is not None:
            parts = [int(x) for x in ip.split(".")] \
                if isinstance(ip, str) else list(ip)
            self.kv[b"ip"] = bytes(parts)
        for key, val in ((b"udp", udp), (b"tcp", tcp), (b"quic", quic)):
            if val is not None:
                self._set_int(key, val, 2)
        for key, val in ((b"eth2", eth2), (b"attnets", attnets),
                         (b"syncnets", syncnets)):
            if val is not None:
                self.kv[key] = val
        return self

    def ip(self) -> str | None:
        raw = self.kv.get(b"ip")
        return ".".join(str(b) for b in raw) if raw else None

    def udp(self) -> int | None:
        raw = self.kv.get(b"udp")
        return int.from_bytes(raw, "big") if raw else None

    def tcp(self) -> int | None:
        raw = self.kv.get(b"tcp")
        return int.from_bytes(raw, "big") if raw else None

    def quic(self) -> int | None:
        raw = self.kv.get(b"quic")
        return int.from_bytes(raw, "big") if raw else None

    def eth2(self) -> bytes | None:
        return self.kv.get(b"eth2")

    def attnets(self) -> bytes | None:
        return self.kv.get(b"attnets")

    def syncnets(self) -> bytes | None:
        return self.kv.get(b"syncnets")
