"""NetworkService: wires transport/gossip/rpc/peers/sync to the chain.

Equivalent of /root/reference/beacon_node/network/src/{service.rs:160,
router.rs:33} + network_beacon_processor/{gossip_methods,rpc_methods}.rs:
gossip is validated through the chain's gossip pipelines then imported;
RPC serves blocks from the store; status exchange drives sync.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass

from ..chain.errors import AttestationError, BlockError
from ..obs import causal
from ..specs.chain_spec import compute_fork_digest
from ..ssz import deserialize, htr, serialize
from ..utils.threads import ThreadGroup
from .gossip import GossipEngine, Topic
from .peer_manager import PeerManager
from .rpc import RpcHandler, StatusMessage
from .sync import SyncManager, encode_block
from .transport import Transport
from .yamux import YamuxError


@dataclass
class NetworkConfig:
    host: str = "127.0.0.1"
    port: int = 0
    target_peers: int = 16
    boot_nodes: list = None
    # UPnP port-mapping attempt at startup (network/src/nat.rs); off by
    # default — it multicasts on the LAN
    upnp_enabled: bool = False
    # False -> serve only two node-id-derived attestation subnets (the
    # reference's default per-node load); the ENR advertisement must
    # match what is actually subscribed
    subscribe_all_subnets: bool = True
    # "noise" | "plaintext" | None (auto: noise when the cryptography
    # package is available, else the plaintext fallback — transport.py)
    security: str | None = None
    # True -> attestation gossip defers SIGNATURE verification to the
    # beacon processor's batch queues (structural checks stay inline on
    # the socket thread); requires a processor.  This is the reference's
    # batch path (batch.rs) and what the signature-flood scenario leans
    # on: one multi-set verification per drained batch, per-item
    # fallback splitting when a batch contains an invalid signature.
    batch_gossip_verification: bool = False


@dataclass
class DeferredAttestation:
    """Gossip attestation that passed structural checks inline; its
    signature verification rides the processor's batch queue.  The
    sender's node id rides along so a failed signature can still be
    charged to the peer that gossiped it (the inline path reports
    validation results synchronously; the batch path must not lose
    that attribution)."""
    attestation: object
    subnet_id: int
    peer_id: str | None = None


class NetworkService:
    def __init__(self, chain, config: NetworkConfig | None = None,
                 processor=None, transport_factory=None,
                 label: str | None = None):
        """`processor`: optional BeaconProcessor — accepted gossip is then
        imported through its priority queues (with attestation batching)
        instead of inline on the socket reader thread.
        `transport_factory`: optional (host, port) -> Transport hook so a
        fault-injecting transport (network/faults.py) can be swapped in
        without subclassing the service.
        `label`: graftpath node label stamped on every causal span this
        node opens (defaults to the transport's label / node-id prefix)."""
        self.chain = chain
        self.config = config or NetworkConfig()
        self.processor = processor
        self._threads = ThreadGroup("network_service")
        self._stopping = False
        if processor is not None:
            processor.batch_handler = self._attestation_batch
            processor.start()
            # chain hooks drive the park-and-replay queue (slot ticks +
            # block imports, work_reprocessing_queue.rs)
            chain.processor = processor
        if transport_factory is not None:
            self.transport = transport_factory(self.config.host,
                                               self.config.port)
        else:
            self.transport = Transport(self.config.host, self.config.port,
                                       security=self.config.security)
        digest = compute_fork_digest(
            chain.head().head_state.fork.current_version,
            chain.genesis_validators_root)
        self.gossip = GossipEngine(self.transport, digest)
        self.rpc = RpcHandler(self.transport)
        if label is not None:
            self.gossip.node_label = label
            self.rpc.node_label = label
        self.node_label = self.gossip.node_label
        self.peers = PeerManager(self.config.target_peers)
        self.sync = SyncManager(chain, self.rpc, self.peers)

        self.transport.on_peer = self._on_peer
        self.transport.on_gossip_rpc = \
            lambda peer, rpc: self.gossip.handle_rpc(peer, rpc)
        self.transport.on_disconnect = self._on_disconnect
        self.gossip.validator = self._validate_gossip
        self.gossip.on_message = self._deliver_gossip
        self.gossip.on_ignored = self._on_ignored_gossip
        self.gossip.on_validation_result = \
            lambda peer, topic, result: self.peers.report(peer.node_id,
                                                          result)
        # unknown-parent chases in flight, keyed by block root (bounded:
        # a spammer gossiping orphan blocks must not fan out lookups)
        self._parent_lookups: set[bytes] = set()
        self._parent_lookup_lock = threading.Lock()
        self.gossip.peer_score = self.peers.score
        self.rpc.on_rate_limited = \
            lambda peer, proto: self.peers.report(peer.node_id,
                                                  "rate_limited")
        self.peers.on_ban = self._ban

        self.gossip.subscribe(Topic.BLOCK)
        self.gossip.subscribe(Topic.AGGREGATE)
        self.gossip.subscribe(Topic.VOLUNTARY_EXIT)
        self.gossip.subscribe(Topic.PROPOSER_SLASHING)
        self.gossip.subscribe(Topic.ATTESTER_SLASHING)
        n_subnets = chain.spec.preset.max_committees_per_slot
        if self.config.subscribe_all_subnets:
            self.attnet_subnets = list(range(n_subnets))
        else:
            nid = int(self.transport.node_id[:16], 16)
            self.attnet_subnets = sorted({nid % n_subnets,
                                          (nid + 1) % n_subnets})
        for subnet in self.attnet_subnets:
            self.gossip.subscribe(Topic.attestation_subnet(subnet))
        # all four sync-committee subnets (SYNC_COMMITTEE_SUBNET_COUNT);
        # recorded so /eth/v1/node/identity can report syncnets honestly
        self.syncnet_subnets = list(range(4))
        for subnet in self.syncnet_subnets:
            self.gossip.subscribe(Topic.sync_subnet(subnet))
        # PeerDAS custody subnets derived from our authenticated node id
        from ..chain.data_columns import (
            compute_subnet_for_column, get_custody_columns,
        )
        self.custody_columns = get_custody_columns(
            bytes.fromhex(self.transport.node_id))
        for subnet in sorted({compute_subnet_for_column(c)
                              for c in self.custody_columns}):
            self.gossip.subscribe(Topic.data_column_subnet(subnet))

        self.rpc.register("status", self._handle_status)
        self.rpc.register("ping", lambda peer, p: {"seq": 1})
        self.rpc.register("metadata",
                          lambda peer, p: {"seq_number": 1, "attnets": "ff"})
        self.rpc.register("goodbye", self._handle_goodbye)
        self.rpc.register("beacon_blocks_by_range", self._blocks_by_range)
        self.rpc.register("beacon_blocks_by_root", self._blocks_by_root)
        # light-client protocols served straight from the server cache
        # (ref: lighthouse_network/src/rpc/protocol.rs:236-266 entries)
        self.rpc.register("light_client_bootstrap", self._lc_bootstrap)
        self.rpc.register("light_client_finality_update",
                          self._lc_finality_update)
        self.rpc.register("light_client_optimistic_update",
                          self._lc_optimistic_update)
        self.rpc.register("light_client_updates_by_range",
                          self._lc_updates_by_range)
        # LAST: only a fully-constructed service may serve the
        # /eth/v1/node/* API view (a failed Transport bind must leave
        # chain.network_service unset — r5 review)
        chain.network_service = self

    @property
    def port(self) -> int:
        return self.transport.port

    def start(self) -> None:
        self.transport.start()
        self.gossip.start_heartbeat()
        for (host, port) in (self.config.boot_nodes or []):
            self.dial(host, port)

    def stop(self) -> None:
        # Shutdown ordering is structural (task_executor/src/lib.rs:12-28;
        # round-5 leak, VERDICT §weak 2): first refuse new work (the
        # _stopping flag parks status exchanges before they can call into
        # a closing sync executor), then stop the things that CREATE work
        # (heartbeat, sync downloads), then join the service threads that
        # might be mid-request, then close the sockets they would have
        # written to, and only then stop the work sink.
        self._stopping = True
        self.gossip.stop(join=True)
        self.sync.stop()                    # no new download futures
        self._threads.join_all(timeout=3)   # status exchanges, timers
        self.transport.stop()
        if self.processor is not None:
            self.processor.stop(join=True)

    def dial(self, host: str, port: int):
        peer = self.transport.dial(host, port)
        return peer

    # -- plumbing ------------------------------------------------------------

    def _on_peer(self, peer) -> None:
        if self._stopping:
            return
        self.peers.on_connect(peer.node_id)
        self.gossip.on_peer_connected(peer)
        self._threads.spawn(self._status_exchange, peer,
                            name="status_exchange")

    def _on_disconnect(self, peer) -> None:
        self.peers.on_disconnect(peer.node_id)
        self.gossip.on_peer_disconnected(peer.node_id)
        # drop the peer from range-sync chain pools too: a banned or
        # vanished peer left in a pool burns a download attempt per
        # batch on guaranteed "peer gone" failures (ISSUE 11)
        self.sync.range.remove_peer(peer.node_id)

    def _ban(self, node_id: str) -> None:
        peer = self.transport.peers.get(node_id)
        if peer is not None:
            peer.close()

    def local_status(self) -> StatusMessage:
        chain = self.chain
        head = chain.head()
        fin_epoch, fin_root = chain.finalized_checkpoint()
        return StatusMessage(
            fork_digest=self.gossip.fork_digest,
            finalized_root=fin_root, finalized_epoch=fin_epoch,
            head_root=head.head_block_root,
            head_slot=head.head_state.slot)

    def _status_exchange(self, peer) -> None:
        if self._stopping:
            return
        try:
            resp = self.rpc.request(peer, "status",
                                    self.local_status().to_json())
            status = StatusMessage.from_json(resp)
        except (TimeoutError, RuntimeError, KeyError, ValueError,
                OSError, YamuxError):
            # OSError/YamuxError: the peer tore down mid-exchange — this
            # runs on its own thread, so failures must not escape
            return
        if status.fork_digest != self.gossip.fork_digest:
            try:
                # spec goodbye reason codes: 1 shutdown, 2 irrelevant
                # network, 3 fault/error
                self.rpc.request(peer, "goodbye", {"reason": 2},
                                 timeout=2.0)
            except (TimeoutError, RuntimeError):
                pass
            finally:
                peer.close()
            return
        if self._stopping:
            # stop() won the race while we waited on the exchange: don't
            # kick a sync drive against the closed download executor
            return
        self.peers.set_status(peer.node_id, status)
        self.sync.maybe_sync()

    def _handle_status(self, peer, payload) -> dict:
        try:
            status = StatusMessage.from_json(payload)
            self.peers.set_status(peer.node_id, status)
        except (KeyError, ValueError):
            pass
        return self.local_status().to_json()

    def _handle_goodbye(self, peer, payload) -> dict:
        # respond first, close shortly after, so the requester sees the
        # ack; the tracked timer is cancelled if the service stops first
        timer = threading.Timer(0.2, peer.close)
        timer.daemon = True
        self._threads.track(timer)
        timer.start()
        return {}

    def _blocks_by_range(self, peer, payload) -> list[str]:
        start = int(payload["start_slot"])
        count = min(int(payload["count"]),
                    self.chain.spec.max_request_blocks)
        out = []
        seen = None
        for slot in range(start, start + count):
            root = self.chain.block_root_at_slot(slot)
            if root is None or root == seen:
                continue
            seen = root
            blk = self.chain.store.get_block(root)
            if blk is not None and blk.message.slot >= start:
                out.append(encode_block(blk, self.chain))
        return out

    def _blocks_by_root(self, peer, payload) -> list[str]:
        out = []
        for root_hex in payload.get("roots", [])[:64]:
            blk = self.chain.store.get_block(bytes.fromhex(root_hex))
            if blk is not None:
                out.append(encode_block(blk, self.chain))
        return out

    # -- light-client req/resp serving ---------------------------------------

    def _lc_chunk(self, obj) -> str:
        data = serialize(type(obj).ssz_type, obj)
        return (self.gossip.fork_digest + data).hex()

    def _lc_bootstrap(self, peer, payload) -> list[str]:
        from ..chain.light_client import bootstrap_ssz
        b = self.chain.light_client_cache.produce_bootstrap(
            bytes.fromhex(payload["root"]))
        try:
            return [self._lc_chunk(bootstrap_ssz(self.chain.T, b))] \
                if b is not None else []
        except ValueError:
            return []      # electra-depth branches don't fit the wire form

    def _lc_finality_update(self, peer, payload) -> list[str]:
        from ..chain.light_client import finality_update_ssz
        u = self.chain.light_client_cache.latest_finality_update
        try:
            return [self._lc_chunk(finality_update_ssz(self.chain.T, u))] \
                if u is not None else []
        except ValueError:
            return []

    def _lc_optimistic_update(self, peer, payload) -> list[str]:
        from ..chain.light_client import optimistic_update_ssz
        u = self.chain.light_client_cache.latest_optimistic_update
        return [self._lc_chunk(optimistic_update_ssz(self.chain.T, u))] \
            if u is not None else []

    def _lc_updates_by_range(self, peer, payload) -> list[str]:
        from ..chain.light_client import update_ssz
        updates = self.chain.light_client_cache.updates_by_range(
            int(payload["start_period"]), int(payload["count"]))
        out = []
        for u in updates:
            try:
                out.append(self._lc_chunk(update_ssz(self.chain.T, u)))
            except ValueError:
                continue
        return out

    # -- gossip validation / delivery ----------------------------------------

    def _validate_gossip(self, topic: str, data: bytes):
        """Returns (result, ctx): ctx carries the verified object to
        delivery on this thread (no shared mutable hand-off)."""
        chain = self.chain
        try:
            if topic == Topic.BLOCK:
                fork = chain.spec.fork_name_at_slot(max(chain.slot(), 0))
                signed = deserialize(
                    chain.T.SignedBeaconBlock[fork].ssz_type, data)
                try:
                    chain.verify_block_for_gossip(signed)
                except BlockError as e:
                    if e.kind == "future_slot":
                        self._park_early_block(signed)
                    elif e.kind == "parent_unknown":
                        # a fork at our height gossips blocks whose whole
                        # branch we missed (post-partition): range sync
                        # never triggers (peer STATUS isn't ahead), so
                        # the gossip pipeline must chase the ancestry —
                        # hand the block to on_ignored for a parent
                        # lookup against the peer that sent it
                        return "ignore", ("unknown_parent", signed)
                    raise
                return "accept", signed
            if topic.startswith("beacon_attestation_"):
                att = deserialize(chain.T.Attestation.ssz_type, data)
                if self.config.batch_gossip_verification and \
                        self.processor is not None:
                    from ..chain.attestation_verification import (
                        verify_unaggregated_checks,
                    )
                    try:
                        # structural checks inline (cheap rejects stay on
                        # the socket thread); signature check deferred to
                        # the processor's batch drain
                        verify_unaggregated_checks(chain, att)
                    except AttestationError as e:
                        self._maybe_park_attestation(att, e,
                                                     aggregated=False)
                        raise
                    subnet = int(topic.rsplit("_", 1)[-1])
                    return "accept", DeferredAttestation(att, subnet)
                try:
                    v = chain.verify_unaggregated_attestation_for_gossip(att)
                except AttestationError as e:
                    self._maybe_park_attestation(att, e, aggregated=False)
                    raise
                return "accept", v
            if topic == Topic.AGGREGATE:
                agg = deserialize(
                    chain.T.SignedAggregateAndProof.ssz_type, data)
                try:
                    v = chain.verify_aggregated_attestation_for_gossip(agg)
                except AttestationError as e:
                    self._maybe_park_attestation(agg, e, aggregated=True)
                    raise
                return "accept", v
            if topic.startswith("data_column_sidecar_"):
                sc = deserialize(chain.T.DataColumnSidecar.ssz_type, data)
                chain.process_data_column_sidecar(sc)
                return "accept", sc
            if topic.startswith("sync_committee_"):
                msg = deserialize(chain.T.SyncCommitteeMessage.ssz_type,
                                  data)
                chain.sync_committee_pool.verify_and_add_message(msg)
                return "accept", None
            return "accept", None
        except BlockError as e:
            if e.kind in ("parent_unknown",):
                return "ignore", None
            return ("reject" if e.kind in ("repeat_proposal",
                                           "invalid_signature",
                                           "incorrect_proposer",
                                           "invalid_block")
                    else "ignore"), None
        except AttestationError as e:
            return ("ignore" if e.kind in ("prior_attestation_known",
                                           "unknown_head_block",
                                           "future_slot") else "reject"), \
                None
        except Exception:
            return "reject", None

    # -- park-and-replay (work_reprocessing_queue.rs) ------------------------

    def _park_early_block(self, signed) -> None:
        """Early-arriving gossip block: park until its slot starts, then
        re-enter the processor as GOSSIP_BLOCK work (early-block parking,
        work_reprocessing_queue.rs:1-60)."""
        if self.processor is None:
            return
        from ..beacon_processor import Work, WorkType
        self.processor.reprocess.park_until_slot(
            signed.message.slot,
            Work(WorkType.GOSSIP_BLOCK,
                 lambda: self._replay_block(signed)),
            current_slot=self.chain.slot())

    def _replay_block(self, signed) -> None:
        """Replayed early block goes through the SAME pipeline as fresh
        gossip: gossip verification first (equivocation/observed-proposer
        bookkeeping), then import with an unknown-parent lookup fallback."""
        try:
            self.chain.verify_block_for_gossip(signed)
        except BlockError:
            return
        try:
            self.chain.process_block(signed, proposal_already_verified=True)
        except BlockError as e:
            if e.kind == "parent_unknown":
                best = self.peers.best_peer_for_sync()
                if best is not None:
                    self.sync.lookup_unknown_parent(htr(signed.message),
                                                    best.node_id)

    def _maybe_park_attestation(self, att_or_agg, err, aggregated) -> None:
        """Unknown-root attestations wait for their block; future-slot
        attestations wait for their slot (unknown-root replay,
        work_reprocessing_queue.rs:1-60)."""
        if self.processor is None:
            return
        from ..beacon_processor import Work, WorkType
        data = (att_or_agg.message.aggregate.data if aggregated
                else att_or_agg.data)
        kind = (WorkType.GOSSIP_AGGREGATE if aggregated
                else WorkType.GOSSIP_ATTESTATION)
        work = Work(kind, lambda: self._replay_attestation(att_or_agg,
                                                           aggregated))
        if err.kind == "unknown_head_block":
            self.processor.reprocess.park_until_block(
                bytes(data.beacon_block_root), work,
                current_slot=self.chain.slot())
        elif err.kind == "future_slot":
            self.processor.reprocess.park_until_slot(
                data.slot, work, current_slot=self.chain.slot())

    def _replay_attestation(self, att_or_agg, aggregated) -> None:
        try:
            if aggregated:
                v = self.chain.verify_aggregated_attestation_for_gossip(
                    att_or_agg)
            else:
                v = self.chain.verify_unaggregated_attestation_for_gossip(
                    att_or_agg)
            self._apply_verified(v)
        except AttestationError:
            pass

    def _deliver_gossip(self, topic: str, data: bytes, peer, ctx) -> None:
        """Route accepted gossip into the priority processor when present
        (network_beacon_processor role), else import inline."""
        if ctx is None or self._stopping:
            return
        if topic == Topic.AGGREGATE:
            # publish->deliver latency, keyed by the content-derived
            # message id the publisher stamped (obs/causal.py)
            causal.tracker().on_attestation_delivered(
                self.gossip._message_id(topic, data))
        if self.processor is not None:
            from ..beacon_processor import Work, WorkType
            if topic == Topic.BLOCK:
                self.processor.submit(Work(
                    WorkType.GOSSIP_BLOCK,
                    lambda: self._import_gossip_block(ctx, peer)))
            elif topic.startswith("beacon_attestation_"):
                if isinstance(ctx, DeferredAttestation):
                    ctx.peer_id = peer.node_id
                self.processor.submit(Work(
                    WorkType.GOSSIP_ATTESTATION, lambda: None,
                    batchable_payload=ctx))
            elif topic == Topic.AGGREGATE:
                self.processor.submit(Work(
                    WorkType.GOSSIP_AGGREGATE,
                    lambda: self._apply_verified(ctx),
                    batchable_payload=ctx))
            return
        try:
            if topic == Topic.BLOCK:
                self._import_gossip_block(ctx, peer)
            elif topic.startswith("beacon_attestation_") or \
                    topic == Topic.AGGREGATE:
                self._apply_verified(ctx)
        except Exception:
            import logging
            logging.getLogger("lighthouse_tpu.network").exception(
                "gossip delivery failed")

    MAX_PARENT_LOOKUPS = 4

    def _on_ignored_gossip(self, topic: str, data: bytes, peer,
                           ctx) -> None:
        """An IGNOREd message the validator wants chased: today that is
        only ("unknown_parent", signed_block) — a fork branch we missed
        entirely (e.g. the far side of a healed partition at equal
        height, where no peer STATUS ever looks 'ahead' and range sync
        stays idle).  Resolve it with a by-root ancestry walk against
        the peer that gossiped the tip."""
        if self._stopping or not isinstance(ctx, tuple) \
                or ctx[0] != "unknown_parent":
            return
        signed = ctx[1]
        root = htr(signed.message)
        with self._parent_lookup_lock:
            if root in self._parent_lookups \
                    or len(self._parent_lookups) >= self.MAX_PARENT_LOOKUPS:
                return
            self._parent_lookups.add(root)
        try:
            self.sync.lookup_unknown_parent(root, peer.node_id)
        except Exception:
            import logging
            logging.getLogger("lighthouse_tpu.network").exception(
                "unknown-parent lookup failed (root %s)", root.hex())
        finally:
            with self._parent_lookup_lock:
                self._parent_lookups.discard(root)

    def _import_gossip_block(self, signed, peer) -> None:
        try:
            self.chain.process_block(signed, proposal_already_verified=True)
        except BlockError as e:
            if e.kind == "parent_unknown":
                self.sync.lookup_unknown_parent(htr(signed.message),
                                                peer.node_id)

    def _apply_verified(self, v) -> None:
        self.chain.apply_attestation_to_fork_choice(v)
        self.chain.add_to_op_pool(v)

    def _attestation_batch(self, verified_list) -> None:
        deferred = []
        for v in verified_list:
            if isinstance(v, DeferredAttestation):
                deferred.append(v)
            elif v is not None:
                self._apply_verified(v)
        if deferred:
            # one multi-set verification for the whole drained batch;
            # invalid entries come back as AttestationError after the
            # per-item fallback split (attestation_verification.py)
            results = self.chain \
                .batch_verify_unaggregated_attestations_for_gossip(
                    [(d.attestation, d.subnet_id) for d in deferred])
            for d, r in zip(deferred, results):
                if not isinstance(r, Exception):
                    self._apply_verified(r)
                elif isinstance(r, AttestationError) \
                        and r.kind == "bad_signature" \
                        and d.peer_id is not None:
                    # deferred-path parity with the inline path: a peer
                    # gossiping provably invalid signatures is charged a
                    # reject even though validation ran on the batch
                    self.peers.report(d.peer_id, "reject")

    # -- publishing ----------------------------------------------------------

    def publish_block(self, signed_block) -> None:
        data = serialize(type(signed_block).ssz_type, signed_block)
        root = htr(signed_block.message)
        # propagation clock starts at the origin publish; every other
        # node's import of this root observes block_propagation_seconds
        causal.tracker().on_block_published(root)
        self.gossip.publish(Topic.BLOCK, data, root=root)

    def publish_attestation(self, attestation, subnet: int = 0) -> None:
        data = serialize(type(attestation).ssz_type, attestation)
        self.gossip.publish(Topic.attestation_subnet(subnet), data)

    def publish_aggregate(self, signed_aggregate) -> None:
        data = serialize(type(signed_aggregate).ssz_type, signed_aggregate)
        causal.tracker().on_attestation_published(
            self.gossip._message_id(Topic.AGGREGATE, data))
        self.gossip.publish(Topic.AGGREGATE, data)

    def publish_sync_committee_message(self, msg, subnet: int = 0) -> None:
        data = serialize(type(msg).ssz_type, msg)
        self.gossip.publish(Topic.sync_subnet(subnet), data)
