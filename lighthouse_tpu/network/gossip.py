"""Gossip pubsub (gossipsub's role; flood-publish with dedup + validation).

Topics mirror lighthouse_network/src/types/topics.rs:109: beacon_block,
beacon_aggregate_and_proof, beacon_attestation_{subnet}, voluntary_exit,
proposer_slashing, attester_slashing, sync_committee_{subnet},
bls_to_execution_change, blob_sidecar_{i}. Message ids are content hashes
(gossipsub v1.1 message-id) and each message is validated before forwarding
(accept/ignore/reject -> peer scoring).
"""
from __future__ import annotations

import hashlib
import json
import threading
import zlib
from collections import OrderedDict


class Topic:
    BLOCK = "beacon_block"
    AGGREGATE = "beacon_aggregate_and_proof"
    VOLUNTARY_EXIT = "voluntary_exit"
    PROPOSER_SLASHING = "proposer_slashing"
    ATTESTER_SLASHING = "attester_slashing"
    BLS_CHANGE = "bls_to_execution_change"

    @staticmethod
    def attestation_subnet(subnet: int) -> str:
        return f"beacon_attestation_{subnet}"

    @staticmethod
    def sync_subnet(subnet: int) -> str:
        return f"sync_committee_{subnet}"

    @staticmethod
    def blob_sidecar(index: int) -> str:
        return f"blob_sidecar_{index}"


class GossipEngine:
    """validator(topic, data) -> 'accept' | 'ignore' | 'reject'."""

    GOSSIP_FRAME = 1
    SEEN_CAP = 16384

    def __init__(self, transport, fork_digest: bytes):
        self.transport = transport
        self.fork_digest = fork_digest
        self.subscriptions: set[str] = set()
        # validator returns (result, ctx); ctx is handed to on_message so the
        # verified/deserialized object flows thread-locally (no shared state)
        self.validator = lambda topic, data: ("accept", None)
        self.on_message = lambda topic, data, peer, ctx: None
        self.on_validation_result = lambda peer, topic, result: None
        self._seen: OrderedDict[bytes, bool] = OrderedDict()
        self._lock = threading.Lock()

    def subscribe(self, topic: str) -> None:
        self.subscriptions.add(topic)

    def unsubscribe(self, topic: str) -> None:
        self.subscriptions.discard(topic)

    def _message_id(self, topic: str, data: bytes) -> bytes:
        return hashlib.sha256(self.fork_digest + topic.encode()
                              + data).digest()[:20]

    def _mark_seen(self, mid: bytes) -> bool:
        with self._lock:
            if mid in self._seen:
                return True
            self._seen[mid] = True
            while len(self._seen) > self.SEEN_CAP:
                self._seen.popitem(last=False)
            return False

    def publish(self, topic: str, data: bytes,
                exclude_peer: str | None = None) -> int:
        mid = self._message_id(topic, data)
        self._mark_seen(mid)
        msg = json.dumps({"topic": topic,
                          "digest": self.fork_digest.hex()}).encode()
        frame = len(msg).to_bytes(2, "little") + msg + zlib.compress(data)
        sent = 0
        for peer in list(self.transport.peers.values()):
            if peer.node_id == exclude_peer:
                continue
            peer.send_frame(self.GOSSIP_FRAME, frame)
            sent += 1
        return sent

    def handle_frame(self, peer, payload: bytes) -> None:
        try:
            hlen = int.from_bytes(payload[:2], "little")
            head = json.loads(payload[2:2 + hlen])
            data = zlib.decompress(payload[2 + hlen:])
            topic = head["topic"]
        except (ValueError, KeyError, zlib.error):
            self.on_validation_result(peer, "?", "reject")
            return
        if head.get("digest") != self.fork_digest.hex():
            self.on_validation_result(peer, topic, "reject")
            return
        if topic not in self.subscriptions:
            return
        mid = self._message_id(topic, data)
        if self._mark_seen(mid):
            return
        result, ctx = self.validator(topic, data)
        self.on_validation_result(peer, topic, result)
        if result == "accept":
            # forward to the mesh (flood) and deliver locally
            self.publish(topic, data, exclude_peer=peer.node_id)
            self.on_message(topic, data, peer, ctx)
