"""Gossipsub-style mesh pubsub.

Round 1 shipped flood-publish; VERDICT item 5 demanded the real thing.
This engine implements the gossipsub v1.1 mechanics the reference vendors
(lighthouse_network/gossipsub/src/behaviour.rs): per-topic MESH of degree
D (GRAFT/PRUNE with prune-backoff), lazy gossip (IHAVE windows over a
message cache + IWANT pulls), subscription tracking, and validation
results feeding peer scores (accept/ignore/reject -> PeerManager) —
plus v1.2 IDONTWANT (the feature the reference's vendored fork exists
for): on receiving a large message, mesh peers are told not to forward
us their copy, cutting duplicate bandwidth for blocks/blobs.
Delivery is O(mesh degree), not O(peers).

Wire (inside one AEAD transport frame, kind=1):
  [u8 msg_kind][body]
    DATA:        [u8 tlen][topic][4B fork_digest][raw-snappy payload]
    SUB/UNSUB/GRAFT/PRUNE: [u8 tlen][topic]
    IHAVE:       [u8 tlen][topic][u16 n][20B mid]*n
    IWANT/IDONTWANT: [u16 n][20B mid]*n

Topics mirror lighthouse_network/src/types/topics.rs:109.  Message ids
are sha256(fork_digest || topic || data)[:20] (gossipsub v1.1 style).
"""
from __future__ import annotations

import hashlib
import random
import struct
import threading
from collections import OrderedDict

from . import snappy


class Topic:
    BLOCK = "beacon_block"
    AGGREGATE = "beacon_aggregate_and_proof"
    VOLUNTARY_EXIT = "voluntary_exit"
    PROPOSER_SLASHING = "proposer_slashing"
    ATTESTER_SLASHING = "attester_slashing"
    BLS_CHANGE = "bls_to_execution_change"

    @staticmethod
    def attestation_subnet(subnet: int) -> str:
        return f"beacon_attestation_{subnet}"

    @staticmethod
    def sync_subnet(subnet: int) -> str:
        return f"sync_committee_{subnet}"

    @staticmethod
    def blob_sidecar(index: int) -> str:
        return f"blob_sidecar_{index}"

    @staticmethod
    def data_column_subnet(subnet: int) -> str:
        return f"data_column_sidecar_{subnet}"


(MSG_DATA, MSG_SUB, MSG_UNSUB, MSG_GRAFT, MSG_PRUNE, MSG_IHAVE, MSG_IWANT,
 MSG_IDONTWANT) = range(8)


def _enc_topic(topic: str) -> bytes:
    t = topic.encode()
    return bytes([len(t)]) + t


def _dec_topic(body: bytes) -> tuple[str, bytes]:
    tlen = body[0]
    return body[1:1 + tlen].decode(), body[1 + tlen:]


class GossipEngine:
    """validator(topic, data) -> ('accept'|'ignore'|'reject', ctx)."""

    GOSSIP_FRAME = 1
    SEEN_CAP = 16384
    D = 8
    D_LO = 6
    D_HI = 12
    HEARTBEAT_SECS = 1.0
    MCACHE_WINDOWS = 5          # kept windows
    GOSSIP_WINDOWS = 3          # advertised via IHAVE
    PRUNE_BACKOFF = 60.0
    MAX_IHAVE_PER_MSG = 64
    MAX_PAYLOAD = 10 * 1024 * 1024
    #: messages at least this large trigger IDONTWANT to mesh peers
    #: (gossipsub v1.2: only worth the control traffic for big payloads)
    IDONTWANT_THRESHOLD = 4 * 1024
    MAX_DONTWANT_PER_PEER = 256

    def __init__(self, transport, fork_digest: bytes):
        self.transport = transport
        self.fork_digest = fork_digest
        self.subscriptions: set[str] = set()
        self.validator = lambda topic, data: ("accept", None)
        self.on_message = lambda topic, data, peer, ctx: None
        self.on_validation_result = lambda peer, topic, result: None
        self.peer_score = lambda node_id: 0.0   # injected by the service
        self.mesh: dict[str, set[str]] = {}
        self.peer_topics: dict[str, set[str]] = {}
        self._backoff: dict[tuple[str, str], float] = {}
        self._seen: OrderedDict[bytes, bool] = OrderedDict()
        # mcache: mid -> (topic, data); windows: list of sets of mids
        self._mcache: dict[bytes, tuple[str, bytes]] = {}
        self._windows: list[set[bytes]] = [set()]
        self._iwant_budget: dict[str, int] = {}
        self._iwant_served: dict[str, set[bytes]] = {}
        # peer -> {mid: heartbeat count at receipt}: mids that peer told
        # us NOT to forward to it (v1.2)
        self._dontwant: dict[str, OrderedDict[bytes, int]] = {}
        self._hb_count = 0
        self._lock = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self._rng = random.Random()

    # -- lifecycle -----------------------------------------------------------

    def start_heartbeat(self) -> None:
        if self._hb_thread is None:
            self._hb_thread = threading.Thread(target=self._hb_loop,
                                               daemon=True)
            self._hb_thread.start()

    def stop(self) -> None:
        self._hb_stop.set()

    def on_peer_connected(self, peer) -> None:
        for topic in sorted(self.subscriptions):
            self._send(peer, MSG_SUB, _enc_topic(topic))

    def on_peer_disconnected(self, node_id: str) -> None:
        with self._lock:
            self.peer_topics.pop(node_id, None)
            self._dontwant.pop(node_id, None)
            for members in self.mesh.values():
                members.discard(node_id)

    # -- subscriptions -------------------------------------------------------

    def subscribe(self, topic: str) -> None:
        self.subscriptions.add(topic)
        self.mesh.setdefault(topic, set())
        for peer in list(self.transport.peers.values()):
            self._send(peer, MSG_SUB, _enc_topic(topic))

    def unsubscribe(self, topic: str) -> None:
        self.subscriptions.discard(topic)
        with self._lock:
            members = self.mesh.pop(topic, set())
        for pid in members:
            self._send_id(pid, MSG_PRUNE, _enc_topic(topic))
        for peer in list(self.transport.peers.values()):
            self._send(peer, MSG_UNSUB, _enc_topic(topic))

    # -- publish / deliver ---------------------------------------------------

    def _message_id(self, topic: str, data: bytes) -> bytes:
        return hashlib.sha256(self.fork_digest + topic.encode()
                              + data).digest()[:20]

    def _mark_seen(self, mid: bytes) -> bool:
        with self._lock:
            if mid in self._seen:
                return True
            self._seen[mid] = True
            while len(self._seen) > self.SEEN_CAP:
                self._seen.popitem(last=False)
            return False

    def _cache_put(self, mid: bytes, topic: str, data: bytes) -> None:
        with self._lock:
            self._mcache[mid] = (topic, data)
            self._windows[0].add(mid)

    def _data_frame(self, topic: str, data: bytes) -> bytes:
        return bytes([MSG_DATA]) + _enc_topic(topic) + self.fork_digest + \
            snappy.compress_block(data)

    def publish(self, topic: str, data: bytes,
                exclude_peer: str | None = None) -> int:
        mid = self._message_id(topic, data)
        self._mark_seen(mid)
        self._cache_put(mid, topic, data)
        frame = self._data_frame(topic, data)
        with self._lock:
            members = set(self.mesh.get(topic, ()))
            if not members:
                # no mesh yet (just subscribed / tiny nets): fall back to
                # topic-subscribed peers up to D
                members = {pid for pid, tps in self.peer_topics.items()
                           if topic in tps}
                members = set(self._sample(members, self.D))
            # v1.2: honor IDONTWANT — peers that already have the message
            # asked us not to send a duplicate
            members = {pid for pid in members
                       if mid not in self._dontwant.get(pid, ())}
        sent = 0
        for pid in members:
            if pid == exclude_peer:
                continue
            if self._send_id(pid, None, frame, raw=True):
                sent += 1
        return sent

    # -- inbound -------------------------------------------------------------

    def handle_frame(self, peer, payload: bytes) -> None:
        if not payload:
            return
        kind, body = payload[0], payload[1:]
        try:
            if kind == MSG_DATA:
                self._handle_data(peer, body)
            elif kind in (MSG_SUB, MSG_UNSUB):
                topic, _ = _dec_topic(body)
                with self._lock:
                    tps = self.peer_topics.setdefault(peer.node_id, set())
                    (tps.add if kind == MSG_SUB else tps.discard)(topic)
            elif kind == MSG_GRAFT:
                self._handle_graft(peer, body)
            elif kind == MSG_PRUNE:
                topic, _ = _dec_topic(body)
                with self._lock:
                    self.mesh.get(topic, set()).discard(peer.node_id)
                    self._backoff[(peer.node_id, topic)] = \
                        _now() + self.PRUNE_BACKOFF
            elif kind == MSG_IHAVE:
                self._handle_ihave(peer, body)
            elif kind == MSG_IWANT:
                self._handle_iwant(peer, body)
            elif kind == MSG_IDONTWANT:
                self._handle_idontwant(peer, body)
        except (ValueError, IndexError, struct.error):
            self.on_validation_result(peer, "?", "reject")

    def _handle_data(self, peer, body: bytes) -> None:
        topic, rest = _dec_topic(body)
        digest, comp = rest[:4], rest[4:]
        if digest != self.fork_digest:
            self.on_validation_result(peer, topic, "reject")
            return
        if topic not in self.subscriptions:
            return             # before decompression: no CPU for spam topics
        data = snappy.decompress_block(comp, self.MAX_PAYLOAD)
        mid = self._message_id(topic, data)
        if self._mark_seen(mid):
            return
        self._cache_put(mid, topic, data)
        if len(data) >= self.IDONTWANT_THRESHOLD:
            # v1.2: tell the rest of the mesh we have it BEFORE validating,
            # so duplicates stop flowing while validation runs
            with self._lock:
                others = [pid for pid in self.mesh.get(topic, ())
                          if pid != peer.node_id]
            body = struct.pack("<H", 1) + mid
            for pid in others:
                self._send_id(pid, MSG_IDONTWANT, body)
        result, ctx = self.validator(topic, data)
        self.on_validation_result(peer, topic, result)
        if result == "accept":
            # forward to the topic mesh only (gossipsub), never flood
            self.publish(topic, data, exclude_peer=peer.node_id)
            self.on_message(topic, data, peer, ctx)

    def _handle_graft(self, peer, body: bytes) -> None:
        topic, _ = _dec_topic(body)
        now = _now()
        with self._lock:
            backoff_until = self._backoff.get((peer.node_id, topic), 0)
            subscribed = topic in self.subscriptions
            score = self.peer_score(peer.node_id)
        if not subscribed or now < backoff_until or score < 0:
            # reject the graft; a backoff violation is penalized
            if now < backoff_until:
                self.on_validation_result(peer, topic, "reject")
            self._send(peer, MSG_PRUNE, _enc_topic(topic))
            return
        with self._lock:
            self.mesh.setdefault(topic, set()).add(peer.node_id)

    def _handle_ihave(self, peer, body: bytes) -> None:
        topic, rest = _dec_topic(body)
        (n,) = struct.unpack_from("<H", rest, 0)
        n = min(n, self.MAX_IHAVE_PER_MSG)
        mids = [rest[2 + 20 * i:2 + 20 * (i + 1)] for i in range(n)]
        budget = self._iwant_budget.get(peer.node_id, 32)
        want = []
        with self._lock:
            for mid in mids:
                if mid not in self._seen and budget > 0:
                    want.append(mid)
                    budget -= 1
        self._iwant_budget[peer.node_id] = budget
        if want and topic in self.subscriptions:
            self._send(peer, MSG_IWANT,
                       struct.pack("<H", len(want)) + b"".join(want))

    MAX_IWANT_SERVED = 128     # per peer per heartbeat (anti-amplification)

    def _handle_iwant(self, peer, body: bytes) -> None:
        (n,) = struct.unpack_from("<H", body, 0)
        n = min(n, self.MAX_IHAVE_PER_MSG)
        for i in range(n):
            mid = body[2 + 20 * i:2 + 20 * (i + 1)]
            with self._lock:
                served = self._iwant_served.setdefault(peer.node_id, set())
                if mid in served or len(served) >= self.MAX_IWANT_SERVED:
                    continue   # each mid served once; bounded reflection
                entry = self._mcache.get(mid)
                if entry is None:
                    continue
                served.add(mid)
                topic, data = entry
            self._send(peer, None, self._data_frame(topic, data),
                       raw=True)

    def _handle_idontwant(self, peer, body: bytes) -> None:
        """v1.2: record mids the peer does not want forwarded (bounded
        per peer; entries age out with the mcache windows)."""
        (n,) = struct.unpack_from("<H", body, 0)
        n = min(n, self.MAX_IHAVE_PER_MSG)
        if len(body) < 2 + 20 * n:
            raise ValueError("truncated IDONTWANT frame")
        with self._lock:
            dw = self._dontwant.setdefault(peer.node_id, OrderedDict())
            for i in range(n):
                dw[body[2 + 20 * i:2 + 20 * (i + 1)]] = self._hb_count
                while len(dw) > self.MAX_DONTWANT_PER_PEER:
                    dw.popitem(last=False)

    # -- heartbeat -----------------------------------------------------------

    def _hb_loop(self) -> None:
        while not self._hb_stop.wait(self.HEARTBEAT_SECS):
            try:
                self.heartbeat()
            except Exception:
                import logging
                logging.getLogger("lighthouse_tpu.network").exception(
                    "gossip heartbeat failed")

    def heartbeat(self) -> None:
        now = _now()
        with self._lock:
            self._backoff = {k: v for k, v in self._backoff.items()
                             if v > now}
            self._iwant_budget.clear()
            self._iwant_served.clear()
            plans_graft: list[tuple[str, str]] = []
            plans_prune: list[tuple[str, str]] = []
            for topic in self.subscriptions:
                members = self.mesh.setdefault(topic, set())
                members &= set(self.transport.peers)
                if len(members) < self.D_LO:
                    candidates = [
                        pid for pid, tps in self.peer_topics.items()
                        if topic in tps and pid not in members
                        and pid in self.transport.peers
                        and self._backoff.get((pid, topic), 0) <= now
                        and self.peer_score(pid) >= 0]
                    for pid in self._sample(candidates,
                                            self.D - len(members)):
                        members.add(pid)
                        plans_graft.append((pid, topic))
                elif len(members) > self.D_HI:
                    for pid in self._sample(members,
                                            len(members) - self.D):
                        members.discard(pid)
                        plans_prune.append((pid, topic))
            # gossip: IHAVE recent mids to a few non-mesh subscribers
            recent: dict[str, list[bytes]] = {}
            for w in self._windows[:self.GOSSIP_WINDOWS]:
                for mid in w:
                    entry = self._mcache.get(mid)
                    if entry:
                        recent.setdefault(entry[0], []).append(mid)
            plans_ihave: list[tuple[str, str, list[bytes]]] = []
            for topic, mids in recent.items():
                members = self.mesh.get(topic, set())
                targets = [pid for pid, tps in self.peer_topics.items()
                           if topic in tps and pid not in members
                           and pid in self.transport.peers]
                for pid in self._sample(targets, self.D_LO):
                    plans_ihave.append(
                        (pid, topic, mids[:self.MAX_IHAVE_PER_MSG]))
            # shift mcache windows
            self._windows.insert(0, set())
            for mid in (self._windows.pop()
                        if len(self._windows) > self.MCACHE_WINDOWS
                        else set()):
                self._mcache.pop(mid, None)
            # IDONTWANT entries age out by heartbeat count, NOT mcache
            # membership: the entries that matter are exactly the ones for
            # messages we have not received yet (pre-receipt suppression),
            # which are never in our mcache
            self._hb_count += 1
            horizon = self._hb_count - self.MCACHE_WINDOWS
            for pid in list(self._dontwant):
                dw = self._dontwant[pid]
                while dw and next(iter(dw.values())) < horizon:
                    dw.popitem(last=False)
                if not dw:
                    del self._dontwant[pid]
        for pid, topic in plans_graft:
            self._send_id(pid, MSG_GRAFT, _enc_topic(topic))
        for pid, topic in plans_prune:
            self._send_id(pid, MSG_PRUNE, _enc_topic(topic))
        for pid, topic, mids in plans_ihave:
            self._send_id(pid, MSG_IHAVE,
                          _enc_topic(topic)
                          + struct.pack("<H", len(mids)) + b"".join(mids))

    # -- helpers -------------------------------------------------------------

    def _sample(self, population, k: int):
        pop = list(population)
        if k >= len(pop):
            return pop
        return self._rng.sample(pop, k)

    def _send(self, peer, kind: int | None, body: bytes,
              raw: bool = False) -> bool:
        frame = body if raw else bytes([kind]) + body
        peer.send_frame(self.GOSSIP_FRAME, frame)
        return True

    def _send_id(self, node_id: str, kind: int | None, body: bytes,
                 raw: bool = False) -> bool:
        peer = self.transport.peers.get(node_id)
        if peer is None:
            return False
        return self._send(peer, kind, body, raw)


def _now() -> float:
    import time
    return time.monotonic()
