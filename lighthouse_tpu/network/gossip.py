"""Gossipsub mesh pubsub — REAL meshsub wire format.

The engine implements the gossipsub v1.1 mechanics the reference vendors
(lighthouse_network/gossipsub/src/behaviour.rs): per-topic MESH of degree
D (GRAFT/PRUNE with prune-backoff), lazy gossip (IHAVE windows over a
message cache + IWANT pulls), subscription tracking, and validation
results feeding peer scores (accept/ignore/reject -> PeerManager) —
plus v1.2 IDONTWANT (the feature the reference's vendored fork exists
for): on receiving a large message, mesh peers are told not to forward
us their copy, cutting duplicate bandwidth for blocks/blobs.
Delivery is O(mesh degree), not O(peers).

Wire (round 3, VERDICT r2 missing #1): varint-delimited gossipsub RPC
protobufs (gossipsub_pb.py) on /meshsub/1.2.0 yamux streams — the exact
frames every libp2p gossipsub speaks.  Topics are the eth2 full form
`/eth2/<fork_digest>/<name>/ssz_snappy` (types/topics.rs:109), payloads
are raw-snappy compressed SSZ, and message ids follow the eth2 p2p spec:
SHA256(MESSAGE_DOMAIN_VALID_SNAPPY || len(topic) || topic ||
decompressed)[:20] (altair+ form).
"""
from __future__ import annotations

import hashlib
import random
import struct
import threading
from collections import OrderedDict

from ..obs import tracing
from . import gossipsub_pb as pb
from . import snappy


def _count(name: str, amount: float = 1) -> None:
    """Catalog counter, sys.modules-gated (wire tests run the engine
    without the metrics stack)."""
    import sys
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if md is not None:
        md.count(name, amount)


def _gauge(name: str, value: float) -> None:
    """Catalog gauge, same sys.modules gating as _count."""
    import sys
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if md is not None:
        md.gauge(name, value)

MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
MESSAGE_DOMAIN_INVALID_SNAPPY = b"\x00\x00\x00\x00"


class Topic:
    BLOCK = "beacon_block"
    AGGREGATE = "beacon_aggregate_and_proof"
    VOLUNTARY_EXIT = "voluntary_exit"
    PROPOSER_SLASHING = "proposer_slashing"
    ATTESTER_SLASHING = "attester_slashing"
    BLS_CHANGE = "bls_to_execution_change"
    LC_FINALITY_UPDATE = "light_client_finality_update"
    LC_OPTIMISTIC_UPDATE = "light_client_optimistic_update"

    @staticmethod
    def attestation_subnet(subnet: int) -> str:
        return f"beacon_attestation_{subnet}"

    @staticmethod
    def sync_subnet(subnet: int) -> str:
        return f"sync_committee_{subnet}"

    @staticmethod
    def blob_sidecar(index: int) -> str:
        return f"blob_sidecar_{index}"

    @staticmethod
    def data_column_subnet(subnet: int) -> str:
        return f"data_column_sidecar_{subnet}"


def full_topic(name: str, fork_digest: bytes) -> str:
    """types/topics.rs topic string form."""
    return f"/eth2/{fork_digest.hex()}/{name}/ssz_snappy"


def parse_topic(topic: str) -> tuple[bytes, str] | None:
    """full topic string -> (fork_digest, bare name), or None."""
    parts = topic.split("/")
    if len(parts) != 5 or parts[1] != "eth2" or parts[4] != "ssz_snappy":
        return None
    try:
        return bytes.fromhex(parts[2]), parts[3]
    except ValueError:
        return None


class GossipEngine:
    """validator(topic, data) -> ('accept'|'ignore'|'reject', ctx)."""

    SEEN_CAP = 16384
    D = 8
    D_LO = 6
    D_HI = 12
    HEARTBEAT_SECS = 1.0
    MCACHE_WINDOWS = 5          # kept windows
    GOSSIP_WINDOWS = 3          # advertised via IHAVE
    PRUNE_BACKOFF = 60.0
    MAX_IHAVE_PER_MSG = 64
    MAX_PAYLOAD = 10 * 1024 * 1024
    #: messages at least this large trigger IDONTWANT to mesh peers
    #: (gossipsub v1.2: only worth the control traffic for big payloads)
    IDONTWANT_THRESHOLD = 4 * 1024
    MAX_DONTWANT_PER_PEER = 256

    def __init__(self, transport, fork_digest: bytes):
        self.transport = transport
        # graftpath node attribution: every causal span this engine opens
        # is stamped with the node's label so cross-node stitching can
        # tell the fleet apart (the network service overrides this with
        # the simulator's n<i> label when it has one)
        self.node_label = (getattr(transport, "label", None)
                           or str(getattr(transport, "node_id", ""))[:8])
        self.fork_digest = fork_digest
        self.subscriptions: set[str] = set()      # bare names
        self.validator = lambda topic, data: ("accept", None)
        self.on_message = lambda topic, data, peer, ctx: None
        # fires when the validator IGNOREs a message but attaches a ctx —
        # e.g. an unknown-parent block that sync should chase rather than
        # forward (ignored messages are never propagated to the mesh)
        self.on_ignored = lambda topic, data, peer, ctx: None
        self.on_validation_result = lambda peer, topic, result: None
        self.peer_score = lambda node_id: 0.0   # injected by the service
        self.mesh: dict[str, set[str]] = {}       # bare name -> node ids
        self.peer_topics: dict[str, set[str]] = {}
        self._backoff: dict[tuple[str, str], float] = {}
        self._seen: OrderedDict[bytes, bool] = OrderedDict()
        # mcache: mid -> (bare topic, data); windows: list of sets of mids
        self._mcache: dict[bytes, tuple[str, bytes]] = {}
        self._windows: list[set[bytes]] = [set()]
        self._iwant_budget: dict[str, int] = {}
        self._iwant_served: dict[str, set[bytes]] = {}
        # peer -> {mid: heartbeat count at receipt}: mids that peer told
        # us NOT to forward to it (v1.2)
        self._dontwant: dict[str, OrderedDict[bytes, int]] = {}
        self._hb_count = 0
        self._lock = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self._rng = random.Random()

    # -- lifecycle -----------------------------------------------------------

    def start_heartbeat(self) -> None:
        if self._hb_thread is None:
            with self._lock:                # double-checked: one loop only
                if self._hb_thread is None:
                    self._hb_thread = threading.Thread(target=self._hb_loop,
                                                       daemon=True)
                    self._hb_thread.start()

    def stop(self, join: bool = True) -> None:
        """Stop the heartbeat; by default WAIT for the thread to exit so
        callers can tear sockets down afterwards without the heartbeat
        racing a closed transport (clean-shutdown discipline,
        task_executor/src/lib.rs:12-28)."""
        self._hb_stop.set()
        t = self._hb_thread
        if join and t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=2)

    def on_peer_connected(self, peer) -> None:
        rpc = pb.Rpc(subscriptions=[
            pb.SubOpts(True, full_topic(t, self.fork_digest))
            for t in sorted(self.subscriptions)])
        if rpc.subscriptions:
            self._send_rpc(peer, rpc)

    def on_peer_disconnected(self, node_id: str) -> None:
        with self._lock:
            self.peer_topics.pop(node_id, None)
            self._dontwant.pop(node_id, None)
            for members in self.mesh.values():
                members.discard(node_id)
        self._mesh_gauge()

    def _mesh_gauge(self) -> None:
        """Feed gossipsub_mesh_peers (total mesh size across topics)
        after any mesh mutation; called outside self._lock."""
        with self._lock:
            total = sum(len(m) for m in self.mesh.values())
        _gauge("gossipsub_mesh_peers", total)

    # -- subscriptions -------------------------------------------------------

    def subscribe(self, topic: str) -> None:
        self.subscriptions.add(topic)
        self.mesh.setdefault(topic, set())
        self._mesh_gauge()
        rpc = pb.Rpc(subscriptions=[
            pb.SubOpts(True, full_topic(topic, self.fork_digest))])
        for peer in list(self.transport.peers.values()):
            self._send_rpc(peer, rpc)

    def unsubscribe(self, topic: str) -> None:
        self.subscriptions.discard(topic)
        with self._lock:
            members = self.mesh.pop(topic, set())
        self._mesh_gauge()
        ft = full_topic(topic, self.fork_digest)
        prune = pb.Rpc(control=pb.ControlMessage(
            prune=[pb.ControlPrune(ft)]))
        for pid in members:
            self._send_rpc_id(pid, prune)
        unsub = pb.Rpc(subscriptions=[pb.SubOpts(False, ft)])
        for peer in list(self.transport.peers.values()):
            self._send_rpc(peer, unsub)

    # -- publish / deliver ---------------------------------------------------

    def _message_id(self, topic: str, data: bytes) -> bytes:
        """eth2 p2p spec (altair+): SHA256(domain || u64le(len(topic)) ||
        topic || decompressed_data)[:20] over the FULL topic string."""
        ft = full_topic(topic, self.fork_digest).encode()
        return hashlib.sha256(
            MESSAGE_DOMAIN_VALID_SNAPPY
            + struct.pack("<Q", len(ft)) + ft + data).digest()[:20]

    def _mark_seen(self, mid: bytes) -> bool:
        with self._lock:
            if mid in self._seen:
                return True
            self._seen[mid] = True
            while len(self._seen) > self.SEEN_CAP:
                self._seen.popitem(last=False)
            return False

    def _cache_put(self, mid: bytes, topic: str, data: bytes) -> None:
        with self._lock:
            self._mcache[mid] = (topic, data)
            self._windows[0].add(mid)

    def _pub_msg(self, topic: str, data: bytes) -> pb.PubMessage:
        return pb.PubMessage(topic=full_topic(topic, self.fork_digest),
                             data=snappy.compress_block(data))

    def publish(self, topic: str, data: bytes,
                exclude_peer: str | None = None,
                root: bytes | None = None) -> int:
        mid = self._message_id(topic, data)
        if topic == Topic.BLOCK:
            # causal publish span: the content-derived message id is the
            # cross-node stitch key (obs/causal.py); the origin publish
            # (service.publish_block) also passes the block root so the
            # sync-path import edge has an anchor — mesh forwards don't
            attrs = {"topic": topic, "message_id": mid,
                     "node": self.node_label}
            if root is not None:
                attrs["root"] = root
            cm = tracing.span("gossip_publish", **attrs)
        else:
            cm = tracing.attach(None)
        with cm:
            return self._fan_out(topic, data, mid, exclude_peer)

    def _fan_out(self, topic: str, data: bytes, mid: bytes,
                        exclude_peer: str | None) -> int:
        self._mark_seen(mid)
        self._cache_put(mid, topic, data)
        _count("gossipsub_messages_published_total")
        framed = pb.frame(pb.Rpc(publish=[self._pub_msg(topic, data)]))
        with self._lock:
            members = set(self.mesh.get(topic, ()))
            if not members:
                # no mesh yet (just subscribed / tiny nets): fall back to
                # topic-subscribed peers up to D
                members = {pid for pid, tps in self.peer_topics.items()
                           if topic in tps}
                members = set(self._sample(members, self.D))
            # v1.2: honor IDONTWANT — peers that already have the message
            # asked us not to send a duplicate
            members = {pid for pid in members
                       if mid not in self._dontwant.get(pid, ())}
        sent = 0
        for pid in members:
            if pid == exclude_peer:
                continue
            peer = self.transport.peers.get(pid)
            if peer is not None:
                # encode ONCE: a 5 MB block re-framed per mesh peer would
                # be ~40 MB of redundant copying on the hot forward path
                peer.send_gossip_rpc(framed)
                sent += 1
        return sent

    # -- inbound -------------------------------------------------------------

    def handle_rpc(self, peer, rpc: pb.Rpc) -> None:
        try:
            for sub in rpc.subscriptions:
                self._handle_sub(peer, sub)
            for msg in rpc.publish:
                self._handle_data(peer, msg)
            if rpc.control is not None:
                for graft in rpc.control.graft:
                    self._handle_graft(peer, graft.topic)
                for prune in rpc.control.prune:
                    self._handle_prune(peer, prune)
                for ihave in rpc.control.ihave:
                    self._handle_ihave(peer, ihave)
                for iwant in rpc.control.iwant:
                    self._handle_iwant(peer, iwant.message_ids)
                for idw in rpc.control.idontwant:
                    self._handle_idontwant(peer, idw.message_ids)
        except (ValueError, IndexError, struct.error, pb.PbError):
            self.on_validation_result(peer, "?", "reject")

    def _bare(self, peer, topic_str: str) -> str | None:
        """Full wire topic -> bare name; wrong-digest topics reject."""
        parsed = parse_topic(topic_str)
        if parsed is None:
            return None
        digest, name = parsed
        if digest != self.fork_digest:
            self.on_validation_result(peer, name, "reject")
            return None
        return name

    def _handle_sub(self, peer, sub: pb.SubOpts) -> None:
        topic = self._bare(peer, sub.topic)
        if topic is None:
            return
        with self._lock:
            tps = self.peer_topics.setdefault(peer.node_id, set())
            (tps.add if sub.subscribe else tps.discard)(topic)

    def _handle_data(self, peer, msg: pb.PubMessage) -> None:
        topic = self._bare(peer, msg.topic)
        if topic is None:
            return
        if topic not in self.subscriptions:
            return             # before decompression: no CPU for spam topics
        data = snappy.decompress_block(msg.data, self.MAX_PAYLOAD)
        mid = self._message_id(topic, data)
        _count("gossipsub_messages_received_total")
        if self._mark_seen(mid):
            _count("gossipsub_duplicates_dropped_total")
            return
        self._cache_put(mid, topic, data)
        if len(data) >= self.IDONTWANT_THRESHOLD:
            # v1.2: tell the rest of the mesh we have it BEFORE validating,
            # so duplicates stop flowing while validation runs
            with self._lock:
                others = [pid for pid in self.mesh.get(topic, ())
                          if pid != peer.node_id]
            idw = pb.Rpc(control=pb.ControlMessage(
                idontwant=[pb.ControlIWant([mid])]))
            for pid in others:
                self._send_rpc_id(pid, idw)
            if others:
                _count("gossipsub_idontwant_sent_total", len(others))
        # one slot-anchored trace per block message: validation (which
        # runs gossip_verify) and delivery (which submits processor work
        # carrying this context) share the trace id, so the block's path
        # from wire to db-write is a single graftscope trace.  The span
        # carries the causal scope (content-derived message id + node
        # label) so obs/causal.py can stitch it to the publisher's span
        # on another node; aggregates get a lighter gossip_deliver span
        # (per-attestation subnet traffic stays span-free — a flood
        # would churn the 4096-span ring out from under the envelopes).
        if topic == Topic.BLOCK:
            cm = tracing.span("block_pipeline", topic=topic,
                              message_id=mid, node=self.node_label)
        elif topic == Topic.AGGREGATE:
            cm = tracing.span("gossip_deliver", topic=topic,
                              message_id=mid, node=self.node_label)
        else:
            cm = tracing.attach(None)
        with cm:
            result, ctx = self.validator(topic, data)
            _count(f"gossipsub_validation_{result}_total")
            self.on_validation_result(peer, topic, result)
            if result == "accept":
                # forward to the topic mesh only (gossipsub), never flood
                self.publish(topic, data, exclude_peer=peer.node_id)
                self.on_message(topic, data, peer, ctx)
            elif result == "ignore" and ctx is not None:
                self.on_ignored(topic, data, peer, ctx)

    def _handle_graft(self, peer, topic_str: str) -> None:
        topic = self._bare(peer, topic_str)
        if topic is None:
            return
        now = _now()
        with self._lock:
            backoff_until = self._backoff.get((peer.node_id, topic), 0)
            subscribed = topic in self.subscriptions
            score = self.peer_score(peer.node_id)
        if not subscribed or now < backoff_until or score < 0:
            # reject the graft; a backoff violation is penalized
            if now < backoff_until:
                self.on_validation_result(peer, topic, "reject")
            self._send_rpc(peer, pb.Rpc(control=pb.ControlMessage(
                prune=[pb.ControlPrune(
                    full_topic(topic, self.fork_digest),
                    backoff=int(self.PRUNE_BACKOFF))])))
            return
        with self._lock:
            self.mesh.setdefault(topic, set()).add(peer.node_id)
        self._mesh_gauge()

    def _handle_prune(self, peer, prune: pb.ControlPrune) -> None:
        topic = self._bare(peer, prune.topic)
        if topic is None:
            return
        backoff = prune.backoff or self.PRUNE_BACKOFF
        with self._lock:
            self.mesh.get(topic, set()).discard(peer.node_id)
            self._backoff[(peer.node_id, topic)] = _now() + float(backoff)
        self._mesh_gauge()

    def _handle_ihave(self, peer, ihave: pb.ControlIHave) -> None:
        topic = self._bare(peer, ihave.topic)
        if topic is None:
            return
        mids = [m for m in ihave.message_ids[:self.MAX_IHAVE_PER_MSG]
                if len(m) == 20]
        budget = self._iwant_budget.get(peer.node_id, 32)
        want = []
        with self._lock:
            for mid in mids:
                if mid not in self._seen and budget > 0:
                    want.append(mid)
                    budget -= 1
        self._iwant_budget[peer.node_id] = budget
        if want and topic in self.subscriptions:
            self._send_rpc(peer, pb.Rpc(control=pb.ControlMessage(
                iwant=[pb.ControlIWant(want)])))

    MAX_IWANT_SERVED = 128     # per peer per heartbeat (anti-amplification)

    def _handle_iwant(self, peer, mids: list[bytes]) -> None:
        send: list[pb.PubMessage] = []
        for mid in mids[:self.MAX_IHAVE_PER_MSG]:
            with self._lock:
                served = self._iwant_served.setdefault(peer.node_id, set())
                if mid in served or len(served) >= self.MAX_IWANT_SERVED:
                    continue   # each mid served once; bounded reflection
                entry = self._mcache.get(mid)
                if entry is None:
                    continue
                served.add(mid)
                topic, data = entry
            send.append(self._pub_msg(topic, data))
        if send:
            self._send_rpc(peer, pb.Rpc(publish=send))

    def _handle_idontwant(self, peer, mids: list[bytes]) -> None:
        """v1.2: record mids the peer does not want forwarded (bounded
        per peer; entries age out with the mcache windows)."""
        with self._lock:
            dw = self._dontwant.setdefault(peer.node_id, OrderedDict())
            for mid in mids[:self.MAX_IHAVE_PER_MSG]:
                if len(mid) != 20:
                    continue
                dw[mid] = self._hb_count
                while len(dw) > self.MAX_DONTWANT_PER_PEER:
                    dw.popitem(last=False)

    # -- heartbeat -----------------------------------------------------------

    def _hb_loop(self) -> None:
        while not self._hb_stop.wait(self.HEARTBEAT_SECS):
            try:
                self.heartbeat()
            except Exception:
                import logging
                logging.getLogger("lighthouse_tpu.network").exception(
                    "gossip heartbeat failed")

    def heartbeat(self) -> None:
        now = _now()
        with self._lock:
            self._backoff = {k: v for k, v in self._backoff.items()
                             if v > now}
            self._iwant_budget.clear()
            self._iwant_served.clear()
            plans_graft: list[tuple[str, str]] = []
            plans_prune: list[tuple[str, str]] = []
            for topic in self.subscriptions:
                members = self.mesh.setdefault(topic, set())
                members &= set(self.transport.peers)
                if len(members) < self.D_LO:
                    candidates = [
                        pid for pid, tps in self.peer_topics.items()
                        if topic in tps and pid not in members
                        and pid in self.transport.peers
                        and self._backoff.get((pid, topic), 0) <= now
                        and self.peer_score(pid) >= 0]
                    for pid in self._sample(candidates,
                                            self.D - len(members)):
                        members.add(pid)
                        plans_graft.append((pid, topic))
                elif len(members) > self.D_HI:
                    for pid in self._sample(members,
                                            len(members) - self.D):
                        members.discard(pid)
                        plans_prune.append((pid, topic))
            # gossip: IHAVE recent mids to a few non-mesh subscribers
            recent: dict[str, list[bytes]] = {}
            for w in self._windows[:self.GOSSIP_WINDOWS]:
                for mid in w:
                    entry = self._mcache.get(mid)
                    if entry:
                        recent.setdefault(entry[0], []).append(mid)
            plans_ihave: list[tuple[str, str, list[bytes]]] = []
            for topic, mids in recent.items():
                members = self.mesh.get(topic, set())
                targets = [pid for pid, tps in self.peer_topics.items()
                           if topic in tps and pid not in members
                           and pid in self.transport.peers]
                for pid in self._sample(targets, self.D_LO):
                    plans_ihave.append(
                        (pid, topic, mids[:self.MAX_IHAVE_PER_MSG]))
            # shift mcache windows
            self._windows.insert(0, set())
            for mid in (self._windows.pop()
                        if len(self._windows) > self.MCACHE_WINDOWS
                        else set()):
                self._mcache.pop(mid, None)
            # IDONTWANT entries age out by heartbeat count, NOT mcache
            # membership: the entries that matter are exactly the ones for
            # messages we have not received yet (pre-receipt suppression),
            # which are never in our mcache
            self._hb_count += 1
            horizon = self._hb_count - self.MCACHE_WINDOWS
            for pid in list(self._dontwant):
                dw = self._dontwant[pid]
                while dw and next(iter(dw.values())) < horizon:
                    dw.popitem(last=False)
                if not dw:
                    del self._dontwant[pid]
        self._mesh_gauge()
        for pid, topic in plans_graft:
            self._send_rpc_id(pid, pb.Rpc(control=pb.ControlMessage(
                graft=[pb.ControlGraft(
                    full_topic(topic, self.fork_digest))])))
        for pid, topic in plans_prune:
            self._send_rpc_id(pid, pb.Rpc(control=pb.ControlMessage(
                prune=[pb.ControlPrune(full_topic(topic, self.fork_digest),
                                       backoff=int(self.PRUNE_BACKOFF))])))
        for pid, topic, mids in plans_ihave:
            self._send_rpc_id(pid, pb.Rpc(control=pb.ControlMessage(
                ihave=[pb.ControlIHave(full_topic(topic, self.fork_digest),
                                       mids)])))

    # -- helpers -------------------------------------------------------------

    def _sample(self, population, k: int):
        pop = list(population)
        if k >= len(pop):
            return pop
        return self._rng.sample(pop, k)

    def _send_rpc(self, peer, rpc: pb.Rpc) -> bool:
        peer.send_gossip_rpc(pb.frame(rpc))
        return True

    def _send_rpc_id(self, node_id: str, rpc: pb.Rpc) -> bool:
        peer = self.transport.peers.get(node_id)
        if peer is None:
            return False
        return self._send_rpc(peer, rpc)


def _now() -> float:
    import time
    return time.monotonic()
