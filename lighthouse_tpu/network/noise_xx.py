"""Noise XX — the REAL Noise Protocol state machine, as libp2p uses it.

Noise_XX_25519_ChaChaPoly_SHA256 per the Noise spec (rev 34): full
CipherState / SymmetricState / HandshakeState objects, HKDF chaining,
and the XX message pattern

    -> e
    <- e, ee, s, es
    -> s, se

with libp2p's identity payload carried in messages 2 and 3: the static
Noise key is certified by the peer's libp2p identity key via a
signature over "noise-libp2p-static-key:" || static_pub (we use
secp256k1 identities, the eth2 default).

Replaces round 2's "noise-like" ad-hoc handshake (VERDICT r2 missing
#1).  Ref: beacon_node/lighthouse_network/src/service/utils.rs:80-130
(build_transport: noise XX authentication upgrade).

Wire framing (libp2p noise spec): every handshake and transport message
is prefixed by a 2-byte big-endian length; transport messages carry
AEAD ciphertext (max 65535 bytes each).
"""
from __future__ import annotations

import hashlib
import hmac as hmac_mod
import struct

try:
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )
    HAVE_CRYPTOGRAPHY = True
except ImportError:   # gate: the STF/chain layers must import without it
    HAVE_CRYPTOGRAPHY = False

    class _MissingCryptography:
        _ERR = ("python 'cryptography' package is required for the noise "
                "transport but is not installed")

        def __init__(self, *a, **kw):
            raise NotImplementedError(self._ERR)

        @classmethod
        def generate(cls, *a, **kw):
            raise NotImplementedError(cls._ERR)

        @classmethod
        def from_public_bytes(cls, *a, **kw):
            raise NotImplementedError(cls._ERR)

    X25519PrivateKey = X25519PublicKey = ChaCha20Poly1305 = \
        _MissingCryptography
    Encoding = PublicFormat = None

from . import secp256k1

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"
LIBP2P_STATIC_PREFIX = b"noise-libp2p-static-key:"
MAX_MSG = 65535


class NoiseError(Exception):
    pass


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hmac(key: bytes, data: bytes) -> bytes:
    return hmac_mod.new(key, data, hashlib.sha256).digest()


def _hkdf2(ck: bytes, ikm: bytes) -> tuple[bytes, bytes]:
    """Noise-spec HKDF with 2 outputs."""
    prk = _hmac(ck, ikm)
    o1 = _hmac(prk, b"\x01")
    o2 = _hmac(prk, o1 + b"\x02")
    return o1, o2


def _dh(priv: X25519PrivateKey, pub_raw: bytes) -> bytes:
    return priv.exchange(X25519PublicKey.from_public_bytes(pub_raw))


def _pub_raw(priv: X25519PrivateKey) -> bytes:
    return priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)


class CipherState:
    """Noise spec 5.1: (k, n) with 12-byte little-endian-counter nonces
    (4 zero bytes || u64le n — the 25519/ChaChaPoly nonce form)."""

    def __init__(self, key: bytes | None = None):
        self.k = key
        self.n = 0

    def has_key(self) -> bool:
        return self.k is not None

    def _nonce(self) -> bytes:
        return b"\x00" * 4 + struct.pack("<Q", self.n)

    def encrypt_with_ad(self, ad: bytes, plaintext: bytes) -> bytes:
        if self.k is None:
            return plaintext
        out = ChaCha20Poly1305(self.k).encrypt(self._nonce(), plaintext, ad)
        self.n += 1
        return out

    def decrypt_with_ad(self, ad: bytes, ciphertext: bytes) -> bytes:
        if self.k is None:
            return ciphertext
        try:
            out = ChaCha20Poly1305(self.k).decrypt(self._nonce(),
                                                   ciphertext, ad)
        except Exception as e:
            raise NoiseError(f"decrypt failed: {e}") from None
        self.n += 1
        return out


class SymmetricState:
    """Noise spec 5.2: (ck, h) + an inner CipherState."""

    def __init__(self):
        self.h = _sha256(PROTOCOL_NAME) if len(PROTOCOL_NAME) > 32 \
            else PROTOCOL_NAME.ljust(32, b"\x00")
        self.ck = self.h
        self.cs = CipherState()

    def mix_hash(self, data: bytes) -> None:
        self.h = _sha256(self.h + data)

    def mix_key(self, ikm: bytes) -> None:
        self.ck, temp_k = _hkdf2(self.ck, ikm)
        self.cs = CipherState(temp_k)

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        ct = self.cs.encrypt_with_ad(self.h, plaintext)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        pt = self.cs.decrypt_with_ad(self.h, ciphertext)
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> tuple[CipherState, CipherState]:
        k1, k2 = _hkdf2(self.ck, b"")
        return CipherState(k1), CipherState(k2)


# -- libp2p identity payload (protobuf NoiseHandshakePayload) -----------------
#
#   message NoiseHandshakePayload {
#     bytes identity_key = 1;   // libp2p PublicKey protobuf
#     bytes identity_sig = 2;
#   }
#   message PublicKey { KeyType Type = 1; bytes Data = 2; }  Secp256k1 = 2

def _pb_bytes_field(tag: int, data: bytes) -> bytes:
    out = bytes([(tag << 3) | 2])
    n = len(data)
    while n >= 0x80:
        out += bytes([(n & 0x7F) | 0x80])
        n >>= 7
    return out + bytes([n]) + data


def _pb_varint_field(tag: int, v: int) -> bytes:
    out = bytes([tag << 3])
    while v >= 0x80:
        out += bytes([(v & 0x7F) | 0x80])
        v >>= 7
    return out + bytes([v])


def _pb_parse(data: bytes) -> dict[int, bytes | int]:
    out: dict[int, bytes | int] = {}
    pos = 0
    while pos < len(data):
        key = data[pos]
        tag, wt = key >> 3, key & 7
        pos += 1
        if wt == 0:
            v, shift = 0, 0
            while True:
                b = data[pos]
                v |= (b & 0x7F) << shift
                pos += 1
                if not b & 0x80:
                    break
                shift += 7
            out[tag] = v
        elif wt == 2:
            n, shift = 0, 0
            while True:
                b = data[pos]
                n |= (b & 0x7F) << shift
                pos += 1
                if not b & 0x80:
                    break
                shift += 7
            out[tag] = data[pos:pos + n]
            pos += n
        else:
            raise NoiseError(f"unsupported protobuf wire type {wt}")
    return out


def _identity_key_pb(pub33: bytes) -> bytes:
    return _pb_varint_field(1, 2) + _pb_bytes_field(2, pub33)   # Secp256k1


def make_payload(identity_priv: int, noise_static_pub: bytes) -> bytes:
    """NoiseHandshakePayload certifying our Noise static key."""
    digest = _sha256(LIBP2P_STATIC_PREFIX + noise_static_pub)
    sig = secp256k1.sign(identity_priv, digest)
    pub = secp256k1.compress(secp256k1.pubkey(identity_priv))
    return _pb_bytes_field(1, _identity_key_pb(pub)) + \
        _pb_bytes_field(2, sig)


def verify_payload(payload: bytes, noise_static_pub: bytes) -> bytes:
    """-> the peer's identity pubkey (compressed secp256k1, 33B)."""
    fields = _pb_parse(payload)
    key_pb = _pb_parse(fields[1])
    if key_pb.get(1) != 2:
        raise NoiseError("identity key is not secp256k1")
    pub33 = key_pb[2]
    digest = _sha256(LIBP2P_STATIC_PREFIX + noise_static_pub)
    if not secp256k1.verify(secp256k1.decompress(pub33), digest, fields[2]):
        raise NoiseError("identity signature invalid")
    return pub33


def peer_id_from_pubkey(pub33: bytes) -> bytes:
    """libp2p peer id: multihash of the PublicKey protobuf.  secp256k1
    keys are short, so identity-hashed: 0x00 || len || pb."""
    pb = _identity_key_pb(pub33)
    return bytes([0x00, len(pb)]) + pb


# -- XX handshake state machine -----------------------------------------------

class HandshakeState:
    """One side of Noise_XX.  Drive with write_message/read_message in
    pattern order; after message 3 both sides hold (send_cs, recv_cs,
    remote_identity)."""

    def __init__(self, initiator: bool, identity_priv: int,
                 static_priv: X25519PrivateKey | None = None,
                 prologue: bytes = b""):
        self.initiator = initiator
        self.identity_priv = identity_priv
        self.s = static_priv or X25519PrivateKey.generate()
        self.e: X25519PrivateKey | None = None
        self.re: bytes | None = None
        self.rs: bytes | None = None
        self.ss = SymmetricState()
        self.ss.mix_hash(prologue)
        self.remote_identity: bytes | None = None   # compressed secp256k1
        self.remote_payload: bytes | None = None

    # message 1: -> e
    def write_msg1(self) -> bytes:
        if not self.initiator:
            raise NoiseError("responder cannot write message 1")
        self.e = X25519PrivateKey.generate()
        e_pub = _pub_raw(self.e)
        self.ss.mix_hash(e_pub)
        self.ss.mix_hash(b"")                       # empty payload
        return e_pub

    def read_msg1(self, msg: bytes) -> None:
        if self.initiator:
            raise NoiseError("initiator cannot read message 1")
        if len(msg) != 32:
            raise NoiseError("bad message 1 length")
        self.re = msg
        self.ss.mix_hash(self.re)
        self.ss.mix_hash(b"")

    # message 2: <- e, ee, s, es  (+ payload)
    def write_msg2(self) -> bytes:
        self.e = X25519PrivateKey.generate()
        e_pub = _pub_raw(self.e)
        self.ss.mix_hash(e_pub)
        self.ss.mix_key(_dh(self.e, self.re))       # ee
        s_pub = _pub_raw(self.s)
        enc_s = self.ss.encrypt_and_hash(s_pub)
        self.ss.mix_key(_dh(self.s, self.re))       # es (responder side)
        payload = make_payload(self.identity_priv, s_pub)
        enc_payload = self.ss.encrypt_and_hash(payload)
        return e_pub + enc_s + enc_payload

    def read_msg2(self, msg: bytes) -> None:
        if len(msg) < 32 + 48:
            raise NoiseError("bad message 2 length")
        self.re = msg[:32]
        self.ss.mix_hash(self.re)
        self.ss.mix_key(_dh(self.e, self.re))       # ee
        enc_s, enc_payload = msg[32:32 + 48], msg[32 + 48:]
        self.rs = self.ss.decrypt_and_hash(enc_s)
        self.ss.mix_key(_dh(self.e, self.rs))       # es (initiator side)
        payload = self.ss.decrypt_and_hash(enc_payload)
        self.remote_identity = verify_payload(payload, self.rs)
        self.remote_payload = payload

    # message 3: -> s, se  (+ payload)
    def write_msg3(self) -> bytes:
        s_pub = _pub_raw(self.s)
        enc_s = self.ss.encrypt_and_hash(s_pub)
        self.ss.mix_key(_dh(self.s, self.re))       # se (initiator side)
        payload = make_payload(self.identity_priv, s_pub)
        enc_payload = self.ss.encrypt_and_hash(payload)
        return enc_s + enc_payload

    def read_msg3(self, msg: bytes) -> None:
        enc_s, enc_payload = msg[:48], msg[48:]
        self.rs = self.ss.decrypt_and_hash(enc_s)
        self.ss.mix_key(_dh(self.e, self.rs))       # se (responder side)
        payload = self.ss.decrypt_and_hash(enc_payload)
        self.remote_identity = verify_payload(payload, self.rs)
        self.remote_payload = payload

    def split(self) -> tuple[CipherState, CipherState]:
        """-> (send, recv) for THIS side (initiator sends with k1)."""
        c1, c2 = self.ss.split()
        return (c1, c2) if self.initiator else (c2, c1)

    @property
    def handshake_hash(self) -> bytes:
        return self.ss.h


# -- framed session over a socket-like object ---------------------------------

def _send_frame(sock, data: bytes) -> None:
    if len(data) > MAX_MSG:
        raise NoiseError("frame too large")
    sock.sendall(struct.pack(">H", len(data)) + data)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise NoiseError("connection closed during noise exchange")
        buf += chunk
    return buf


def _recv_frame(sock) -> bytes:
    (n,) = struct.unpack(">H", _recv_exact(sock, 2))
    return _recv_exact(sock, n)


class NoiseSession:
    """An authenticated, encrypted session after a completed handshake."""

    def __init__(self, send_cs: CipherState, recv_cs: CipherState,
                 remote_identity: bytes, handshake_hash: bytes):
        self.send_cs = send_cs
        self.recv_cs = recv_cs
        self.remote_identity = remote_identity
        self.remote_peer_id = peer_id_from_pubkey(remote_identity)
        self.handshake_hash = handshake_hash

    def send(self, sock, data: bytes) -> None:
        # chunk to respect the 65535-byte noise message bound (16B tag)
        for off in range(0, len(data), MAX_MSG - 16) or [0]:
            chunk = data[off:off + MAX_MSG - 16]
            _send_frame(sock, self.send_cs.encrypt_with_ad(b"", chunk))

    def recv(self, sock) -> bytes:
        return self.recv_cs.decrypt_with_ad(b"", _recv_frame(sock))


def initiator_handshake(sock, identity_priv: int) -> NoiseSession:
    hs = HandshakeState(True, identity_priv)
    _send_frame(sock, hs.write_msg1())
    hs.read_msg2(_recv_frame(sock))
    _send_frame(sock, hs.write_msg3())
    send_cs, recv_cs = hs.split()
    return NoiseSession(send_cs, recv_cs, hs.remote_identity,
                        hs.handshake_hash)


def responder_handshake(sock, identity_priv: int) -> NoiseSession:
    hs = HandshakeState(False, identity_priv)
    hs.read_msg1(_recv_frame(sock))
    _send_frame(sock, hs.write_msg2())
    hs.read_msg3(_recv_frame(sock))
    send_cs, recv_cs = hs.split()
    return NoiseSession(send_cs, recv_cs, hs.remote_identity,
                        hs.handshake_hash)
