"""Pure-Python snappy codec: raw/block format + the framing format.

The consensus wire spec uses snappy in both shapes (ref:
beacon_node/lighthouse_network/src/rpc/codec/ssz_snappy.rs): gossip
payloads are raw-snappy blocks, req/resp chunks are snappy FRAMES
(stream identifier + CRC32C-masked chunks).  No snappy library is baked
into this image, so both are implemented here; compression is a greedy
4-byte-hash matcher (valid output beats maximal ratio), decompression is
format-complete and bounds-checked.
"""
from __future__ import annotations

import struct

MAX_UNCOMPRESSED = 64 * 1024 * 1024

# -- varint -------------------------------------------------------------------


def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    out = 0
    while True:
        if pos >= len(data) or shift > 35:
            raise ValueError("bad varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


# -- raw (block) format -------------------------------------------------------

def compress_block(data: bytes) -> bytes:
    """Greedy matcher: 4-byte hash table, 2-byte-offset copies."""
    n = len(data)
    out = bytearray(_uvarint(n))
    if n == 0:
        return bytes(out)
    table: dict[int, int] = {}
    i = 0
    lit_start = 0

    def emit_literal(start: int, end: int) -> None:
        length = end - start
        while length > 0:
            take = min(length, 60)
            if take < 60:
                out.append((take - 1) << 2)
            else:
                # use the 1-extra-byte form for runs of 60..255
                take = min(length, 256)
                out.append(60 << 2)
                out.append(take - 1)
            out.extend(data[start:start + take])
            start += take
            length -= take

    while i + 4 <= n:
        key = int.from_bytes(data[i:i + 4], "little")
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF and \
                data[cand:cand + 4] == data[i:i + 4]:
            emit_literal(lit_start, i)
            # extend the match
            m = 4
            while i + m < n and m < 64 and data[cand + m] == data[i + m]:
                m += 1
            offset = i - cand
            # copy with 2-byte offset: tag 10, len 1..64
            out.append(((m - 1) << 2) | 2)
            out += struct.pack("<H", offset)
            i += m
            lit_start = i
        else:
            i += 1
    emit_literal(lit_start, n)
    return bytes(out)


def decompress_block(data: bytes, max_len: int = MAX_UNCOMPRESSED) -> bytes:
    want, pos = _read_uvarint(data, 0)
    if want > max_len:
        raise ValueError("snappy: declared size too large")
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:                       # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise ValueError("snappy: truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise ValueError("snappy: truncated literal")
            out += data[pos:pos + length]
            pos += length
        else:                               # copy
            if kind == 1:
                length = ((tag >> 2) & 0x7) + 4
                if pos + 1 > n:
                    raise ValueError("snappy: truncated copy-1")
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                if pos + 2 > n:
                    raise ValueError("snappy: truncated copy-2")
                offset = struct.unpack_from("<H", data, pos)[0]
                pos += 2
            else:
                length = (tag >> 2) + 1
                if pos + 4 > n:
                    raise ValueError("snappy: truncated copy-4")
                offset = struct.unpack_from("<I", data, pos)[0]
                pos += 4
            if offset == 0 or offset > len(out):
                raise ValueError("snappy: bad copy offset")
            if len(out) + length > max_len:
                raise ValueError("snappy: output too large")
            start = len(out) - offset
            for k in range(length):        # may self-overlap (RLE)
                out.append(out[start + k])
    if len(out) != want:
        raise ValueError("snappy: length mismatch")
    return bytes(out)


# -- CRC32C (Castagnoli, reflected 0x82F63B78) --------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- framing format -----------------------------------------------------------

_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"
_MAX_CHUNK = 65536


def compress_frames(data: bytes) -> bytes:
    out = bytearray(_STREAM_ID)
    for off in range(0, max(len(data), 1), _MAX_CHUNK):
        chunk = data[off:off + _MAX_CHUNK]
        crc = struct.pack("<I", _masked_crc(chunk))
        comp = compress_block(chunk)
        if len(comp) < len(chunk):
            body = crc + comp
            out += b"\x00" + struct.pack("<I", len(body))[:3] + body
        else:
            body = crc + chunk
            out += b"\x01" + struct.pack("<I", len(body))[:3] + body
    return bytes(out)


def decompress_frames(data: bytes, max_len: int = MAX_UNCOMPRESSED) -> bytes:
    if not data.startswith(_STREAM_ID):
        raise ValueError("snappy-frames: missing stream identifier")
    pos = len(_STREAM_ID)
    out = bytearray()
    while pos < len(data):
        if pos + 4 > len(data):
            raise ValueError("snappy-frames: truncated chunk header")
        kind = data[pos]
        length = int.from_bytes(data[pos + 1:pos + 4], "little")
        pos += 4
        if pos + length > len(data):
            raise ValueError("snappy-frames: truncated chunk")
        body = data[pos:pos + length]
        pos += length
        if kind == 0x00 or kind == 0x01:
            if length < 4:
                raise ValueError("snappy-frames: chunk too short")
            want_crc = struct.unpack("<I", body[:4])[0]
            payload = (decompress_block(body[4:], max_len) if kind == 0
                       else body[4:])
            if _masked_crc(payload) != want_crc:
                raise ValueError("snappy-frames: CRC mismatch")
            out += payload
            if len(out) > max_len:
                raise ValueError("snappy-frames: output too large")
        elif kind == 0xFF:
            if body != _STREAM_ID[4:]:
                raise ValueError("snappy-frames: bad stream identifier")
        elif 0x80 <= kind <= 0xFE:
            continue                        # skippable padding
        else:
            raise ValueError(f"snappy-frames: reserved chunk {kind:#x}")
    return bytes(out)
