"""Req/resp RPC (lighthouse_network/src/rpc: protocol.rs:236-266).

Protocols: status, goodbye, ping, metadata, beacon_blocks_by_range,
beacon_blocks_by_root. Payloads are zlib-compressed SSZ (the SSZ-snappy
framing's role). Blocking request API with per-request ids + timeouts;
token-bucket rate limiting per protocol (rpc/rate_limiter.rs).
"""
from __future__ import annotations

import json
import threading
import time
import zlib
from dataclasses import dataclass


@dataclass
class StatusMessage:
    fork_digest: bytes
    finalized_root: bytes
    finalized_epoch: int
    head_root: bytes
    head_slot: int

    def to_json(self) -> dict:
        return {"fork_digest": self.fork_digest.hex(),
                "finalized_root": self.finalized_root.hex(),
                "finalized_epoch": self.finalized_epoch,
                "head_root": self.head_root.hex(),
                "head_slot": self.head_slot}

    @classmethod
    def from_json(cls, d: dict) -> "StatusMessage":
        return cls(bytes.fromhex(d["fork_digest"]),
                   bytes.fromhex(d["finalized_root"]),
                   int(d["finalized_epoch"]),
                   bytes.fromhex(d["head_root"]), int(d["head_slot"]))


class RateLimiter:
    """Token bucket per (peer, protocol) (rpc/rate_limiter.rs)."""

    LIMITS = {"beacon_blocks_by_range": (128, 10.0),
              "beacon_blocks_by_root": (128, 10.0),
              "status": (16, 10.0), "ping": (16, 10.0),
              "metadata": (8, 10.0), "goodbye": (2, 10.0)}

    def __init__(self):
        self._buckets: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def allow(self, peer_id: str, protocol: str, cost: int = 1) -> bool:
        cap, window = self.LIMITS.get(protocol, (64, 10.0))
        now = time.monotonic()
        with self._lock:
            tokens, ts = self._buckets.get((peer_id, protocol), (cap, now))
            tokens = min(cap, tokens + (now - ts) * cap / window)
            if tokens < cost:
                self._buckets[(peer_id, protocol)] = (tokens, now)
                return False
            self._buckets[(peer_id, protocol)] = (tokens - cost, now)
            return True


class RpcHandler:
    """Wire: frame kind 2 = request {id, protocol, payload}; kind 3 =
    response {id, code, payload}. Handlers are registered per protocol."""

    REQ_FRAME = 2
    RESP_FRAME = 3

    def __init__(self, transport):
        self.transport = transport
        self.handlers: dict[str, callable] = {}
        self.rate_limiter = RateLimiter()
        self.on_rate_limited = lambda peer, protocol: None
        self._pending: dict[int, list] = {}
        self._events: dict[int, threading.Event] = {}
        self._next_id = 0
        self._lock = threading.Lock()

    def register(self, protocol: str, handler) -> None:
        """handler(peer, request_obj) -> response_obj (json-able)."""
        self.handlers[protocol] = handler

    def request(self, peer, protocol: str, payload: dict,
                timeout: float = 10.0):
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            ev = threading.Event()
            self._events[req_id] = ev
        msg = zlib.compress(json.dumps(
            {"id": req_id, "protocol": protocol,
             "payload": payload}).encode())
        peer.send_frame(self.REQ_FRAME, msg)
        if not ev.wait(timeout):
            with self._lock:
                self._events.pop(req_id, None)
                self._pending.pop(req_id, None)
            raise TimeoutError(f"rpc {protocol} timed out")
        with self._lock:
            self._events.pop(req_id, None)
            code, resp = self._pending.pop(req_id)
        if code != 0:
            raise RuntimeError(f"rpc error {code}: {resp}")
        return resp

    def handle_frame(self, peer, kind: int, payload: bytes) -> None:
        try:
            msg = json.loads(zlib.decompress(payload))
        except (ValueError, zlib.error):
            return
        if not isinstance(msg, dict) or "id" not in msg:
            return
        if kind == self.REQ_FRAME:
            protocol = msg.get("protocol", "?")
            if not self.rate_limiter.allow(peer.node_id, protocol):
                self.on_rate_limited(peer, protocol)
                self._respond(peer, msg["id"], 429, "rate limited")
                return
            handler = self.handlers.get(protocol)
            if handler is None:
                self._respond(peer, msg["id"], 404, "unknown protocol")
                return
            try:
                resp = handler(peer, msg.get("payload"))
                self._respond(peer, msg["id"], 0, resp)
            except Exception as e:
                self._respond(peer, msg["id"], 500, repr(e))
        elif kind == self.RESP_FRAME:
            with self._lock:
                ev = self._events.get(msg["id"])
                if ev is not None:
                    self._pending[msg["id"]] = (msg["code"], msg.get("payload"))
                    ev.set()

    def _respond(self, peer, req_id: int, code: int, payload) -> None:
        msg = zlib.compress(json.dumps(
            {"id": req_id, "code": code, "payload": payload}).encode())
        peer.send_frame(self.RESP_FRAME, msg)
