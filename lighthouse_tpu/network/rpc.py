"""Req/resp RPC over SSZ-snappy (lighthouse_network/src/rpc).

Protocols: status, goodbye, ping, metadata, beacon_blocks_by_range,
beacon_blocks_by_root (protocol.rs:236-266).  The wire is binary:

  request frame  (kind 2): [u32 req_id][u8 plen][protocol][snappy-frames(ssz)]
  response frame (kind 3): [u32 req_id][u8 code][snappy-frames(body)]

Payloads are spec-shaped SSZ wrapped in the snappy FRAMING format with
CRC32C (rpc/codec/ssz_snappy.rs); block chunks carry the fork context
byte.  Handlers keep the dict-level API (codec converts at the
boundary); blocking request API with per-request ids + timeouts;
token-bucket rate limiting per protocol (rpc/rate_limiter.rs).
"""
from __future__ import annotations

import struct
import threading
import time
from dataclasses import dataclass

from . import snappy


@dataclass
class StatusMessage:
    fork_digest: bytes
    finalized_root: bytes
    finalized_epoch: int
    head_root: bytes
    head_slot: int

    def to_json(self) -> dict:
        return {"fork_digest": self.fork_digest.hex(),
                "finalized_root": self.finalized_root.hex(),
                "finalized_epoch": self.finalized_epoch,
                "head_root": self.head_root.hex(),
                "head_slot": self.head_slot}

    @classmethod
    def from_json(cls, d: dict) -> "StatusMessage":
        return cls(bytes.fromhex(d["fork_digest"]),
                   bytes.fromhex(d["finalized_root"]),
                   int(d["finalized_epoch"]),
                   bytes.fromhex(d["head_root"]), int(d["head_slot"]))


# ---------------------------------------------------------------------------
# per-protocol SSZ codecs (dict <-> canonical SSZ bytes)
# ---------------------------------------------------------------------------

def _enc_status(d: dict) -> bytes:
    return (bytes.fromhex(d["fork_digest"])
            + bytes.fromhex(d["finalized_root"])
            + struct.pack("<Q", int(d["finalized_epoch"]))
            + bytes.fromhex(d["head_root"])
            + struct.pack("<Q", int(d["head_slot"])))


def _dec_status(b: bytes) -> dict:
    if len(b) != 84:
        raise ValueError("bad status size")
    return {"fork_digest": b[0:4].hex(), "finalized_root": b[4:36].hex(),
            "finalized_epoch": struct.unpack_from("<Q", b, 36)[0],
            "head_root": b[44:76].hex(),
            "head_slot": struct.unpack_from("<Q", b, 76)[0]}


def _enc_u64(key):
    def enc(d):
        return struct.pack("<Q", int((d or {}).get(key, 0)))

    def dec(b):
        if len(b) != 8:
            raise ValueError("bad u64 payload")
        return {key: struct.unpack("<Q", b)[0]}
    return enc, dec


def _enc_empty(_d) -> bytes:
    return b""


def _dec_empty(_b) -> dict:
    return {}


def _enc_metadata(d: dict) -> bytes:
    attnets = bytes.fromhex((d or {}).get("attnets", "00"))
    return struct.pack("<Q", int((d or {}).get("seq_number", 0))) \
        + attnets[:8].ljust(8, b"\x00")


def _dec_metadata(b: bytes) -> dict:
    if len(b) != 16:
        raise ValueError("bad metadata size")
    return {"seq_number": struct.unpack_from("<Q", b)[0],
            "attnets": b[8:16].hex()}


def _enc_by_range(d: dict) -> bytes:
    return struct.pack("<QQQ", int(d["start_slot"]), int(d["count"]),
                       int(d.get("step", 1)))


def _dec_by_range(b: bytes) -> dict:
    if len(b) != 24:
        raise ValueError("bad by_range size")
    s, c, st = struct.unpack("<QQQ", b)
    return {"start_slot": s, "count": c, "step": st}


def _enc_by_root(d: dict) -> bytes:
    roots = [bytes.fromhex(r) for r in d.get("roots", [])]
    if any(len(r) != 32 for r in roots):
        raise ValueError("bad root size")
    return b"".join(roots)


def _dec_by_root(b: bytes) -> dict:
    if len(b) % 32:
        raise ValueError("bad by_root size")
    return {"roots": [b[i:i + 32].hex() for i in range(0, len(b), 32)]}


def _enc_blocks(chunks: list) -> bytes:
    """Response chunk list: [u32 len][fork-context-byte + ssz]* — each
    entry is the hex string produced by sync.encode_block."""
    out = bytearray()
    for h in chunks or []:
        raw = bytes.fromhex(h)
        out += struct.pack("<I", len(raw)) + raw
    return bytes(out)


def _dec_blocks(b: bytes) -> list:
    out = []
    pos = 0
    while pos < len(b):
        if pos + 4 > len(b):
            raise ValueError("truncated chunk header")
        (length,) = struct.unpack_from("<I", b, pos)
        pos += 4
        if pos + length > len(b) or length > 16 * 1024 * 1024:
            raise ValueError("bad chunk length")
        out.append(b[pos:pos + length].hex())
        pos += length
    return out


_PING_ENC, _PING_DEC = _enc_u64("seq")
_GOODBYE_ENC, _GOODBYE_DEC = _enc_u64("reason")

# protocol -> (enc_req, dec_req, enc_resp, dec_resp)
CODECS: dict[str, tuple] = {
    "status": (_enc_status, _dec_status, _enc_status, _dec_status),
    "ping": (_PING_ENC, _PING_DEC, _PING_ENC, _PING_DEC),
    "goodbye": (_GOODBYE_ENC, _GOODBYE_DEC, _enc_empty, _dec_empty),
    "metadata": (_enc_empty, _dec_empty, _enc_metadata, _dec_metadata),
    "beacon_blocks_by_range": (_enc_by_range, _dec_by_range,
                               _enc_blocks, _dec_blocks),
    "beacon_blocks_by_root": (_enc_by_root, _dec_by_root,
                              _enc_blocks, _dec_blocks),
}


class RateLimiter:
    """Token bucket per (peer, protocol) (rpc/rate_limiter.rs)."""

    LIMITS = {"beacon_blocks_by_range": (128, 10.0),
              "beacon_blocks_by_root": (128, 10.0),
              "status": (16, 10.0), "ping": (16, 10.0),
              "metadata": (8, 10.0), "goodbye": (2, 10.0)}

    def __init__(self):
        self._buckets: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def allow(self, peer_id: str, protocol: str, cost: int = 1) -> bool:
        cap, window = self.LIMITS.get(protocol, (64, 10.0))
        now = time.monotonic()
        with self._lock:
            tokens, ts = self._buckets.get((peer_id, protocol), (cap, now))
            tokens = min(cap, tokens + (now - ts) * cap / window)
            if tokens < cost:
                self._buckets[(peer_id, protocol)] = (tokens, now)
                return False
            self._buckets[(peer_id, protocol)] = (tokens - cost, now)
            return True


class RpcHandler:
    REQ_FRAME = 2
    RESP_FRAME = 3

    def __init__(self, transport):
        self.transport = transport
        self.handlers: dict[str, callable] = {}
        self.rate_limiter = RateLimiter()
        self.on_rate_limited = lambda peer, protocol: None
        self._pending: dict[int, tuple] = {}
        self._req_proto: dict[int, str] = {}
        self._events: dict[int, threading.Event] = {}
        self._next_id = 0
        self._lock = threading.Lock()

    def register(self, protocol: str, handler) -> None:
        """handler(peer, request_dict) -> response object (per codec)."""
        self.handlers[protocol] = handler

    def request(self, peer, protocol: str, payload: dict,
                timeout: float = 10.0):
        enc_req = CODECS[protocol][0]
        # encode BEFORE registering the waiter: a codec error must not
        # leak _events/_req_proto entries
        body = snappy.compress_frames(enc_req(payload or {}))
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            ev = threading.Event()
            self._events[req_id] = ev
            self._req_proto[req_id] = protocol
        proto_b = protocol.encode()
        msg = struct.pack("<IB", req_id, len(proto_b)) + proto_b + body
        peer.send_frame(self.REQ_FRAME, msg)
        if not ev.wait(timeout):
            with self._lock:
                self._events.pop(req_id, None)
                self._pending.pop(req_id, None)
                self._req_proto.pop(req_id, None)
            raise TimeoutError(f"rpc {protocol} timed out")
        with self._lock:
            self._events.pop(req_id, None)
            self._req_proto.pop(req_id, None)
            code, resp = self._pending.pop(req_id)
        if code != 0:
            raise RuntimeError(f"rpc error {code}")
        return resp

    def handle_frame(self, peer, kind: int, payload: bytes) -> None:
        if kind == self.REQ_FRAME:
            self._handle_request(peer, payload)
        elif kind == self.RESP_FRAME:
            self._handle_response(peer, payload)

    def _handle_request(self, peer, payload: bytes) -> None:
        try:
            req_id, plen = struct.unpack_from("<IB", payload, 0)
            protocol = payload[5:5 + plen].decode()
            body = payload[5 + plen:]
        except (struct.error, UnicodeDecodeError):
            return
        if not self.rate_limiter.allow(peer.node_id, protocol):
            self.on_rate_limited(peer, protocol)
            self._respond(peer, req_id, 429, b"")
            return
        codec = CODECS.get(protocol)
        handler = self.handlers.get(protocol)
        if codec is None or handler is None:
            self._respond(peer, req_id, 404, b"")
            return
        try:
            req = codec[1](snappy.decompress_frames(body))
            resp = handler(peer, req)
            self._respond(peer, req_id, 0,
                          snappy.compress_frames(codec[2](resp)))
        except Exception:
            self._respond(peer, req_id, 500, b"")

    def _handle_response(self, peer, payload: bytes) -> None:
        try:
            req_id, code = struct.unpack_from("<IB", payload, 0)
            body = payload[5:]
        except struct.error:
            return
        with self._lock:
            ev = self._events.get(req_id)
            protocol = self._req_proto.get(req_id)
        if ev is None or protocol is None:
            return
        resp = None
        if code == 0:
            try:
                resp = CODECS[protocol][3](snappy.decompress_frames(body))
            except (ValueError, KeyError, IndexError, struct.error,
                    UnicodeDecodeError):
                code = 502          # undecodable response
        with self._lock:
            if req_id in self._events:
                self._pending[req_id] = (code, resp)
                ev.set()

    def _respond(self, peer, req_id: int, code: int, body: bytes) -> None:
        peer.send_frame(self.RESP_FRAME,
                        struct.pack("<IB", req_id, code) + body)
