"""Req/resp RPC — the REAL eth2 stream protocol over yamux.

Each request runs on its own negotiated stream (ref: beacon_node/
lighthouse_network/src/rpc/protocol.rs:236-266 protocol ids;
rpc/codec/ssz_snappy.rs framing):

    protocol id:  /eth2/beacon_chain/req/<name>/<version>/ssz_snappy
    request:      varint(ssz_len) || snappy-frames(ssz)      (one payload;
                  metadata requests are empty)
    response:     chunk*  where chunk =
                  result(1B: 0 ok, 1 invalid, 2 server_error, 3 unavail)
                  || [4B fork-context, block chunks on v2 protocols]
                  || varint(ssz_len) || snappy-frames(ssz)
    requester half-closes (FIN) after the request; responder writes its
    chunks and closes.

The dict-level codec API from round 2 is retained above the wire
(handlers speak dicts / hex chunk strings); token-bucket rate limiting
per (peer, protocol) as in rpc/rate_limiter.rs.
"""
from __future__ import annotations

import hashlib
import struct
import threading
import time
from dataclasses import dataclass

from ..obs import tracing
from . import snappy
from .multistream import write_uvarint
from .yamux import Stream, YamuxEOF, YamuxError

RESULT_SUCCESS = 0
RESULT_INVALID_REQUEST = 1
RESULT_SERVER_ERROR = 2
RESULT_RESOURCE_UNAVAILABLE = 3
RESULT_RATE_LIMITED = 139       # lighthouse extension code


@dataclass
class StatusMessage:
    fork_digest: bytes
    finalized_root: bytes
    finalized_epoch: int
    head_root: bytes
    head_slot: int

    def to_json(self) -> dict:
        return {"fork_digest": self.fork_digest.hex(),
                "finalized_root": self.finalized_root.hex(),
                "finalized_epoch": self.finalized_epoch,
                "head_root": self.head_root.hex(),
                "head_slot": self.head_slot}

    @classmethod
    def from_json(cls, d: dict) -> "StatusMessage":
        return cls(bytes.fromhex(d["fork_digest"]),
                   bytes.fromhex(d["finalized_root"]),
                   int(d["finalized_epoch"]),
                   bytes.fromhex(d["head_root"]), int(d["head_slot"]))


# ---------------------------------------------------------------------------
# per-protocol SSZ codecs (dict <-> canonical SSZ bytes)
# ---------------------------------------------------------------------------

def _enc_status(d: dict) -> bytes:
    return (bytes.fromhex(d["fork_digest"])
            + bytes.fromhex(d["finalized_root"])
            + struct.pack("<Q", int(d["finalized_epoch"]))
            + bytes.fromhex(d["head_root"])
            + struct.pack("<Q", int(d["head_slot"])))


def _dec_status(b: bytes) -> dict:
    if len(b) != 84:
        raise ValueError("bad status size")
    return {"fork_digest": b[0:4].hex(), "finalized_root": b[4:36].hex(),
            "finalized_epoch": struct.unpack_from("<Q", b, 36)[0],
            "head_root": b[44:76].hex(),
            "head_slot": struct.unpack_from("<Q", b, 76)[0]}


def _enc_u64(key):
    def enc(d):
        return struct.pack("<Q", int((d or {}).get(key, 0)))

    def dec(b):
        if len(b) != 8:
            raise ValueError("bad u64 payload")
        return {key: struct.unpack("<Q", b)[0]}
    return enc, dec


def _enc_empty(_d) -> bytes:
    return b""


def _dec_empty(_b) -> dict:
    return {}


def _enc_metadata(d: dict) -> bytes:
    attnets = bytes.fromhex((d or {}).get("attnets", "00"))
    syncnets = bytes.fromhex((d or {}).get("syncnets", "00"))
    return struct.pack("<Q", int((d or {}).get("seq_number", 0))) \
        + attnets[:8].ljust(8, b"\x00") + syncnets[:1].ljust(1, b"\x00")


def _dec_metadata(b: bytes) -> dict:
    if len(b) not in (16, 17):      # v1 (no syncnets) tolerated
        raise ValueError("bad metadata size")
    return {"seq_number": struct.unpack_from("<Q", b)[0],
            "attnets": b[8:16].hex(),
            "syncnets": b[16:17].hex() if len(b) > 16 else "00"}


def _enc_by_range(d: dict) -> bytes:
    return struct.pack("<QQQ", int(d["start_slot"]), int(d["count"]),
                       int(d.get("step", 1)))


def _dec_by_range(b: bytes) -> dict:
    if len(b) != 24:
        raise ValueError("bad by_range size")
    s, c, st = struct.unpack("<QQQ", b)
    return {"start_slot": s, "count": c, "step": st}


def _enc_by_root(d: dict) -> bytes:
    roots = [bytes.fromhex(r) for r in d.get("roots", [])]
    if any(len(r) != 32 for r in roots):
        raise ValueError("bad root size")
    return b"".join(roots)


def _dec_by_root(b: bytes) -> dict:
    if len(b) % 32:
        raise ValueError("bad by_root size")
    return {"roots": [b[i:i + 32].hex() for i in range(0, len(b), 32)]}


def _enc_lc_bootstrap_req(d: dict) -> bytes:
    root = bytes.fromhex(d["root"])
    if len(root) != 32:
        raise ValueError("bad root size")
    return root


def _dec_lc_bootstrap_req(b: bytes) -> dict:
    if len(b) != 32:
        raise ValueError("bad root size")
    return {"root": b.hex()}


def _enc_lc_range_req(d: dict) -> bytes:
    return struct.pack("<QQ", int(d["start_period"]), int(d["count"]))


def _dec_lc_range_req(b: bytes) -> dict:
    if len(b) != 16:
        raise ValueError("bad range size")
    s, c = struct.unpack("<QQ", b)
    return {"start_period": s, "count": c}


def _enc_hexpayload(h) -> bytes:
    """Opaque context-prefixed payload chunks carried as hex strings."""
    return bytes.fromhex(h or "")


def _dec_hexpayload(b: bytes):
    return b.hex()


_PING_ENC, _PING_DEC = _enc_u64("seq")
_GOODBYE_ENC, _GOODBYE_DEC = _enc_u64("reason")


@dataclass(frozen=True)
class ProtocolSpec:
    name: str
    version: int
    enc_req: callable
    dec_req: callable
    enc_resp: callable
    dec_resp: callable
    #: response is a stream of context-prefixed chunks (each returned as
    #: a hex string), not a single SSZ payload
    chunked: bool = False
    #: v2 chunks lead with a 4-byte fork-context (blocks, LC updates)
    context_bytes: bool = False
    #: a response chunk is expected (goodbye tolerates none)
    expect_response: bool = True

    @property
    def id(self) -> str:
        return f"/eth2/beacon_chain/req/{self.name}/{self.version}" \
            "/ssz_snappy"


_SPECS = [
    ProtocolSpec("status", 1, _enc_status, _dec_status,
                 _enc_status, _dec_status),
    ProtocolSpec("goodbye", 1, _GOODBYE_ENC, _GOODBYE_DEC,
                 _enc_empty, _dec_empty, expect_response=False),
    ProtocolSpec("ping", 1, _PING_ENC, _PING_DEC, _PING_ENC, _PING_DEC),
    ProtocolSpec("metadata", 2, _enc_empty, _dec_empty,
                 _enc_metadata, _dec_metadata),
    ProtocolSpec("beacon_blocks_by_range", 2, _enc_by_range, _dec_by_range,
                 _enc_hexpayload, _dec_hexpayload, chunked=True,
                 context_bytes=True),
    ProtocolSpec("beacon_blocks_by_root", 2, _enc_by_root, _dec_by_root,
                 _enc_hexpayload, _dec_hexpayload, chunked=True,
                 context_bytes=True),
    ProtocolSpec("blob_sidecars_by_range", 1, _enc_by_range, _dec_by_range,
                 _enc_hexpayload, _dec_hexpayload, chunked=True,
                 context_bytes=True),
    ProtocolSpec("blob_sidecars_by_root", 1, _enc_by_root, _dec_by_root,
                 _enc_hexpayload, _dec_hexpayload, chunked=True,
                 context_bytes=True),
    ProtocolSpec("data_column_sidecars_by_range", 1, _enc_by_range,
                 _dec_by_range, _enc_hexpayload, _dec_hexpayload,
                 chunked=True, context_bytes=True),
    ProtocolSpec("data_column_sidecars_by_root", 1, _enc_by_root,
                 _dec_by_root, _enc_hexpayload, _dec_hexpayload,
                 chunked=True, context_bytes=True),
    ProtocolSpec("light_client_bootstrap", 1, _enc_lc_bootstrap_req,
                 _dec_lc_bootstrap_req, _enc_hexpayload, _dec_hexpayload,
                 chunked=True, context_bytes=True),
    ProtocolSpec("light_client_optimistic_update", 1, _enc_empty,
                 _dec_empty, _enc_hexpayload, _dec_hexpayload,
                 chunked=True, context_bytes=True),
    ProtocolSpec("light_client_finality_update", 1, _enc_empty,
                 _dec_empty, _enc_hexpayload, _dec_hexpayload,
                 chunked=True, context_bytes=True),
    ProtocolSpec("light_client_updates_by_range", 1, _enc_lc_range_req,
                 _dec_lc_range_req, _enc_hexpayload, _dec_hexpayload,
                 chunked=True, context_bytes=True),
]
SPECS: dict[str, ProtocolSpec] = {s.name: s for s in _SPECS}
BY_ID: dict[str, ProtocolSpec] = {s.id: s for s in _SPECS}


class RateLimiter:
    """Token bucket per (peer, protocol) (rpc/rate_limiter.rs)."""

    LIMITS = {"beacon_blocks_by_range": (128, 10.0),
              "beacon_blocks_by_root": (128, 10.0),
              "blob_sidecars_by_range": (128, 10.0),
              "blob_sidecars_by_root": (128, 10.0),
              "light_client_updates_by_range": (64, 10.0),
              "status": (16, 10.0), "ping": (16, 10.0),
              "metadata": (8, 10.0), "goodbye": (2, 10.0)}

    def __init__(self):
        self._buckets: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def allow(self, peer_id: str, protocol: str, cost: int = 1) -> bool:
        cap, window = self.LIMITS.get(protocol, (64, 10.0))
        now = time.monotonic()
        with self._lock:
            tokens, ts = self._buckets.get((peer_id, protocol), (cap, now))
            tokens = min(cap, tokens + (now - ts) * cap / window)
            if tokens < cost:
                self._buckets[(peer_id, protocol)] = (tokens, now)
                return False
            self._buckets[(peer_id, protocol)] = (tokens - cost, now)
            return True


# -- stream payload codec (varint + snappy frames) ----------------------------

MAX_PAYLOAD = 32 * 1024 * 1024


def write_payload(stream: Stream, ssz: bytes) -> None:
    stream.write(write_uvarint(len(ssz)) + snappy.compress_frames(ssz))


def read_payload(stream: Stream, timeout: float = 10.0) -> bytes:
    """varint(len) || snappy frames, decoded incrementally frame by
    frame (each snappy frame header carries its own length — the
    property the real codec exploits to know where a chunk ends)."""
    n = _read_stream_uvarint(stream, timeout)
    if n > MAX_PAYLOAD:
        raise ValueError(f"payload too large ({n})")
    out = bytearray()
    while len(out) < n:
        hdr = stream.read_exact(4, timeout)
        ftype = hdr[0]
        flen = int.from_bytes(hdr[1:4], "little")
        if flen > 1 << 24:
            raise ValueError("snappy frame too large")
        body = stream.read_exact(flen, timeout)
        if ftype == 0xFF:                   # stream identifier
            if body != snappy._STREAM_ID[4:]:
                raise ValueError("bad snappy stream id")
        elif ftype == 0x00:                 # compressed data
            raw = snappy.decompress_block(body[4:], MAX_PAYLOAD)
            if snappy._masked_crc(raw) != int.from_bytes(body[:4],
                                                         "little"):
                raise ValueError("snappy crc mismatch")
            out += raw
        elif ftype == 0x01:                 # uncompressed data
            raw = body[4:]
            if snappy._masked_crc(raw) != int.from_bytes(body[:4],
                                                         "little"):
                raise ValueError("snappy crc mismatch")
            out += raw
        elif 0x80 <= ftype <= 0xFD:
            continue                        # skippable padding
        else:
            raise ValueError(f"bad snappy frame type {ftype:#x}")
    if len(out) != n:
        raise ValueError(f"payload length mismatch {len(out)} != {n}")
    return bytes(out)


def _read_stream_uvarint(stream: Stream, timeout: float) -> int:
    shift = v = 0
    while True:
        b = stream.read_exact(1, timeout)[0]
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v
        shift += 7
        if shift > 63:
            raise ValueError("varint overflow")


def _req_id(spec, req_ssz: bytes) -> str:
    """Content-derived request id: both sides of a stream hold the exact
    same request bytes (the requester encodes them, the responder reads
    them), so hashing protocol id + payload yields a shared identifier
    WITHOUT any wire change — graftpath stitches rpc_request/rpc_serve
    spans across nodes on it."""
    if spec.name == "metadata":
        req_ssz = b""              # responder never reads a payload
    return hashlib.sha256(spec.id.encode() + req_ssz).hexdigest()[:16]


class RpcHandler:
    """Stream-per-request req/resp engine over the libp2p transport."""

    def __init__(self, transport):
        self.transport = transport
        self.node_label = (getattr(transport, "label", None)
                           or str(getattr(transport, "node_id", ""))[:8])
        self.handlers: dict[str, callable] = {}
        self.rate_limiter = RateLimiter()
        self.on_rate_limited = lambda peer, protocol: None
        transport.on_rpc_stream = self.serve_stream
        transport.rpc_protocols = [s.id for s in _SPECS]

    def register(self, protocol: str, handler) -> None:
        """handler(peer, request_dict) -> response object (per codec)."""
        self.handlers[protocol] = handler

    # -- requester side --------------------------------------------------------

    def request(self, peer, protocol: str, payload: dict,
                timeout: float = 10.0):
        spec = SPECS[protocol]
        req_ssz = spec.enc_req(payload or {})
        with tracing.span("rpc_request", protocol=spec.name,
                          req_id=_req_id(spec, req_ssz),
                          node=self.node_label):
            try:
                stream, _ = peer.open_protocol([spec.id], timeout)
            except Exception as e:
                raise TimeoutError(
                    f"rpc {protocol}: open failed: {e}") from None
            try:
                if req_ssz or spec.name != "metadata":
                    write_payload(stream, req_ssz)
                stream.close()                  # FIN: request complete
                if spec.chunked:
                    return self._read_chunks(spec, stream, timeout)
                return self._read_single(spec, stream, timeout)
            finally:
                if not stream.reset:
                    stream.close()

    def _read_result_byte(self, spec, stream, timeout: float) -> int | None:
        """-> result code, or None on CLEAN EOF only; a stall or RST
        raises (a truncated chunk stream must not look complete —
        sync would mis-penalize peers on 'short' batches otherwise)."""
        try:
            b = stream.read_exact(1, timeout)
        except YamuxEOF:
            return None
        except YamuxError as e:
            raise TimeoutError(f"rpc {spec.name}: {e}") from None
        return b[0]

    def _read_single(self, spec, stream, timeout: float):
        code = self._read_result_byte(spec, stream, timeout)
        if code is None:
            if not spec.expect_response:
                return {}
            raise TimeoutError(f"rpc {spec.name}: no response")
        if code != RESULT_SUCCESS:
            raise RuntimeError(f"rpc error {code}")
        return spec.dec_resp(read_payload(stream, timeout))

    def _read_chunks(self, spec, stream, timeout: float) -> list:
        out = []
        while True:
            code = self._read_result_byte(spec, stream, timeout)
            if code is None:
                return out                     # clean EOF: stream done
            if code != RESULT_SUCCESS:
                raise RuntimeError(f"rpc error {code}")
            ctx = stream.read_exact(4, timeout) if spec.context_bytes \
                else b""
            ssz = read_payload(stream, timeout)
            out.append(spec.dec_resp(ctx + ssz))

    # -- responder side --------------------------------------------------------

    def serve_stream(self, peer, protocol_id: str, stream: Stream) -> None:
        spec = BY_ID.get(protocol_id)
        if spec is None:
            stream.rst()
            return
        if not self.rate_limiter.allow(peer.node_id, spec.name):
            self.on_rate_limited(peer, spec.name)
            stream.write(bytes([RESULT_RATE_LIMITED]))
            write_payload(stream, b"rate limited")
            stream.close()
            return
        handler = self.handlers.get(spec.name)
        if handler is None:
            stream.write(bytes([RESULT_RESOURCE_UNAVAILABLE]))
            write_payload(stream, b"unsupported")
            stream.close()
            return
        try:
            req_ssz = b"" if spec.name == "metadata" \
                else read_payload(stream)
            req = spec.dec_req(req_ssz)
        except (ValueError, YamuxError, struct.error):
            stream.write(bytes([RESULT_INVALID_REQUEST]))
            write_payload(stream, b"bad request")
            stream.close()
            return
        try:
            with tracing.span("rpc_serve", protocol=spec.name,
                              req_id=_req_id(spec, req_ssz),
                              node=self.node_label):
                resp = handler(peer, req)
        except Exception:
            stream.write(bytes([RESULT_SERVER_ERROR]))
            write_payload(stream, b"server error")
            stream.close()
            return
        try:
            if spec.chunked:
                for chunk_hex in resp or []:
                    raw = spec.enc_resp(chunk_hex)
                    stream.write(bytes([RESULT_SUCCESS]))
                    if spec.context_bytes:
                        stream.write(raw[:4])
                        write_payload(stream, raw[4:])
                    else:
                        write_payload(stream, raw)
            elif spec.expect_response or resp:
                stream.write(bytes([RESULT_SUCCESS]))
                write_payload(stream, spec.enc_resp(resp))
            stream.close()
        except (YamuxError, OSError):
            pass
