"""Key-value backends.

`KeyValueStore` mirrors the column-oriented trait at
/root/reference/beacon_node/store/src/lib.rs:53; `NativeKvStore` binds the
C++ log-structured engine (native/kvstore.cpp — the LevelDB-equivalent);
`MemoryStore` is the test backend (src/memory_store.rs).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path


class StoreError(Exception):
    pass


class KeyValueStore:
    """Byte-oriented KV with ordered prefix iteration."""

    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def put(self, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: bytes) -> None:
        raise NotImplementedError

    def exists(self, key: bytes) -> bool:
        return self.get(key) is not None

    def iter_prefix(self, prefix: bytes):
        """Yield (key, value) in key order for keys starting with prefix."""
        raise NotImplementedError

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    def do_atomically(self, ops: list[tuple[str, bytes, bytes | None]],
                      fsync: bool = False) -> None:
        """ops: ("put", key, value) | ("delete", key, None).

        The batch is all-or-nothing: a failing op rolls the already-applied
        prefix back before re-raising, so a half-applied batch is never
        observable.  Backends with a native batch primitive (NativeKvStore)
        override this with a genuinely atomic commit; `fsync` asks for a
        durability barrier where the backend supports one.
        """
        undo: list[tuple[str, bytes, bytes | None]] = []
        try:
            for op, key, value in ops:
                if op not in ("put", "delete"):
                    raise StoreError(f"unknown batch op {op!r}")
                undo.append((op, key, self.get(key)))
                if op == "put":
                    self.put(key, value)
                else:
                    self.delete(key)
        except BaseException:
            for _op, key, old in reversed(undo):
                try:
                    if old is None:
                        self.delete(key)
                    else:
                        self.put(key, old)
                except Exception:       # rollback is best-effort
                    pass
            raise
        self.sync()


class MemoryStore(KeyValueStore):
    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._data[key] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._data.pop(key, None)

    def do_atomically(self, ops: list[tuple[str, bytes, bytes | None]],
                      fsync: bool = False) -> None:
        """Genuinely atomic: the lock is held across the whole batch (no
        reader interleaves with a half-applied batch) and a failing op
        restores every prior write before re-raising."""
        with self._lock:
            undo: list[tuple[bytes, bytes | None]] = []
            try:
                for op, key, value in ops:
                    undo.append((key, self._data.get(key)))
                    if op == "put":
                        self._data[key] = bytes(value)
                    elif op == "delete":
                        self._data.pop(key, None)
                    else:
                        raise StoreError(f"unknown batch op {op!r}")
            except BaseException:
                for key, old in reversed(undo):
                    if old is None:
                        self._data.pop(key, None)
                    else:
                        self._data[key] = old
                raise

    def iter_prefix(self, prefix: bytes):
        with self._lock:
            keys = sorted(k for k in self._data if k.startswith(prefix))
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v


_LIB_CACHE: dict[str, ctypes.CDLL] = {}


def _load_native() -> ctypes.CDLL:
    root = Path(__file__).resolve().parents[2]
    so = root / "native" / "libkvstore.so"
    key = str(so)
    if key in _LIB_CACHE:
        return _LIB_CACHE[key]
    if not so.exists():
        build = root / "native" / "build.sh"
        subprocess.run(["sh", str(build)], check=True, capture_output=True)
    lib = ctypes.CDLL(str(so))
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [ctypes.c_char_p]
    lib.kv_close.argtypes = [ctypes.c_void_p]
    lib.kv_put.restype = ctypes.c_int
    lib.kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                           ctypes.c_char_p, ctypes.c_size_t]
    lib.kv_delete.restype = ctypes.c_int
    lib.kv_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_size_t]
    lib.kv_write_batch.restype = ctypes.c_int
    lib.kv_write_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_size_t, ctypes.c_int]
    lib.kv_get_len.restype = ctypes.c_int64
    lib.kv_get_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_size_t]
    lib.kv_get_copy.restype = ctypes.c_int64
    lib.kv_get_copy.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_size_t, ctypes.c_char_p,
                                ctypes.c_size_t]
    lib.kv_count.restype = ctypes.c_uint64
    lib.kv_count.argtypes = [ctypes.c_void_p]
    lib.kv_sync.restype = ctypes.c_int
    lib.kv_sync.argtypes = [ctypes.c_void_p]
    lib.kv_compact.restype = ctypes.c_int
    lib.kv_compact.argtypes = [ctypes.c_void_p]
    lib.kv_iter_prefix.restype = ctypes.c_void_p
    lib.kv_iter_prefix.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_size_t]
    lib.kv_iter_next.restype = ctypes.c_int
    lib.kv_iter_next.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_size_t),
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_size_t)]
    lib.kv_iter_destroy.argtypes = [ctypes.c_void_p]
    _LIB_CACHE[key] = lib
    return lib


class NativeKvStore(KeyValueStore):
    """ctypes binding to native/kvstore.cpp."""

    def __init__(self, path: str | os.PathLike):
        self._lib = _load_native()
        os.makedirs(os.path.dirname(os.fspath(path)) or ".", exist_ok=True)
        self._h = self._lib.kv_open(os.fspath(path).encode())
        if not self._h:
            raise StoreError(f"cannot open kv store at {path}")

    def get(self, key: bytes) -> bytes | None:
        n = self._lib.kv_get_len(self._h, key, len(key))
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.kv_get_copy(self._h, key, len(key), buf, int(n))
        if got < 0:
            raise StoreError("kv read error")
        return buf.raw[:got]

    def put(self, key: bytes, value: bytes) -> None:
        if self._lib.kv_put(self._h, key, len(key), value, len(value)) != 0:
            raise StoreError("kv write error")

    def delete(self, key: bytes) -> None:
        if self._lib.kv_delete(self._h, key, len(key)) != 0:
            raise StoreError("kv delete error")

    def iter_prefix(self, prefix: bytes):
        it = self._lib.kv_iter_prefix(self._h, prefix, len(prefix))
        try:
            k = ctypes.c_char_p()
            kl = ctypes.c_size_t()
            v = ctypes.c_char_p()
            vl = ctypes.c_size_t()
            while self._lib.kv_iter_next(it, ctypes.byref(k),
                                         ctypes.byref(kl), ctypes.byref(v),
                                         ctypes.byref(vl)):
                key = ctypes.string_at(k, kl.value)
                val = ctypes.string_at(v, vl.value)
                yield key, val
        finally:
            self._lib.kv_iter_destroy(it)

    def do_atomically(self, ops: list[tuple[str, bytes, bytes | None]],
                      fsync: bool = False) -> None:
        """One CRC'd batch record in the native log: replay applies it
        all-or-nothing, so partial-batch bytes are never visible after a
        crash.  `fsync=True` adds an fsync barrier at the commit point."""
        import struct
        parts = [struct.pack("<I", len(ops))]
        for op, key, value in ops:
            if op == "put":
                parts.append(struct.pack("<II", len(key), len(value)))
                parts.append(bytes(key))
                parts.append(bytes(value))
            elif op == "delete":
                parts.append(struct.pack("<II", len(key), 0xFFFFFFFF))
                parts.append(bytes(key))
            else:
                raise StoreError(f"unknown batch op {op!r}")
        payload = b"".join(parts)
        rc = self._lib.kv_write_batch(self._h, payload, len(payload),
                                      1 if fsync else 0)
        if rc != 0:
            raise StoreError(f"kv batch write error (rc={rc})")

    def sync(self) -> None:
        self._lib.kv_sync(self._h)

    def compact(self) -> None:
        if self._lib.kv_compact(self._h) != 0:
            raise StoreError("kv compact failed")

    def __len__(self) -> int:
        return int(self._lib.kv_count(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None
