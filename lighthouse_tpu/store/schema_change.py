"""On-disk schema migrations.

Equivalent of /root/reference/beacon_node/beacon_chain/src/
schema_change.rs + store/src/metadata.rs CURRENT_SCHEMA_VERSION: on
open, the store upgrades older layouts in place.

v1 -> v2: per-slot freezer block-root entries (`fbr:` + be64 slot) are
re-packed into the chunked root vector (`cbr:`, chunked_vector.py) and
the old keys dropped.
"""
from __future__ import annotations

import struct


def migrate_schema(db) -> None:
    from .hot_cold import FREEZER_BLOCK_ROOT, METADATA, SCHEMA_VERSION
    current = db.schema_version()
    if current >= SCHEMA_VERSION:
        return
    if current <= 1:
        _migrate_v1_to_v2(db)
    db.hot.put(METADATA + b"schema", struct.pack("<I", SCHEMA_VERSION))
    db.hot.sync()
    db.cold.sync()


def _migrate_v1_to_v2(db) -> None:
    from .hot_cold import FREEZER_BLOCK_ROOT
    moved = 0
    for key, root in list(db.cold.iter_prefix(FREEZER_BLOCK_ROOT)):
        (slot,) = struct.unpack(">Q", key[len(FREEZER_BLOCK_ROOT):])
        db.block_roots.put(slot, root)
        db.cold.delete(key)
        moved += 1
    if moved:
        import logging
        logging.getLogger("lighthouse_tpu.store").info(
            "schema v1->v2: repacked %d freezer block roots into chunks",
            moved)
