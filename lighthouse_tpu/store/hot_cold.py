"""Hot/cold split database.

Equivalent of /root/reference/beacon_node/store/src/hot_cold_store.rs:50:
- hot DB: all unfinalized blocks; full states at epoch boundaries; per-slot
  `HotStateSummary`s pointing at their epoch-boundary state; states rebuilt
  by block replay (BlockReplayer, reconstruct.rs).
- freezer ("cold") DB: finalized block roots by slot + sparse restore-point
  states every `slots_per_restore_point`.
- `Split` marks the hot/cold boundary (hot_cold_store.rs:2715); `migrate`
  moves finalized data across it and prunes abandoned forks.
"""
from __future__ import annotations

import os
import struct
import sys
from dataclasses import dataclass

from ..containers import get_types
from ..containers.state import BeaconState
from ..obs import tracing
from ..specs.chain_spec import ChainSpec, ForkName
from ..ssz import deserialize, htr, serialize
from .kv import KeyValueStore, StoreError

# column prefixes
BLOCK = b"b:"
HOT_STATE_FULL = b"S:"
HOT_STATE_SUMMARY = b"s:"
FREEZER_BLOCK_ROOT = b"fbr:"   # v1 layout: slot (be64) -> block root
FREEZER_BLOCK_CHUNK = b"cbr:"  # v2 layout: chunked root vector
FREEZER_STATE_CHUNK = b"csr:"  # v2: chunked state-root vector
FREEZER_STATE = b"fst:"        # slot (be64) -> full state
BLOBS = b"o:"
METADATA = b"m:"
ITEM = b"i:"                   # generic persisted items (fork choice, op pool)

SCHEMA_VERSION = 2             # v2: chunked freezer root vectors


def _count(name: str, amount: float = 1) -> None:
    """Catalog counter, sys.modules-gated so standalone store use stays
    metrics-free (same discipline as obs.tracing)."""
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if md is not None:
        md.count(name, amount)


@dataclass
class Split:
    slot: int = 0
    state_root: bytes = b"\x00" * 32


@dataclass
class StoreOp:
    """One logical mutation in an atomic hot-DB commit batch
    (store/src/lib.rs StoreOp): build a list, hand it to
    `HotColdDB.do_atomically`, and either every op lands or none does —
    the crash-consistency unit for block import, head persistence and
    migration."""

    kind: str
    key: bytes = b""
    obj: object = None
    latest_block_root: bytes | None = None

    @classmethod
    def put_block(cls, block_root: bytes, signed_block) -> "StoreOp":
        return cls("put_block", block_root, signed_block)

    @classmethod
    def put_state(cls, state_root: bytes, state,
                  latest_block_root: bytes | None = None) -> "StoreOp":
        """`latest_block_root` lets callers that already know the root of
        ``state.latest_block_header`` (with its state_root filled) skip the
        hash_tree_root the summary would otherwise force — block import
        knows it: it IS the block's root when ``state`` is a post-block
        state at the block's own slot."""
        return cls("put_state", state_root, state, latest_block_root)

    @classmethod
    def put_blobs(cls, block_root: bytes, blobs: list) -> "StoreOp":
        return cls("put_blobs", block_root, blobs)

    @classmethod
    def delete_block(cls, block_root: bytes) -> "StoreOp":
        return cls("delete_block", block_root)

    @classmethod
    def delete_state(cls, state_root: bytes) -> "StoreOp":
        return cls("delete_state", state_root)

    @classmethod
    def put_item(cls, key: bytes, value: bytes) -> "StoreOp":
        return cls("put_item", key, value)

    @classmethod
    def put_meta(cls, key: bytes, value: bytes) -> "StoreOp":
        return cls("put_meta", key, value)


@dataclass
class StoreConfig:
    slots_per_restore_point: int = 2048
    compact_on_prune: bool = True
    state_cache_size: int = 8      # replayed/cold states kept hot in RAM


class _StateCache:
    """Bounded LRU of fully-materialized states (store/src/state_cache.rs
    role): cold-state loads replay O(slots_per_restore_point) blocks, so
    repeated historical reads must not re-pay that."""

    def __init__(self, capacity: int):
        from collections import OrderedDict
        self.capacity = capacity
        self._od = OrderedDict()

    def get(self, key):
        st = self._od.get(key)
        if st is not None:
            self._od.move_to_end(key)
        return st

    def put(self, key, state) -> None:
        self._od[key] = state
        self._od.move_to_end(key)
        while len(self._od) > self.capacity:
            self._od.popitem(last=False)

    def clear(self) -> None:
        self._od.clear()


class HotColdDB:
    def __init__(self, hot: KeyValueStore, cold: KeyValueStore,
                 spec: ChainSpec, config: StoreConfig | None = None):
        from .chunked_vector import ChunkedRootVector
        self.hot = hot
        self.cold = cold
        self.spec = spec
        self.T = get_types(spec.preset)
        self.config = config or StoreConfig()
        self.split = self._load_split()
        self.block_roots = ChunkedRootVector(cold, FREEZER_BLOCK_CHUNK)
        self.state_roots = ChunkedRootVector(cold, FREEZER_STATE_CHUNK)
        self.state_cache = _StateCache(self.config.state_cache_size)
        from .schema_change import migrate_schema
        migrate_schema(self)
        self._put_meta(b"schema", struct.pack("<I", SCHEMA_VERSION))
        if os.environ.get("LHTPU_FSCK_ON_OPEN"):
            from .fsck import run_fsck
            report = run_fsck(self)
            if report.errors:
                import logging
                logging.getLogger("lighthouse_tpu.store").warning(
                    "fsck at open found %d error(s): %s",
                    len(report.errors), "; ".join(report.errors[:5]))

    # -- metadata ------------------------------------------------------------

    def _put_meta(self, key: bytes, value: bytes) -> None:
        self.hot.put(METADATA + key, value)

    def _get_meta(self, key: bytes) -> bytes | None:
        return self.hot.get(METADATA + key)

    def _load_split(self) -> Split:
        raw = self._get_meta(b"split")
        if raw is None:
            return Split()
        slot, root = struct.unpack("<Q", raw[:8])[0], raw[8:40]
        return Split(slot, root)

    def schema_version(self) -> int:
        raw = self._get_meta(b"schema")
        return struct.unpack("<I", raw)[0] if raw else 0

    def put_item(self, key: bytes, value: bytes) -> None:
        self.hot.put(ITEM + key, value)

    def get_item(self, key: bytes) -> bytes | None:
        return self.hot.get(ITEM + key)

    # -- atomic commit batches ----------------------------------------------

    def _block_kv_ops(self, block_root: bytes, signed_block) -> list:
        fork = signed_block.fork_name
        data = bytes([fork.value]) + serialize(
            type(signed_block).ssz_type, signed_block)
        return [("put", BLOCK + block_root, data)]

    def _state_kv_ops(self, state_root: bytes, state: BeaconState,
                      latest_block_root: bytes | None = None) -> list:
        p = self.T.preset
        ops = []
        if state.slot % p.slots_per_epoch == 0:
            data = bytes([state.fork_name.value]) + state.serialize()
            ops.append(("put", HOT_STATE_FULL + state_root, data))
        if latest_block_root is None:
            latest_block_root = self._latest_block_root(state)
        boundary_slot = (state.slot // p.slots_per_epoch) * p.slots_per_epoch
        boundary_root = (state_root if state.slot == boundary_slot
                         else state.state_roots[
                             boundary_slot % p.slots_per_historical_root
                         ].tobytes())
        summary = struct.pack("<Q", state.slot) + latest_block_root \
            + boundary_root
        ops.append(("put", HOT_STATE_SUMMARY + state_root, summary))
        return ops

    def _blobs_kv_ops(self, block_root: bytes, blobs: list) -> list:
        from ..ssz import List as SSZList
        t = SSZList(self.T.BlobSidecar.ssz_type,
                    self.T.preset.max_blob_commitments_per_block)
        return [("put", BLOBS + block_root, serialize(t, blobs))]

    def _kv_ops_for(self, op: StoreOp) -> list:
        if op.kind == "put_block":
            return self._block_kv_ops(op.key, op.obj)
        if op.kind == "put_state":
            return self._state_kv_ops(op.key, op.obj, op.latest_block_root)
        if op.kind == "put_blobs":
            return self._blobs_kv_ops(op.key, op.obj)
        if op.kind == "delete_block":
            return [("delete", BLOCK + op.key, None)]
        if op.kind == "delete_state":
            return [("delete", HOT_STATE_FULL + op.key, None),
                    ("delete", HOT_STATE_SUMMARY + op.key, None)]
        if op.kind == "put_item":
            return [("put", ITEM + op.key, op.obj)]
        if op.kind == "put_meta":
            return [("put", METADATA + op.key, op.obj)]
        raise StoreError(f"unknown StoreOp kind {op.kind!r}")

    def do_atomically(self, ops: list[StoreOp], fsync: bool = True) -> None:
        """Commit a list of StoreOps as ONE atomic hot-DB batch: after a
        crash either every op is visible or none is (native backends frame
        the batch as a single CRC'd log record).  This is the only
        sanctioned write path for block import / head persistence /
        migration — graftlint's store-atomicity rule flags direct puts
        there."""
        kv_ops: list = []
        for op in ops:
            kv_ops.extend(self._kv_ops_for(op))
        self.hot.do_atomically(kv_ops, fsync=fsync)
        _count("store_batch_commit_total")
        _count("store_hot_db_ops_total", len(kv_ops))

    # -- blocks --------------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block) -> None:
        for _op, key, value in self._block_kv_ops(block_root, signed_block):
            self.hot.put(key, value)
        _count("store_hot_db_ops_total")

    def get_block(self, block_root: bytes):
        raw = self.hot.get(BLOCK + block_root)
        if raw is None:
            return None
        fork = ForkName(raw[0])
        cls = self.T.SignedBeaconBlock[fork]
        return deserialize(cls.ssz_type, raw[1:])

    def block_exists(self, block_root: bytes) -> bool:
        return self.hot.exists(BLOCK + block_root)

    def iter_hot_blocks(self):
        """(root, signed_block) over every hot block, ascending by slot —
        the raw material fork-choice rebuild and fsck walk after a crash
        ate the persisted snapshot.  Undecodable blocks are skipped."""
        found = []
        for key, _ in self.hot.iter_prefix(BLOCK):
            root = key[len(BLOCK):]
            try:
                blk = self.get_block(root)
            except Exception:
                continue
            if blk is not None:
                found.append((blk.message.slot, root, blk))
        found.sort(key=lambda t: t[0])
        for _slot, root, blk in found:
            yield root, blk

    def delete_block(self, block_root: bytes) -> None:
        self.hot.delete(BLOCK + block_root)

    # -- blobs ---------------------------------------------------------------

    def put_blobs(self, block_root: bytes, blobs: list) -> None:
        for _op, key, value in self._blobs_kv_ops(block_root, blobs):
            self.hot.put(key, value)

    def get_blobs(self, block_root: bytes) -> list | None:
        from ..ssz import List as SSZList
        raw = self.hot.get(BLOBS + block_root)
        if raw is None:
            return None
        t = SSZList(self.T.BlobSidecar.ssz_type,
                    self.T.preset.max_blob_commitments_per_block)
        return deserialize(t, raw)

    # -- hot states ----------------------------------------------------------

    def put_state(self, state_root: bytes, state: BeaconState) -> None:
        for _op, key, value in self._state_kv_ops(state_root, state):
            self.hot.put(key, value)
        _count("store_hot_db_ops_total")

    def hot_state_summary(self, state_root: bytes
                          ) -> tuple[int, bytes, bytes] | None:
        """(slot, latest_block_root, epoch_boundary_root) for a hot state,
        or None when no (well-formed) summary exists."""
        raw = self.hot.get(HOT_STATE_SUMMARY + state_root)
        if raw is None or len(raw) != 72:
            return None
        return struct.unpack("<Q", raw[:8])[0], raw[8:40], raw[40:72]

    @staticmethod
    def _latest_block_root(state: BeaconState) -> bytes:
        from ..state_transition.helpers import latest_block_header_root
        return latest_block_header_root(state)

    def get_hot_state(self, state_root: bytes) -> BeaconState | None:
        raw = self.hot.get(HOT_STATE_FULL + state_root)
        if raw is not None:
            fork = ForkName(raw[0])
            return BeaconState.from_ssz_bytes(raw[1:], self.T, self.spec,
                                              fork)
        summary = self.hot.get(HOT_STATE_SUMMARY + state_root)
        if summary is None:
            return None
        slot = struct.unpack("<Q", summary[:8])[0]
        latest_block_root = summary[8:40]
        boundary_root = summary[40:72]
        boundary_raw = self.hot.get(HOT_STATE_FULL + boundary_root)
        if boundary_raw is None:
            raise StoreError("missing epoch boundary state")
        state = BeaconState.from_ssz_bytes(
            boundary_raw[1:], self.T, self.spec, ForkName(boundary_raw[0]))
        # collect blocks (boundary, slot] by walking back from the summary's
        # latest block
        blocks = []
        root = latest_block_root
        while True:
            blk = self.get_block(root)
            if blk is None or blk.message.slot <= state.slot:
                break
            blocks.append(blk)
            root = blk.message.parent_root
        blocks.reverse()
        from ..state_transition import BlockReplayer
        return BlockReplayer(state).apply_blocks(blocks, target_slot=slot)

    def get_state(self, state_root: bytes,
                  slot: int | None = None) -> BeaconState | None:
        st = self.get_hot_state(state_root)
        if st is not None:
            return st
        if slot is not None:
            return self.load_cold_state_by_slot(slot)
        return None

    def delete_state(self, state_root: bytes) -> None:
        self.hot.delete(HOT_STATE_FULL + state_root)
        self.hot.delete(HOT_STATE_SUMMARY + state_root)

    def store_genesis(self, genesis_block_root: bytes,
                      genesis_state: BeaconState,
                      genesis_block=None) -> None:
        """Anchor the DB: genesis state goes to both hot and freezer (the
        slot-0 restore point every cold reconstruction bottoms out on).

        Commit order is the crash contract: freezer first, then ONE hot
        batch whose `anchor_slot` meta is the commit point — a crash
        between the two leaves a store with no anchor, which boots as
        fresh and simply re-runs genesis."""
        from ..utils.crashpoints import crashpoint
        root = genesis_state.hash_tree_root()
        slot = genesis_state.slot
        cold_ops = [("put", FREEZER_STATE + struct.pack(">Q", slot),
                     bytes([genesis_state.fork_name.value])
                     + genesis_state.serialize())]
        cold_ops.extend(self.block_roots.stage_puts(
            {slot: genesis_block_root}))
        self.cold.do_atomically(cold_ops)
        _count("store_cold_db_ops_total", len(cold_ops))
        crashpoint("genesis:mid_store")
        ops = [StoreOp.put_state(root, genesis_state),
               StoreOp.put_meta(b"genesis_block_root", genesis_block_root),
               StoreOp.put_meta(b"anchor_slot", struct.pack("<Q", slot))]
        if genesis_block is not None:
            ops.insert(0, StoreOp.put_block(genesis_block_root,
                                            genesis_block))
        self.do_atomically(ops)

    def anchor_state(self) -> BeaconState | None:
        """The state this DB was anchored on (FromStore resume boots here)."""
        raw = self._get_meta(b"anchor_slot")
        if raw is None:
            return None
        slot = struct.unpack("<Q", raw)[0]
        data = self.cold.get(FREEZER_STATE + struct.pack(">Q", slot))
        if data is None:
            return None
        return BeaconState.from_ssz_bytes(data[1:], self.T, self.spec,
                                          ForkName(data[0]))

    def genesis_block_root(self) -> bytes | None:
        return self._get_meta(b"genesis_block_root")

    # -- backfill anchor (checkpoint sync: oldest known block) ---------------

    def set_backfill_anchor(self, slot: int, parent_root: bytes) -> None:
        self._put_meta(b"backfill", struct.pack("<Q", slot) + parent_root)

    def backfill_anchor(self) -> tuple[int, bytes] | None:
        raw = self._get_meta(b"backfill")
        if raw is None:
            return None
        return struct.unpack("<Q", raw[:8])[0], raw[8:40]

    # -- freezer -------------------------------------------------------------

    def freezer_put_block_root(self, slot: int, block_root: bytes) -> None:
        self.block_roots.put(slot, block_root)
        _count("store_cold_db_ops_total")

    def freezer_block_root_at_slot(self, slot: int) -> bytes | None:
        return self.block_roots.get(slot)

    def freezer_put_state_root(self, slot: int, state_root: bytes) -> None:
        self.state_roots.put(slot, state_root)

    def freezer_state_root_at_slot(self, slot: int) -> bytes | None:
        return self.state_roots.get(slot)

    def freezer_put_state(self, slot: int, state: BeaconState) -> None:
        data = bytes([state.fork_name.value]) + state.serialize()
        self.cold.put(FREEZER_STATE + struct.pack(">Q", slot), data)
        _count("store_cold_db_ops_total")

    def load_cold_state_by_slot(self, slot: int) -> BeaconState | None:
        """Nearest restore point at/below `slot` + block replay, behind
        the bounded state cache (state_cache.rs role)."""
        cached = self.state_cache.get(("cold", slot))
        if cached is not None:
            _count("store_state_cache_hits_total")
            return cached.copy()
        _count("store_state_cache_misses_total")
        srp = self.config.slots_per_restore_point
        rp_slot = (slot // srp) * srp
        raw = None
        while rp_slot >= 0:
            raw = self.cold.get(FREEZER_STATE + struct.pack(">Q", rp_slot))
            if raw is not None:
                break
            if rp_slot == 0:
                break
            rp_slot -= srp
        if raw is None:
            return None
        state = BeaconState.from_ssz_bytes(raw[1:], self.T, self.spec,
                                           ForkName(raw[0]))
        if state.slot != slot:
            with tracing.span("cold_state_replay", target_slot=int(slot),
                              from_slot=int(state.slot)):
                blocks = []
                seen = None
                for s, root in self.block_roots.range(state.slot + 1,
                                                      slot + 1):
                    if root is None or root == seen:
                        continue  # skipped slot (same root repeated)
                    seen = root
                    blk = self.get_block(root)
                    if blk is not None and blk.message.slot > state.slot:
                        blocks.append(blk)
                from ..state_transition import BlockReplayer
                state = BlockReplayer(state).apply_blocks(blocks,
                                                          target_slot=slot)
        self.state_cache.put(("cold", slot), state)
        return state.copy()

    def prune_blobs(self, before_slot: int) -> int:
        """Drop blob sidecars for blocks older than `before_slot` (the
        data-availability window boundary; store/src/hot_cold_store.rs
        try_prune_blobs)."""
        removed = 0
        for key, _ in list(self.hot.iter_prefix(BLOBS)):
            root = key[len(BLOBS):]
            blk = self.get_block(root)
            if blk is None or blk.message.slot < before_slot:
                self.hot.delete(key)
                removed += 1
        return removed

    # -- migration (freezing) ------------------------------------------------

    def migrate_database(self, finalized_slot: int,
                         finalized_state_root: bytes,
                         finalized_block_root: bytes,
                         canonical_roots: dict[int, bytes],
                         abandoned_block_roots: list[bytes] = (),
                         abandoned_state_roots: list[bytes] = ()) -> None:
        """Advance the split: record canonical block roots in the freezer,
        store restore points, prune abandoned forks and hot states below the
        split (store/src/migrate.rs + hot_cold_store.rs migration)."""
        if finalized_slot <= self.split.slot:
            return
        with tracing.span("store_migration",
                          finalized_slot=int(finalized_slot)):
            self._migrate_database(finalized_slot, finalized_state_root,
                                   finalized_block_root, canonical_roots,
                                   abandoned_block_roots,
                                   abandoned_state_roots)

    def _migrate_database(self, finalized_slot: int,
                          finalized_state_root: bytes,
                          finalized_block_root: bytes,
                          canonical_roots: dict[int, bytes],
                          abandoned_block_roots: list[bytes] = (),
                          abandoned_state_roots: list[bytes] = ()) -> None:
        """Two commit points: (1) ONE cold batch lands every freezer write;
        (2) ONE hot batch lands prunes + the advanced split.  A crash
        between them leaves the old split in place, so the next migration
        replays the (idempotent) freezer writes from the old boundary."""
        from ..utils.crashpoints import crashpoint
        srp = self.config.slots_per_restore_point
        block_root_puts: dict[int, bytes] = {}
        state_root_puts: dict[int, bytes] = {}
        cold_ops: list = []
        for slot in range(self.split.slot, finalized_slot + 1):
            root = canonical_roots.get(slot)
            if root is None:
                continue
            block_root_puts[slot] = root
            blk = self.get_block(root)
            if blk is not None:
                state_root_puts[slot] = blk.message.state_root
            if slot % srp == 0:
                st = None
                if blk is not None:
                    st = self.get_hot_state(blk.message.state_root)
                if st is not None:
                    cold_ops.append(
                        ("put", FREEZER_STATE + struct.pack(">Q", slot),
                         bytes([st.fork_name.value]) + st.serialize()))
        cold_ops.extend(self.block_roots.stage_puts(block_root_puts))
        cold_ops.extend(self.state_roots.stage_puts(state_root_puts))
        self.cold.do_atomically(cold_ops, fsync=True)
        _count("store_batch_commit_total")
        _count("store_cold_db_ops_total", len(cold_ops))
        crashpoint("migrate:mid_freeze")
        # hot batch: prune abandoned forks + stale states, advance the split
        hot_ops = [StoreOp.delete_block(root)
                   for root in abandoned_block_roots]
        hot_ops += [StoreOp.delete_state(root)
                    for root in abandoned_state_roots]
        # drop hot states strictly below the new split (keep the finalized
        # one)
        for key, summary in list(self.hot.iter_prefix(HOT_STATE_SUMMARY)):
            slot = struct.unpack("<Q", summary[:8])[0]
            state_root = key[len(HOT_STATE_SUMMARY):]
            if slot < finalized_slot and state_root != finalized_state_root:
                hot_ops.append(StoreOp.delete_state(state_root))
        hot_ops.append(StoreOp.put_meta(
            b"split", struct.pack("<Q", finalized_slot)
            + finalized_state_root))
        crashpoint("migrate:before_split_write")
        self.do_atomically(hot_ops, fsync=True)
        self.split = Split(finalized_slot, finalized_state_root)

    # -- iteration -----------------------------------------------------------

    def iter_block_roots_back(self, head_root: bytes):
        """Walk (root, slot) back through parent links, crossing into the
        freezer's chunked vector below the split (iter.rs equivalent)."""
        root = head_root
        while True:
            blk = self.get_block(root)
            if blk is None:
                # below the split: continue from the chunked freezer roots
                yield from self._iter_freezer_back(self.split.slot)
                return
            yield root, blk.message.slot
            if blk.message.slot == 0:
                return
            if blk.message.slot <= self.split.slot:
                yield from self._iter_freezer_back(blk.message.slot - 1)
                return
            root = blk.message.parent_root

    def _iter_freezer_back(self, from_slot: int):
        seen = None
        for slot in range(from_slot, -1, -1):
            root = self.block_roots.get(slot)
            if root is None or root == seen:
                continue
            seen = root
            yield root, slot

    def forwards_block_roots_iterator(self, start_slot: int,
                                      end_slot: int,
                                      head_root: bytes | None = None):
        """(slot, root) ascending: freezer chunks below the split, then
        the hot chain walked from `head_root`
        (store/src/forwards_iter.rs)."""
        boundary = min(end_slot, self.split.slot)
        last = None
        for slot, root in self.block_roots.range(start_slot, boundary + 1):
            if root is not None:
                last = root
            if last is not None:
                yield slot, last
        if end_slot <= self.split.slot or head_root is None:
            return
        # hot side: walk parents back to the split, then emit ascending
        # with skipped slots carrying the prior root (spec block_roots
        # fill-forward semantics)
        chain = []                       # (slot, root), descending
        root = head_root
        while True:
            blk = self.get_block(root)
            if blk is None:
                break
            chain.append((blk.message.slot, root))
            if blk.message.slot <= self.split.slot + 1 or \
                    blk.message.slot == 0:
                break
            root = blk.message.parent_root
        chain.reverse()
        idx = 0
        current = None
        for want in range(max(start_slot, self.split.slot + 1),
                          end_slot + 1):
            while idx < len(chain) and chain[idx][0] <= want:
                current = chain[idx][1]
                idx += 1
            if current is not None:
                yield want, current
