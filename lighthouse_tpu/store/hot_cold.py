"""Hot/cold split database.

Equivalent of /root/reference/beacon_node/store/src/hot_cold_store.rs:50:
- hot DB: all unfinalized blocks; full states at epoch boundaries; per-slot
  `HotStateSummary`s pointing at their epoch-boundary state; states rebuilt
  by block replay (BlockReplayer, reconstruct.rs).
- freezer ("cold") DB: finalized block roots by slot + sparse restore-point
  states every `slots_per_restore_point`.
- `Split` marks the hot/cold boundary (hot_cold_store.rs:2715); `migrate`
  moves finalized data across it and prunes abandoned forks.
"""
from __future__ import annotations

import struct
import sys
from dataclasses import dataclass

from ..containers import get_types
from ..containers.state import BeaconState
from ..obs import tracing
from ..specs.chain_spec import ChainSpec, ForkName
from ..ssz import deserialize, htr, serialize
from .kv import KeyValueStore, StoreError

# column prefixes
BLOCK = b"b:"
HOT_STATE_FULL = b"S:"
HOT_STATE_SUMMARY = b"s:"
FREEZER_BLOCK_ROOT = b"fbr:"   # v1 layout: slot (be64) -> block root
FREEZER_BLOCK_CHUNK = b"cbr:"  # v2 layout: chunked root vector
FREEZER_STATE_CHUNK = b"csr:"  # v2: chunked state-root vector
FREEZER_STATE = b"fst:"        # slot (be64) -> full state
BLOBS = b"o:"
METADATA = b"m:"
ITEM = b"i:"                   # generic persisted items (fork choice, op pool)

SCHEMA_VERSION = 2             # v2: chunked freezer root vectors


def _count(name: str, amount: float = 1) -> None:
    """Catalog counter, sys.modules-gated so standalone store use stays
    metrics-free (same discipline as obs.tracing)."""
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if md is not None:
        md.count(name, amount)


@dataclass
class Split:
    slot: int = 0
    state_root: bytes = b"\x00" * 32


@dataclass
class StoreConfig:
    slots_per_restore_point: int = 2048
    compact_on_prune: bool = True
    state_cache_size: int = 8      # replayed/cold states kept hot in RAM


class _StateCache:
    """Bounded LRU of fully-materialized states (store/src/state_cache.rs
    role): cold-state loads replay O(slots_per_restore_point) blocks, so
    repeated historical reads must not re-pay that."""

    def __init__(self, capacity: int):
        from collections import OrderedDict
        self.capacity = capacity
        self._od = OrderedDict()

    def get(self, key):
        st = self._od.get(key)
        if st is not None:
            self._od.move_to_end(key)
        return st

    def put(self, key, state) -> None:
        self._od[key] = state
        self._od.move_to_end(key)
        while len(self._od) > self.capacity:
            self._od.popitem(last=False)

    def clear(self) -> None:
        self._od.clear()


class HotColdDB:
    def __init__(self, hot: KeyValueStore, cold: KeyValueStore,
                 spec: ChainSpec, config: StoreConfig | None = None):
        from .chunked_vector import ChunkedRootVector
        self.hot = hot
        self.cold = cold
        self.spec = spec
        self.T = get_types(spec.preset)
        self.config = config or StoreConfig()
        self.split = self._load_split()
        self.block_roots = ChunkedRootVector(cold, FREEZER_BLOCK_CHUNK)
        self.state_roots = ChunkedRootVector(cold, FREEZER_STATE_CHUNK)
        self.state_cache = _StateCache(self.config.state_cache_size)
        from .schema_change import migrate_schema
        migrate_schema(self)
        self._put_meta(b"schema", struct.pack("<I", SCHEMA_VERSION))

    # -- metadata ------------------------------------------------------------

    def _put_meta(self, key: bytes, value: bytes) -> None:
        self.hot.put(METADATA + key, value)

    def _get_meta(self, key: bytes) -> bytes | None:
        return self.hot.get(METADATA + key)

    def _load_split(self) -> Split:
        raw = self._get_meta(b"split")
        if raw is None:
            return Split()
        slot, root = struct.unpack("<Q", raw[:8])[0], raw[8:40]
        return Split(slot, root)

    def _store_split(self) -> None:
        self._put_meta(b"split",
                       struct.pack("<Q", self.split.slot)
                       + self.split.state_root)

    def schema_version(self) -> int:
        raw = self._get_meta(b"schema")
        return struct.unpack("<I", raw)[0] if raw else 0

    def put_item(self, key: bytes, value: bytes) -> None:
        self.hot.put(ITEM + key, value)

    def get_item(self, key: bytes) -> bytes | None:
        return self.hot.get(ITEM + key)

    # -- blocks --------------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block) -> None:
        fork = signed_block.fork_name
        data = bytes([fork.value]) + serialize(
            type(signed_block).ssz_type, signed_block)
        self.hot.put(BLOCK + block_root, data)
        _count("store_hot_db_ops_total")

    def get_block(self, block_root: bytes):
        raw = self.hot.get(BLOCK + block_root)
        if raw is None:
            return None
        fork = ForkName(raw[0])
        cls = self.T.SignedBeaconBlock[fork]
        return deserialize(cls.ssz_type, raw[1:])

    def block_exists(self, block_root: bytes) -> bool:
        return self.hot.exists(BLOCK + block_root)

    def delete_block(self, block_root: bytes) -> None:
        self.hot.delete(BLOCK + block_root)

    # -- blobs ---------------------------------------------------------------

    def put_blobs(self, block_root: bytes, blobs: list) -> None:
        from ..ssz import List as SSZList
        t = SSZList(self.T.BlobSidecar.ssz_type,
                    self.T.preset.max_blob_commitments_per_block)
        self.hot.put(BLOBS + block_root, serialize(t, blobs))

    def get_blobs(self, block_root: bytes) -> list | None:
        from ..ssz import List as SSZList
        raw = self.hot.get(BLOBS + block_root)
        if raw is None:
            return None
        t = SSZList(self.T.BlobSidecar.ssz_type,
                    self.T.preset.max_blob_commitments_per_block)
        return deserialize(t, raw)

    # -- hot states ----------------------------------------------------------

    def put_state(self, state_root: bytes, state: BeaconState) -> None:
        p = self.T.preset
        if state.slot % p.slots_per_epoch == 0:
            data = bytes([state.fork_name.value]) + state.serialize()
            self.hot.put(HOT_STATE_FULL + state_root, data)
        latest_block_root = self._latest_block_root(state)
        boundary_slot = (state.slot // p.slots_per_epoch) * p.slots_per_epoch
        boundary_root = (state_root if state.slot == boundary_slot
                         else state.state_roots[
                             boundary_slot % p.slots_per_historical_root
                         ].tobytes())
        summary = struct.pack("<Q", state.slot) + latest_block_root \
            + boundary_root
        self.hot.put(HOT_STATE_SUMMARY + state_root, summary)
        _count("store_hot_db_ops_total")

    @staticmethod
    def _latest_block_root(state: BeaconState) -> bytes:
        from ..state_transition.helpers import latest_block_header_root
        return latest_block_header_root(state)

    def get_hot_state(self, state_root: bytes) -> BeaconState | None:
        raw = self.hot.get(HOT_STATE_FULL + state_root)
        if raw is not None:
            fork = ForkName(raw[0])
            return BeaconState.from_ssz_bytes(raw[1:], self.T, self.spec,
                                              fork)
        summary = self.hot.get(HOT_STATE_SUMMARY + state_root)
        if summary is None:
            return None
        slot = struct.unpack("<Q", summary[:8])[0]
        latest_block_root = summary[8:40]
        boundary_root = summary[40:72]
        boundary_raw = self.hot.get(HOT_STATE_FULL + boundary_root)
        if boundary_raw is None:
            raise StoreError("missing epoch boundary state")
        state = BeaconState.from_ssz_bytes(
            boundary_raw[1:], self.T, self.spec, ForkName(boundary_raw[0]))
        # collect blocks (boundary, slot] by walking back from the summary's
        # latest block
        blocks = []
        root = latest_block_root
        while True:
            blk = self.get_block(root)
            if blk is None or blk.message.slot <= state.slot:
                break
            blocks.append(blk)
            root = blk.message.parent_root
        blocks.reverse()
        from ..state_transition import BlockReplayer
        return BlockReplayer(state).apply_blocks(blocks, target_slot=slot)

    def get_state(self, state_root: bytes,
                  slot: int | None = None) -> BeaconState | None:
        st = self.get_hot_state(state_root)
        if st is not None:
            return st
        if slot is not None:
            return self.load_cold_state_by_slot(slot)
        return None

    def delete_state(self, state_root: bytes) -> None:
        self.hot.delete(HOT_STATE_FULL + state_root)
        self.hot.delete(HOT_STATE_SUMMARY + state_root)

    def store_genesis(self, genesis_block_root: bytes,
                      genesis_state: BeaconState) -> None:
        """Anchor the DB: genesis state goes to both hot and freezer (the
        slot-0 restore point every cold reconstruction bottoms out on)."""
        root = genesis_state.hash_tree_root()
        self.put_state(root, genesis_state)
        self.freezer_put_state(genesis_state.slot, genesis_state)
        self.freezer_put_block_root(genesis_state.slot, genesis_block_root)
        self._put_meta(b"genesis_block_root", genesis_block_root)
        self._put_meta(b"anchor_slot",
                       struct.pack("<Q", genesis_state.slot))

    def anchor_state(self) -> BeaconState | None:
        """The state this DB was anchored on (FromStore resume boots here)."""
        raw = self._get_meta(b"anchor_slot")
        if raw is None:
            return None
        slot = struct.unpack("<Q", raw)[0]
        data = self.cold.get(FREEZER_STATE + struct.pack(">Q", slot))
        if data is None:
            return None
        return BeaconState.from_ssz_bytes(data[1:], self.T, self.spec,
                                          ForkName(data[0]))

    def genesis_block_root(self) -> bytes | None:
        return self._get_meta(b"genesis_block_root")

    # -- backfill anchor (checkpoint sync: oldest known block) ---------------

    def set_backfill_anchor(self, slot: int, parent_root: bytes) -> None:
        self._put_meta(b"backfill", struct.pack("<Q", slot) + parent_root)

    def backfill_anchor(self) -> tuple[int, bytes] | None:
        raw = self._get_meta(b"backfill")
        if raw is None:
            return None
        return struct.unpack("<Q", raw[:8])[0], raw[8:40]

    # -- freezer -------------------------------------------------------------

    def freezer_put_block_root(self, slot: int, block_root: bytes) -> None:
        self.block_roots.put(slot, block_root)
        _count("store_cold_db_ops_total")

    def freezer_block_root_at_slot(self, slot: int) -> bytes | None:
        return self.block_roots.get(slot)

    def freezer_put_state_root(self, slot: int, state_root: bytes) -> None:
        self.state_roots.put(slot, state_root)

    def freezer_state_root_at_slot(self, slot: int) -> bytes | None:
        return self.state_roots.get(slot)

    def freezer_put_state(self, slot: int, state: BeaconState) -> None:
        data = bytes([state.fork_name.value]) + state.serialize()
        self.cold.put(FREEZER_STATE + struct.pack(">Q", slot), data)
        _count("store_cold_db_ops_total")

    def load_cold_state_by_slot(self, slot: int) -> BeaconState | None:
        """Nearest restore point at/below `slot` + block replay, behind
        the bounded state cache (state_cache.rs role)."""
        cached = self.state_cache.get(("cold", slot))
        if cached is not None:
            _count("store_state_cache_hits_total")
            return cached.copy()
        _count("store_state_cache_misses_total")
        srp = self.config.slots_per_restore_point
        rp_slot = (slot // srp) * srp
        raw = None
        while rp_slot >= 0:
            raw = self.cold.get(FREEZER_STATE + struct.pack(">Q", rp_slot))
            if raw is not None:
                break
            if rp_slot == 0:
                break
            rp_slot -= srp
        if raw is None:
            return None
        state = BeaconState.from_ssz_bytes(raw[1:], self.T, self.spec,
                                           ForkName(raw[0]))
        if state.slot != slot:
            with tracing.span("cold_state_replay", target_slot=int(slot),
                              from_slot=int(state.slot)):
                blocks = []
                seen = None
                for s, root in self.block_roots.range(state.slot + 1,
                                                      slot + 1):
                    if root is None or root == seen:
                        continue  # skipped slot (same root repeated)
                    seen = root
                    blk = self.get_block(root)
                    if blk is not None and blk.message.slot > state.slot:
                        blocks.append(blk)
                from ..state_transition import BlockReplayer
                state = BlockReplayer(state).apply_blocks(blocks,
                                                          target_slot=slot)
        self.state_cache.put(("cold", slot), state)
        return state.copy()

    def prune_blobs(self, before_slot: int) -> int:
        """Drop blob sidecars for blocks older than `before_slot` (the
        data-availability window boundary; store/src/hot_cold_store.rs
        try_prune_blobs)."""
        removed = 0
        for key, _ in list(self.hot.iter_prefix(BLOBS)):
            root = key[len(BLOBS):]
            blk = self.get_block(root)
            if blk is None or blk.message.slot < before_slot:
                self.hot.delete(key)
                removed += 1
        return removed

    # -- migration (freezing) ------------------------------------------------

    def migrate_database(self, finalized_slot: int,
                         finalized_state_root: bytes,
                         finalized_block_root: bytes,
                         canonical_roots: dict[int, bytes],
                         abandoned_block_roots: list[bytes] = (),
                         abandoned_state_roots: list[bytes] = ()) -> None:
        """Advance the split: record canonical block roots in the freezer,
        store restore points, prune abandoned forks and hot states below the
        split (store/src/migrate.rs + hot_cold_store.rs migration)."""
        if finalized_slot <= self.split.slot:
            return
        with tracing.span("store_migration",
                          finalized_slot=int(finalized_slot)):
            self._migrate_database(finalized_slot, finalized_state_root,
                                   finalized_block_root, canonical_roots,
                                   abandoned_block_roots,
                                   abandoned_state_roots)

    def _migrate_database(self, finalized_slot: int,
                          finalized_state_root: bytes,
                          finalized_block_root: bytes,
                          canonical_roots: dict[int, bytes],
                          abandoned_block_roots: list[bytes] = (),
                          abandoned_state_roots: list[bytes] = ()) -> None:
        srp = self.config.slots_per_restore_point
        for slot in range(self.split.slot, finalized_slot + 1):
            root = canonical_roots.get(slot)
            if root is not None:
                self.freezer_put_block_root(slot, root)
        # restore points + freezer state-root vector
        for slot in range(self.split.slot, finalized_slot + 1):
            root = canonical_roots.get(slot)
            if root is None:
                continue
            blk = self.get_block(root)
            if blk is not None:
                self.freezer_put_state_root(slot, blk.message.state_root)
            if slot % srp == 0:
                st = None
                if blk is not None:
                    st = self.get_hot_state(blk.message.state_root)
                if st is not None:
                    self.freezer_put_state(slot, st)
        # prune abandoned forks
        for root in abandoned_block_roots:
            self.delete_block(root)
        for root in abandoned_state_roots:
            self.delete_state(root)
        # drop hot states strictly below the new split (keep the finalized one)
        for key, summary in list(self.hot.iter_prefix(HOT_STATE_SUMMARY)):
            slot = struct.unpack("<Q", summary[:8])[0]
            state_root = key[len(HOT_STATE_SUMMARY):]
            if slot < finalized_slot and state_root != finalized_state_root:
                self.delete_state(state_root)
        self.split = Split(finalized_slot, finalized_state_root)
        self._store_split()
        self.hot.sync()
        self.cold.sync()

    # -- iteration -----------------------------------------------------------

    def iter_block_roots_back(self, head_root: bytes):
        """Walk (root, slot) back through parent links, crossing into the
        freezer's chunked vector below the split (iter.rs equivalent)."""
        root = head_root
        while True:
            blk = self.get_block(root)
            if blk is None:
                # below the split: continue from the chunked freezer roots
                yield from self._iter_freezer_back(self.split.slot)
                return
            yield root, blk.message.slot
            if blk.message.slot == 0:
                return
            if blk.message.slot <= self.split.slot:
                yield from self._iter_freezer_back(blk.message.slot - 1)
                return
            root = blk.message.parent_root

    def _iter_freezer_back(self, from_slot: int):
        seen = None
        for slot in range(from_slot, -1, -1):
            root = self.block_roots.get(slot)
            if root is None or root == seen:
                continue
            seen = root
            yield root, slot

    def forwards_block_roots_iterator(self, start_slot: int,
                                      end_slot: int,
                                      head_root: bytes | None = None):
        """(slot, root) ascending: freezer chunks below the split, then
        the hot chain walked from `head_root`
        (store/src/forwards_iter.rs)."""
        boundary = min(end_slot, self.split.slot)
        last = None
        for slot, root in self.block_roots.range(start_slot, boundary + 1):
            if root is not None:
                last = root
            if last is not None:
                yield slot, last
        if end_slot <= self.split.slot or head_root is None:
            return
        # hot side: walk parents back to the split, then emit ascending
        # with skipped slots carrying the prior root (spec block_roots
        # fill-forward semantics)
        chain = []                       # (slot, root), descending
        root = head_root
        while True:
            blk = self.get_block(root)
            if blk is None:
                break
            chain.append((blk.message.slot, root))
            if blk.message.slot <= self.split.slot + 1 or \
                    blk.message.slot == 0:
                break
            root = blk.message.parent_root
        chain.reverse()
        idx = 0
        current = None
        for want in range(max(start_slot, self.split.slot + 1),
                          end_slot + 1):
            while idx < len(chain) and chain[idx][0] <= want:
                current = chain[idx][1]
                idx += 1
            if current is not None:
                yield want, current
