"""Store consistency checker (fsck).

The durability counterpart of graftwatch's runtime SLOs: after a crash,
a restore, or a suspicious restart, ``run_fsck`` walks the hot/cold
split database and reports every structural invariant violation it can
find without replaying states:

- split/anchor agreement: the anchor restore point exists, the split
  meta parses, and the split state is still materialized in hot;
- block connectivity: every hot block's parent is either another hot
  block, recorded in the freezer root vector, or an explicit anchor
  (genesis / checkpoint-sync backfill boundary);
- state reachability: every hot state summary points at an epoch
  boundary whose full state exists (the replay path would otherwise
  raise mid-read), and no full state is orphaned without its summary;
- persisted-chain items: the fork-choice snapshot parses, its nodes'
  blocks exist, and the head item's sequence number matches the
  snapshot's (a mismatch is the signature of a crash between the two
  commit points — `resume_chain` repairs it, after which fsck is clean).

Errors are real corruption or torn commits; warnings are conditions a
node tolerates (e.g. blobs for an unknown block).  Runnable at open
(``LHTPU_FSCK_ON_OPEN=1``) and offline via ``tools/store/fsck.py``.
"""
from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field

from .hot_cold import (
    BLOBS, BLOCK, FREEZER_STATE, HOT_STATE_FULL, HOT_STATE_SUMMARY,
    HotColdDB,
)

_FC_KEY = b"fork_choice"
_HEAD_KEY = b"head"
_OP_POOL_KEY = b"op_pool"


@dataclass
class FsckReport:
    errors: list[str] = field(default_factory=list)
    warnings: list[str] = field(default_factory=list)
    checked: dict[str, int] = field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.errors

    def to_dict(self) -> dict:
        return {"clean": self.clean, "errors": list(self.errors),
                "warnings": list(self.warnings),
                "checked": dict(self.checked)}

    def render(self) -> str:
        lines = [f"store fsck: {'clean' if self.clean else 'CORRUPT'} "
                 + " ".join(f"{k}={v}" for k, v in sorted(
                     self.checked.items()))]
        lines += [f"  error: {e}" for e in self.errors]
        lines += [f"  warn:  {w}" for w in self.warnings]
        return "\n".join(lines)


def _count_metric(n: int) -> None:
    import sys
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if md is not None and n:
        md.count("store_fsck_errors_total", n)


def run_fsck(db: HotColdDB) -> FsckReport:
    r = FsckReport()
    _check_anchor_and_split(db, r)
    blocks = _check_blocks(db, r)
    _check_states(db, r)
    _check_blobs(db, r, blocks)
    _check_persisted_items(db, r, blocks)
    _count_metric(len(r.errors))
    return r


def _check_anchor_and_split(db: HotColdDB, r: FsckReport) -> None:
    anchor_raw = db._get_meta(b"anchor_slot")
    if anchor_raw is None:
        r.errors.append("no anchor_slot meta (store was never anchored)")
        return
    if len(anchor_raw) != 8:
        r.errors.append("anchor_slot meta has wrong length")
        return
    (anchor_slot,) = struct.unpack("<Q", anchor_raw)
    if db.cold.get(FREEZER_STATE + struct.pack(">Q", anchor_slot)) is None:
        r.errors.append(
            f"anchor restore point missing in freezer (slot {anchor_slot})")
    split_raw = db._get_meta(b"split")
    if split_raw is not None:
        if len(split_raw) < 40:
            r.errors.append("split meta has wrong length")
        else:
            (split_slot,) = struct.unpack("<Q", split_raw[:8])
            split_root = split_raw[8:40]
            if split_slot > 0 and \
                    db.hot.get(HOT_STATE_FULL + split_root) is None:
                r.errors.append(
                    f"split state {split_root.hex()[:12]} (slot "
                    f"{split_slot}) not materialized in hot DB")
    r.checked["anchors"] = 1


def _check_blocks(db: HotColdDB, r: FsckReport) -> dict[bytes, tuple]:
    """Returns root -> (slot, parent_root) for every hot block."""
    blocks: dict[bytes, tuple] = {}
    genesis_root = db.genesis_block_root()
    backfill = db.backfill_anchor()
    for key, _ in db.hot.iter_prefix(BLOCK):
        root = key[len(BLOCK):]
        try:
            blk = db.get_block(root)
        except Exception as exc:
            r.errors.append(f"block {root.hex()[:12]} undecodable: {exc!r}")
            continue
        blocks[root] = (blk.message.slot, blk.message.parent_root)
    for root, (slot, parent) in blocks.items():
        if slot == 0 or root == genesis_root:
            continue
        if parent in blocks:
            continue
        if backfill is not None and slot <= backfill[0]:
            continue  # history below the checkpoint-sync anchor
        # canonical history: the parent may live only as a freezer root
        if slot - 1 <= db.split.slot and \
                db.freezer_block_root_at_slot(slot - 1) == parent:
            continue
        r.errors.append(
            f"block {root.hex()[:12]} (slot {slot}) missing parent "
            f"{parent.hex()[:12]}")
    r.checked["blocks"] = len(blocks)
    return blocks


def _check_states(db: HotColdDB, r: FsckReport) -> None:
    summaries: dict[bytes, tuple] = {}
    fulls: set[bytes] = set()
    for key, _ in db.hot.iter_prefix(HOT_STATE_FULL):
        fulls.add(key[len(HOT_STATE_FULL):])
    for key, raw in db.hot.iter_prefix(HOT_STATE_SUMMARY):
        root = key[len(HOT_STATE_SUMMARY):]
        if len(raw) != 72:
            r.errors.append(f"state summary {root.hex()[:12]} malformed")
            continue
        slot = struct.unpack("<Q", raw[:8])[0]
        summaries[root] = (slot, raw[8:40], raw[40:72])
    for root, (slot, _latest, boundary) in summaries.items():
        if boundary not in fulls:
            r.errors.append(
                f"state {root.hex()[:12]} (slot {slot}) points at epoch "
                f"boundary {boundary.hex()[:12]} with no full state "
                f"(replay from it would fail)")
    for root in fulls:
        if root not in summaries:
            r.errors.append(
                f"orphan full state {root.hex()[:12]} has no summary")
    r.checked["state_summaries"] = len(summaries)
    r.checked["full_states"] = len(fulls)


def _check_blobs(db: HotColdDB, r: FsckReport,
                 blocks: dict[bytes, tuple]) -> None:
    n = 0
    for key, _ in db.hot.iter_prefix(BLOBS):
        n += 1
        root = key[len(BLOBS):]
        if root not in blocks:
            r.warnings.append(
                f"blobs for unknown block {root.hex()[:12]}")
    r.checked["blob_entries"] = n


def _check_persisted_items(db: HotColdDB, r: FsckReport,
                           blocks: dict[bytes, tuple]) -> None:
    raw_fc = db.get_item(_FC_KEY)
    raw_head = db.get_item(_HEAD_KEY)
    raw_pool = db.get_item(_OP_POOL_KEY)
    fc_seq = None
    if raw_fc is not None:
        try:
            doc = json.loads(raw_fc)
            fc_seq = doc.get("seq")
            for nd in doc["nodes"]:
                root = bytes.fromhex(nd["root"])
                slot = nd["slot"]
                if root not in blocks and slot > db.split.slot and slot > 0:
                    r.errors.append(
                        f"fork-choice node {root.hex()[:12]} (slot "
                        f"{slot}) has no stored block")
        except Exception as exc:
            r.errors.append(f"fork-choice snapshot unreadable: {exc!r}")
    if raw_head is None and fc_seq is not None:
        r.errors.append(
            f"torn persist: fork-choice snapshot at seq {fc_seq} but no "
            f"head item (crash between commit points; resume repairs "
            f"this)")
    if raw_head is not None:
        if len(raw_head) == 32:
            head_seq, head_root = None, raw_head          # legacy layout
        elif len(raw_head) == 40:
            head_seq = struct.unpack("<Q", raw_head[:8])[0]
            head_root = raw_head[8:]
        else:
            r.errors.append("head item has wrong length")
            head_seq = head_root = None
        if head_root is not None and head_root not in blocks:
            r.errors.append(
                f"persisted head {head_root.hex()[:12]} has no stored "
                f"block")
        if head_seq is not None and fc_seq is not None and \
                head_seq != fc_seq:
            r.errors.append(
                f"torn persist: head seq {head_seq} != fork-choice seq "
                f"{fc_seq} (crash between commit points; resume repairs "
                f"this)")
    if raw_pool is not None:
        try:
            json.loads(raw_pool)
        except Exception as exc:
            r.errors.append(f"op-pool snapshot unreadable: {exc!r}")
    r.checked["persisted_items"] = sum(
        x is not None for x in (raw_fc, raw_head, raw_pool))
