"""Storage layer (L5): hot/cold split database.

Equivalent of /root/reference/beacon_node/store: `KeyValueStore` trait
(src/lib.rs:53), `HotColdDB` (src/hot_cold_store.rs:50), `MemoryStore`,
LevelDB backend (here: the C++ kvstore in native/, via ctypes), state
reconstruction by block replay (src/reconstruct.rs).
"""
from .kv import KeyValueStore, MemoryStore, NativeKvStore, StoreError
from .hot_cold import HotColdDB, Split, StoreConfig, StoreOp
from .fsck import FsckReport, run_fsck
