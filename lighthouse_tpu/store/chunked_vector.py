"""Chunked root vectors for the freezer.

Equivalent of /root/reference/beacon_node/store/src/chunked_vector.rs:
instead of one KV entry per slot, 32-byte roots are packed into
fixed-size chunks (CHUNK_SIZE roots per entry).  Range reads touch
O(range / CHUNK_SIZE) entries instead of O(range), and the freezer holds
~128x fewer keys — the property lighthouse's forwards iterators and
historical reconstruction depend on.

Layout: key = prefix + chunk_index (be64); value = concatenated 32-byte
roots (possibly short in the tail chunk).  Gaps are zero-filled: a slot
whose root was never recorded reads as None (all-zero sentinel), which
matches the reference's default-chunk behavior for pre-anchor slots.
"""
from __future__ import annotations

import struct

CHUNK_SIZE = 128
ROOT_LEN = 32
_ZERO = b"\x00" * ROOT_LEN


class ChunkedRootVector:
    def __init__(self, kv, prefix: bytes):
        self.kv = kv
        self.prefix = prefix

    def _key(self, chunk_index: int) -> bytes:
        return self.prefix + struct.pack(">Q", chunk_index)

    def put(self, slot: int, root: bytes) -> None:
        if len(root) != ROOT_LEN:
            raise ValueError("root must be 32 bytes")
        ci, off = divmod(slot, CHUNK_SIZE)
        chunk = bytearray(self.kv.get(self._key(ci)) or b"")
        need = (off + 1) * ROOT_LEN
        if len(chunk) < need:
            chunk += b"\x00" * (need - len(chunk))
        chunk[off * ROOT_LEN:(off + 1) * ROOT_LEN] = root
        self.kv.put(self._key(ci), bytes(chunk))

    def stage_puts(self, puts: dict[int, bytes]) -> list[tuple]:
        """Fold many slot->root writes into per-chunk KV put ops (one op
        per touched chunk) WITHOUT writing — the caller commits them in an
        atomic `do_atomically` batch alongside its other freezer writes.
        The read-modify-write of each chunk happens here, against the
        currently-visible chunk contents."""
        by_chunk: dict[int, dict[int, bytes]] = {}
        for slot, root in puts.items():
            if len(root) != ROOT_LEN:
                raise ValueError("root must be 32 bytes")
            ci, off = divmod(slot, CHUNK_SIZE)
            by_chunk.setdefault(ci, {})[off] = root
        ops: list[tuple] = []
        for ci in sorted(by_chunk):
            chunk = bytearray(self.kv.get(self._key(ci)) or b"")
            for off, root in sorted(by_chunk[ci].items()):
                need = (off + 1) * ROOT_LEN
                if len(chunk) < need:
                    chunk += b"\x00" * (need - len(chunk))
                chunk[off * ROOT_LEN:(off + 1) * ROOT_LEN] = root
            ops.append(("put", self._key(ci), bytes(chunk)))
        return ops

    def get(self, slot: int) -> bytes | None:
        ci, off = divmod(slot, CHUNK_SIZE)
        chunk = self.kv.get(self._key(ci))
        if chunk is None or len(chunk) < (off + 1) * ROOT_LEN:
            return None
        root = bytes(chunk[off * ROOT_LEN:(off + 1) * ROOT_LEN])
        return None if root == _ZERO else root

    def range(self, start_slot: int, end_slot: int):
        """Yield (slot, root|None) for start <= slot < end, reading each
        chunk once."""
        if end_slot <= start_slot:
            return
        ci_start = start_slot // CHUNK_SIZE
        ci_end = (end_slot - 1) // CHUNK_SIZE
        for ci in range(ci_start, ci_end + 1):
            chunk = self.kv.get(self._key(ci)) or b""
            base = ci * CHUNK_SIZE
            lo = max(start_slot, base)
            hi = min(end_slot, base + CHUNK_SIZE)
            for slot in range(lo, hi):
                off = (slot - base) * ROOT_LEN
                root = bytes(chunk[off:off + ROOT_LEN]) \
                    if len(chunk) >= off + ROOT_LEN else _ZERO
                yield slot, (None if root == _ZERO else root)

    def prune_before(self, slot: int) -> int:
        """Drop whole chunks strictly below slot; returns chunks removed
        (partial head chunks are kept — cheap and simple, like the
        reference's per-chunk granularity)."""
        removed = 0
        ci = slot // CHUNK_SIZE
        # walk down until a missing chunk (dense from anchor upward)
        j = ci - 1
        while j >= 0:
            key = self._key(j)
            if self.kv.get(key) is None:
                break
            self.kv.delete(key)
            removed += 1
            j -= 1
        return removed
