"""SSZ: SimpleSerialize codec + merkleization.

Equivalent of the external `ethereum_ssz` + `tree_hash` crates used by the
reference (/root/reference/Cargo.toml:121-181 and consensus/types). Types are
first-class *objects* (not Python classes): ``uint64``, ``Vector(uint8, 32)``,
``List(Validator, 2**40)`` — a deliberately functional design so the
array-oriented BeaconState backend (lighthouse_tpu.ctypes_.beacon_state) can
map SSZ schemas onto device arrays.
"""
from .types import (
    SSZType, Boolean, UInt, ByteVector, ByteList, Bitvector, Bitlist,
    Vector, List, Container, Union, container, field_types,
    boolean, uint8, uint16, uint32, uint64, uint128, uint256,
    Bytes4, Bytes8, Bytes20, Bytes32, Bytes48, Bytes96, Root,
    default_value,
)
from .codec import serialize, deserialize, is_fixed_size, fixed_size
from .merkle import (
    hash_tree_root, htr, merkleize_chunks, mix_in_length, mix_in_selector,
    pack_bytes, next_pow_of_two, chunk_count,
)
