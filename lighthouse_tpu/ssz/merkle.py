"""SSZ merkleization: hash_tree_root (tree_hash crate equivalent).

The host path uses hashlib; the bulk path for large arrays lives in
lighthouse_tpu.ops.sha256 (vmapped TPU hash-tree kernel) and is selected by
the array-backed BeaconState (see consensus/types/src/beacon_state.rs:2031
`update_tree_hash_cache` in the reference for the cached-tree-hash design).
"""
from __future__ import annotations

from typing import Any

from ..utils.hash import ZERO_HASHES, hash_concat, sha256
from .codec import serialize
from .types import (
    SSZType, Boolean, UInt, ByteVector, ByteList, Bitvector, Bitlist,
    Vector, List, Container, Union, UnionValue,
)

BYTES_PER_CHUNK = 32


def next_pow_of_two(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def pack_bytes(data: bytes) -> list[bytes]:
    """Right-pad to a multiple of 32 and split into chunks."""
    if not data:
        return []
    pad = (-len(data)) % BYTES_PER_CHUNK
    data = data + b"\x00" * pad
    return [data[i:i + 32] for i in range(0, len(data), 32)]


#: chunk count above which the C++ batch hasher takes over from hashlib
_NATIVE_THRESHOLD = 32


def merkleize_chunks(chunks: list[bytes], limit: int | None = None) -> bytes:
    """Merkleize chunks into a single root, padding with zero subtrees.

    ``limit`` is the maximum chunk count (defines tree depth for Lists).
    Large trees route through the C++ batch hasher (one FFI call per tree);
    small ones stay on hashlib.
    """
    count = len(chunks)
    if limit is None:
        limit = next_pow_of_two(count)
    if count > limit:
        raise ValueError("chunk count exceeds limit")
    depth = max(0, (limit - 1).bit_length())
    if count == 0:
        return ZERO_HASHES[depth]
    if count >= _NATIVE_THRESHOLD:
        from ..utils.native_hash import get_lib, merkle_root_pow2
        if get_lib() is not None:
            dense = next_pow_of_two(count)
            data = b"".join(chunks) + b"\x00" * 32 * (dense - count)
            root = merkle_root_pow2(data)
            for d in range(dense.bit_length() - 1, depth):
                root = hash_concat(root, ZERO_HASHES[d])
            return root
    nodes = list(chunks)
    for d in range(depth):
        if len(nodes) % 2:
            nodes.append(ZERO_HASHES[d])
        nodes = [hash_concat(nodes[i], nodes[i + 1])
                 for i in range(0, len(nodes), 2)]
    return nodes[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_concat(root, length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return hash_concat(root, selector.to_bytes(32, "little"))


def chunk_count(typ: SSZType) -> int:
    if isinstance(typ, (Boolean, UInt)):
        return 1
    if isinstance(typ, ByteVector):
        return (typ.length + 31) // 32
    if isinstance(typ, ByteList):
        return (typ.limit + 31) // 32
    if isinstance(typ, Bitvector):
        return (typ.length + 255) // 256
    if isinstance(typ, Bitlist):
        return (typ.limit + 255) // 256
    if isinstance(typ, Vector):
        if isinstance(typ.elem, (Boolean, UInt)):
            from .codec import fixed_size
            return (typ.length * fixed_size(typ.elem) + 31) // 32
        return typ.length
    if isinstance(typ, List):
        if isinstance(typ.elem, (Boolean, UInt)):
            from .codec import fixed_size
            return (typ.limit * fixed_size(typ.elem) + 31) // 32
        return typ.limit
    if isinstance(typ, Container):
        return len(typ.fields)
    raise TypeError(f"no chunk count for {typ!r}")


def _bits_to_chunk_bytes(bits) -> bytes:
    out = bytearray((len(bits) + 7) // 8)
    for i, b in enumerate(bits):
        if b:
            out[i // 8] |= 1 << (i % 8)
    return bytes(out)


def hash_tree_root(typ: SSZType, value: Any) -> bytes:
    if isinstance(typ, (Boolean, UInt)):
        return serialize(typ, value).ljust(32, b"\x00")
    if isinstance(typ, ByteVector):
        return merkleize_chunks(pack_bytes(bytes(value)), chunk_count(typ))
    if isinstance(typ, ByteList):
        root = merkleize_chunks(pack_bytes(bytes(value)), chunk_count(typ))
        return mix_in_length(root, len(value))
    if isinstance(typ, Bitvector):
        return merkleize_chunks(
            pack_bytes(_bits_to_chunk_bytes(value)), chunk_count(typ))
    if isinstance(typ, Bitlist):
        root = merkleize_chunks(
            pack_bytes(_bits_to_chunk_bytes(value)), chunk_count(typ))
        return mix_in_length(root, len(value))
    if isinstance(typ, Vector):
        if isinstance(typ.elem, (Boolean, UInt)):
            data = b"".join(serialize(typ.elem, v) for v in value)
            return merkleize_chunks(pack_bytes(data), chunk_count(typ))
        roots = [hash_tree_root(typ.elem, v) for v in value]
        return merkleize_chunks(roots, typ.length)
    if isinstance(typ, List):
        if isinstance(typ.elem, (Boolean, UInt)):
            data = b"".join(serialize(typ.elem, v) for v in value)
            root = merkleize_chunks(pack_bytes(data), chunk_count(typ))
        else:
            roots = [hash_tree_root(typ.elem, v) for v in value]
            root = merkleize_chunks(roots, typ.limit)
        return mix_in_length(root, len(value))
    if isinstance(typ, Container):
        # Array-backed containers (e.g. the SoA BeaconState) can provide
        # their own accelerated root.
        custom = getattr(value, "__custom_hash_tree_root__", None)
        if custom is not None:
            return custom()
        roots = [hash_tree_root(t, getattr(value, n)) for n, t in typ.fields]
        return merkleize_chunks(roots, next_pow_of_two(len(roots)))
    if isinstance(typ, Union):
        assert isinstance(value, UnionValue)
        opt = typ.options[value.selector]
        root = b"\x00" * 32 if opt is None else hash_tree_root(opt, value.value)
        return mix_in_selector(root, value.selector)
    raise TypeError(f"cannot hash {typ!r}")


def htr(value: Any) -> bytes:
    """hash_tree_root of a @container dataclass instance."""
    return hash_tree_root(type(value).ssz_type, value)
