"""Generalized-index merkle proofs + incremental deposit tree.

Equivalent of /root/reference/consensus/merkle_proof/src/lib.rs: a sparse
`MerkleTree` supporting push_leaf/generate_proof, and `verify_merkle_proof`
for fixed-depth branches (deposit contract tree, state proofs, light client).
"""
from __future__ import annotations

from ..utils.hash import ZERO_HASHES, hash_concat

MAX_TREE_DEPTH = 32


class MerkleTreeError(Exception):
    pass


class MerkleTree:
    """Right-zero-padded sparse binary merkle tree with incremental append."""

    __slots__ = ("depth", "_leaves", "_hash_cache")

    def __init__(self, depth: int, leaves: list[bytes] | None = None):
        if depth > MAX_TREE_DEPTH:
            raise MerkleTreeError("depth too large")
        self.depth = depth
        self._leaves: list[bytes] = list(leaves or [])
        if len(self._leaves) > (1 << depth):
            raise MerkleTreeError("too many leaves")
        self._hash_cache: bytes | None = None

    def push_leaf(self, leaf: bytes) -> None:
        if len(self._leaves) >= (1 << self.depth):
            raise MerkleTreeError("tree is full")
        self._leaves.append(leaf)
        self._hash_cache = None

    def __len__(self) -> int:
        return len(self._leaves)

    def hash(self) -> bytes:
        if self._hash_cache is None:
            nodes = list(self._leaves)
            for d in range(self.depth):
                if len(nodes) % 2:
                    nodes.append(ZERO_HASHES[d])
                nodes = [hash_concat(nodes[i], nodes[i + 1])
                         for i in range(0, len(nodes), 2)]
            self._hash_cache = nodes[0] if nodes else ZERO_HASHES[self.depth]
        return self._hash_cache

    def generate_proof(self, index: int) -> list[bytes]:
        """Sibling path (bottom-up) for leaf `index`."""
        if index >= (1 << self.depth):
            raise MerkleTreeError("index out of range")
        proof = []
        nodes = list(self._leaves)
        idx = index
        for d in range(self.depth):
            if len(nodes) % 2:
                nodes.append(ZERO_HASHES[d])
            sib = idx ^ 1
            proof.append(nodes[sib] if sib < len(nodes) else ZERO_HASHES[d])
            nodes = [hash_concat(nodes[i], nodes[i + 1])
                     for i in range(0, len(nodes), 2)]
            idx //= 2
        return proof


def merkle_root_from_branch(leaf: bytes, branch: list[bytes],
                            index: int) -> bytes:
    """Fold a bottom-up sibling branch into a root."""
    node = leaf
    for i, sib in enumerate(branch):
        if (index >> i) & 1:
            node = hash_concat(sib, node)
        else:
            node = hash_concat(node, sib)
    return node


def verify_merkle_proof(leaf: bytes, branch: list[bytes], depth: int,
                        index: int, root: bytes) -> bool:
    if len(branch) != depth:
        return False
    return merkle_root_from_branch(leaf, branch, index) == root


# -- generalized indices (spec ssz/merkle-proofs.md) -------------------------

def generalized_index_depth(gindex: int) -> int:
    return gindex.bit_length() - 1


def verify_merkle_proof_gindex(leaf: bytes, branch: list[bytes],
                               gindex: int, root: bytes) -> bool:
    depth = generalized_index_depth(gindex)
    index = gindex - (1 << depth)
    return verify_merkle_proof(leaf, branch, depth, index, root)
