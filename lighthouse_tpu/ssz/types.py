"""SSZ type descriptors.

Each SSZ type is an instance of an SSZType subclass. Containers are Python
dataclasses declared with the ``@container`` decorator whose field annotations
*are* SSZType instances:

    @container
    class Checkpoint:
        epoch: uint64
        root: Root

Values are plain Python: int, bool, bytes, list, dataclass instances.
"""
from __future__ import annotations

import dataclasses
from typing import Any


class SSZType:
    """Base descriptor; concrete logic lives in codec.py / merkle.py."""

    def __repr__(self) -> str:  # pragma: no cover
        return self.__class__.__name__


class Boolean(SSZType):
    pass


class UInt(SSZType):
    def __init__(self, byte_len: int):
        assert byte_len in (1, 2, 4, 8, 16, 32)
        self.byte_len = byte_len

    def __repr__(self) -> str:
        return f"uint{self.byte_len * 8}"


class ByteVector(SSZType):
    def __init__(self, length: int):
        assert length > 0
        self.length = length

    def __repr__(self) -> str:
        return f"ByteVector[{self.length}]"


class ByteList(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self) -> str:
        return f"ByteList[{self.limit}]"


class Bitvector(SSZType):
    def __init__(self, length: int):
        assert length > 0
        self.length = length

    def __repr__(self) -> str:
        return f"Bitvector[{self.length}]"


class Bitlist(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self) -> str:
        return f"Bitlist[{self.limit}]"


class Vector(SSZType):
    def __init__(self, elem: SSZType, length: int):
        assert length > 0
        self.elem = elem
        self.length = length

    def __repr__(self) -> str:
        return f"Vector[{self.elem!r}, {self.length}]"


class List(SSZType):
    def __init__(self, elem: SSZType, limit: int):
        self.elem = elem
        self.limit = limit

    def __repr__(self) -> str:
        return f"List[{self.elem!r}, {self.limit}]"


class Container(SSZType):
    """Descriptor wrapping a @container dataclass."""

    def __init__(self, cls: type):
        self.cls = cls
        self.fields: list[tuple[str, SSZType]] = list(cls.__ssz_fields__.items())

    def __repr__(self) -> str:
        return self.cls.__name__


class Union(SSZType):
    """SSZ Union[None | T1 | T2 ...]; options[i] may be None (only at index 0)."""

    def __init__(self, options: list[SSZType | None]):
        assert 1 <= len(options) <= 128
        assert all(o is None for o in options[:1] if o is None)
        self.options = options


@dataclasses.dataclass
class UnionValue:
    selector: int
    value: Any


# ---------------------------------------------------------------------------
# Canonical basic-type singletons
# ---------------------------------------------------------------------------

boolean = Boolean()
uint8 = UInt(1)
uint16 = UInt(2)
uint32 = UInt(4)
uint64 = UInt(8)
uint128 = UInt(16)
uint256 = UInt(32)

Bytes4 = ByteVector(4)
Bytes8 = ByteVector(8)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)
Root = Bytes32


def default_value(typ: SSZType) -> Any:
    """The SSZ default (zeroed) value for a type."""
    if isinstance(typ, Boolean):
        return False
    if isinstance(typ, UInt):
        return 0
    if isinstance(typ, ByteVector):
        return b"\x00" * typ.length
    if isinstance(typ, ByteList):
        return b""
    if isinstance(typ, Bitvector):
        return [False] * typ.length
    if isinstance(typ, (Bitlist, List)):
        return []
    if isinstance(typ, Vector):
        return [default_value(typ.elem) for _ in range(typ.length)]
    if isinstance(typ, Container):
        return typ.cls()
    if isinstance(typ, Union):
        first = typ.options[0]
        return UnionValue(0, None if first is None else default_value(first))
    raise TypeError(f"no default for {typ!r}")


def container(cls: type) -> type:
    """Decorator: turn an annotated class into an SSZ container dataclass.

    Adds: ``__ssz_fields__`` (name -> SSZType), ``ssz_type`` (Container
    descriptor), per-field zeroed defaults, and a ``copy()`` deep-copy helper.
    """
    ssz_fields: dict[str, SSZType] = {}
    for name, ann in cls.__dict__.get("__annotations__", {}).items():
        if isinstance(ann, SSZType):
            ssz_fields[name] = ann
    cls.__ssz_fields__ = ssz_fields

    # dataclass defaults: zeroed SSZ values (mutable ones via factories)
    for name, typ in ssz_fields.items():
        if not hasattr(cls, name):
            if isinstance(typ, (Boolean, UInt, ByteVector, ByteList)):
                setattr(cls, name, dataclasses.field(
                    default=default_value(typ)))
            else:
                setattr(cls, name, dataclasses.field(
                    default_factory=lambda t=typ: default_value(t)))
    dc = dataclasses.dataclass(cls)
    dc.ssz_type = Container(dc)

    def copy(self):
        out = {}
        for name, typ in ssz_fields.items():
            out[name] = _copy_value(typ, getattr(self, name))
        return dc(**out)

    dc.copy = copy
    return dc


def _copy_value(typ: SSZType, v: Any) -> Any:
    if isinstance(typ, (Boolean, UInt, ByteVector, ByteList)):
        return v
    if isinstance(typ, (Bitvector, Bitlist)):
        return list(v)
    if isinstance(typ, (Vector, List)):
        return [_copy_value(typ.elem, e) for e in v]
    if isinstance(typ, Container):
        return v.copy()
    if isinstance(typ, Union):
        opt = typ.options[v.selector]
        return UnionValue(v.selector,
                          None if opt is None else _copy_value(opt, v.value))
    raise TypeError(f"cannot copy {typ!r}")


def field_types(value: Any) -> list[tuple[str, SSZType]]:
    return list(type(value).__ssz_fields__.items())
