"""SSZ serialize/deserialize (ethereum_ssz equivalent)."""
from __future__ import annotations

from typing import Any

from .types import (
    SSZType, Boolean, UInt, ByteVector, ByteList, Bitvector, Bitlist,
    Vector, List, Container, Union, UnionValue,
)

BYTES_PER_LENGTH_OFFSET = 4


class DeserializeError(ValueError):
    pass


def is_fixed_size(typ: SSZType) -> bool:
    if isinstance(typ, (Boolean, UInt, ByteVector, Bitvector)):
        return True
    if isinstance(typ, (ByteList, Bitlist, List, Union)):
        return False
    if isinstance(typ, Vector):
        return is_fixed_size(typ.elem)
    if isinstance(typ, Container):
        return all(is_fixed_size(t) for _, t in typ.fields)
    raise TypeError(f"unknown type {typ!r}")


def fixed_size(typ: SSZType) -> int:
    """Serialized size of a fixed-size type (offset slot size otherwise)."""
    if isinstance(typ, Boolean):
        return 1
    if isinstance(typ, UInt):
        return typ.byte_len
    if isinstance(typ, ByteVector):
        return typ.length
    if isinstance(typ, Bitvector):
        return (typ.length + 7) // 8
    if isinstance(typ, Vector) and is_fixed_size(typ.elem):
        return typ.length * fixed_size(typ.elem)
    if isinstance(typ, Container) and is_fixed_size(typ):
        return sum(fixed_size(t) for _, t in typ.fields)
    raise TypeError(f"{typ!r} is not fixed size")


def _pack_bits(bits, with_delimiter: bool) -> bytes:
    n = len(bits)
    total = n + (1 if with_delimiter else 0)
    out = bytearray((total + 7) // 8 if total else (1 if with_delimiter else 0))
    if with_delimiter and not out:
        out = bytearray(1)
    for i, b in enumerate(bits):
        if b:
            out[i // 8] |= 1 << (i % 8)
    if with_delimiter:
        out[n // 8] |= 1 << (n % 8)
    return bytes(out)


def _unpack_bits(data: bytes, n: int) -> list[bool]:
    return [bool(data[i // 8] >> (i % 8) & 1) for i in range(n)]


def serialize(typ: SSZType, value: Any) -> bytes:
    if isinstance(typ, Boolean):
        return b"\x01" if value else b"\x00"
    if isinstance(typ, UInt):
        return int(value).to_bytes(typ.byte_len, "little")
    if isinstance(typ, ByteVector):
        b = bytes(value)
        if len(b) != typ.length:
            raise ValueError(f"ByteVector[{typ.length}] got {len(b)} bytes")
        return b
    if isinstance(typ, ByteList):
        b = bytes(value)
        if len(b) > typ.limit:
            raise ValueError("ByteList over limit")
        return b
    if isinstance(typ, Bitvector):
        if len(value) != typ.length:
            raise ValueError("Bitvector length mismatch")
        return _pack_bits(value, with_delimiter=False)
    if isinstance(typ, Bitlist):
        if len(value) > typ.limit:
            raise ValueError("Bitlist over limit")
        return _pack_bits(value, with_delimiter=True)
    if isinstance(typ, (Vector, List)):
        if isinstance(typ, Vector) and len(value) != typ.length:
            raise ValueError(f"Vector length {len(value)} != {typ.length}")
        if isinstance(typ, List) and len(value) > typ.limit:
            raise ValueError("List over limit")
        return _serialize_sequence([typ.elem] * len(value), value)
    if isinstance(typ, Container):
        types = [t for _, t in typ.fields]
        values = [getattr(value, n) for n, _ in typ.fields]
        return _serialize_sequence(types, values)
    if isinstance(typ, Union):
        assert isinstance(value, UnionValue)
        opt = typ.options[value.selector]
        body = b"" if opt is None else serialize(opt, value.value)
        return bytes([value.selector]) + body
    raise TypeError(f"cannot serialize {typ!r}")


def _serialize_sequence(types: list[SSZType], values: list[Any]) -> bytes:
    fixed_parts: list[bytes | None] = []
    variable_parts: list[bytes] = []
    for t, v in zip(types, values):
        if is_fixed_size(t):
            fixed_parts.append(serialize(t, v))
            variable_parts.append(b"")
        else:
            fixed_parts.append(None)
            variable_parts.append(serialize(t, v))
    fixed_len = sum(
        len(p) if p is not None else BYTES_PER_LENGTH_OFFSET
        for p in fixed_parts)
    out = bytearray()
    offset = fixed_len
    for p, v in zip(fixed_parts, variable_parts):
        if p is not None:
            out += p
        else:
            out += offset.to_bytes(BYTES_PER_LENGTH_OFFSET, "little")
            offset += len(v)
    for v in variable_parts:
        out += v
    return bytes(out)


def deserialize(typ: SSZType, data: bytes) -> Any:
    if isinstance(typ, Boolean):
        if data == b"\x01":
            return True
        if data == b"\x00":
            return False
        raise DeserializeError("bad boolean")
    if isinstance(typ, UInt):
        if len(data) != typ.byte_len:
            raise DeserializeError("bad uint length")
        return int.from_bytes(data, "little")
    if isinstance(typ, ByteVector):
        if len(data) != typ.length:
            raise DeserializeError("bad ByteVector length")
        return bytes(data)
    if isinstance(typ, ByteList):
        if len(data) > typ.limit:
            raise DeserializeError("ByteList over limit")
        return bytes(data)
    if isinstance(typ, Bitvector):
        if len(data) != (typ.length + 7) // 8:
            raise DeserializeError("bad Bitvector length")
        if typ.length % 8 and data[-1] >> (typ.length % 8):
            raise DeserializeError("Bitvector high bits set")
        return _unpack_bits(data, typ.length)
    if isinstance(typ, Bitlist):
        if not data:
            raise DeserializeError("empty Bitlist payload")
        last = data[-1]
        if last == 0:
            raise DeserializeError("missing Bitlist delimiter")
        n = (len(data) - 1) * 8 + last.bit_length() - 1
        if n > typ.limit:
            raise DeserializeError("Bitlist over limit")
        return _unpack_bits(data, n)
    if isinstance(typ, Vector):
        if is_fixed_size(typ.elem):
            es = fixed_size(typ.elem)
            if len(data) != es * typ.length:
                raise DeserializeError("bad Vector length")
            return [deserialize(typ.elem, data[i * es:(i + 1) * es])
                    for i in range(typ.length)]
        parts = _split_variable(data)
        if len(parts) != typ.length:
            raise DeserializeError("bad Vector element count")
        return [deserialize(typ.elem, p) for p in parts]
    if isinstance(typ, List):
        if is_fixed_size(typ.elem):
            es = fixed_size(typ.elem)
            if es == 0 or len(data) % es:
                raise DeserializeError("bad List length")
            n = len(data) // es
            if n > typ.limit:
                raise DeserializeError("List over limit")
            return [deserialize(typ.elem, data[i * es:(i + 1) * es])
                    for i in range(n)]
        parts = _split_variable(data)
        if len(parts) > typ.limit:
            raise DeserializeError("List over limit")
        return [deserialize(typ.elem, p) for p in parts]
    if isinstance(typ, Container):
        return _deserialize_container(typ, data)
    if isinstance(typ, Union):
        if not data:
            raise DeserializeError("empty union")
        sel = data[0]
        if sel >= len(typ.options):
            raise DeserializeError("bad union selector")
        opt = typ.options[sel]
        if opt is None:
            if len(data) != 1:
                raise DeserializeError("None union with body")
            return UnionValue(0, None)
        return UnionValue(sel, deserialize(opt, data[1:]))
    raise TypeError(f"cannot deserialize {typ!r}")


def _split_variable(data: bytes) -> list[bytes]:
    """Split an all-variable-size sequence body by its offset table."""
    if not data:
        return []
    first = int.from_bytes(data[:BYTES_PER_LENGTH_OFFSET], "little")
    if first % BYTES_PER_LENGTH_OFFSET or first == 0:
        raise DeserializeError("bad first offset")
    if first > len(data):
        # bound BEFORE allocating the offset table: a corrupted first
        # offset must not drive a multi-GB allocation (r5 fuzz review)
        raise DeserializeError("first offset beyond data")
    n = first // BYTES_PER_LENGTH_OFFSET
    offsets = [int.from_bytes(
        data[i * 4:(i + 1) * 4], "little") for i in range(n)]
    offsets.append(len(data))
    parts = []
    for i in range(n):
        if offsets[i] > offsets[i + 1] or offsets[i] > len(data):
            raise DeserializeError("offsets not monotonic")
        parts.append(data[offsets[i]:offsets[i + 1]])
    return parts


def _deserialize_container(typ: Container, data: bytes) -> Any:
    pos = 0
    fixed_raw: list[tuple[str, SSZType, bytes | int]] = []
    offsets: list[int] = []
    for name, t in typ.fields:
        if is_fixed_size(t):
            es = fixed_size(t)
            fixed_raw.append((name, t, data[pos:pos + es]))
            pos += es
        else:
            off = int.from_bytes(data[pos:pos + 4], "little")
            fixed_raw.append((name, t, off))
            offsets.append(off)
            pos += 4
    if not offsets and len(data) != pos:
        # fully-fixed container: decoding must consume EVERY byte —
        # trailing garbage is a distinct wire form for the same value
        # (found by the r5 SSZ fuzzer, tests/test_fuzz.py)
        raise DeserializeError("container length mismatch")
    offsets.append(len(data))
    if len(offsets) > 1 and offsets[0] != pos:
        raise DeserializeError("first offset != fixed size")
    kw = {}
    oi = 0
    for name, t, raw in fixed_raw:
        if isinstance(raw, int):
            start, end = offsets[oi], offsets[oi + 1]
            if start > end or end > len(data):
                raise DeserializeError("bad container offsets")
            kw[name] = deserialize(t, data[start:end])
            oi += 1
        else:
            if len(raw) != fixed_size(t):
                raise DeserializeError("container truncated")
            kw[name] = deserialize(t, raw)
    return typ.cls(**kw)
