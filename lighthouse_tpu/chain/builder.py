"""BeaconChainBuilder (beacon_chain/src/builder.rs equivalent): staged wiring
of store/clock/execution-layer/genesis, incl. checkpoint-sync anchors
(client/src/builder.rs:341-497)."""
from __future__ import annotations

from ..containers.state import BeaconState
from ..specs.chain_spec import ChainSpec
from ..state_transition import interop_genesis_state
from ..state_transition.helpers import latest_block_header_root
from ..store import HotColdDB, MemoryStore
from ..utils.slot_clock import ManualSlotClock, SlotClock, SystemTimeSlotClock
from .beacon_chain import BeaconChain, ChainConfig
from .execution import ExecutionLayerInterface, MockExecutionLayer


class BeaconChainBuilder:
    def __init__(self, spec: ChainSpec):
        self.spec = spec
        self._store: HotColdDB | None = None
        self._clock: SlotClock | None = None
        self._el: ExecutionLayerInterface | None = None
        self._genesis_state: BeaconState | None = None
        self._genesis_block = None
        self._config = ChainConfig()

    def store(self, store: HotColdDB) -> "BeaconChainBuilder":
        self._store = store
        return self

    def slot_clock(self, clock: SlotClock) -> "BeaconChainBuilder":
        self._clock = clock
        return self

    def execution_layer(self, el: ExecutionLayerInterface
                        ) -> "BeaconChainBuilder":
        self._el = el
        return self

    def chain_config(self, config: ChainConfig) -> "BeaconChainBuilder":
        self._config = config
        return self

    def genesis_state(self, state: BeaconState) -> "BeaconChainBuilder":
        self._genesis_state = state
        return self

    def interop_genesis(self, secret_keys: list[int],
                        genesis_time: int = 0) -> "BeaconChainBuilder":
        self._genesis_state = interop_genesis_state(
            self.spec, secret_keys, genesis_time=genesis_time)
        return self

    def weak_subjectivity_anchor(self, state: BeaconState,
                                 signed_block) -> "BeaconChainBuilder":
        """Checkpoint sync: anchor on a finalized state+block
        (ClientGenesis::CheckpointSyncUrl / WeakSubjSszBytes)."""
        self._genesis_state = state
        self._genesis_block = signed_block
        return self

    def resume_from_store(self, store: HotColdDB,
                          anchor=None) -> "BeaconChainBuilder":
        """ClientGenesis::FromStore (client/src/config.rs:33): boot from a
        previously-anchored database. Pass `anchor` when already loaded (it
        is a full cold-state fetch)."""
        anchor = anchor if anchor is not None else store.anchor_state()
        if anchor is None:
            raise ValueError("store has no anchor to resume from")
        self._store = store
        self._genesis_state = anchor
        # restore the anchor block so head_block is never None even when
        # fork choice was never persisted (pre-first-finalization restarts)
        root = store.genesis_block_root()
        if root is not None:
            self._genesis_block = store.get_block(root)
        self._resume = True
        return self

    def build(self) -> BeaconChain:
        assert self._genesis_state is not None, "genesis required"
        store = self._store or HotColdDB(MemoryStore(), MemoryStore(),
                                         self.spec)
        clock = self._clock or SystemTimeSlotClock(
            self._genesis_state.genesis_time, self.spec.seconds_per_slot)
        el = self._el or MockExecutionLayer()
        chain = BeaconChain(self.spec, store, clock, el,
                            self._genesis_state, self._genesis_block,
                            self._config)
        if getattr(self, "_resume", False):
            chain.resume()
        return chain
