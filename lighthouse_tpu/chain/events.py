"""Server-sent-event style chain event bus
(/root/reference/beacon_node/beacon_chain/src/events.rs)."""
from __future__ import annotations

import queue
import threading


EVENT_KINDS = ("head", "block", "attestation", "finalized_checkpoint",
               "chain_reorg", "voluntary_exit", "blob_sidecar",
               "payload_attributes", "block_gossip")


class EventHandler:
    def __init__(self, capacity: int = 1024):
        self._subs: list[tuple[set[str], queue.Queue]] = []
        #: synchronous listeners: (kinds, fn) called inline from emit().
        #: emit() runs under the chain lock, so listeners must be cheap
        #: and must never raise (the serving tier's cache invalidation
        #: is the intended consumer).
        self._listeners: list[tuple[set[str], object]] = []
        self._lock = threading.Lock()
        self.capacity = capacity

    def subscribe(self, kinds=None) -> queue.Queue:
        q: queue.Queue = queue.Queue(self.capacity)
        with self._lock:
            self._subs.append((set(kinds or EVENT_KINDS), q))
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            self._subs = [(k, s) for k, s in self._subs if s is not q]

    def add_listener(self, kinds, fn) -> None:
        with self._lock:
            self._listeners.append((set(kinds or EVENT_KINDS), fn))

    def remove_listener(self, fn) -> None:
        with self._lock:
            self._listeners = [(k, f) for k, f in self._listeners
                               if f is not fn]

    def emit(self, kind: str, payload) -> None:
        with self._lock:
            subs = list(self._subs)
            listeners = list(self._listeners)
        for kinds, fn in listeners:
            if kind in kinds:
                try:
                    fn(kind, payload)
                except Exception:
                    pass
        for kinds, q in subs:
            if kind in kinds:
                try:
                    q.put_nowait((kind, payload))
                except queue.Full:
                    pass

    def has_subscribers(self) -> bool:
        return bool(self._subs)
