"""Server-sent-event style chain event bus
(/root/reference/beacon_node/beacon_chain/src/events.rs)."""
from __future__ import annotations

import queue
import threading


EVENT_KINDS = ("head", "block", "attestation", "finalized_checkpoint",
               "chain_reorg", "voluntary_exit", "blob_sidecar",
               "payload_attributes", "block_gossip")


class EventHandler:
    def __init__(self, capacity: int = 1024):
        self._subs: list[tuple[set[str], queue.Queue]] = []
        self._lock = threading.Lock()
        self.capacity = capacity

    def subscribe(self, kinds=None) -> queue.Queue:
        q: queue.Queue = queue.Queue(self.capacity)
        with self._lock:
            self._subs.append((set(kinds or EVENT_KINDS), q))
        return q

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            self._subs = [(k, s) for k, s in self._subs if s is not q]

    def emit(self, kind: str, payload) -> None:
        with self._lock:
            subs = list(self._subs)
        for kinds, q in subs:
            if kind in kinds:
                try:
                    q.put_nowait((kind, payload))
                except queue.Full:
                    pass

    def has_subscribers(self) -> bool:
        return bool(self._subs)
