"""Chain error taxonomy (block_verification.rs BlockError,
attestation_verification.rs Error equivalents — collapsed to the variants the
router/sync layers actually dispatch on)."""
from __future__ import annotations


class ChainError(Exception):
    pass


class BlockError(ChainError):
    def __init__(self, kind: str, detail: str = ""):
        self.kind = kind
        self.detail = detail
        super().__init__(f"{kind}: {detail}" if detail else kind)


class AttestationError(ChainError):
    def __init__(self, kind: str, detail: str = ""):
        self.kind = kind
        self.detail = detail
        super().__init__(f"{kind}: {detail}" if detail else kind)


# block error kinds (block_verification.rs:BlockError)
PARENT_UNKNOWN = "parent_unknown"
FUTURE_SLOT = "future_slot"
ALREADY_KNOWN = "already_known"
REPEAT_PROPOSAL = "repeat_proposal"
INVALID_SIGNATURE = "invalid_signature"
INVALID_BLOCK = "invalid_block"
FINALIZED_SLOT = "would_revert_finalized"
INCORRECT_PROPOSER = "incorrect_proposer"
AVAILABILITY_PENDING = "availability_pending"
EXECUTION_INVALID = "execution_invalid"

# attestation error kinds
UNKNOWN_HEAD_BLOCK = "unknown_head_block"
PAST_SLOT = "past_slot"
PRIOR_SEEN = "prior_attestation_known"
BAD_SIGNATURE = "bad_signature"
BAD_TARGET = "bad_target"
NOT_AGGREGATOR = "invalid_selection_proof"
EMPTY_AGGREGATION_BITS = "empty_aggregation_bits"
