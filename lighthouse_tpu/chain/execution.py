"""Execution layer interface + in-process mock.

The real engine-API HTTP client (JWT, newPayload/forkchoiceUpdated/getPayload)
lives in lighthouse_tpu.execution_layer; this module defines the interface the
chain consumes and the MockExecutionLayer used by the harness — equivalent of
/root/reference/beacon_node/execution_layer/src/test_utils/
{mock_execution_layer.rs:12, execution_block_generator.rs}.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


class ExecutionLayerInterface:
    def notify_new_payload(self, payload) -> str:
        """'valid' | 'invalid' | 'optimistic' (SYNCING/ACCEPTED)."""
        raise NotImplementedError

    def notify_forkchoice_updated(self, head_hash: bytes, safe_hash: bytes,
                                  finalized_hash: bytes,
                                  payload_attributes=None):
        raise NotImplementedError

    def get_payload(self, payload_id) -> object:
        raise NotImplementedError


@dataclass
class MockExecutionBlock:
    block_hash: bytes
    parent_hash: bytes
    block_number: int


class MockExecutionLayer(ExecutionLayerInterface):
    """Accepts every payload whose parent it knows; tests can mark hashes
    invalid or answer 'optimistic' to exercise optimistic sync
    (payload_invalidation.rs test style)."""

    def __init__(self):
        self.blocks: dict[bytes, MockExecutionBlock] = {}
        self.invalid_hashes: set[bytes] = set()
        self.syncing = False
        self.forkchoice_calls: list = []
        zero = b"\x00" * 32
        self.blocks[zero] = MockExecutionBlock(zero, zero, 0)

    def notify_new_payload(self, payload) -> str:
        if payload.block_hash in self.invalid_hashes:
            return "invalid"
        if self.syncing:
            return "optimistic"
        self.blocks[payload.block_hash] = MockExecutionBlock(
            payload.block_hash, payload.parent_hash, payload.block_number)
        return "valid"

    def notify_forkchoice_updated(self, head_hash, safe_hash, finalized_hash,
                                  payload_attributes=None):
        self.forkchoice_calls.append((head_hash, finalized_hash))
        if head_hash in self.invalid_hashes:
            return ("invalid", None)
        payload_id = None
        if payload_attributes is not None:
            payload_id = hashlib.sha256(
                head_hash + repr(payload_attributes).encode()).digest()[:8]
            self._prep = (payload_id, head_hash, payload_attributes)
        return ("optimistic" if self.syncing else "valid", payload_id)

    def get_payload(self, payload_id):
        return getattr(self, "_prep", None)
