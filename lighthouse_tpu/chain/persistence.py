"""Chain persistence: fork choice, head, op pool — restart resume.

Equivalent of the reference's persisted_fork_choice.rs / persist_head
(beacon_chain.rs:612,662) + operation_pool/persistence.rs: everything needed
to resume after a restart is written to the hot DB under ITEM keys, and
`ClientGenesis::FromStore` boots from it.

Crash contract (the sequence-number protocol):

`persist_chain` commits THREE batches in a fixed order, all stamped with
the same monotonic sequence number (meta key ``persist_seq``):

1. fork-choice snapshot (JSON doc carries ``"seq"``) + the advanced
   ``persist_seq`` meta — one atomic batch;
2. the head item (``<Q`` seq || 32-byte head root);
3. the op-pool snapshot (JSON doc carries ``"seq"``).

Because the store log is append-only and each batch is one CRC'd record,
a crash can only leave a *prefix* of the sequence: the head's seq is
never ahead of the fork-choice seq.  `resume_chain` exploits that to
repair rather than trust:

- fork-choice snapshot unreadable (torn/corrupt/flipped bits) → rebuild
  the proto array from stored blocks, anchored at the split/finalized
  state (hot states below the split are pruned, so nothing older can
  re-enter);
- head seq != fork-choice seq (crash between batches 1 and 2) → the head
  item is stale: derive the head from the restored fork choice instead;
- head's state unloadable → walk back parent-by-parent to the newest
  ancestor whose state IS loadable;
- individually corrupt op-pool entries → skipped and counted, never
  fatal.

Any repair is re-persisted immediately so a subsequent `fsck` run is
clean, and the whole episode is recorded in `LAST_RECOVERY` for the
graftwatch flight recorder / offline doctor.
"""
from __future__ import annotations

import json
import logging
import struct

from ..fork_choice import ForkChoice
from ..fork_choice.proto_array import ExecutionStatus, ProtoNode, VoteTracker
from ..store import StoreOp
from ..utils.crashpoints import crashpoint

FORK_CHOICE_KEY = b"fork_choice"
HEAD_KEY = b"head"
OP_POOL_KEY = b"op_pool"
PERSIST_SEQ_META = b"persist_seq"

log = logging.getLogger("lighthouse_tpu.chain")

#: report of the most recent `resume_chain` in this process (None = never
#: resumed).  Embedded in the flight-recorder dump so the offline doctor
#: can correlate post-restart incidents with what recovery repaired.
LAST_RECOVERY: dict | None = None


def last_recovery_report() -> dict | None:
    return LAST_RECOVERY


def _count(name: str, amount: float = 1) -> None:
    import sys
    md = sys.modules.get("lighthouse_tpu.api.metrics_defs")
    if md is not None:
        md.count(name, amount)


def _hex(b: bytes | None) -> str | None:
    return b.hex() if b is not None else None


def _unhex(s) -> bytes | None:
    return bytes.fromhex(s) if s is not None else None


# -- persist -----------------------------------------------------------------


def load_persist_seq(store) -> int:
    raw = store._get_meta(PERSIST_SEQ_META)
    if raw is None or len(raw) != 8:
        return 0
    return struct.unpack("<Q", raw)[0]


def _fork_choice_doc(chain, seq: int | None) -> dict:
    fc = chain.fork_choice
    pa = fc.proto_array
    doc = {
        "justified": [fc.justified_checkpoint[0],
                      _hex(fc.justified_checkpoint[1])],
        "finalized": [fc.finalized_checkpoint[0],
                      _hex(fc.finalized_checkpoint[1])],
        "u_justified": [fc.unrealized_justified_checkpoint[0],
                        _hex(fc.unrealized_justified_checkpoint[1])],
        "u_finalized": [fc.unrealized_finalized_checkpoint[0],
                        _hex(fc.unrealized_finalized_checkpoint[1])],
        "current_slot": fc.current_slot,
        "equivocating": sorted(fc.equivocating_indices),
        "votes": [[_hex(v.current_root), _hex(v.next_root), v.next_epoch]
                  for v in fc.votes],
        "nodes": [{
            "slot": n.slot, "root": _hex(n.root),
            "parent": n.parent, "state_root": _hex(n.state_root),
            "target": _hex(n.target_root),
            "jc": [n.justified_checkpoint[0], _hex(n.justified_checkpoint[1])],
            "fc": [n.finalized_checkpoint[0], _hex(n.finalized_checkpoint[1])],
            "ujc": ([n.unrealized_justified_checkpoint[0],
                     _hex(n.unrealized_justified_checkpoint[1])]
                    if n.unrealized_justified_checkpoint else None),
            "ufc": ([n.unrealized_finalized_checkpoint[0],
                     _hex(n.unrealized_finalized_checkpoint[1])]
                    if n.unrealized_finalized_checkpoint else None),
            "weight": n.weight,
            "best_child": n.best_child, "best_descendant": n.best_descendant,
            "exec": n.execution_status.value,
            "exec_hash": _hex(n.execution_block_hash),
        } for n in pa.nodes],
    }
    if seq is not None:
        doc["seq"] = seq
    return doc


def _op_pool_doc(chain, seq: int | None) -> dict:
    from ..ssz import serialize
    pool = chain.op_pool
    T = chain.T
    with pool._lock:
        atts = [a for bucket in pool._attestations.values() for a in bucket]
        doc = {
            "attestations": [serialize(type(a).ssz_type, a).hex()
                             for a in atts],
            "att_electra": [hasattr(a, "committee_bits") for a in atts],
            "exits": [serialize(T.SignedVoluntaryExit.ssz_type, e).hex()
                      for e in pool._voluntary_exits.values()],
            "proposer_slashings": [
                serialize(T.ProposerSlashing.ssz_type, s).hex()
                for s in pool._proposer_slashings.values()],
            "attester_slashings": [
                serialize(type(s).ssz_type, s).hex()
                for s in pool._attester_slashings],
            "as_electra": [
                "Electra" in type(s).__name__
                for s in pool._attester_slashings],
            "bls_changes": [
                serialize(T.SignedBLSToExecutionChange.ssz_type, c).hex()
                for c in pool._bls_changes.values()],
        }
    if seq is not None:
        doc["seq"] = seq
    return doc


def persist_fork_choice(chain, seq: int | None = None) -> None:
    """Batch 1: fork-choice snapshot + advanced persist_seq, atomically."""
    doc = _fork_choice_doc(chain, seq)
    ops = [StoreOp.put_item(FORK_CHOICE_KEY, json.dumps(doc).encode())]
    if seq is not None:
        ops.append(StoreOp.put_meta(PERSIST_SEQ_META,
                                    struct.pack("<Q", seq)))
    chain.store.do_atomically(ops, fsync=False)


def persist_head(chain, seq: int | None = None) -> None:
    """Batch 2: the head item, seq-stamped so a crash between batches is
    detectable as head_seq != fork_choice_seq on resume."""
    head_root = chain.head().head_block_root
    value = (struct.pack("<Q", seq) + head_root if seq is not None
             else head_root)
    chain.store.do_atomically([StoreOp.put_item(HEAD_KEY, value)],
                              fsync=False)


def persist_op_pool(chain, seq: int | None = None) -> None:
    """Batch 3: op-pool snapshot."""
    doc = _op_pool_doc(chain, seq)
    chain.store.do_atomically(
        [StoreOp.put_item(OP_POOL_KEY, json.dumps(doc).encode())],
        fsync=False)


def persist_chain(chain) -> None:
    seq = load_persist_seq(chain.store) + 1
    persist_fork_choice(chain, seq)
    crashpoint("persist:between_fc_and_head")
    persist_head(chain, seq)
    crashpoint("persist:between_head_and_op_pool")
    persist_op_pool(chain, seq)


# -- restore -----------------------------------------------------------------


def restore_fork_choice(chain) -> bool:
    ok, _seq = _restore_fork_choice(chain)
    return ok


def _restore_fork_choice(chain) -> tuple[bool, int | None]:
    """(restored, snapshot_seq).  Never raises: torn/corrupt snapshots
    return (False, None) so `resume_chain` can fall through to the
    rebuild path instead of hard-crashing at boot."""
    raw = chain.store.get_item(FORK_CHOICE_KEY)
    if raw is None:
        return False, None
    try:
        doc = json.loads(raw)
        fc = chain.fork_choice
        justified = (doc["justified"][0], _unhex(doc["justified"][1]))
        finalized = (doc["finalized"][0], _unhex(doc["finalized"][1]))
        nodes = []
        indices = {}
        for nd in doc["nodes"]:
            node = ProtoNode(
                slot=nd["slot"], root=_unhex(nd["root"]),
                parent=nd["parent"],
                state_root=_unhex(nd["state_root"]),
                target_root=_unhex(nd["target"]),
                justified_checkpoint=(nd["jc"][0], _unhex(nd["jc"][1])),
                finalized_checkpoint=(nd["fc"][0], _unhex(nd["fc"][1])),
                unrealized_justified_checkpoint=(
                    (nd["ujc"][0], _unhex(nd["ujc"][1]))
                    if nd.get("ujc") else None),
                unrealized_finalized_checkpoint=(
                    (nd["ufc"][0], _unhex(nd["ufc"][1]))
                    if nd.get("ufc") else None),
                weight=nd["weight"], best_child=nd["best_child"],
                best_descendant=nd["best_descendant"],
                execution_status=ExecutionStatus(nd["exec"]),
                execution_block_hash=_unhex(nd["exec_hash"]))
            indices[node.root] = len(nodes)
            nodes.append(node)
        votes = [VoteTracker(_unhex(c), _unhex(nx), e)
                 for c, nx, e in doc["votes"]]
    except Exception as exc:
        log.warning("fork-choice snapshot unreadable (%r); will rebuild "
                    "from stored blocks", exc)
        return False, None
    # parsed cleanly: only now mutate the live fork choice
    fc.justified_checkpoint = justified
    fc.finalized_checkpoint = finalized
    fc.unrealized_justified_checkpoint = (doc["u_justified"][0],
                                          _unhex(doc["u_justified"][1]))
    fc.unrealized_finalized_checkpoint = (doc["u_finalized"][0],
                                          _unhex(doc["u_finalized"][1]))
    fc.current_slot = doc["current_slot"]
    fc.equivocating_indices = set(doc["equivocating"])
    fc.votes = votes
    pa = fc.proto_array
    pa.nodes = nodes
    pa.indices = indices
    pa.justified_checkpoint = justified
    pa.finalized_checkpoint = finalized
    return True, doc.get("seq")


def _anchor_fork_choice_at_split(chain) -> ForkChoice | None:
    """A fresh fork choice anchored at the split/finalized block — the
    deepest point whose state is still materialized in hot.  None when the
    split state or its summary is itself unusable (caller keeps the
    genesis-anchored instance)."""
    store = chain.store
    summary = store.hot_state_summary(store.split.state_root)
    if summary is None:
        return None
    try:
        anchor_state = store.get_hot_state(store.split.state_root)
    except Exception:
        anchor_state = None
    if anchor_state is None:
        return None
    anchor_root = summary[1]          # latest_block_root at the split state
    fc = ForkChoice(chain.spec, anchor_root, anchor_state)
    fc.balances_provider = chain._justified_balances
    return fc


def _replay_missing_blocks(chain) -> int:
    """Feed every stored hot block that fork choice doesn't know (and whose
    parent it does) back through on_block.  Ascending slot order makes one
    pass sufficient; blocks with unloadable states are skipped — they're
    exactly what the head walk-back ladder routes around."""
    fc = chain.fork_choice
    current_slot = chain.slot()
    added = 0
    for root, blk in chain.store.iter_hot_blocks():
        msg = blk.message
        if fc.contains_block(root) or \
                not fc.contains_block(msg.parent_root):
            continue
        try:
            state = chain.store.get_hot_state(msg.state_root)
        except Exception:
            state = None
        if state is None:
            continue
        try:
            fc.on_block(max(current_slot, msg.slot), msg, root, state)
        except Exception as exc:
            log.warning("fork-choice rebuild: skipping block %s: %r",
                        root.hex()[:12], exc)
            continue
        added += 1
    return added


def rebuild_fork_choice(chain) -> int:
    """Reconstruct fork choice from stored blocks (snapshot unreadable or
    absent).  Returns the number of blocks (re-)registered."""
    if chain.store.split.slot > 0:
        fc = _anchor_fork_choice_at_split(chain)
        if fc is not None:
            with chain._lock:
                chain.fork_choice = fc
        else:
            log.warning("fork-choice rebuild: split state unusable, "
                        "keeping the anchor-state instance")
    return _replay_missing_blocks(chain)


def restore_op_pool(chain) -> int:
    n, _skipped, _seq = _restore_op_pool(chain)
    return n


def _restore_op_pool(chain) -> tuple[int, int, int | None]:
    """(restored, skipped, seq): each entry decodes independently, so one
    flipped bit costs one attestation, not the whole pool."""
    from ..ssz import deserialize
    raw = chain.store.get_item(OP_POOL_KEY)
    if raw is None:
        return 0, 0, None
    try:
        doc = json.loads(raw)
    except Exception as exc:
        log.warning("op-pool snapshot unreadable (%r); dropping it", exc)
        return 0, 1, None
    T = chain.T
    n = skipped = 0

    def _each(items, fn):
        nonlocal n, skipped
        for it in items:
            try:
                fn(*it) if isinstance(it, tuple) else fn(it)
                n += 1
            except Exception:
                skipped += 1

    _each(list(zip(doc.get("attestations", []),
                   doc.get("att_electra", []))),
          lambda hexa, is_electra: chain.op_pool.insert_attestation(
              deserialize((T.AttestationElectra if is_electra
                           else T.Attestation).ssz_type,
                          bytes.fromhex(hexa))))
    _each(doc.get("exits", []),
          lambda hexe: chain.op_pool.insert_voluntary_exit(
              deserialize(T.SignedVoluntaryExit.ssz_type,
                          bytes.fromhex(hexe))))
    _each(doc.get("proposer_slashings", []),
          lambda hexs: chain.op_pool.insert_proposer_slashing(
              deserialize(T.ProposerSlashing.ssz_type, bytes.fromhex(hexs))))
    _each(list(zip(doc.get("attester_slashings", []),
                   doc.get("as_electra", []))),
          lambda hexs, is_electra: chain.op_pool.insert_attester_slashing(
              deserialize((T.AttesterSlashingElectra if is_electra
                           else T.AttesterSlashing).ssz_type,
                          bytes.fromhex(hexs))))
    _each(doc.get("bls_changes", []),
          lambda hexc: chain.op_pool.insert_bls_to_execution_change(
              deserialize(T.SignedBLSToExecutionChange.ssz_type,
                          bytes.fromhex(hexc))))
    return n, skipped, doc.get("seq")


# -- resume (the repair ladder) ----------------------------------------------


def _try_set_head(chain, head_root: bytes) -> bool:
    head_block = chain.store.get_block(head_root)
    if head_block is None:
        return False
    try:
        head_state = chain.store.get_hot_state(
            head_block.message.state_root)
    except Exception:
        head_state = None
    if head_state is None:
        return False
    from .beacon_chain import CanonicalHead
    with chain._lock:
        chain.canonical_head = CanonicalHead(head_root, head_block,
                                             head_state)
    chain._cache_snapshot(head_root, head_state)
    return True


def _repair_head(chain, head_root: bytes, report: dict) -> bool:
    """Walk back from `head_root` to the newest ancestor whose state is
    loadable; 0 steps is the happy path."""
    root = head_root
    steps = 0
    while root is not None and root != b"\x00" * 32:
        if _try_set_head(chain, root):
            if steps:
                report["repairs"].append(
                    f"head {head_root.hex()[:12]} had no loadable state; "
                    f"walked back {steps} block(s) to {root.hex()[:12]}")
                log.warning("resume: %s", report["repairs"][-1])
            report["head_walked_back"] = steps
            return True
        blk = chain.store.get_block(root)
        if blk is None or blk.message.slot == 0:
            return False
        root = blk.message.parent_root
        steps += 1
    return False


def resume_chain(chain) -> bool:
    """Restore fork choice + head + op pool from the store (FromStore boot),
    repairing whatever a crash tore (module docstring has the ladder).
    Returns True when prior state existed."""
    global LAST_RECOVERY
    report: dict = {"restored": False, "fork_choice_rebuilt": False,
                    "repairs": [], "op_pool_skipped": 0,
                    "head_walked_back": 0, "seq": None}
    LAST_RECOVERY = report
    store = chain.store

    restored, fc_seq = _restore_fork_choice(chain)
    report["restored"] = restored
    report["seq"] = fc_seq
    if restored:
        # snapshot may predate the newest imported blocks (crash after the
        # import batch, before the next persist): top it up from the store
        added = _replay_missing_blocks(chain)
        if added:
            report["repairs"].append(
                f"fork choice topped up with {added} stored block(s) "
                f"missing from the snapshot")
    else:
        snapshot_existed = store.get_item(FORK_CHOICE_KEY) is not None
        added = rebuild_fork_choice(chain)
        if snapshot_existed:
            report["fork_choice_rebuilt"] = True
            report["repairs"].append(
                f"fork-choice snapshot unreadable; rebuilt from stored "
                f"blocks ({added} registered)")
        elif added or store.split.slot > 0 or \
                store.get_item(HEAD_KEY) is not None:
            # no snapshot but real history: a crash beat the first persist
            report["fork_choice_rebuilt"] = True
            report["repairs"].append(
                f"no fork-choice snapshot; rebuilt from stored blocks "
                f"({added} registered)")
        else:
            return False                   # genuinely fresh store

    n_ops, skipped, _pool_seq = _restore_op_pool(chain)
    report["op_pool_skipped"] = skipped
    if skipped:
        report["repairs"].append(
            f"op-pool restore skipped {skipped} corrupt entr"
            f"{'y' if skipped == 1 else 'ies'} (kept {n_ops})")

    # head: trust the persisted item only when its seq matches the
    # fork-choice snapshot's (append order guarantees head_seq <= fc_seq;
    # a mismatch means the crash hit between the two batches)
    head_root = None
    raw_head = store.get_item(HEAD_KEY)
    if raw_head is None and fc_seq is not None:
        # persist_chain always writes the head right after the snapshot,
        # so a seq-stamped snapshot with no head item is the crash
        # landing between the first persist's two batches
        report["repairs"].append(
            f"torn persist: fork-choice snapshot at seq {fc_seq} but no "
            f"head item; deriving head from fork choice")
    if raw_head is not None:
        if len(raw_head) == 40:
            head_seq = struct.unpack("<Q", raw_head[:8])[0]
            head_root = raw_head[8:]
            if fc_seq is not None and head_seq != fc_seq:
                report["repairs"].append(
                    f"torn persist: head item at seq {head_seq} vs "
                    f"fork-choice seq {fc_seq}; deriving head from fork "
                    f"choice")
                head_root = None
        elif len(raw_head) == 32:          # legacy, pre-seq layout
            head_root = raw_head
        else:
            report["repairs"].append("head item malformed; deriving head "
                                     "from fork choice")
    if head_root is not None and \
            not chain.fork_choice.contains_block(head_root):
        report["repairs"].append(
            f"persisted head {head_root.hex()[:12]} unknown to fork "
            f"choice; deriving head from fork choice")
        head_root = None
    if head_root is None:
        try:
            head_root = chain.fork_choice.get_head(chain.slot())
        except Exception as exc:
            log.warning("resume: get_head failed during repair: %r", exc)
            head_root = None
    if head_root is not None:
        if not _repair_head(chain, head_root, report):
            report["repairs"].append(
                f"no ancestor of {head_root.hex()[:12]} has a loadable "
                f"state; keeping the anchor head")
            log.warning("resume: %s", report["repairs"][-1])

    if report["repairs"]:
        _count("store_recovery_repairs_total", len(report["repairs"]))
        log.warning("resume: %d repair(s) applied: %s",
                    len(report["repairs"]), "; ".join(report["repairs"]))
        try:
            # re-persist so the store is internally consistent again
            # (fsck's seq cross-check comes back clean)
            persist_chain(chain)
        except Exception:                  # pragma: no cover - best effort
            log.exception("resume: re-persist after repair failed")
    return True
