"""Chain persistence: fork choice, head, op pool — restart resume.

Equivalent of the reference's persisted_fork_choice.rs / persist_head
(beacon_chain.rs:612,662) + operation_pool/persistence.rs: everything needed
to resume after a restart is written to the hot DB under ITEM keys, and
`ClientGenesis::FromStore` boots from it.
"""
from __future__ import annotations

import json

from ..fork_choice import ForkChoice
from ..fork_choice.proto_array import ExecutionStatus, ProtoNode, VoteTracker

FORK_CHOICE_KEY = b"fork_choice"
HEAD_KEY = b"head"
OP_POOL_KEY = b"op_pool"


def _hex(b: bytes | None) -> str | None:
    return b.hex() if b is not None else None


def _unhex(s) -> bytes | None:
    return bytes.fromhex(s) if s is not None else None


def persist_fork_choice(chain) -> None:
    fc = chain.fork_choice
    pa = fc.proto_array
    doc = {
        "justified": [fc.justified_checkpoint[0],
                      _hex(fc.justified_checkpoint[1])],
        "finalized": [fc.finalized_checkpoint[0],
                      _hex(fc.finalized_checkpoint[1])],
        "u_justified": [fc.unrealized_justified_checkpoint[0],
                        _hex(fc.unrealized_justified_checkpoint[1])],
        "u_finalized": [fc.unrealized_finalized_checkpoint[0],
                        _hex(fc.unrealized_finalized_checkpoint[1])],
        "current_slot": fc.current_slot,
        "equivocating": sorted(fc.equivocating_indices),
        "votes": [[_hex(v.current_root), _hex(v.next_root), v.next_epoch]
                  for v in fc.votes],
        "nodes": [{
            "slot": n.slot, "root": _hex(n.root),
            "parent": n.parent, "state_root": _hex(n.state_root),
            "target": _hex(n.target_root),
            "jc": [n.justified_checkpoint[0], _hex(n.justified_checkpoint[1])],
            "fc": [n.finalized_checkpoint[0], _hex(n.finalized_checkpoint[1])],
            "ujc": ([n.unrealized_justified_checkpoint[0],
                     _hex(n.unrealized_justified_checkpoint[1])]
                    if n.unrealized_justified_checkpoint else None),
            "ufc": ([n.unrealized_finalized_checkpoint[0],
                     _hex(n.unrealized_finalized_checkpoint[1])]
                    if n.unrealized_finalized_checkpoint else None),
            "weight": n.weight,
            "best_child": n.best_child, "best_descendant": n.best_descendant,
            "exec": n.execution_status.value,
            "exec_hash": _hex(n.execution_block_hash),
        } for n in pa.nodes],
    }
    chain.store.put_item(FORK_CHOICE_KEY, json.dumps(doc).encode())
    chain.store.put_item(HEAD_KEY, chain.head().head_block_root)


def restore_fork_choice(chain) -> bool:
    raw = chain.store.get_item(FORK_CHOICE_KEY)
    if raw is None:
        return False
    doc = json.loads(raw)
    fc = chain.fork_choice
    fc.justified_checkpoint = (doc["justified"][0],
                               _unhex(doc["justified"][1]))
    fc.finalized_checkpoint = (doc["finalized"][0],
                               _unhex(doc["finalized"][1]))
    fc.unrealized_justified_checkpoint = (doc["u_justified"][0],
                                          _unhex(doc["u_justified"][1]))
    fc.unrealized_finalized_checkpoint = (doc["u_finalized"][0],
                                          _unhex(doc["u_finalized"][1]))
    fc.current_slot = doc["current_slot"]
    fc.equivocating_indices = set(doc["equivocating"])
    fc.votes = [VoteTracker(_unhex(c), _unhex(nx), e)
                for c, nx, e in doc["votes"]]
    pa = fc.proto_array
    pa.nodes = []
    pa.indices = {}
    for nd in doc["nodes"]:
        node = ProtoNode(
            slot=nd["slot"], root=_unhex(nd["root"]), parent=nd["parent"],
            state_root=_unhex(nd["state_root"]),
            target_root=_unhex(nd["target"]),
            justified_checkpoint=(nd["jc"][0], _unhex(nd["jc"][1])),
            finalized_checkpoint=(nd["fc"][0], _unhex(nd["fc"][1])),
            unrealized_justified_checkpoint=(
                (nd["ujc"][0], _unhex(nd["ujc"][1]))
                if nd.get("ujc") else None),
            unrealized_finalized_checkpoint=(
                (nd["ufc"][0], _unhex(nd["ufc"][1]))
                if nd.get("ufc") else None),
            weight=nd["weight"], best_child=nd["best_child"],
            best_descendant=nd["best_descendant"],
            execution_status=ExecutionStatus(nd["exec"]),
            execution_block_hash=_unhex(nd["exec_hash"]))
        pa.indices[node.root] = len(pa.nodes)
        pa.nodes.append(node)
    pa.justified_checkpoint = fc.justified_checkpoint
    pa.finalized_checkpoint = fc.finalized_checkpoint
    return True


def persist_op_pool(chain) -> None:
    from ..ssz import serialize
    pool = chain.op_pool
    T = chain.T
    with pool._lock:
        atts = [a for bucket in pool._attestations.values() for a in bucket]
        doc = {
            "attestations": [serialize(type(a).ssz_type, a).hex()
                             for a in atts],
            "att_electra": [hasattr(a, "committee_bits") for a in atts],
            "exits": [serialize(T.SignedVoluntaryExit.ssz_type, e).hex()
                      for e in pool._voluntary_exits.values()],
            "proposer_slashings": [
                serialize(T.ProposerSlashing.ssz_type, s).hex()
                for s in pool._proposer_slashings.values()],
            "attester_slashings": [
                serialize(type(s).ssz_type, s).hex()
                for s in pool._attester_slashings],
            "as_electra": [
                "Electra" in type(s).__name__
                for s in pool._attester_slashings],
            "bls_changes": [
                serialize(T.SignedBLSToExecutionChange.ssz_type, c).hex()
                for c in pool._bls_changes.values()],
        }
    chain.store.put_item(OP_POOL_KEY, json.dumps(doc).encode())


def restore_op_pool(chain) -> int:
    from ..ssz import deserialize
    raw = chain.store.get_item(OP_POOL_KEY)
    if raw is None:
        return 0
    doc = json.loads(raw)
    T = chain.T
    n = 0
    for hexa, is_electra in zip(doc["attestations"],
                                doc.get("att_electra", [])):
        t = (T.AttestationElectra if is_electra else T.Attestation).ssz_type
        chain.op_pool.insert_attestation(deserialize(t, bytes.fromhex(hexa)))
        n += 1
    for hexe in doc["exits"]:
        chain.op_pool.insert_voluntary_exit(
            deserialize(T.SignedVoluntaryExit.ssz_type, bytes.fromhex(hexe)))
        n += 1
    for hexs in doc["proposer_slashings"]:
        chain.op_pool.insert_proposer_slashing(
            deserialize(T.ProposerSlashing.ssz_type, bytes.fromhex(hexs)))
        n += 1
    for hexs, is_electra in zip(doc.get("attester_slashings", []),
                                doc.get("as_electra", [])):
        t = (T.AttesterSlashingElectra if is_electra
             else T.AttesterSlashing).ssz_type
        chain.op_pool.insert_attester_slashing(
            deserialize(t, bytes.fromhex(hexs)))
        n += 1
    for hexc in doc["bls_changes"]:
        chain.op_pool.insert_bls_to_execution_change(
            deserialize(T.SignedBLSToExecutionChange.ssz_type,
                        bytes.fromhex(hexc)))
        n += 1
    return n


def persist_chain(chain) -> None:
    persist_fork_choice(chain)
    persist_op_pool(chain)


def resume_chain(chain) -> bool:
    """Restore fork choice + head + op pool from the store (FromStore boot).
    Returns True when prior state existed."""
    if not restore_fork_choice(chain):
        return False
    restore_op_pool(chain)
    head_root = chain.store.get_item(HEAD_KEY)
    if head_root is not None and \
            chain.fork_choice.contains_block(head_root):
        head_block = chain.store.get_block(head_root)
        head_state = (chain.store.get_hot_state(head_block.message.state_root)
                      if head_block else None)
        if head_block is not None and head_state is not None:
            from .beacon_chain import CanonicalHead
            with chain._lock:
                chain.canonical_head = CanonicalHead(head_root, head_block,
                                                     head_state)
            chain._cache_snapshot(head_root, head_state)
    return True
