"""Per-block arrival/processing timeline cache.

Equivalent of /root/reference/beacon_node/beacon_chain/src/
block_times_cache.rs: for each recent block root, record when it was
first observed, when consensus verification finished (imported), and
when it became head — the late-block forensics the ValidatorMonitor and
the re-org heuristic read.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ..api import metrics_defs
from ..obs import tracing

MAX_ENTRIES = 64


@dataclass
class BlockTimes:
    slot: int = 0
    observed_at: float | None = None
    imported_at: float | None = None
    became_head_at: float | None = None
    #: seconds into the slot when first seen (the lateness signal)
    observed_delay: float | None = None


class BlockTimesCache:
    def __init__(self, genesis_time: int, seconds_per_slot: int):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self._entries: OrderedDict[bytes, BlockTimes] = OrderedDict()
        self._lock = threading.Lock()

    def _entry(self, root: bytes, slot: int) -> BlockTimes:
        e = self._entries.get(root)
        if e is None:
            e = BlockTimes(slot=slot)
            self._entries[root] = e
            while len(self._entries) > MAX_ENTRIES:
                self._entries.popitem(last=False)
        return e

    def _slot_start(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def on_observed(self, root: bytes, slot: int,
                    now: float | None = None) -> None:
        now = now if now is not None else time.time()
        with self._lock:
            e = self._entry(root, slot)
            if e.observed_at is None:
                e.observed_at = now
                e.observed_delay = max(0.0, now - self._slot_start(slot))
                metrics_defs.observe("beacon_block_observed_delay_seconds",
                                     e.observed_delay)
                # anchor the active trace to the slot timeline
                tracing.annotate(
                    observed_delay_s=round(e.observed_delay, 6))

    def on_imported(self, root: bytes, slot: int,
                    now: float | None = None) -> None:
        now = now if now is not None else time.time()
        with self._lock:
            e = self._entry(root, slot)
            if e.imported_at is None:
                e.imported_at = now
                if e.observed_at is not None:
                    imported_delay = max(0.0, now - e.observed_at)
                    metrics_defs.observe(
                        "beacon_block_imported_delay_seconds",
                        imported_delay)
                    tracing.annotate(
                        imported_delay_s=round(imported_delay, 6))

    def on_became_head(self, root: bytes, slot: int,
                       now: float | None = None) -> None:
        now = now if now is not None else time.time()
        with self._lock:
            e = self._entry(root, slot)
            if e.became_head_at is None:
                e.became_head_at = now
                if e.imported_at is not None:
                    head_delay = max(0.0, now - e.imported_at)
                    metrics_defs.observe(
                        "beacon_block_head_delay_seconds", head_delay)
                    tracing.annotate(head_delay_s=round(head_delay, 6))

    def get(self, root: bytes) -> BlockTimes | None:
        with self._lock:
            return self._entries.get(root)

    def recent(self, n: int = 16) -> list[tuple[bytes, BlockTimes]]:
        with self._lock:
            return list(self._entries.items())[-n:]
